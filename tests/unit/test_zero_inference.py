"""ZeRO-Inference weight streaming (reference: ZeRO-3 offload_param powering
ZeRO-Inference — layer weights resident on host, streamed per layer).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
import deepspeed_tpu.comm as dist
from deepspeed_tpu.models.causal_lm import CausalLM
from deepspeed_tpu.models.transformer import TransformerConfig


@pytest.fixture(autouse=True)
def no_mesh():
    dist.set_mesh(None)
    yield


def _model(**over):
    base = dict(vocab_size=64, n_layer=3, n_head=4, d_model=32, d_ff=64,
                max_seq=256, remat=False, attention_backend="xla")
    base.update(over)
    return CausalLM(TransformerConfig(**base))


def _engines(model, params):
    base = deepspeed_tpu.init_inference(model, dtype="fp32", params=params)
    streamed = deepspeed_tpu.init_inference(
        model, dtype="fp32", params=params,
        zero={"stage": 3, "offload_param": {"device": "cpu"}})
    return base, streamed


def test_streamed_layers_live_on_host():
    model = _model()
    params = model.init_params(jax.random.key(0))
    _, eng = _engines(model, params)
    assert eng._stream_weights
    # layer weights are host numpy arrays, not device buffers
    assert all(isinstance(a, np.ndarray)
               for a in jax.tree.leaves(eng._host_layers[0]))
    # non-layer params went to device without a layers subtree
    assert "layers" not in eng.params


def test_streamed_forward_matches_resident():
    model = _model()
    params = model.init_params(jax.random.key(0))
    base, eng = _engines(model, params)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 10)),
                       jnp.int32)
    want = np.asarray(base.forward(toks), np.float32)
    got = np.asarray(eng.forward(toks), np.float32)
    np.testing.assert_allclose(got[:, :10], want, rtol=2e-4, atol=2e-4)


def test_streamed_generate_matches_resident():
    model = _model()
    params = model.init_params(jax.random.key(0))
    base, eng = _engines(model, params)
    prompt = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
    want = np.asarray(base.generate(prompt, max_new_tokens=6))
    got = np.asarray(eng.generate(prompt, max_new_tokens=6))
    np.testing.assert_array_equal(got, want)


def test_streamed_generate_eos_early_exit():
    model = _model()
    params = model.init_params(jax.random.key(0))
    _, eng = _engines(model, params)
    prompt = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
    full = np.asarray(eng.generate(prompt, max_new_tokens=6))
    eos = int(full[0, 5])  # second generated token
    cut = np.asarray(eng.generate(prompt, max_new_tokens=6, eos_token_id=eos))
    assert cut.shape[1] <= full.shape[1]
    assert eos in cut[0, 4:]


def test_streaming_composes_with_int8():
    """int8 weights stream as int8 (4x less host->device traffic)."""
    model = _model(tie_embeddings=True)
    params = model.init_params(jax.random.key(0))
    eng = deepspeed_tpu.init_inference(
        model, dtype="int8", params=params,
        zero={"stage": 3, "offload_param": {"device": "cpu"}})
    from deepspeed_tpu.ops.quant import Quantized8
    qleaves = [a for a in jax.tree.leaves(eng._host_layers[0],
                                          is_leaf=lambda x: isinstance(x, Quantized8))
               if isinstance(x := a, Quantized8)]
    assert qleaves, "layer weights not quantized on host"
    toks = jnp.asarray([[1, 2, 3]], jnp.int32)
    out = eng.forward(toks)
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())


def test_streaming_nvme_matches_resident(tmp_path):
    """NVMe ZeRO-Inference (reference partitioned_param_swapper.py:35):
    layer weights live on disk via the aio engine; forward + generate match
    the fully-resident engine and host RAM holds no layer copy."""
    model = _model()
    params = model.init_params(jax.random.key(0))
    base = deepspeed_tpu.init_inference(model, dtype="fp32", params=params)
    dist.set_mesh(None)
    eng = deepspeed_tpu.init_inference(
        model, dtype="fp32", params=params,
        zero={"stage": 3, "offload_param": {"device": "nvme",
                                            "nvme_path": str(tmp_path)}})
    assert eng._stream_weights and eng._stream_nvme
    assert eng._host_layers is None          # nothing resident in host RAM
    import glob
    import os
    sub = glob.glob(str(tmp_path / "zero_inference_*"))
    assert sub and os.listdir(sub[0]), "no swap files written"

    toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 10)),
                       jnp.int32)
    want = np.asarray(base.forward(toks), np.float32)
    got = np.asarray(eng.forward(toks), np.float32)
    np.testing.assert_allclose(got[:, :10], want, rtol=2e-4, atol=2e-4)

    prompt = jnp.asarray([[3, 1, 4]], jnp.int32)
    g_want = np.asarray(base.generate(prompt, max_new_tokens=5))
    g_got = np.asarray(eng.generate(prompt, max_new_tokens=5))
    np.testing.assert_array_equal(g_got, g_want)


def test_streaming_nvme_requires_path():
    model = _model()
    params = model.init_params(jax.random.key(0))
    with pytest.raises(ValueError, match="nvme_path"):
        deepspeed_tpu.init_inference(
            model, dtype="fp32", params=params,
            zero={"stage": 3, "offload_param": {"device": "nvme"}})


def test_streamed_generate_zero_new_tokens():
    model = _model()
    params = model.init_params(jax.random.key(0))
    _, eng = _engines(model, params)
    prompt = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
    out = np.asarray(eng.generate(prompt, max_new_tokens=0))
    assert out.shape == (1, 4)


def test_params_in_config_dict_honored():
    """Weights riding in the config dict must not be silently dropped."""
    model = _model()
    p1 = model.init_params(jax.random.key(7))
    eng = deepspeed_tpu.init_inference(model, config={"dtype": "fp32",
                                                      "params": p1})
    got = np.asarray(eng.params["embed"]["tokens"], np.float32)
    want = np.asarray(p1["embed"]["tokens"], np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_streaming_nvme_cleans_up_on_release(tmp_path):
    import gc
    import glob
    model = _model()
    params = model.init_params(jax.random.key(0))
    eng = deepspeed_tpu.init_inference(
        model, dtype="fp32", params=params,
        zero={"stage": 3, "offload_param": {"device": "nvme",
                                            "nvme_path": str(tmp_path)}})
    assert glob.glob(str(tmp_path / "zero_inference_*"))
    eng._swap_cleanup()          # what GC / interpreter exit runs
    del eng
    gc.collect()
    assert not glob.glob(str(tmp_path / "zero_inference_*")), \
        "swap dir leaked after engine release"


def test_streaming_composes_with_tp():
    """ZeRO-Inference streaming x tensor parallelism: layers stream to the
    device SHARDED over tp; logits match the fully-resident tp=1 engine."""
    model = _model()
    params = model.init_params(jax.random.key(0))
    base = deepspeed_tpu.init_inference(model, dtype="fp32", params=params)
    toks = jnp.asarray(np.random.default_rng(3).integers(0, 64, (2, 10)),
                       jnp.int32)
    want = np.asarray(base.forward(toks), np.float32)
    dist.set_mesh(None)
    eng = deepspeed_tpu.init_inference(
        model, dtype="fp32", params=params,
        tensor_parallel={"tp_size": 2},
        zero={"stage": 3, "offload_param": {"device": "cpu"}})
    assert eng._stream_weights and eng._layer_put_shardings is not None
    got = np.asarray(eng.forward(toks), np.float32)
    np.testing.assert_allclose(got[:, :10], want, rtol=2e-4, atol=2e-4)
    gen = np.asarray(eng.generate(jnp.asarray([[5, 9, 2]], jnp.int32),
                                  max_new_tokens=4))
    g_ref = np.asarray(base.generate(jnp.asarray([[5, 9, 2]], jnp.int32),
                                     max_new_tokens=4))
    np.testing.assert_array_equal(gen, g_ref)


@pytest.mark.parametrize("mode", ["int8", "nvme"])
def test_streaming_tp_composes_with_quant_and_nvme(mode, tmp_path):
    """The sharded layer-put path with Quantized8 nodes (int8) and with
    NVMe-reconstructed trees: tp=2 streamed logits match tp=1 resident."""
    model = _model()
    params = model.init_params(jax.random.key(0))
    toks = jnp.asarray(np.random.default_rng(5).integers(0, 64, (2, 10)),
                       jnp.int32)
    if mode == "int8":
        extra = {"dtype": "int8", "quant": {"weight": {"q_groups": 8}}}
        zero = {"stage": 3, "offload_param": {"device": "cpu"}}
    else:
        extra = {"dtype": "fp32"}
        zero = {"stage": 3, "offload_param": {"device": "nvme",
                                              "nvme_path": str(tmp_path)}}
    ref = deepspeed_tpu.init_inference(model, params=params, **extra)
    want = np.asarray(ref.forward(toks), np.float32)
    dist.set_mesh(None)
    eng = deepspeed_tpu.init_inference(
        model, params=params, tensor_parallel={"tp_size": 2},
        zero=zero, **extra)
    assert eng._layer_put_shardings is not None
    got = np.asarray(eng.forward(toks), np.float32)
    if mode == "int8":
        # bf16 activations: sharded-contraction reduction order perturbs at
        # the bf16 ulp scale (same budget as test_int8_tp_matches_tp1)
        assert np.abs(got[:, :10] - want[:, :10]).max() < \
            0.05 * max(1.0, np.abs(want).max())
    else:
        np.testing.assert_allclose(got[:, :10], want[:, :10],
                                   rtol=2e-4, atol=2e-4)


def test_streamed_forward_with_attention_mask():
    """attention_mask now flows into the streamed path as the cache-slot
    pad bias; logits match the resident engine under the same mask."""
    model = _model()
    params = model.init_params(jax.random.key(0))
    base, eng = _engines(model, params)
    toks = jnp.asarray(np.random.default_rng(7).integers(0, 64, (2, 10)),
                       jnp.int32)
    mask = np.ones((2, 10), np.int32)
    mask[0, :3] = 0   # left-padded row
    want = np.asarray(base.forward(toks, attention_mask=mask), np.float32)
    got = np.asarray(eng.forward(toks, attention_mask=mask), np.float32)
    # rows/positions whose visible keys are all masked are degenerate; row 0
    # positions >=3 and all of row 1 are well-defined
    np.testing.assert_allclose(got[0, 3:10], want[0, 3:10], rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(got[1, :10], want[1, :10], rtol=2e-4, atol=2e-4)
    # 1-D prompt + 1-D mask broadcast together (no deep IndexError)
    one = np.asarray(eng.forward(jnp.asarray(toks[0]), attention_mask=mask[0]),
                     np.float32)
    np.testing.assert_allclose(one[0, 3:10], got[0, 3:10], rtol=1e-5, atol=1e-5)
