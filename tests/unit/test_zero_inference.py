"""ZeRO-Inference weight streaming (reference: ZeRO-3 offload_param powering
ZeRO-Inference — layer weights resident on host, streamed per layer).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
import deepspeed_tpu.comm as dist
from deepspeed_tpu.models.causal_lm import CausalLM
from deepspeed_tpu.models.transformer import TransformerConfig


@pytest.fixture(autouse=True)
def no_mesh():
    dist.set_mesh(None)
    yield


def _model(**over):
    base = dict(vocab_size=64, n_layer=3, n_head=4, d_model=32, d_ff=64,
                max_seq=256, remat=False, attention_backend="xla")
    base.update(over)
    return CausalLM(TransformerConfig(**base))


def _engines(model, params):
    base = deepspeed_tpu.init_inference(model, dtype="fp32", params=params)
    streamed = deepspeed_tpu.init_inference(
        model, dtype="fp32", params=params,
        zero={"stage": 3, "offload_param": {"device": "cpu"}})
    return base, streamed


def test_streamed_layers_live_on_host():
    model = _model()
    params = model.init_params(jax.random.key(0))
    _, eng = _engines(model, params)
    assert eng._stream_weights
    # layer weights are host numpy arrays, not device buffers
    assert all(isinstance(a, np.ndarray)
               for a in jax.tree.leaves(eng._host_layers[0]))
    # non-layer params went to device without a layers subtree
    assert "layers" not in eng.params


def test_streamed_forward_matches_resident():
    model = _model()
    params = model.init_params(jax.random.key(0))
    base, eng = _engines(model, params)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 10)),
                       jnp.int32)
    want = np.asarray(base.forward(toks), np.float32)
    got = np.asarray(eng.forward(toks), np.float32)
    np.testing.assert_allclose(got[:, :10], want, rtol=2e-4, atol=2e-4)


def test_streamed_generate_matches_resident():
    model = _model()
    params = model.init_params(jax.random.key(0))
    base, eng = _engines(model, params)
    prompt = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
    want = np.asarray(base.generate(prompt, max_new_tokens=6))
    got = np.asarray(eng.generate(prompt, max_new_tokens=6))
    np.testing.assert_array_equal(got, want)


def test_streamed_generate_eos_early_exit():
    model = _model()
    params = model.init_params(jax.random.key(0))
    _, eng = _engines(model, params)
    prompt = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
    full = np.asarray(eng.generate(prompt, max_new_tokens=6))
    eos = int(full[0, 5])  # second generated token
    cut = np.asarray(eng.generate(prompt, max_new_tokens=6, eos_token_id=eos))
    assert cut.shape[1] <= full.shape[1]
    assert eos in cut[0, 4:]


def test_streaming_composes_with_int8():
    """int8 weights stream as int8 (4x less host->device traffic)."""
    model = _model(tie_embeddings=True)
    params = model.init_params(jax.random.key(0))
    eng = deepspeed_tpu.init_inference(
        model, dtype="int8", params=params,
        zero={"stage": 3, "offload_param": {"device": "cpu"}})
    from deepspeed_tpu.ops.quant import Quantized8
    qleaves = [a for a in jax.tree.leaves(eng._host_layers[0],
                                          is_leaf=lambda x: isinstance(x, Quantized8))
               if isinstance(x := a, Quantized8)]
    assert qleaves, "layer weights not quantized on host"
    toks = jnp.asarray([[1, 2, 3]], jnp.int32)
    out = eng.forward(toks)
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())


def test_streaming_nvme_matches_resident(tmp_path):
    """NVMe ZeRO-Inference (reference partitioned_param_swapper.py:35):
    layer weights live on disk via the aio engine; forward + generate match
    the fully-resident engine and host RAM holds no layer copy."""
    model = _model()
    params = model.init_params(jax.random.key(0))
    base = deepspeed_tpu.init_inference(model, dtype="fp32", params=params)
    dist.set_mesh(None)
    eng = deepspeed_tpu.init_inference(
        model, dtype="fp32", params=params,
        zero={"stage": 3, "offload_param": {"device": "nvme",
                                            "nvme_path": str(tmp_path)}})
    assert eng._stream_weights and eng._stream_nvme
    assert eng._host_layers is None          # nothing resident in host RAM
    import glob
    import os
    sub = glob.glob(str(tmp_path / "zero_inference_*"))
    assert sub and os.listdir(sub[0]), "no swap files written"

    toks = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 10)),
                       jnp.int32)
    want = np.asarray(base.forward(toks), np.float32)
    got = np.asarray(eng.forward(toks), np.float32)
    np.testing.assert_allclose(got[:, :10], want, rtol=2e-4, atol=2e-4)

    prompt = jnp.asarray([[3, 1, 4]], jnp.int32)
    g_want = np.asarray(base.generate(prompt, max_new_tokens=5))
    g_got = np.asarray(eng.generate(prompt, max_new_tokens=5))
    np.testing.assert_array_equal(g_got, g_want)


def test_streaming_nvme_requires_path():
    model = _model()
    params = model.init_params(jax.random.key(0))
    with pytest.raises(ValueError, match="nvme_path"):
        deepspeed_tpu.init_inference(
            model, dtype="fp32", params=params,
            zero={"stage": 3, "offload_param": {"device": "nvme"}})


def test_streamed_generate_zero_new_tokens():
    model = _model()
    params = model.init_params(jax.random.key(0))
    _, eng = _engines(model, params)
    prompt = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
    out = np.asarray(eng.generate(prompt, max_new_tokens=0))
    assert out.shape == (1, 4)


def test_params_in_config_dict_honored():
    """Weights riding in the config dict must not be silently dropped."""
    model = _model()
    p1 = model.init_params(jax.random.key(7))
    eng = deepspeed_tpu.init_inference(model, config={"dtype": "fp32",
                                                      "params": p1})
    got = np.asarray(eng.params["embed"]["tokens"], np.float32)
    want = np.asarray(p1["embed"]["tokens"], np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_streaming_nvme_cleans_up_on_release(tmp_path):
    import gc
    import glob
    model = _model()
    params = model.init_params(jax.random.key(0))
    eng = deepspeed_tpu.init_inference(
        model, dtype="fp32", params=params,
        zero={"stage": 3, "offload_param": {"device": "nvme",
                                            "nvme_path": str(tmp_path)}})
    assert glob.glob(str(tmp_path / "zero_inference_*"))
    eng._swap_cleanup()          # what GC / interpreter exit runs
    del eng
    gc.collect()
    assert not glob.glob(str(tmp_path / "zero_inference_*")), \
        "swap dir leaked after engine release"


def test_streaming_composes_with_tp():
    """ZeRO-Inference streaming x tensor parallelism: layers stream to the
    device SHARDED over tp; logits match the fully-resident tp=1 engine."""
    model = _model()
    params = model.init_params(jax.random.key(0))
    base = deepspeed_tpu.init_inference(model, dtype="fp32", params=params)
    toks = jnp.asarray(np.random.default_rng(3).integers(0, 64, (2, 10)),
                       jnp.int32)
    want = np.asarray(base.forward(toks), np.float32)
    dist.set_mesh(None)
    eng = deepspeed_tpu.init_inference(
        model, dtype="fp32", params=params,
        tensor_parallel={"tp_size": 2},
        zero={"stage": 3, "offload_param": {"device": "cpu"}})
    assert eng._stream_weights and eng._layer_put_shardings is not None
    got = np.asarray(eng.forward(toks), np.float32)
    np.testing.assert_allclose(got[:, :10], want, rtol=2e-4, atol=2e-4)
    gen = np.asarray(eng.generate(jnp.asarray([[5, 9, 2]], jnp.int32),
                                  max_new_tokens=4))
    g_ref = np.asarray(base.generate(jnp.asarray([[5, 9, 2]], jnp.int32),
                                     max_new_tokens=4))
    np.testing.assert_array_equal(gen, g_ref)


@pytest.mark.parametrize("mode", ["int8", "nvme"])
def test_streaming_tp_composes_with_quant_and_nvme(mode, tmp_path):
    """The sharded layer-put path with Quantized8 nodes (int8) and with
    NVMe-reconstructed trees: tp=2 streamed logits match tp=1 resident."""
    model = _model()
    params = model.init_params(jax.random.key(0))
    toks = jnp.asarray(np.random.default_rng(5).integers(0, 64, (2, 10)),
                       jnp.int32)
    if mode == "int8":
        extra = {"dtype": "int8", "quant": {"weight": {"q_groups": 8}}}
        zero = {"stage": 3, "offload_param": {"device": "cpu"}}
    else:
        extra = {"dtype": "fp32"}
        zero = {"stage": 3, "offload_param": {"device": "nvme",
                                              "nvme_path": str(tmp_path)}}
    ref = deepspeed_tpu.init_inference(model, params=params, **extra)
    want = np.asarray(ref.forward(toks), np.float32)
    dist.set_mesh(None)
    eng = deepspeed_tpu.init_inference(
        model, params=params, tensor_parallel={"tp_size": 2},
        zero=zero, **extra)
    assert eng._layer_put_shardings is not None
    got = np.asarray(eng.forward(toks), np.float32)
    if mode == "int8":
        # bf16 activations: sharded-contraction reduction order perturbs at
        # the bf16 ulp scale (same budget as test_int8_tp_matches_tp1)
        assert np.abs(got[:, :10] - want[:, :10]).max() < \
            0.05 * max(1.0, np.abs(want).max())
    else:
        np.testing.assert_allclose(got[:, :10], want[:, :10],
                                   rtol=2e-4, atol=2e-4)


def test_streamed_forward_with_attention_mask():
    """attention_mask now flows into the streamed path as the cache-slot
    pad bias; logits match the resident engine under the same mask."""
    model = _model()
    params = model.init_params(jax.random.key(0))
    base, eng = _engines(model, params)
    toks = jnp.asarray(np.random.default_rng(7).integers(0, 64, (2, 10)),
                       jnp.int32)
    mask = np.ones((2, 10), np.int32)
    mask[0, :3] = 0   # left-padded row
    want = np.asarray(base.forward(toks, attention_mask=mask), np.float32)
    got = np.asarray(eng.forward(toks, attention_mask=mask), np.float32)
    # rows/positions whose visible keys are all masked are degenerate; row 0
    # positions >=3 and all of row 1 are well-defined
    np.testing.assert_allclose(got[0, 3:10], want[0, 3:10], rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(got[1, :10], want[1, :10], rtol=2e-4, atol=2e-4)
    # 1-D prompt + 1-D mask broadcast together (no deep IndexError)
    one = np.asarray(eng.forward(jnp.asarray(toks[0]), attention_mask=mask[0]),
                     np.float32)
    np.testing.assert_allclose(one[0, 3:10], got[0, 3:10], rtol=1e-5, atol=1e-5)


def test_streamed_step_double_buffers(tmp_path):
    """Double-buffering contract (reference pipelined swapper read-ahead):
    before blk(i) is dispatched, layer i+1's H2D copy must already be in
    flight and layer i+2's NVMe reads submitted — I/O and H2D overlap
    compute instead of serializing with it."""
    model = _model()  # 3 layers
    params = model.init_params(jax.random.key(0))
    eng = deepspeed_tpu.init_inference(
        model, dtype="fp32", params=params,
        zero={"stage": 3, "offload_param": {"device": "nvme",
                                            "nvme_path": str(tmp_path)}})
    toks = jnp.asarray([[1, 2, 3]], jnp.int32)
    eng.generate(toks, max_new_tokens=1)  # build + compile _stream_jits

    events = []
    submit, finish, put = eng._fetch_submit, eng._fetch_finish, eng._put_layer
    eng._fetch_submit = lambda i: (events.append(("submit", i)), submit(i))[1]
    fin_idx = iter(range(10))
    eng._fetch_finish = lambda h: (events.append(("finish", next(fin_idx))), finish(h))[1]
    put_idx = iter(range(10))
    eng._put_layer = lambda lp: (events.append(("put", next(put_idx))), put(lp))[1]
    emb, blk, head = eng._stream_jits
    blk_idx = iter(range(10))

    def blk_rec(*a, **kw):
        events.append(("blk", next(blk_idx)))
        return blk(*a, **kw)
    eng._stream_jits = (emb, blk_rec, head)

    eng.generate(toks, max_new_tokens=1)
    order = {e: i for i, e in enumerate(events)}
    # layer 1's H2D starts before layer 0's compute is dispatched
    assert order[("put", 1)] < order[("blk", 0)], events
    # layer 2's NVMe reads are in flight while layer 0 computes
    assert order[("submit", 2)] < order[("blk", 0)], events
    # at most one submit outstanding at any moment: the swapper's wait() is
    # global, so a second in-flight batch would be silently absorbed by the
    # wrong finish and the read-ahead overlap would vanish
    pend = 0
    for kind, i in events:
        if kind == "submit":
            pend += 1
            assert pend <= 1, events
        elif kind == "finish":
            pend -= 1
    assert pend == 0, events


def test_streamed_nvme_sweeps_stale_dirs(tmp_path):
    """A SIGKILLed process leaks its model-sized swap dir; the next engine
    init under the same nvme_path reclaims dirs whose owner pid is dead and
    leaves live-owned or unmarked dirs alone."""
    import os
    import subprocess
    child = subprocess.Popen(["true"])
    child.wait()  # reaped => the pid no longer exists
    dead_pid = child.pid

    from deepspeed_tpu.inference.engine import InferenceEngine
    me_scope, _ = InferenceEngine._owner_marker().rsplit(":", 1)
    stale = tmp_path / "zero_inference_stale"
    stale.mkdir()
    (stale / "owner.pid").write_text(f"{me_scope}:{dead_pid}")
    (stale / "L0_0.swp").write_bytes(b"x" * 64)
    live = tmp_path / "zero_inference_live"
    live.mkdir()
    (live / "owner.pid").write_text(InferenceEngine._owner_marker())
    unmarked = tmp_path / "zero_inference_old"
    unmarked.mkdir()
    # a dead pid in ANOTHER scope (host / boot / pid namespace) must never
    # be judged — os.kill can't see across pid namespaces
    foreign = tmp_path / "zero_inference_foreign"
    foreign.mkdir()
    (foreign / "owner.pid").write_text(f"otherhost:deadbeef:pid:[1]:{dead_pid}")

    model = _model(n_layer=1)
    params = model.init_params(jax.random.key(0))
    eng = deepspeed_tpu.init_inference(
        model, dtype="fp32", params=params,
        zero={"stage": 3, "offload_param": {"device": "nvme",
                                            "nvme_path": str(tmp_path)}})
    assert not stale.exists(), "dead-owner dir not swept"
    assert live.exists(), "live-owner dir must survive"
    assert unmarked.exists(), "unmarked dir must survive"
    assert foreign.exists(), "foreign host/boot dir must survive"
    # the new engine's own dir carries the marker for future sweeps
    own = [d for d in tmp_path.glob("zero_inference_*/owner.pid")
           if d.read_text() == InferenceEngine._owner_marker()
           and d.parent != live]
    assert own, "new swap dir missing owner.pid marker"
