"""Training health observatory: on-device numerics sentinels (math pinned
on CPU, no extra compiles), host-side anomaly detectors on synthetic step
streams, debug-bundle dumps, memory gauges, serving KV gauges, and the
``dscli health`` renderer."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
import deepspeed_tpu.comm as dist
from deepspeed_tpu.models import CausalLM
from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.monitor.config import HealthConfig, get_telemetry_config
from deepspeed_tpu.monitor.health import (HealthMonitor, StepHealth,
                                          compute_sentinels, health_cli,
                                          make_bucket_assignment,
                                          read_last_snapshots,
                                          render_health_table,
                                          sample_memory_gauges,
                                          sentinel_to_dict)
from deepspeed_tpu.monitor.metrics import (MetricsRegistry, get_registry,
                                           validate_snapshot)


@pytest.fixture(autouse=True)
def clean_state():
    """Fresh mesh + fresh GLOBAL registry/watchdog per test (engines
    create their metric families at init, so the reset must come first)."""
    from deepspeed_tpu.monitor.trace import get_compile_watchdog
    dist.set_mesh(None)
    get_registry().reset()
    get_registry().set_enabled(True)
    get_compile_watchdog().reset()
    yield
    dist.set_mesh(None)
    get_registry().reset()
    get_registry().set_enabled(True)
    get_compile_watchdog().reset()


def tiny_model(**over):
    base = dict(vocab_size=64, n_layer=2, n_head=2, d_model=32, d_ff=64,
                max_seq=32, remat=False, attention_backend="xla")
    base.update(over)
    return CausalLM(TransformerConfig(**base))


def make_engine(health=None, fp16=False, **cfg_over):
    model = tiny_model()
    params = model.init_params(jax.random.key(0))
    tel = {"enabled": True}
    if health is not None:
        tel["health"] = health
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "mesh": {"dp": -1},
        "steps_per_print": 0,
        "telemetry": tel,
    }
    if fp16:
        config["fp16"] = {"enabled": True}
    config.update(cfg_over)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                               model_parameters=params,
                                               config=config)
    return engine


def train_batch(engine):
    dp = dist.get_world_size(dist.data_parallel_axes(engine.mesh))
    rows = engine.train_micro_batch_size_per_gpu() * \
        engine.gradient_accumulation_steps() * dp
    rng = np.random.default_rng(0)
    return {"input_ids": rng.integers(0, 64, size=(rows, 32)).astype(np.int32)}


def force_nonfinite_grads(engine):
    """Make the compiled step produce non-finite loss/grads (multiplying
    the loss by inf propagates inf/nan into every gradient). Must run
    BEFORE the first train_batch so the lazy jit closes over it."""
    orig = engine.loss_fn
    engine.loss_fn = lambda p, b, rng: orig(p, b, rng) * jnp.float32(np.inf)


# --------------------------------------------------------------------- #
# sentinel math (pure, pinned on CPU)


class TestSentinels:

    def _trees(self):
        grads = {"embed": jnp.asarray([1.0, -2.0, 3.0]),
                 "layers": {"w": jnp.asarray([[0.5, -0.5], [1.5, 2.5]])},
                 "head": jnp.asarray([4.0])}
        new = jax.tree.map(lambda g: g * 10.0, grads)
        return grads, new

    def test_clean_values_match_reference(self):
        grads, new = self._trees()
        assignment, names = make_bucket_assignment(grads, 8)
        vec = compute_sentinels(grads, new, jnp.asarray(0.5), None,
                                assignment, names)
        d = sentinel_to_dict(vec, names)
        flat = np.concatenate([np.asarray(l).ravel()
                               for l in jax.tree.leaves(grads)])
        assert d["nonfinite_grads"] == 0 and d["nonfinite_params"] == 0
        assert d["grad_norm"] == pytest.approx(np.linalg.norm(flat), rel=1e-6)
        pflat = np.concatenate([np.asarray(l).ravel()
                                for l in jax.tree.leaves(new)])
        assert d["param_norm"] == pytest.approx(np.linalg.norm(pflat), rel=1e-6)
        assert d["update_norm"] == pytest.approx(0.5)
        assert d["update_ratio"] == pytest.approx(
            0.5 / d["param_norm"], rel=1e-5)
        # per-group buckets match per-group norms
        assert set(names) == {"embed", "layers", "head"}
        assert d["bucket_norms"]["embed"] == pytest.approx(
            np.linalg.norm([1, -2, 3]), rel=1e-6)
        assert d["bucket_norms"]["layers"] == pytest.approx(
            np.linalg.norm([0.5, -0.5, 1.5, 2.5]), rel=1e-6)
        assert d["bucket_norms"]["head"] == pytest.approx(4.0, rel=1e-6)

    def test_nonfinite_counts(self):
        grads, new = self._trees()
        grads["embed"] = jnp.asarray([np.nan, np.inf, 3.0])
        new["head"] = jnp.asarray([np.nan])
        assignment, names = make_bucket_assignment(grads, 8)
        d = sentinel_to_dict(
            compute_sentinels(grads, new, 0.0, None, assignment, names), names)
        assert d["nonfinite_grads"] == 2
        assert d["nonfinite_params"] == 1

    def test_grad_norm_passthrough_not_recomputed(self):
        grads, new = self._trees()
        assignment, names = make_bucket_assignment(grads, 8)
        vec = compute_sentinels(grads, new, 0.0, jnp.asarray(123.0),
                                assignment, names)
        assert sentinel_to_dict(vec, names)["grad_norm"] == 123.0

    def test_bucket_cap_merges_into_other(self):
        tree = {f"g{i}": jnp.ones((2,)) for i in range(6)}
        assignment, names = make_bucket_assignment(tree, 4)
        assert len(names) == 4 and names[-1] == "other"
        assert max(assignment) == 3
        assert assignment[:3] == (0, 1, 2)  # first groups keep their bucket
        assert assignment[3:] == (3, 3, 3)  # tail collapses


# --------------------------------------------------------------------- #
# anomaly detectors on synthetic step streams


def hcfg(**over):
    base = dict(enabled=True, action="record", window=50, warmup_steps=5,
                loss_ewma_alpha=0.1)
    base.update(over)
    return HealthConfig(**base)


def rec(step, loss=1.0, gn=1.0, **kw):
    return StepHealth(step=step, loss=loss, grad_norm=gn, step_time_s=0.1,
                      wait_time_s=0.001, **kw)


class TestDetectors:

    def test_loss_spike_fires_and_steady_noise_does_not(self):
        mon = HealthMonitor(hcfg(), registry=MetricsRegistry())
        rng = np.random.default_rng(0)
        for i in range(30):
            fired = mon.observe_step(rec(i, loss=1.0 + 0.01 * rng.standard_normal()))
            assert fired == []
        assert "loss_spike" in mon.observe_step(rec(30, loss=10.0))
        assert mon.report()["anomalies"]["loss_spike"] == 1

    def test_grad_explosion(self):
        mon = HealthMonitor(hcfg(grad_norm_factor=10.0),
                            registry=MetricsRegistry())
        for i in range(20):
            assert mon.observe_step(rec(i, gn=1.0)) == []
        assert "grad_explosion" in mon.observe_step(rec(20, gn=150.0))

    def test_plateau_fires_only_without_improvement(self):
        mon = HealthMonitor(hcfg(plateau_steps=5), registry=MetricsRegistry())
        for i in range(16):
            mon.observe_step(rec(i, loss=2.0))
        assert mon.report()["anomalies"]["plateau"] >= 2
        mon2 = HealthMonitor(hcfg(plateau_steps=5), registry=MetricsRegistry())
        for i in range(16):
            mon2.observe_step(rec(i, loss=2.0 - 0.1 * i))
        assert mon2.report()["anomalies"]["plateau"] == 0

    def test_sustained_overflow_vs_sporadic(self):
        mon = HealthMonitor(hcfg(overflow_window=3), registry=MetricsRegistry())
        for i in range(6):
            mon.observe_step(rec(i, loss=float("nan"), gn=float("nan"),
                                 skipped=True))
        assert mon.report()["anomalies"]["overflow"] == 2   # at 3 and 6
        # fp16 skips are NOT double-counted as nonfinite anomalies
        assert mon.report()["anomalies"]["nonfinite"] == 0
        mon2 = HealthMonitor(hcfg(overflow_window=3), registry=MetricsRegistry())
        for i in range(12):
            mon2.observe_step(rec(i, skipped=(i % 2 == 0)))
        assert mon2.report()["anomalies"]["overflow"] == 0

    def test_data_stall(self):
        mon = HealthMonitor(hcfg(data_stall_steps=4, data_stall_fraction=0.5),
                            registry=MetricsRegistry())
        for i in range(4):
            fired = mon.observe_step(StepHealth(step=i, loss=1.0, grad_norm=1.0,
                                                step_time_s=0.1, wait_time_s=0.9))
        assert "data_stall" in fired
        assert mon.report()["data_stall_fraction"] == pytest.approx(0.9)
        mon2 = HealthMonitor(hcfg(data_stall_steps=4), registry=MetricsRegistry())
        for i in range(12):
            assert mon2.observe_step(rec(i)) == []

    def test_unknown_grad_norm_is_not_an_anomaly(self):
        # grad_norm=None means "not measured" (e.g. the 1-bit optimizer
        # path) — it must not read as a non-finite norm
        mon = HealthMonitor(hcfg(), registry=MetricsRegistry())
        for i in range(10):
            assert mon.observe_step(StepHealth(step=i, loss=2.0)) == []
        assert mon.report()["anomalies"]["nonfinite"] == 0
        # a MEASURED non-finite norm still fires
        assert "nonfinite" in mon.observe_step(
            StepHealth(step=10, loss=2.0, grad_norm=float("inf")))

    def test_nonfinite_immediate_and_counter(self):
        reg = MetricsRegistry()
        mon = HealthMonitor(hcfg(), registry=reg)
        assert "nonfinite" in mon.observe_step(rec(0, nonfinite_grads=7))
        assert reg.snapshot()["counters"][
            'health/anomalies{type="nonfinite"}'] == 1
        # pre-created zero children for every other detector
        assert reg.snapshot()["counters"][
            'health/anomalies{type="loss_spike"}'] == 0

    def test_warn_action_is_rate_limited_and_record_is_silent(self, monkeypatch):
        from deepspeed_tpu.monitor import health as health_mod
        warnings = []
        monkeypatch.setattr(health_mod.logger, "warning",
                            lambda msg, *a, **k: warnings.append(str(msg)))
        mon = HealthMonitor(hcfg(action="warn", window=10, overflow_window=1),
                            registry=MetricsRegistry())
        for i in range(25):
            mon.observe_step(rec(i, skipped=True, loss=float("nan"),
                                 gn=float("nan")))
        assert mon.report()["anomalies"]["overflow"] == 25
        assert 1 <= len(warnings) <= 4          # ~one per 10-step window
        warnings.clear()
        mon2 = HealthMonitor(hcfg(action="record", overflow_window=1),
                             registry=MetricsRegistry())
        for i in range(25):
            mon2.observe_step(rec(i, skipped=True))
        assert warnings == []

    def test_invalid_action_raises(self):
        with pytest.raises(ValueError, match="action"):
            HealthMonitor(hcfg(action="explode"), registry=MetricsRegistry())


# --------------------------------------------------------------------- #
# debug bundles


class TestDebugBundle:

    def test_dump_contents_and_limit(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("train/steps").inc(3)
        mon = HealthMonitor(
            hcfg(action="dump", window=1, dump_dir=str(tmp_path),
                 dump_limit=2, keep_last_steps=5, overflow_window=1),
            registry=reg, bucket_names=("embed", "layers"),
            snapshot_fn=reg.snapshot)
        for i in range(8):
            mon.observe_step(rec(i, skipped=True))
        bundles = sorted(p for p in tmp_path.iterdir() if p.is_dir())
        assert len(bundles) == 2                       # dump_limit respected
        b = bundles[0]
        report = json.load(open(b / "report.json"))
        assert report["fired"] == ["overflow"]
        assert report["record"]["skipped"] is True
        assert report["bucket_names"] == ["embed", "layers"]
        assert report["config"]["dump_limit"] == 2
        steps = [json.loads(l) for l in open(b / "steps.jsonl")]
        assert 1 <= len(steps) <= 5
        assert all("loss" in s and "grad_norm" in s for s in steps)
        tel = json.load(open(b / "telemetry.json"))
        assert tel["counters"]["train/steps"] == 3


# --------------------------------------------------------------------- #
# memory telemetry


class TestMemoryTelemetry:

    def test_sample_memory_gauges_host_rss_and_report(self):
        reg = MetricsRegistry()
        report = sample_memory_gauges(reg)
        assert report["host_rss_bytes"] > 0
        snap = reg.snapshot()
        assert snap["gauges"]["mem/host_rss_bytes"] > 0
        # device gauges appear exactly for devices exposing stats
        assert isinstance(report["devices"], dict)
        for name, st in report["devices"].items():
            key = f'mem/hbm_bytes_in_use{{device="{name}"}}'
            assert (key in snap["gauges"]) == bool(st)

    def test_accelerator_memory_report_shape(self):
        from deepspeed_tpu.accelerator import get_accelerator
        acc = get_accelerator()
        rep = acc.memory_report()
        assert len(rep) == acc.local_device_count()
        for st in rep.values():
            assert st == {} or {"bytes_in_use", "peak_bytes_in_use",
                                "bytes_limit", "headroom_bytes"} <= set(st)


# --------------------------------------------------------------------- #
# config parsing


class TestHealthConfig:

    def test_defaults_off_and_bool_shorthand(self):
        assert get_telemetry_config({}).health.enabled is False
        cfg = get_telemetry_config({"telemetry": {"health": True}})
        assert cfg.health.enabled is True
        assert cfg.enabled is True            # health implies telemetry
        # null = defaults, like the parent telemetry section
        assert get_telemetry_config(
            {"telemetry": {"health": None}}).health.enabled is False
        # "on"/"off" shorthand, like the parent section
        assert get_telemetry_config(
            {"telemetry": {"health": "on"}}).health.enabled is True
        assert get_telemetry_config(
            {"telemetry": {"health": "off"}}).health.enabled is False
        with pytest.raises(ValueError, match="health"):
            get_telemetry_config({"telemetry": {"health": "sometimes"}})

    def test_explicit_telemetry_off_wins(self):
        cfg = get_telemetry_config(
            {"telemetry": {"enabled": False, "health": {"enabled": True}}})
        assert cfg.enabled is False

    def test_threshold_passthrough(self):
        cfg = get_telemetry_config(
            {"telemetry": {"health": {"enabled": True, "window": 7,
                                      "action": "dump", "sentinels": False}}})
        assert cfg.health.window == 7
        assert cfg.health.action == "dump"
        assert cfg.health.sentinels is False


# --------------------------------------------------------------------- #
# serving KV pool gauges


class TestServingKvGauges:

    def test_free_and_fragmentation_gauges(self):
        from deepspeed_tpu.inference.block_allocator import BlockAllocator
        from deepspeed_tpu.inference.scheduler import (
            ContinuousBatchingScheduler, ServingTelemetry)
        reg = MetricsRegistry()
        sched = ContinuousBatchingScheduler(
            BlockAllocator(9, 8), 2, 8, telemetry=ServingTelemetry(reg))
        sched.add_request(np.arange(5, dtype=np.int32), max_new=3)
        fr = []
        tok = 0
        while True:
            action = sched.next_action()
            g = reg.snapshot()["gauges"]
            assert g["serving/kv_blocks_free"] + g["serving/kv_blocks_used"] == 8
            assert 0.0 <= g["serving/kv_fragmentation"] <= 1.0
            fr.append(g["serving/kv_fragmentation"])
            if action is None:
                break
            kind, payload = action
            if kind == "prefill":
                sched.record_prefill(payload, tok)
            else:
                for r in list(payload):
                    sched.record_decode(r, tok)
            tok += 1
        g = reg.snapshot()["gauges"]
        assert g["serving/kv_blocks_free"] == 8      # all returned
        assert g["serving/kv_fragmentation"] == 0.0
        # mid-run: one block held 5-7 cached tokens of 8 slots
        assert max(fr) > 0.0


# --------------------------------------------------------------------- #
# `dscli health` renderer + CLI


def write_fixture_jsonl(reg, path, steps=(9, 10)):
    reg.counter("train/steps").inc(10)
    reg.gauge("train/loss").set(3.21)
    reg.gauge("train/mfu").set(0.42)
    reg.gauge("train/tokens_per_sec").set(12345)
    reg.histogram("train/step_time_ms").observe(100.0)
    reg.histogram("train/grad_norm").observe(1.5)
    reg.gauge("train/loss_scale").set(32768)
    reg.gauge("train/skipped_steps").set(1)
    reg.counter("health/anomalies",
                labelnames=("type",)).labels(type="loss_spike").inc(2)
    reg.gauge("train/data_stall_fraction").set(0.25)
    reg.gauge("mem/hbm_bytes_in_use",
              labelnames=("device",)).labels(device="tpu:0").set(12e9)
    reg.gauge("mem/hbm_bytes_limit",
              labelnames=("device",)).labels(device="tpu:0").set(16e9)
    reg.gauge("mem/host_rss_bytes").set(8e9)
    reg.histogram("serving/ttft_ms").observe(12.0)
    reg.gauge("serving/queue_depth").set(3)
    reg.gauge("serving/kv_block_utilization").set(0.8)
    reg.gauge("serving/kv_blocks_free").set(12)
    for s in steps:
        reg.write_jsonl(path, step=s)


class TestHealthCLI:

    def test_render_from_fixture_jsonl(self, tmp_path):
        path = str(tmp_path / "tel.jsonl")
        write_fixture_jsonl(MetricsRegistry(), path)
        recs = read_last_snapshots(path, 2)
        assert len(recs) == 2 and recs[-1]["step"] == 10
        table = render_health_table(recs[-1], recs[-2])
        for needle in ("step 10", "MFU 0.420", "loss 3.21", "grad_norm",
                       "loss_scale 32768", "skipped 1/10",
                       "loss_spike:2", "data-stall 25.0%",
                       "HBM 11.2GB/14.9GB", "host RSS 7.5GB",
                       "TTFT p50 12.0ms", "queue 3", "KV util 0.80 free 12"):
            assert needle in table, (needle, table)

    def test_cli_once_and_missing_file(self, tmp_path, capsys):
        path = str(tmp_path / "tel.jsonl")
        write_fixture_jsonl(MetricsRegistry(), path)
        assert health_cli([path, "--once"]) == 0
        assert "MFU" in capsys.readouterr().out
        assert health_cli([str(tmp_path / "nope.jsonl"), "--once"]) == 1

    def test_tail_skips_corrupt_lines(self, tmp_path):
        path = tmp_path / "tel.jsonl"
        path.write_text('not json\n{"step": 1, "counters": {}}\n{"broken\n'
                        '{"step": 2, "counters": {}}\n')
        recs = read_last_snapshots(str(path), 2)
        assert [r["step"] for r in recs] == [1, 2]

    def test_render_empty_snapshot(self):
        out = render_health_table({"step": 0})
        assert "no recognized series" in out


# --------------------------------------------------------------------- #
# engine wiring (the acceptance pins)


class TestEngineHealth:

    def test_clean_run_zero_anomalies_and_no_extra_compiles(self):
        engine = make_engine(health={"enabled": True})
        for _ in range(3):
            engine.train_batch(train_batch(engine))
        snap = engine.telemetry_snapshot()
        validate_snapshot(snap)
        # sentinel collection rode the SAME compiled step: exactly one
        # watched entry point, compiled exactly once
        assert snap["compile"]["by_fn"] == {"engine.train_batch[gas=1]": 1}
        # clean run: every detector at an explicit zero
        for t in HealthMonitor.DETECTORS:
            assert snap["counters"][f'health/anomalies{{type="{t}"}}'] == 0
        # satellite: pre-clip grad norm recorded every step, clipping off
        assert snap["histograms"]["train/grad_norm"]["count"] == 3
        assert snap["histograms"]["train/grad_norm"]["min"] > 0
        assert snap["gauges"]["train/loss"] > 0
        assert snap["gauges"]["health/grad_norm"] > 0
        assert 0.0 <= snap["gauges"]["train/data_stall_fraction"] <= 1.0
        assert snap["gauges"]["mem/host_rss_bytes"] > 0
        rep = engine.health_report()
        assert rep["enabled"] and rep["steps"] == 3
        assert rep["anomalies"] == {t: 0 for t in HealthMonitor.DETECTORS}
        assert rep["bucket_names"]                      # layer groups named
        assert rep["last"]["update_ratio"] > 0
        assert len(rep["last"]["bucket_norms"]) == len(rep["bucket_names"])

    def test_forced_nonfinite_fires_warns_and_dumps(self, tmp_path,
                                                    monkeypatch):
        from deepspeed_tpu.monitor import health as health_mod
        warnings = []
        monkeypatch.setattr(health_mod.logger, "warning",
                            lambda msg, *a, **k: warnings.append(str(msg)))
        engine = make_engine(health={"enabled": True, "action": "dump",
                                     "window": 2, "warmup_steps": 0,
                                     "dump_dir": str(tmp_path)})
        force_nonfinite_grads(engine)
        for _ in range(3):
            engine.train_batch(train_batch(engine))
        snap = engine.telemetry_snapshot()
        assert snap["counters"]['health/anomalies{type="nonfinite"}'] == 3
        # rate-limited: window 2 suppresses the middle step's warning
        fired_warns = [w for w in warnings
                       if w.startswith("health: nonfinite")]
        assert 1 <= len(fired_warns) < 3
        bundles = sorted(p for p in tmp_path.iterdir() if p.is_dir())
        assert bundles, "no debug bundle on disk"
        names = {p.name for p in bundles[0].iterdir()}
        assert {"report.json", "steps.jsonl", "telemetry.json"} <= names
        report = json.load(open(bundles[0] / "report.json"))
        assert "nonfinite" in report["fired"]
        assert report["record"]["nonfinite_grads"] > 0
        # still no extra compiles
        assert snap["compile"]["by_fn"] == {"engine.train_batch[gas=1]": 1}

    @pytest.mark.slow  # engine-level duplicates of detector/gauge pins
    def test_fp16_skip_gauges_and_health_off_warning(self, monkeypatch):
        from deepspeed_tpu.runtime import engine as engine_mod
        warnings = []
        monkeypatch.setattr(engine_mod.logger, "warning",
                            lambda msg, *a, **k: warnings.append(str(msg)))
        # health OFF: the engine's own rate-limited warning surfaces skips
        engine = make_engine(health={"overflow_window": 2}, fp16=True)
        assert engine._health is None
        force_nonfinite_grads(engine)
        for _ in range(4):
            engine.train_batch(train_batch(engine))
        snap = engine.telemetry_snapshot()
        assert snap["gauges"]["train/skipped_steps"] == 4
        assert snap["gauges"]["train/loss_scale"] > 0
        assert sum("overflow skipped" in w for w in warnings) == 2  # at 2, 4
        assert engine.skipped_steps == 4

    @pytest.mark.slow  # sentinel flow through the trio path
    def test_trio_step_records_grad_norm_and_health(self, tmp_path):
        jsonl = str(tmp_path / "tel.jsonl")
        engine = make_engine(health={"enabled": True},
                             **{"telemetry": {"enabled": True,
                                              "jsonl_path": jsonl,
                                              "steps_per_snapshot": 1,
                                              "health": {"enabled": True}}})
        engine.forward(train_batch(engine))
        engine.backward()
        engine.step()
        # the trio boundary flushes the sink too (not just train_batch)
        recs = read_last_snapshots(jsonl)
        assert recs and recs[-1]["step"] == 1
        snap = engine.telemetry_snapshot()
        assert snap["histograms"]["train/grad_norm"]["count"] == 1
        rep = engine.health_report()
        assert rep["steps"] == 1
        # wait/busy measured on the trio path too (not hard-coded zero):
        # one boundary -> one data-wait sample, fraction in range
        assert snap["histograms"]["train/data_wait_ms"]["count"] == 1
        assert 0.0 <= snap["gauges"]["train/data_stall_fraction"] <= 1.0
        assert rep["last"]["step_time_s"] > 0

    @pytest.mark.slow  # health-off engine stays inert beyond base telemetry
    def test_health_off_no_health_series(self):
        engine = make_engine()
        engine.train_batch(train_batch(engine))
        snap = engine.telemetry_snapshot()
        assert not any(k.startswith("health/") for k in snap["counters"])
        assert engine.health_report() == {"enabled": False}
        # base telemetry still records the reused pre-clip norm
        assert snap["histograms"]["train/grad_norm"]["count"] == 1
