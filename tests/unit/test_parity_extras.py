"""TiledLinear / eigenvalue / sparse-grad parity components.

Reference analogues: ``tests/unit/runtime/zero/test_zero_tiled.py``, the
eigenvalue path of ``runtime/quantize.py``, and the engine's sparse
allreduce tests.
"""

import jax
from deepspeed_tpu.utils.jax_compat import shard_map
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
from deepspeed_tpu.runtime.sparse_tensor import SparseTensor, sparse_all_reduce
from deepspeed_tpu.runtime.zero.tiling import TiledLinear


class TestTiledLinear:
    @pytest.mark.parametrize("in_splits,out_splits", [(1, 1), (2, 4), (4, 2)])
    @pytest.mark.parametrize("scan_tiles", [False, True])
    def test_matches_dense(self, in_splits, out_splits, scan_tiles):
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.normal(size=(32, 64)), jnp.float32)
        b = jnp.asarray(rng.normal(size=64), jnp.float32)
        x = jnp.asarray(rng.normal(size=(3, 5, 32)), jnp.float32)
        tl = TiledLinear(32, 64, in_splits, out_splits, scan_tiles=scan_tiles)
        p = tl.from_dense(w, b)
        want = x @ w + b
        got = tl(p, x)
        assert float(jnp.abs(got - want).max()) < 1e-4
        assert float(jnp.abs(tl.to_dense(p) - w).max()) == 0.0

    def test_uneven_split_rejected(self):
        with pytest.raises(ValueError, match="not divisible"):
            TiledLinear(30, 64, in_splits=4)

    def test_init_shapes(self):
        tl = TiledLinear(32, 64, 2, 4)
        p = tl.init_params(jax.random.key(0))
        assert p["w"].shape == (4, 2, 16, 16)
        assert p["b"].shape == (64,)


class TestEigenvalue:
    def test_known_quadratic(self):
        """L = 0.5 xᵀAx per block: block eigs 4 and 10 → [0.4, 1.0]."""
        rng = np.random.default_rng(0)
        A1 = jnp.asarray(np.diag([4.0, 1.0, 0.5]), jnp.float32)
        A2 = jnp.asarray(np.diag([10.0, 2.0]), jnp.float32)
        params = {"a": jnp.asarray(rng.normal(size=3), jnp.float32),
                  "b": jnp.asarray(rng.normal(size=2), jnp.float32)}

        def loss(p):
            return 0.5 * p["a"] @ A1 @ p["a"] + 0.5 * p["b"] @ A2 @ p["b"]

        ev = Eigenvalue(max_iter=200, tol=1e-4)
        blocks = [{"a": jnp.ones(3), "b": jnp.zeros(2)},
                  {"a": jnp.zeros(3), "b": jnp.ones(2)}]
        out = ev.compute_eigenvalue(loss, params, blocks)
        assert abs(out[0] - 0.4) < 1e-2
        assert out[1] == 1.0

    def test_post_process_zero_block(self):
        assert Eigenvalue().post_process([0.0, -5.0]) == [1.0, 1.0]
        assert Eigenvalue().post_process([]) == []


class TestSparseTensor:
    def test_dense_roundtrip_with_duplicates(self):
        ids = jnp.asarray([1, 3, 1], jnp.int32)
        vals = jnp.asarray([[1.0, 0.0], [0.0, 2.0], [4.0, 0.0]], jnp.float32)
        st = SparseTensor.from_embedding_grad(ids, vals, vocab_size=5)
        dense = st.to_dense()
        assert dense.shape == (5, 2)
        assert float(dense[1, 0]) == 5.0  # duplicate rows accumulate
        assert float(dense[3, 1]) == 2.0

    def test_sparse_all_reduce_matches_dense(self, devices):
        """shard_map over dp: gathered sparse sum == dense psum."""
        mesh = Mesh(np.array(devices[:4]), ("dp",))
        vocab, D, n = 16, 4, 3
        rng = np.random.default_rng(1)
        ids = jnp.asarray(rng.integers(0, vocab, size=(4, n)), jnp.int32)
        vals = jnp.asarray(rng.normal(size=(4, n, D)), jnp.float32)

        def body(i, v):
            st = SparseTensor.from_embedding_grad(i[0], v[0], vocab)
            red = sparse_all_reduce(st, "dp", average=True)
            return red.to_dense()[None]

        out = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P("dp"), P("dp")),
            out_specs=P("dp")))(ids, vals)
        # every rank holds the same averaged dense grad
        want = jnp.zeros((vocab, D)).at[ids.reshape(-1)].add(
            vals.reshape(-1, D)) / 4
        for r in range(4):
            assert float(jnp.abs(out[r] - want).max()) < 1e-6
