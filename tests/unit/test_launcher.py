"""Launcher / CLI tests (reference tests/unit/launcher/: arg parsing,
hostfile, filters, multinode cmd construction — all hardware-free), plus a
real 2-process local launch smoke test and elasticity planning tests."""

import json
import os
import subprocess
import sys
import tempfile

import pytest

from deepspeed_tpu.elasticity import (ElasticityIncompatibleWorldSize, compute_elastic_config,
                                      get_candidate_batch_sizes, get_valid_gpus)
from deepspeed_tpu.launcher import launch as ds_launch
from deepspeed_tpu.launcher import runner as ds_runner
from deepspeed_tpu.launcher.multinode_runner import (IMPIRunner, MPICHRunner, MVAPICHRunner,
                                                     OpenMPIRunner, PDSHRunner, SlurmRunner)


_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))


def _hostfile(tmp_path, text):
    p = tmp_path / "hostfile"
    p.write_text(text)
    return str(p)


class TestHostfile:

    def test_parse(self, tmp_path):
        hf = _hostfile(tmp_path, "worker-0 slots=4\nworker-1 slots=8\n# comment\n\n")
        pool = ds_runner.fetch_hostfile(hf)
        assert pool == {"worker-0": 4, "worker-1": 8}

    def test_bad_format(self, tmp_path):
        hf = _hostfile(tmp_path, "worker-0 gpus=4\n")
        with pytest.raises(ValueError):
            ds_runner.fetch_hostfile(hf)

    def test_duplicate_host(self, tmp_path):
        hf = _hostfile(tmp_path, "w slots=4\nw slots=2\n")
        with pytest.raises(ValueError):
            ds_runner.fetch_hostfile(hf)

    def test_missing_returns_none(self):
        assert ds_runner.fetch_hostfile("/nonexistent/hostfile") is None


class TestResourceFilter:

    POOL = {"worker-0": 4, "worker-1": 4}

    def test_no_filter(self):
        out = ds_runner.parse_resource_filter(self.POOL)
        assert out == {"worker-0": [0, 1, 2, 3], "worker-1": [0, 1, 2, 3]}

    def test_include_host(self):
        out = ds_runner.parse_resource_filter(self.POOL, include_str="worker-1")
        assert out == {"worker-1": [0, 1, 2, 3]}

    def test_include_slots(self):
        out = ds_runner.parse_resource_filter(self.POOL, include_str="worker-0:0,2")
        assert out == {"worker-0": [0, 2]}

    def test_exclude_host(self):
        out = ds_runner.parse_resource_filter(self.POOL, exclude_str="worker-0")
        assert out == {"worker-1": [0, 1, 2, 3]}

    def test_exclude_slots(self):
        out = ds_runner.parse_resource_filter(self.POOL, exclude_str="worker-1:1,3")
        assert out == {"worker-0": [0, 1, 2, 3], "worker-1": [0, 2]}

    def test_mutually_exclusive(self):
        with pytest.raises(ValueError):
            ds_runner.parse_resource_filter(self.POOL, include_str="worker-0",
                                            exclude_str="worker-1")

    def test_unknown_host(self):
        with pytest.raises(ValueError):
            ds_runner.parse_resource_filter(self.POOL, include_str="worker-9")


class TestWorldInfo:

    def test_roundtrip(self):
        info = {"worker-0": [0, 1], "worker-1": [0, 1, 2]}
        enc = ds_runner.encode_world_info(info)
        assert ds_runner.decode_world_info(enc) == info

    def test_rank_env(self):
        info = {"a": [0, 1], "b": [0, 1]}
        env = ds_launch.build_rank_env(info, node_rank=1, local_rank_idx=1,
                                       master_addr="10.0.0.1", master_port=29500)
        assert env["RANK"] == "3"
        assert env["LOCAL_RANK"] == "1"
        assert env["WORLD_SIZE"] == "4"
        assert env["COORDINATOR_ADDRESS"] == "10.0.0.1:29500"
        assert env["PROCESS_ID"] == "3"


class _Args:
    def __init__(self, **kw):
        self.hostfile = kw.get("hostfile", "/job/hostfile")
        self.master_addr = kw.get("master_addr", "worker-0")
        self.master_port = kw.get("master_port", 29500)
        self.include = kw.get("include", "")
        self.exclude = kw.get("exclude", "")
        self.num_nodes = kw.get("num_nodes", -1)
        self.user_script = kw.get("user_script", "train.py")
        self.user_args = kw.get("user_args", ["--foo", "bar"])
        self.launcher_args = ""


class TestMultinodeRunners:

    RESOURCES = {"worker-0": [0, 1], "worker-1": [0, 1]}

    def test_pdsh_cmd(self):
        runner = PDSHRunner(_Args(), "WORLDINFO")
        runner.add_export("JAX_FOO", "1")
        env = {}
        cmd = runner.get_cmd(env, self.RESOURCES)
        assert cmd[0] == "pdsh"
        assert "worker-0,worker-1" in cmd
        assert env["PDSH_RCMD_TYPE"] == "ssh"
        joined = " ".join(cmd)
        assert "--world_info=WORLDINFO" in joined
        assert "deepspeed_tpu.launcher.launch" in joined
        assert "export JAX_FOO=1" in joined
        assert "train.py" in cmd and "--foo" in cmd

    def test_openmpi_cmd(self):
        runner = OpenMPIRunner(_Args(), "WORLDINFO")
        runner.add_export("DS_X", "y")
        cmd = runner.get_cmd({}, self.RESOURCES)
        assert cmd[:3] == ["mpirun", "-n", "4"]
        assert "-x" in cmd and "DS_X=y" in cmd
        assert cmd[-4:] == ["-u", "train.py", "--foo", "bar"]

    def test_mpich_cmd(self):
        runner = MPICHRunner(_Args(), "WORLDINFO")
        cmd = runner.get_cmd({}, self.RESOURCES)
        assert cmd[:5] == ["mpirun", "-n", "4", "-ppn", "2"]

    def test_slurm_cmd(self):
        runner = SlurmRunner(_Args(num_nodes=2), "WORLDINFO")
        runner.add_export("A", "b")
        cmd = runner.get_cmd({}, self.RESOURCES)
        assert cmd[:3] == ["srun", "-n", "4"]
        assert "--nodes" in cmd
        assert "--export" in cmd
        export_val = cmd[cmd.index("--export") + 1]
        assert export_val.startswith("ALL,") and "A=b" in export_val
        assert "MASTER_ADDR=worker-0" in export_val  # coordinator rides along

    def test_mvapich_cmd(self, tmp_path, monkeypatch):
        monkeypatch.setattr(MVAPICHRunner, "HOSTFILE", str(tmp_path / "hosts"))
        runner = MVAPICHRunner(_Args(), "WORLDINFO")
        cmd = runner.get_cmd({}, self.RESOURCES)
        assert cmd[:5] == ["mpirun", "-np", "4", "-ppn", "2"]
        assert "-env" in cmd and "MV2_SUPPORT_DL=1" in cmd
        assert "MASTER_ADDR=worker-0" in cmd
        hosts = (tmp_path / "hosts").read_text().split()
        assert hosts == ["worker-0", "worker-1"]
        assert cmd[-4:] == ["-u", "train.py", "--foo", "bar"]

    def test_mvapich_rejects_uneven_nodes(self, tmp_path, monkeypatch):
        monkeypatch.setattr(MVAPICHRunner, "HOSTFILE", str(tmp_path / "hosts"))
        runner = MVAPICHRunner(_Args(), "WORLDINFO")
        with pytest.raises(ValueError, match="same number"):
            runner.get_cmd({}, {"worker-0": [0, 1], "worker-1": [0]})

    def test_impi_cmd(self):
        runner = IMPIRunner(_Args(), "WORLDINFO")
        cmd = runner.get_cmd({}, self.RESOURCES)
        assert cmd[:5] == ["mpirun", "-ppn", "2", "-n", "4"]
        assert "-hosts" in cmd and "worker-0,worker-1" in cmd
        assert "-genv" in cmd and "MASTER_PORT" in cmd
        assert cmd[-4:] == ["-u", "train.py", "--foo", "bar"]


class TestLocalLaunch:
    """Real 2-process spawn (the reference's DistributedTest analogue for the
    launcher itself)."""

    @pytest.mark.slow
    def test_two_process_launch(self, tmp_path):
        script = tmp_path / "worker.py"
        script.write_text(
            "import os, json, sys\n"
            "out = {k: os.environ[k] for k in ('RANK','LOCAL_RANK','WORLD_SIZE','MASTER_ADDR')}\n"
            "open(os.path.join(os.path.dirname(__file__), f'out_{os.environ[\"RANK\"]}.json'), 'w')"
            ".write(json.dumps(out))\n")
        info = ds_runner.encode_world_info({"localhost": [0, 1]})
        env = os.environ.copy()
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        # workers must not grab the TPU or spin up jax
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
             f"--world_info={info}", "--node_rank=0",
             "--master_addr=127.0.0.1", "--master_port=29511", str(script)],
            env=env, capture_output=True, timeout=120)
        assert proc.returncode == 0, proc.stderr.decode()
        for rank in (0, 1):
            data = json.loads((tmp_path / f"out_{rank}.json").read_text())
            assert data["WORLD_SIZE"] == "2"
            assert data["RANK"] == str(rank)

    @pytest.mark.nightly
    def test_failing_rank_kills_job(self, tmp_path):
        script = tmp_path / "worker.py"
        script.write_text(
            "import os, sys, time\n"
            "if os.environ['RANK'] == '1': sys.exit(3)\n"
            "time.sleep(30)\n")
        info = ds_runner.encode_world_info({"localhost": [0, 1]})
        env = os.environ.copy()
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
             f"--world_info={info}", "--node_rank=0",
             "--master_addr=127.0.0.1", "--master_port=29512", str(script)],
            env=env, capture_output=True, timeout=60)
        assert proc.returncode == 3


class TestElasticity:

    CONFIG = {
        "elasticity": {
            "enabled": True,
            "max_train_batch_size": 2000,
            "micro_batch_sizes": [2, 4, 6],
            "min_gpus": 1,
            "max_gpus": 10000,
            "version": 0.1,
        }
    }

    def test_candidates(self):
        # reference HCN scaling: every base lands on 24 (base * largest
        # HCN <= 32/base), collapsing to one maximally-divisible candidate
        c = get_candidate_batch_sizes([2, 4, 6], 32)
        assert c == [24]

    def test_valid_gpus(self):
        assert get_valid_gpus(24, [2, 4, 6], 1, 12) == [1, 2, 3, 4, 6, 12]

    def test_compute_plan(self):
        batch, valid = compute_elastic_config(self.CONFIG)
        assert batch <= 2000
        assert len(valid) > 0
        # every valid world size must evenly decompose the batch
        for g in valid[:20]:
            assert any(batch % (g * m) == 0 for m in [2, 4, 6])

    def test_world_size_resolution(self):
        batch, micro, gas = compute_elastic_config(self.CONFIG, world_size=4)
        assert batch == micro * gas * 4

    def test_incompatible_world_size(self):
        cfg = {"elasticity": {**self.CONFIG["elasticity"], "micro_batch_sizes": [2],
                              "max_train_batch_size": 4}}
        with pytest.raises(ElasticityIncompatibleWorldSize):
            compute_elastic_config(cfg, world_size=3)


class TestElasticPlannerReferenceParity:
    """Table-driven reproduction of the reference planner's outputs
    (deepspeed/elasticity/elasticity.py:25-80 HCN candidate enumeration +
    factor-based valid-GPU search; expected values from the reference's own
    tests/unit/elasticity/test_elastic.py)."""

    TEN_K = {"elasticity": {"enabled": True, "max_train_batch_size": 10000,
                            "micro_batch_sizes": [8, 12, 16, 17],
                            "min_gpus": 32, "max_gpus": 1500, "min_time": 20,
                            "version": 0.1}}

    def test_basic_10k(self):
        batch, valid = compute_elastic_config(self.TEN_K)
        assert batch == 9792
        assert len(valid) == 23
        for g in valid:
            assert batch % g == 0
            assert any((batch // g) % m == 0
                       for m in self.TEN_K["elasticity"]["micro_batch_sizes"])

    def test_world_size_micro_batch_selection(self):
        _, micro, _ = compute_elastic_config(self.TEN_K, world_size=64)
        assert micro == 17

    def test_incompatible_world_size_128(self):
        with pytest.raises(ElasticityIncompatibleWorldSize):
            compute_elastic_config(self.TEN_K, world_size=128)

    def test_proper_mbsz(self):
        cfg = {"elasticity": {**self.TEN_K["elasticity"],
                              "max_train_batch_size": 32,
                              "micro_batch_sizes": [1, 2, 3, 7],
                              "min_gpus": 1}}
        _, micro, _ = compute_elastic_config(cfg, world_size=7)
        assert micro == 3

    def test_hcn_candidates(self):
        # base 8 with max 10000: largest HCN <= 1250 is 840 -> 6720; etc.
        assert get_candidate_batch_sizes([8], 10000) == [6720]
        assert get_candidate_batch_sizes([8, 12, 16, 17], 10000) == \
            sorted({840 * 8, 720 * 12, 360 * 16, 360 * 17})


class TestDscliSsh:
    """``dscli ssh`` (reference bin/ds_ssh): pdsh broadcast over the
    hostfile's hosts."""

    def test_ssh_invokes_pdsh_with_hosts(self, tmp_path, monkeypatch):
        hf = tmp_path / "hostfile"
        hf.write_text("nodeA slots=4\nnodeB slots=4\n")
        fake = tmp_path / "pdsh"
        log = tmp_path / "pdsh.log"
        fake.write_text(f"#!/bin/sh\necho \"$@\" > {log}\n")
        fake.chmod(0o755)
        monkeypatch.setenv("PATH", f"{tmp_path}:{os.environ['PATH']}")

        from deepspeed_tpu.cli import _ssh
        rc = _ssh(["-f", str(hf), "hostname", "-f"])
        assert rc == 0
        assert log.read_text().strip() == "-w nodeA,nodeB hostname -f"

    def test_ssh_missing_hostfile(self, tmp_path, monkeypatch):
        fake = tmp_path / "pdsh"
        fake.write_text("#!/bin/sh\n")
        fake.chmod(0o755)
        monkeypatch.setenv("PATH", f"{tmp_path}:{os.environ['PATH']}")
        from deepspeed_tpu.cli import _ssh
        with pytest.raises(RuntimeError, match="hostfile"):
            _ssh(["-f", str(tmp_path / "nope"), "true"])


@pytest.mark.slow
def test_bin_scripts_run_from_checkout(tmp_path):
    """bin/dscli and bin/ds_report work straight from a checkout with no
    install and no PYTHONPATH (they bootstrap the repo root)."""
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               DS_ACCELERATOR="cpu")
    env.pop("PYTHONPATH", None)
    for args, marker in ((["bin/ds_report"], "device count"),
                         (["bin/dscli", "report"], "device count")):
        r = subprocess.run([sys.executable] + [os.path.join(_repo_root(), a)
                                               for a in args[:1]] + args[1:],
                           env=env, cwd=str(tmp_path), capture_output=True,
                           text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
        assert marker in r.stdout


def _repo_root():
    return os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
