"""Randomized configuration sweep of the streaming-attention core.

The core is the most intricate hand-written math in the repo (custom VJP,
padding, GQA, positions); this fuzz harness compares forward AND all
gradients against dense AD across random shapes/feature combinations.
A small subset runs in the default tier; the full sweep is nightly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.sequence._streaming import chunked_attention


def _dense(q, k, v, mask, slopes, causal, qpos0, kpos0):
    rep = q.shape[2] // k.shape[2]
    kk = jnp.repeat(k, rep, axis=2).astype(jnp.float32)
    vv = jnp.repeat(v, rep, axis=2).astype(jnp.float32)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), kk) * scale
    qpos = qpos0 + jnp.arange(q.shape[1])[:, None]
    kpos = kpos0 + jnp.arange(k.shape[1])[None, :]
    if slopes is not None:
        logits = logits + slopes[None, :, None, None] * \
            (kpos - qpos).astype(jnp.float32)[None, None]
    if causal:
        logits = jnp.where((qpos >= kpos)[None, None], logits, -1e9)
    if mask is not None:
        logits = logits + mask[:, None, None, :]
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    p = jnp.exp(logits - lse[..., None])
    return jnp.einsum("bhqk,bkhd->bqhd", p, vv)


def _one_case(seed: int):
    r = np.random.default_rng(seed)
    B = int(r.integers(1, 3))
    Sq = int(r.integers(1, 33))
    Sk = int(r.integers(Sq, 64))          # causal needs kpos range >= qpos
    KV = int(r.choice([1, 2, 4]))
    H = KV * int(r.choice([1, 2, 3]))
    Hd = int(r.choice([8, 16, 32]))
    chunk = int(r.choice([4, 8, 16, 1024]))
    causal = bool(r.integers(0, 2))
    qpos0 = int(r.integers(0, Sk - Sq + 1)) if causal else int(r.integers(0, 8))

    q = jnp.asarray(r.normal(size=(B, Sq, H, Hd)), jnp.float32)
    k = jnp.asarray(r.normal(size=(B, Sk, KV, Hd)), jnp.float32)
    v = jnp.asarray(r.normal(size=(B, Sk, KV, Hd)), jnp.float32)
    mask = (jnp.asarray(r.normal(size=(B, Sk)) * 0.2, jnp.float32)
            if r.integers(0, 2) else None)
    slopes = (jnp.asarray(r.uniform(0.05, 0.4, size=H), jnp.float32)
              if r.integers(0, 2) else None)

    out, _ = chunked_attention(q, k, v, mask, slopes, jnp.int32(qpos0),
                               jnp.int32(0), causal, chunk, jnp.float32)
    ref = _dense(q, k, v, mask, slopes, causal, qpos0, 0)
    fwd_err = float(jnp.abs(out - ref).max())
    assert fwd_err < 5e-5, (seed, B, Sq, Sk, H, KV, Hd, chunk, causal, fwd_err)

    def loss_c(q, k, v):
        o, _ = chunked_attention(q, k, v, mask, slopes, jnp.int32(qpos0),
                                 jnp.int32(0), causal, chunk, jnp.float32)
        return jnp.sum(jnp.tanh(o))

    def loss_d(q, k, v):
        return jnp.sum(jnp.tanh(_dense(q, k, v, mask, slopes, causal, qpos0, 0)))

    gc = jax.grad(loss_c, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gc, gd):
        gerr = float(jnp.abs(a - b).max())
        assert gerr < 5e-4, (seed, name, B, Sq, Sk, H, KV, Hd, chunk, causal, gerr)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(5))
def test_streaming_fuzz_smoke(seed):
    _one_case(seed)


@pytest.mark.nightly
@pytest.mark.parametrize("seed", range(5, 60))
def test_streaming_fuzz_nightly(seed):
    _one_case(seed)
