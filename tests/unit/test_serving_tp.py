"""Tensor-parallel paged serving: head-sharded KV pools and mesh-parallel
fused decode. Covers the ``auto_tp`` heuristics (column/row/embed/bias
spec emission, divisibility guards), THE acceptance pin — ``generate_batch``
under ``serving.tp=2`` and ``tp=4`` is greedy-token-identical to the tp=1
paged engine in every covered scenario (eviction pressure, prefix cache
on/off + re-hit, chunked prefill, speculation) on the forced 8-CPU-device
mesh — the shard_map'd Pallas paged-kernel path (interpret mode) against a
replicated einsum reference AND its dispatch from the sharded engine, the
``serving_sharded_steady`` compile-budget contract, and the ``serving/tp``
telemetry annotation."""

import importlib
import os
import sys

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
import deepspeed_tpu.comm as dist
from deepspeed_tpu.inference.auto_tp import (auto_tp_specs,
                                             validate_tp_specs)
from deepspeed_tpu.models import CausalLM
from deepspeed_tpu.models.transformer import TransformerConfig

_TOOLS = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                      "..", "..", "tools"))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)


@pytest.fixture(autouse=True)
def clean_mesh():
    dist.set_mesh(None)
    yield
    dist.set_mesh(None)


def tiny_model(**over):
    base = dict(vocab_size=64, n_layer=2, n_head=4, d_model=32, d_ff=64,
                max_seq=64, remat=False)
    base.update(over)
    return CausalLM(TransformerConfig(**base))


def make_engine(model=None, tp=0, **srv):
    """A paged serving engine on a FRESH mesh (every engine pins its own
    mesh per serve via ``_mesh_scope``, so mixed-tp engines coexist)."""
    dist.set_mesh(None)
    serving = {"block_size": 8, "max_running": 2}
    serving.update(srv)
    if tp:
        serving["tp"] = tp
    return deepspeed_tpu.init_inference(model or tiny_model(), dtype="fp32",
                                        serving=serving)


def _prompts(lens=(5, 11, 3, 8), vocab=64, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=n).astype(np.int32) for n in lens]


def _assert_same(outs_a, outs_b):
    assert len(outs_a) == len(outs_b)
    for a, b in zip(outs_a, outs_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------------------------------- #
# auto_tp: spec emission + divisibility guards


class TestAutoTP:

    def _gpt2_tree(self):
        """GPT-2-shaped param pytree: fused-qkv-free naming, c_fc/c_proj
        MLP, wte embedding — the AutoTP reference shapes."""
        z = np.zeros
        return {
            "wte": z((64, 16)),
            "h": {
                "attn": {"q_proj": {"w": z((16, 16)), "b": z((16,))},
                         "k_proj": {"w": z((16, 16)), "b": z((16,))},
                         "v_proj": {"w": z((16, 16)), "b": z((16,))},
                         "out_proj": {"w": z((16, 16)), "b": z((16,))}},
                "mlp": {"c_fc": {"w": z((16, 64)), "b": z((64,))},
                        "c_proj": {"w": z((64, 16)), "b": z((16,))}},
                "ln_1": {"scale": z((16,)), "bias": z((16,))},
            },
        }

    def test_column_row_embed_bias_emission(self):
        specs = auto_tp_specs(self._gpt2_tree())
        # column: qkv + c_fc shard the OUTPUT (last) dim; their biases too
        assert specs["h"]["attn"]["q_proj"]["w"] == P(None, "tp")
        assert specs["h"]["attn"]["q_proj"]["b"] == P("tp")
        assert specs["h"]["mlp"]["c_fc"]["w"] == P(None, "tp")
        assert specs["h"]["mlp"]["c_fc"]["b"] == P("tp")
        # row: out_proj + c_proj shard the INPUT dim; biases replicate
        # (added once, after the all-reduce)
        assert specs["h"]["attn"]["out_proj"]["w"] == P("tp", None)
        assert specs["h"]["attn"]["out_proj"]["b"] == P(None)
        assert specs["h"]["mlp"]["c_proj"]["w"] == P("tp", None)
        assert specs["h"]["mlp"]["c_proj"]["b"] == P(None)
        # embeddings vocab-shard dim 0; norms replicate
        assert specs["wte"] == P("tp", None)
        assert specs["h"]["ln_1"]["scale"] == P(None)

    def test_divisibility_guard_replicates_not_crashes(self):
        # 16-wide projections over tp=3: every pattern rule must fall back
        # to replication (with a warning), never emit a spec that crashes
        specs = auto_tp_specs(self._gpt2_tree(), tp=3)
        flat = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
        assert all(all(s is None for s in sp) for sp in flat), (
            "non-divisible dims must replicate under tp=3")
        # tp=2 divides everything: the full layout comes back
        specs2 = auto_tp_specs(self._gpt2_tree(), tp=2)
        assert specs2["h"]["attn"]["q_proj"]["w"] == P(None, "tp")

    def test_divisibility_guard_is_per_tensor(self):
        tree = {"q_proj": np.zeros((16, 12)), "w_down": np.zeros((10, 16))}
        specs = auto_tp_specs(tree, tp=4)
        assert specs["q_proj"] == P(None, "tp")       # 12 % 4 == 0
        assert specs["w_down"] == P(None, None)       # 10 % 4 != 0

    def test_validate_tp_specs_drops_nondividing(self, devices):
        from jax.sharding import Mesh
        mesh = Mesh(np.array(devices[:8]).reshape(2, 4), ("dp", "tp"))
        params = {"wq": np.zeros((8, 12)), "wo": np.zeros((10, 8))}
        specs = {"wq": P(None, "tp"), "wo": P("tp", None)}
        got = validate_tp_specs(params, specs, mesh)
        assert got["wq"] == P(None, "tp")     # 12 % 4 == 0: kept
        assert got["wo"] == P(None, None)     # 10 % 4 != 0: replicated


# --------------------------------------------------------------------- #
# config plumbing


class TestServingTPConfig:

    def test_serving_tp_builds_tp_mesh_and_shards(self):
        e = make_engine(tp=2)
        assert e.mesh.shape.get("tp") == 2
        wq = e.params["layers"]["attn"]["wq"]
        assert "tp" in [s for s in wq.sharding.spec if s is not None]
        pools, _ = e._paged_pools(9, 8)
        assert "tp" in [s for s in pools["k"].sharding.spec
                        if s is not None]

    def test_serving_tp_conflict_with_tensor_parallel_raises(self):
        dist.set_mesh(None)
        with pytest.raises(ValueError, match="serving.tp"):
            deepspeed_tpu.init_inference(
                tiny_model(), dtype="fp32",
                tensor_parallel={"tp_size": 4}, serving={"tp": 2})

    def test_tensor_parallel_alone_still_shards_serving(self):
        dist.set_mesh(None)
        e = deepspeed_tpu.init_inference(
            tiny_model(), dtype="fp32", tensor_parallel={"tp_size": 2},
            serving={"block_size": 8, "max_running": 2})
        assert e.mesh.shape.get("tp") == 2
        pools, _ = e._paged_pools(9, 8)
        assert "tp" in [s for s in pools["k"].sharding.spec
                        if s is not None]

    def test_serving_tp_honored_under_foreign_mesh(self):
        """Review regression: an engine configured serving.tp=2 while a
        FOREIGN global mesh (no tp axis — e.g. a training run's) is live
        must not silently adopt it and serve unsharded — it builds a
        private tp mesh, really shards, leaves the global mesh alone, and
        produces the tp=1 tokens."""
        prompts = _prompts((5, 9))
        want = make_engine().generate_batch(prompts, max_new_tokens=6)
        dist.init_mesh({"dp": -1})          # a training run's mesh, no tp
        foreign = dist.get_mesh()
        e = deepspeed_tpu.init_inference(
            tiny_model(), dtype="fp32",
            serving={"block_size": 8, "max_running": 2, "tp": 2})
        assert e.mesh.shape.get("tp") == 2, (
            "engine adopted the foreign mesh and dropped serving.tp")
        assert dist.get_mesh() is foreign, (
            "engine clobbered the global mesh")
        wq = e.params["layers"]["attn"]["wq"]
        assert "tp" in [s for s in wq.sharding.spec if s is not None]
        _assert_same(want, e.generate_batch(prompts, max_new_tokens=6))
        assert dist.get_mesh() is foreign   # _mesh_scope restored it

    def test_kv_heads_not_dividing_tp_replicates_pools(self):
        # kv_heads=3 over tp=2: params still shard where dims divide, but
        # the KV pools replicate (warning, never a crash) — and the engine
        # still serves (greedy determinism pinned; full tp-vs-tp1 identity
        # for the replicated-pool layout rides the tp2/tp4 pins above,
        # where the SAME einsum core runs on a replicated-KV operand)
        model_kw = dict(vocab_size=64, n_layer=2, n_head=6, n_kv_head=3,
                        d_model=48, d_ff=64, max_seq=64, remat=False)
        e = make_engine(model=CausalLM(TransformerConfig(**model_kw)), tp=2)
        pools, _ = e._paged_pools(9, 8)
        assert all(s is None for s in pools["k"].sharding.spec), (
            "kv_heads % tp != 0 must replicate the pools")
        wq = e.params["layers"]["attn"]["wq"]
        assert "tp" in [s for s in wq.sharding.spec if s is not None], (
            "params must still shard where their dims divide")
        out = e.generate_batch(_prompts((5,)), max_new_tokens=4)
        assert out[0].shape == (9,)
        _assert_same(out, e.generate_batch(_prompts((5,)), max_new_tokens=4))


# --------------------------------------------------------------------- #
# THE acceptance pin: sharded-vs-single-chip token identity


class TestShardedIdentity:

    def test_identity_tp2_and_tp4(self):
        prompts = _prompts()
        ref = make_engine().generate_batch(prompts, max_new_tokens=8)
        _assert_same(ref, make_engine(tp=2).generate_batch(
            prompts, max_new_tokens=8))
        _assert_same(ref, make_engine(tp=4).generate_batch(
            prompts, max_new_tokens=8))

    def test_identity_under_eviction_pressure(self):
        # 5 blocks of 8 for two ~20-token streams: preemption + recompute
        # under tp=2 must schedule AND decode exactly as at tp=1 (the
        # allocator is replicated host state — eviction is shard-invariant)
        prompts = _prompts((5, 11))
        ref = make_engine(max_num_blocks=5).generate_batch(
            prompts, max_new_tokens=10)
        got = make_engine(tp=2, max_num_blocks=5).generate_batch(
            prompts, max_new_tokens=10)
        _assert_same(ref, got)

    def test_identity_prefix_cache_rehit_across_serves(self):
        # shared system prefix + a SECOND serve of the same prompts: the
        # tp engine's content-addressed cache (replicated block ids over
        # head-sharded pool shards) must reproduce the tp=1 tokens on both
        # the cold and the fully-cached serve
        rng = np.random.default_rng(3)
        sysp = rng.integers(0, 64, size=24).astype(np.int32)
        prompts = [np.concatenate(
            [sysp, rng.integers(0, 64, size=k).astype(np.int32)])
            for k in (3, 6)]
        ref_e = make_engine(prefill_chunk_tokens=8)
        tp_e = make_engine(tp=2, prefill_chunk_tokens=8)
        for serve in range(2):
            ref = ref_e.generate_batch(prompts, max_new_tokens=6)
            got = tp_e.generate_batch(prompts, max_new_tokens=6)
            _assert_same(ref, got)
        # the second serve really re-hit the persisted allocator
        assert tp_e._paged_alloc is not None

    def test_identity_prefix_cache_off(self):
        prompts = _prompts((5, 9))
        ref = make_engine(prefix_caching="off").generate_batch(
            prompts, max_new_tokens=8)
        got = make_engine(tp=2, prefix_caching="off").generate_batch(
            prompts, max_new_tokens=8)
        _assert_same(ref, got)

    def test_identity_chunked_prefill(self):
        prompts = _prompts((26, 37), seed=5)
        ref = make_engine(prefill_chunk_tokens=8).generate_batch(
            prompts, max_new_tokens=6)
        got = make_engine(tp=2, prefill_chunk_tokens=8).generate_batch(
            prompts, max_new_tokens=6)
        _assert_same(ref, got)

    def test_identity_speculative(self):
        # repetitive prompts so the proposer fires: the fused verify step
        # under tp=2 (same sharded attention impl as decode) must accept
        # exactly the candidates the tp=1 verify accepts
        rng = np.random.default_rng(4)
        motif = rng.integers(0, 64, size=12).astype(np.int32)
        prompts = [np.tile(motif, 4)]
        spec = {"speculative": {"mode": "ngram", "k": 4}}
        ref = make_engine(**spec).generate_batch(prompts, max_new_tokens=12)
        tp_e = make_engine(tp=2, **spec)
        got = tp_e.generate_batch(prompts, max_new_tokens=12)
        _assert_same(ref, got)
        st = tp_e._last_serve_stats
        assert st["spec_accepted"] > 0, (
            f"scenario never speculated under tp: {st}")


# --------------------------------------------------------------------- #
# the shard_map'd Pallas kernel path (interpret mode on CPU)


def _einsum_reference(q, kp, vp, bt, pos, scale):
    """Replicated numpy softmax-attention reference through the block
    tables — independent of both the kernel and the jax einsum core."""
    B, H, Hd = q.shape
    bs, KV = kp.shape[1], kp.shape[2]
    G = H // KV
    out = np.zeros_like(q, dtype=np.float32)
    for b in range(B):
        k = kp[bt[b]].reshape(-1, KV, Hd).astype(np.float32)
        v = vp[bt[b]].reshape(-1, KV, Hd).astype(np.float32)
        S = k.shape[0]
        valid = np.arange(S) <= pos[b]
        for h in range(H):
            g = h // G
            s = (q[b, h].astype(np.float32) @ k[:, g].T) * scale
            s = np.where(valid, s, -1e30)
            p = np.exp(s - s.max())
            p = p / p.sum()
            out[b, h] = p @ v[:, g]
    return out


class TestShardedKernelPath:

    def test_shard_map_kernel_matches_einsum_reference(self, devices):
        """The shard_map'd paged kernel (interpret mode, heads split over
        tp=2) against the replicated einsum reference on randomized block
        tables."""
        from jax.sharding import Mesh

        from deepspeed_tpu.models.transformer import _paged_decode_sharded

        mesh = Mesh(np.array(devices[:8]).reshape(4, 2), ("dp", "tp"))
        rng = np.random.default_rng(0)
        B, H, KV, Hd, bs, NB, nmax = 3, 4, 2, 64, 128, 7, 3
        q = rng.standard_normal((B, H, Hd)).astype(np.float32)
        kp = rng.standard_normal((NB, bs, KV, Hd)).astype(np.float32)
        vp = rng.standard_normal((NB, bs, KV, Hd)).astype(np.float32)
        bt = np.stack([rng.permutation(np.arange(1, NB))[:nmax]
                       for _ in range(B)]).astype(np.int32)
        pos = np.asarray([37, 200, 129], np.int32)
        scale = Hd ** -0.5

        dist.set_mesh(mesh)
        got = _paged_decode_sharded(q, kp, vp, bt, pos, None, None, mesh,
                                    scale=scale)
        assert got is not None, "sharded kernel path refused a legal shape"
        want = _einsum_reference(q, kp, vp, bt, pos, scale)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5,
                                   rtol=1e-5)

    def test_shard_ok_rejects_off_envelope(self, devices):
        from jax.sharding import Mesh

        from deepspeed_tpu.models.transformer import _paged_shard_ok

        mesh = Mesh(np.array(devices[:8]).reshape(4, 2), ("dp", "tp"))
        assert _paged_shard_ok(mesh, 4, 2, 64, 128)
        assert not _paged_shard_ok(mesh, 4, 3, 64, 128)   # KV % tp
        assert not _paged_shard_ok(mesh, 5, 2, 64, 128)   # H % tp
        assert not _paged_shard_ok(mesh, 4, 2, 32, 128)   # Hd % 64
        assert not _paged_shard_ok(mesh, 4, 2, 64, 64)    # bs % 128

    def test_engine_decodes_through_sharded_kernel(self, monkeypatch):
        """THE acceptance pin for the kernel path: a tp=2 engine with a
        kernel-envelope model (Hd=64, block_size=128, backend='flash')
        dispatches the Pallas paged kernel (counted at trace time,
        interpret mode on CPU) instead of the SPMD einsum fallback — and
        its greedy tokens match the tp=1 einsum-path engine exactly."""
        pda = importlib.import_module(
            "deepspeed_tpu.ops.pallas.paged_decode_attention")
        calls = {"n": 0}
        orig = pda.paged_decode_attention

        def counting(*a, **k):
            calls["n"] += 1
            return orig(*a, **k)

        monkeypatch.setattr(pda, "paged_decode_attention", counting)

        kw = dict(vocab_size=64, n_layer=1, n_head=4, n_kv_head=2,
                  d_model=256, d_ff=128, max_seq=256, remat=False)
        m_ref = CausalLM(TransformerConfig(**kw, attention_backend="auto"))
        params = m_ref.init_params(jax.random.key(0))
        prompts = _prompts((9, 14), seed=1)

        dist.set_mesh(None)
        ref_e = deepspeed_tpu.init_inference(
            m_ref, params=params, dtype="fp32",
            serving={"block_size": 128, "max_running": 2})
        ref = ref_e.generate_batch(prompts, max_new_tokens=6)
        assert calls["n"] == 0, "einsum reference engine touched the kernel"

        dist.set_mesh(None)
        m_tp = CausalLM(TransformerConfig(**kw, attention_backend="flash"))
        tp_e = deepspeed_tpu.init_inference(
            m_tp, params=params, dtype="fp32",
            serving={"block_size": 128, "max_running": 2, "tp": 2})
        got = tp_e.generate_batch(prompts, max_new_tokens=6)
        assert calls["n"] > 0, (
            "tp=2 decode fell back to the SPMD einsum path instead of the "
            "shard_map'd paged kernel")
        _assert_same(ref, got)


# --------------------------------------------------------------------- #
# compile-budget contract: serving_sharded_steady


class TestShardedSteadyContract:

    @pytest.fixture(autouse=True)
    def clean_state(self):
        from deepspeed_tpu.monitor.metrics import get_registry
        from deepspeed_tpu.monitor.trace import get_compile_watchdog
        dist.set_mesh(None)
        get_registry().reset()
        get_registry().set_enabled(True)
        get_compile_watchdog().reset()
        yield
        dist.set_mesh(None)
        get_registry().reset()
        get_registry().set_enabled(True)
        get_compile_watchdog().reset()

    def test_serving_sharded_steady_contract(self):
        """Sharding must not multiply programs: one generate_batch under
        serving.tp=2 with prefix caching AND speculation on compiles each
        fused entry exactly as often as its tp=1 budget — paged decode and
        verify ONCE — verified through the CompileWatchdog."""
        from dslint.contracts import check_compile_budgets

        dist.set_mesh(None)
        engine = deepspeed_tpu.init_inference(
            tiny_model(), dtype="fp32", telemetry=True,
            serving={"block_size": 8, "max_running": 2, "tp": 2,
                     "speculative": {"mode": "ngram", "k": 4}})
        rng = np.random.default_rng(0)
        motif = rng.integers(0, 64, size=10).astype(np.int32)
        prompts = [np.tile(motif, 3),
                   rng.integers(0, 64, size=7).astype(np.int32),
                   rng.integers(0, 64, size=12).astype(np.int32)]
        engine.generate_batch(prompts, max_new_tokens=10)
        st = engine._last_serve_stats
        assert st["verify_steps"] >= 1, "scenario never speculated"
        by_fn = engine.telemetry_snapshot()["compile"]["by_fn"]
        assert by_fn.get("inference.paged_decode", 0) <= 1, (
            "fused decode recompiled under tp — sharding multiplied "
            "programs")
        violations = check_compile_budgets(by_fn, "serving_sharded_steady",
                                           strict=True)
        assert violations == [], "\n".join(violations)


# --------------------------------------------------------------------- #
# telemetry: global KV gauges annotated with the tp degree


class TestTpTelemetry:

    @pytest.fixture(autouse=True)
    def clean_registry(self):
        from deepspeed_tpu.monitor.metrics import get_registry
        get_registry().reset()
        get_registry().set_enabled(True)
        yield
        get_registry().reset()
        get_registry().set_enabled(True)

    def test_kv_gauges_global_with_tp_annotation(self):
        from deepspeed_tpu.monitor.health import (health_summary,
                                                  render_summary_table)
        dist.set_mesh(None)
        engine = deepspeed_tpu.init_inference(
            tiny_model(), dtype="fp32", telemetry=True,
            serving={"block_size": 8, "max_running": 2, "tp": 2,
                     "max_num_blocks": 9})
        engine.generate_batch(_prompts((5, 9)), max_new_tokens=4)
        snap = engine.telemetry_snapshot()
        g = snap["gauges"]
        assert g.get("serving/tp") == 2.0
        # block counts are GLOBAL per slice (allocator is replicated):
        # a 9-block pool reports 9-block capacity numbers, not 9 / tp
        assert g.get("serving/kv_blocks_free", -1) + \
            g.get("serving/kv_blocks_used", -1) >= 0
        assert g["serving/kv_blocks_free"] <= 8   # 9 minus dummy, global
        summary = health_summary(snap)
        assert summary["serving"]["tp"] == 2.0
        table = render_summary_table(summary)
        assert "[tp=2]" in table, table

    def test_no_tp_annotation_at_tp1(self):
        from deepspeed_tpu.monitor.health import (health_summary,
                                                  render_summary_table)
        dist.set_mesh(None)
        engine = deepspeed_tpu.init_inference(
            tiny_model(), dtype="fp32", telemetry=True,
            serving={"block_size": 8, "max_running": 2})
        engine.generate_batch(_prompts((5,)), max_new_tokens=3)
        table = render_summary_table(
            health_summary(engine.telemetry_snapshot()))
        assert "[tp=" not in table
