"""Toy models for unit tests (mirrors reference tests/unit/simple_model.py)."""

import jax
import jax.numpy as jnp
import numpy as np


class SimpleModel:
    """Two-layer MLP regression model; loss = MSE.

    Batch: dict(x=[B, dim], y=[B, dim]).
    """

    def __init__(self, hidden_dim: int = 16, nlayers: int = 2):
        self.hidden_dim = hidden_dim
        self.nlayers = nlayers

    def init_params(self, rng):
        keys = jax.random.split(rng, self.nlayers)
        params = {}
        for i, k in enumerate(keys):
            params[f"layer_{i}"] = {
                "w": jax.random.normal(k, (self.hidden_dim, self.hidden_dim), jnp.float32) * 0.1,
                "b": jnp.zeros((self.hidden_dim,), jnp.float32),
            }
        return params

    def forward(self, params, x):
        h = x
        for i in range(self.nlayers):
            layer = params[f"layer_{i}"]
            h = h @ layer["w"] + layer["b"]
            if i < self.nlayers - 1:
                h = jax.nn.relu(h)
        return h

    def loss(self, params, batch):
        pred = self.forward(params, batch["x"])
        return jnp.mean((pred - batch["y"])**2)


def _w_true(hidden_dim: int):
    # one fixed ground-truth mapping shared by every batch/seed
    rng = np.random.default_rng(1234)
    return rng.normal(size=(hidden_dim, hidden_dim)).astype(np.float32) * 0.3


def random_dataset(n_samples: int, hidden_dim: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n_samples, hidden_dim)).astype(np.float32)
    y = x @ _w_true(hidden_dim)
    return [{"x": x[i], "y": y[i]} for i in range(n_samples)]


def random_batch(batch_size: int, hidden_dim: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(batch_size, hidden_dim)).astype(np.float32)
    return {"x": x, "y": x @ _w_true(hidden_dim)}
