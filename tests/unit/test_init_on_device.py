"""OnDevice meta/dtype init context (reference utils/init_on_device.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import CausalLM, gpt2, llama
from deepspeed_tpu.models.bert import BertConfig, BertModel
from deepspeed_tpu.models.moe_lm import MoEConfig, MoECausalLM
from deepspeed_tpu.models.pipeline import PipelinedCausalLM
from deepspeed_tpu.models.transformer import TransformerConfig


def _leaves(tree):
    return jax.tree.leaves(tree)


def test_meta_init_allocates_nothing():
    model = gpt2("125m")  # 124M params: would be ~500 MB f32 if materialised
    with deepspeed_tpu.OnDevice(device="meta"):
        params = model.init_params(jax.random.key(0))
    assert all(isinstance(l, jax.ShapeDtypeStruct) for l in _leaves(params))
    # shapes match the real init exactly
    real_shapes = jax.eval_shape(
        lambda r: model.init_params(r), jax.random.key(0))
    assert jax.tree.map(lambda a: a.shape, params) == \
           jax.tree.map(lambda a: a.shape, real_shapes)


def test_meta_init_dtype_override():
    model = llama("tiny", n_layer=2, d_model=64, n_head=4, d_ff=128,
                  vocab_size=128, max_seq=32)
    with deepspeed_tpu.OnDevice(dtype=jnp.bfloat16, device="meta"):
        params = model.init_params(jax.random.key(0))
    assert all(l.dtype == jnp.bfloat16 for l in _leaves(params))


def test_device_init_with_dtype_cast():
    model = CausalLM(TransformerConfig(vocab_size=64, n_layer=1, n_head=2,
                                       d_model=16, max_seq=16))
    with deepspeed_tpu.OnDevice(dtype=jnp.bfloat16):
        params = model.init_params(jax.random.key(0))
    leaves = _leaves(params)
    assert all(hasattr(l, "addressable_shards") or hasattr(l, "device")
               or isinstance(l, jax.Array) for l in leaves)  # real arrays
    assert all(l.dtype == jnp.bfloat16 for l in leaves)


def test_outside_context_is_untouched():
    model = CausalLM(TransformerConfig(vocab_size=64, n_layer=1, n_head=2,
                                       d_model=16, max_seq=16))
    params = model.init_params(jax.random.key(0))
    assert all(isinstance(l, jax.Array) for l in _leaves(params))
    assert _leaves(params)[0].dtype == jnp.float32


@pytest.mark.parametrize("build", [
    lambda: PipelinedCausalLM(TransformerConfig(vocab_size=64, n_layer=2,
                                                n_head=2, d_model=16,
                                                max_seq=16), 2),
    lambda: BertModel(BertConfig(vocab_size=64, max_seq=16, n_layer=1,
                                 n_head=2, d_model=16, d_ff=32)),
    lambda: MoECausalLM(TransformerConfig(vocab_size=64, n_layer=2, n_head=2,
                                          d_model=16, max_seq=16),
                        MoEConfig(num_experts=2)),
])
def test_meta_init_every_family(build):
    model = build()
    with deepspeed_tpu.OnDevice(device="meta"):
        params = model.init_params(jax.random.key(0))
    assert all(isinstance(l, jax.ShapeDtypeStruct) for l in _leaves(params))


def test_invalid_device_rejected():
    with pytest.raises(ValueError, match="meta"):
        deepspeed_tpu.OnDevice(device="cuda:0")


def test_nested_disabled_context_is_noop():
    """OnDevice(enabled=False) must not cancel an active outer context
    (reference semantics: the patch simply isn't applied)."""
    model = CausalLM(TransformerConfig(vocab_size=64, n_layer=1, n_head=2,
                                       d_model=16, max_seq=16))
    with deepspeed_tpu.OnDevice(device="meta"):
        with deepspeed_tpu.OnDevice(dtype=jnp.float16, enabled=False):
            params = model.init_params(jax.random.key(0))
    assert all(isinstance(l, jax.ShapeDtypeStruct) for l in _leaves(params))
    assert _leaves(params)[0].dtype == jnp.float32  # disabled dtype ignored


def test_meta_covers_module_level_inits():
    """PipelineModule / fused layer / TiledLinear init_params honor the
    context too — not just the model zoo."""
    from deepspeed_tpu.ops.transformer.training_kernels import (
        DeepSpeedTransformerLayer, DeepSpeedTransformerConfig)
    from deepspeed_tpu.runtime.zero.tiling import TiledLinear

    layer = DeepSpeedTransformerLayer(DeepSpeedTransformerConfig(
        hidden_size=32, heads=4, intermediate_size=64, seq_length=16))
    tiled = TiledLinear(32, 32, in_splits=2, out_splits=2)
    with deepspeed_tpu.OnDevice(device="meta"):
        lp = layer.init_params(jax.random.key(0))
        tp = tiled.init_params(jax.random.key(0))
    assert all(isinstance(l, jax.ShapeDtypeStruct) for l in _leaves(lp))
    assert all(isinstance(l, jax.ShapeDtypeStruct) for l in _leaves(tp))


def test_meta_covers_moe_layer():
    from deepspeed_tpu.moe.layer import MoE
    moe = MoE(hidden_size=16, num_experts=2)
    with deepspeed_tpu.OnDevice(device="meta"):
        params = moe.init_params(jax.random.key(0))
    assert all(isinstance(l, jax.ShapeDtypeStruct) for l in _leaves(params))
