"""Flight recorder + request-lifecycle tracing + on-demand profiling:
ring-buffer invariants, the disabled-mode zero-overhead pin, the
deterministic event sequence of a pinned ``generate_batch`` (including
preemption and a prefix-cache hit), chrome-trace serving export validated
by ``tools/validate_trace.py``, events.jsonl in anomaly/emergency
bundles, the profiler capture window, and the new CLI surfaces
(``dscli trace --validate``, ``dscli profile``, ``dscli health --json``).
"""

import importlib.util
import json
import os
import threading
from collections import Counter
from pathlib import Path

import jax
import numpy as np
import pytest

import deepspeed_tpu
import deepspeed_tpu.comm as dist
from deepspeed_tpu.models import CausalLM
from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.monitor import events as events_mod
from deepspeed_tpu.monitor.events import (EVENT_KINDS, Event, FlightRecorder,
                                          get_flight_recorder,
                                          render_serving_trace)
from deepspeed_tpu.monitor.trace import ProfileWindow, StepTracer

_VT_PATH = Path(__file__).resolve().parents[2] / "tools" / "validate_trace.py"
_spec = importlib.util.spec_from_file_location("validate_trace", _VT_PATH)
validate_trace = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(validate_trace)


@pytest.fixture(autouse=True)
def clean_state():
    """Fresh mesh + fresh global registry/watchdog/recorder per test (the
    recorder is process-global: engines enable it in place)."""
    from deepspeed_tpu.monitor.metrics import get_registry
    from deepspeed_tpu.monitor.trace import get_compile_watchdog

    def _reset():
        dist.set_mesh(None)
        get_registry().reset()
        get_registry().set_enabled(True)
        get_compile_watchdog().reset()
        rec = get_flight_recorder()
        rec.disable()
        rec.clear()

    _reset()
    yield
    _reset()


def tiny_model(**over):
    base = dict(vocab_size=64, n_layer=2, n_head=4, d_model=32, d_ff=64,
                max_seq=64, remat=False)
    base.update(over)
    return CausalLM(TransformerConfig(**base))


def serving_engine(**serving):
    base = {"block_size": 8, "max_running": 2}
    base.update(serving)
    return deepspeed_tpu.init_inference(
        tiny_model(), dtype="fp32",
        telemetry={"enabled": True, "events": True}, serving=base)


def train_engine(telemetry=None):
    dist.set_mesh(None)
    model = tiny_model(max_seq=32, n_head=2, attention_backend="xla")
    params = model.init_params(jax.random.key(0))
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "mesh": {"dp": -1},            # all 8 virtual CPU devices
        "steps_per_print": 0,
    }
    if telemetry is not None:
        config["telemetry"] = telemetry
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=config)
    rng = np.random.default_rng(0)
    rows = engine.train_micro_batch_size_per_gpu() * \
        engine.gradient_accumulation_steps() * \
        dist.get_world_size(dist.data_parallel_axes(engine.mesh))

    def batch():
        return {"input_ids": rng.integers(0, 64, size=(rows, 32))
                .astype(np.int32)}

    return engine, batch


# --------------------------------------------------------------------- #
# the recorder itself


class TestFlightRecorder:

    def test_ring_bound_and_drop_counter(self):
        r = FlightRecorder(capacity=4, enabled=True)
        for i in range(7):
            r.emit("req.enqueue", rid=i, prompt_tokens=1, max_new=1)
        assert len(r) == 4 and r.dropped == 3
        # a flight recorder keeps the TAIL (newest events survive)
        assert [e.rid for e in r.snapshot()] == [3, 4, 5, 6]
        r.clear()
        assert len(r) == 0 and r.dropped == 0

    def test_typed_kinds_rejected(self):
        r = FlightRecorder(enabled=True)
        with pytest.raises(ValueError, match="unknown event kind"):
            r.emit("req.not_a_kind")
        assert "req.admit" in EVENT_KINDS

    def test_disabled_emit_is_flag_check_no_allocation(self, monkeypatch):
        r = FlightRecorder(enabled=False)

        def boom(*a, **k):
            raise AssertionError("Event allocated in disabled mode")

        # patch the module-global name emit() resolves (patching
        # Event.__new__ itself can't be restored cleanly)
        monkeypatch.setattr(events_mod, "Event", boom)
        for _ in range(100):
            r.emit("req.admit", rid=0, cached_tokens=0)
        assert len(r) == 0 and r.dropped == 0

    def test_monotonic_timestamps_and_explicit_start(self):
        r = FlightRecorder(enabled=True)
        r.emit("serve.begin", requests=1)
        r.emit("serve.end", t_ns=123, dur_ns=45, requests=1)
        a, b = r.snapshot()
        assert a.ts_ns > 0 and b.ts_ns == 123 and b.dur_ns == 45
        assert b.to_dict() == {"ts_ns": 123, "kind": "serve.end",
                               "dur_ns": 45, "requests": 1}

    def test_thread_safety_under_concurrent_emit(self):
        r = FlightRecorder(capacity=256, enabled=True)

        def work(tid):
            for i in range(500):
                r.emit("req.enqueue", rid=tid * 1000 + i,
                       prompt_tokens=1, max_new=1)

        threads = [threading.Thread(target=work, args=(t,)) for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(r) == 256
        assert r.dropped == 4 * 500 - 256

    def test_write_jsonl_roundtrip_validates(self, tmp_path):
        r = FlightRecorder(capacity=3, enabled=True)
        for i in range(5):
            r.emit("req.enqueue", rid=i, prompt_tokens=2, max_new=1)
        p = r.write_jsonl(str(tmp_path / "events.jsonl"))
        lines = Path(p).read_text().splitlines()
        # dropped header + 3 retained events
        assert json.loads(lines[0]) == {"ts_ns": json.loads(lines[0])["ts_ns"],
                                        "kind": "recorder.dropped", "count": 2}
        assert len(lines) == 4
        assert validate_trace.validate_path(p) == []

    def test_enable_resize_keeps_newest(self):
        r = FlightRecorder(capacity=8, enabled=True)
        for i in range(6):
            r.emit("req.enqueue", rid=i, prompt_tokens=1, max_new=1)
        r.enable(capacity=3)
        assert [e.rid for e in r.snapshot()] == [3, 4, 5]


# --------------------------------------------------------------------- #
# serving trace rendering (synthetic events — renderer unit coverage)


def _ev(kind, ts, **kw):
    data = {k: v for k, v in kw.items()
            if k not in ("rid", "step", "dur_ns")}
    return Event(ts_ns=ts, kind=kind, rid=kw.get("rid"),
                 step=kw.get("step"), dur_ns=kw.get("dur_ns"),
                 data=data or None)


class TestServingTraceRender:

    def test_one_span_per_request_even_when_preempted(self):
        evs = [
            _ev("req.enqueue", 100, rid=0, prompt_tokens=4),
            _ev("req.admit", 200, rid=0, cached_tokens=0, blocks=1),
            _ev("req.prefill", 300, rid=0, dur_ns=50, tokens=4),
            _ev("req.preempt", 400, rid=0, blocks=1, recompute_tokens=5),
            _ev("req.admit", 500, rid=0, cached_tokens=0, blocks=2),
            _ev("decode.tick", 600, dur_ns=40, rids=[0], n=1),
            _ev("req.retire", 700, rid=0, generated=3, preemptions=1),
        ]
        doc = render_serving_trace(evs)
        spans = [e for e in doc["traceEvents"] if e.get("cat") == "request"]
        assert len(spans) == 1
        span = spans[0]
        # first admission -> retire, preemption folded into args
        assert span["ts"] == pytest.approx(0.1) \
            and span["dur"] == pytest.approx(0.5)
        assert span["args"]["preemptions"] == 1
        names = Counter(e["name"] for e in doc["traceEvents"]
                        if e["ph"] == "X" and e.get("cat") != "request")
        assert names["prefill"] == 1 and names["decode"] == 1
        assert validate_trace.validate_chrome_trace(doc) == []

    def test_counter_tracks_and_incomplete_requests(self):
        evs = [
            _ev("req.admit", 10, rid=7, cached_tokens=0, blocks=1),
            _ev("sched.gauge", 20, queued=2, running=1, kv_used=3, kv_free=4),
            _ev("decode.tick", 30, dur_ns=5, rids=[7], n=1),
        ]
        doc = render_serving_trace(evs)
        counters = {e["name"]: e["args"] for e in doc["traceEvents"]
                    if e["ph"] == "C"}
        assert counters["queue_depth"] == {"queued": 2, "running": 1}
        assert counters["kv_blocks"] == {"used": 3, "free": 4}
        span = next(e for e in doc["traceEvents"]
                    if e.get("cat") == "request")
        assert span["args"]["incomplete"] is True
        assert validate_trace.validate_chrome_trace(doc) == []

    def test_empty_events_render_empty_doc(self):
        doc = render_serving_trace([])
        assert doc["traceEvents"] == []
        assert validate_trace.validate_chrome_trace(doc) == []


# --------------------------------------------------------------------- #
# the schema validator (negatives: drift must not pass silently)


class TestValidator:

    def test_chrome_negatives(self):
        bad_ph = {"traceEvents": [{"ph": "Z", "name": "x", "ts": 0}]}
        assert any("unknown ph" in e
                   for e in validate_trace.validate_chrome_trace(bad_ph))
        bad_counter = {"traceEvents": [
            {"ph": "C", "name": "q", "ts": 0, "pid": 1, "tid": 0,
             "args": {"v": "high"}}]}
        assert any("counter args" in e
                   for e in validate_trace.validate_chrome_trace(bad_counter))
        two_spans = {"traceEvents": [
            {"ph": "X", "cat": "request", "name": "request 0", "ts": 0,
             "dur": 10, "pid": 1, "tid": 0},
            {"ph": "X", "cat": "request", "name": "request 0b", "ts": 20,
             "dur": 10, "pid": 1, "tid": 0}]}
        assert any("request spans" in e
                   for e in validate_trace.validate_chrome_trace(two_spans))
        outside = {"traceEvents": [
            {"ph": "X", "cat": "request", "name": "request 0", "ts": 100,
             "dur": 10, "pid": 1, "tid": 0},
            {"ph": "X", "name": "decode", "ts": 500, "dur": 10,
             "pid": 1, "tid": 0}]}
        assert any("outside its request span" in e
                   for e in validate_trace.validate_chrome_trace(outside))
        assert validate_trace.validate_chrome_trace([]) \
            == ["top level must be an object with a 'traceEvents' list"]

    def test_events_jsonl_negatives(self):
        bad_kind = [json.dumps({"ts_ns": 1, "kind": "req.bogus"})]
        assert any("unknown kind" in e
                   for e in validate_trace.validate_events_jsonl(bad_kind))
        bad_ts = [json.dumps({"ts_ns": "soon", "kind": "req.admit"})]
        assert any("ts_ns" in e
                   for e in validate_trace.validate_events_jsonl(bad_ts))
        assert validate_trace.validate_events_jsonl([]) \
            == ["no events (empty file)"]
        ok = [json.dumps({"ts_ns": 5, "kind": "req.admit", "rid": 1})]
        assert validate_trace.validate_events_jsonl(ok) == []

    def test_auto_sniff(self, tmp_path):
        chrome = tmp_path / "t.json"
        chrome.write_text(json.dumps({"traceEvents": []}))
        assert validate_trace.validate_path(str(chrome)) == []
        jsonl = tmp_path / "e.jsonl"
        jsonl.write_text(json.dumps({"ts_ns": 1, "kind": "req.admit"}) + "\n")
        assert validate_trace.validate_path(str(jsonl)) == []


# --------------------------------------------------------------------- #
# serving events end-to-end (the tentpole acceptance pins)


class TestServingEvents:

    def test_deterministic_sequence_with_preemption_and_cache_hit(self):
        # 5 blocks of 8 for two streams that outgrow them: deterministic
        # preemption; the victim's re-admission probes the cache and HITS
        # its own still-cold blocks (prefix caching is auto-on)
        engine = serving_engine(max_num_blocks=5)
        prompts = [np.arange(1, 6, dtype=np.int32),
                   np.arange(10, 21, dtype=np.int32)]
        outs = engine.generate_batch(prompts, max_new_tokens=10)
        evs = get_flight_recorder().snapshot()
        kinds = Counter(e.kind for e in evs)
        assert kinds["serve.begin"] == 1 and kinds["serve.end"] == 1
        assert kinds["req.enqueue"] == 2 and kinds["req.retire"] == 2
        assert kinds["req.preempt"] >= 1
        hits = [e for e in evs if e.kind == "req.cache_hit"]
        assert hits and all(e.data["tokens"] > 0 for e in hits)
        # per-request lifecycle: ONE enqueue and ONE retire per rid;
        # admits == 1 + that rid's preemptions; events in causal order
        for rid in (0, 1):
            seq = [e.kind for e in evs if e.rid == rid]
            assert seq[0] == "req.enqueue" and seq[-1] == "req.retire"
            assert seq.count("req.enqueue") == 1
            assert seq.count("req.retire") == 1
            assert seq.count("req.admit") == 1 + seq.count("req.preempt")
        # decode ticks carry the fused rid set
        ticks = [e for e in evs if e.kind == "decode.tick"]
        assert ticks and all(set(e.data["rids"]) <= {0, 1} for e in ticks)
        # the traced run still produces the exact greedy tokens
        for p, o in zip(prompts, outs):
            ref = engine.generate(p[None, :], max_new_tokens=10)
            np.testing.assert_array_equal(np.asarray(o), np.asarray(ref)[0])

    def test_export_serving_trace_validates(self, tmp_path):
        # THE acceptance pin: chrome-trace export with exactly one
        # admission->retire span per request (incl. the preempted one),
        # child slices for every prefill chunk / decode tick / COW copy,
        # and queue-depth + KV-block counter tracks — all validated by
        # tools/validate_trace.py
        engine = serving_engine(max_num_blocks=5)
        prompts = [np.arange(1, 6, dtype=np.int32),
                   np.arange(10, 21, dtype=np.int32),
                   np.arange(30, 33, dtype=np.int32)]
        engine.generate_batch(prompts, max_new_tokens=10)
        path = str(tmp_path / "serving.json")
        assert engine.export_serving_trace(path) == path
        assert validate_trace.validate_path(path) == []
        doc = json.loads(Path(path).read_text())
        spans = [e for e in doc["traceEvents"] if e.get("cat") == "request"]
        assert sorted(e["tid"] for e in spans) == [0, 1, 2]
        evs = get_flight_recorder().snapshot()
        child_names = Counter(e["name"] for e in doc["traceEvents"]
                              if e["ph"] == "X" and e.get("cat") == "serving")
        # every recorded compute event has its child slice (decode ticks
        # fan out to one slice per fused rid)
        n_prefill = sum(1 for e in evs if e.kind == "req.prefill")
        n_chunk = sum(1 for e in evs if e.kind == "req.prefill_chunk")
        n_cow = sum(1 for e in evs if e.kind == "req.cow_copy")
        n_decode = sum(len(e.data["rids"]) for e in evs
                       if e.kind == "decode.tick")
        assert child_names.get("prefill", 0) == n_prefill
        assert child_names.get("prefill_chunk", 0) == n_chunk
        assert child_names.get("cow_copy", 0) == n_cow
        assert child_names.get("decode", 0) == n_decode
        assert n_decode > 0
        counters = {e["name"] for e in doc["traceEvents"] if e["ph"] == "C"}
        assert counters == {"queue_depth", "kv_blocks"}
        # rids stay unique across generate_batch calls: a second serve
        # adds three MORE request tracks instead of colliding with 0-2
        engine.generate_batch(prompts, max_new_tokens=4)
        engine.export_serving_trace(path)
        doc = json.loads(Path(path).read_text())
        spans = [e for e in doc["traceEvents"] if e.get("cat") == "request"]
        assert sorted(e["tid"] for e in spans) == [0, 1, 2, 3, 4, 5]
        assert validate_trace.validate_path(path) == []

    def test_disabled_mode_allocates_nothing(self, monkeypatch):
        # events off (telemetry on): the scheduler/engine hot paths gate
        # at one None check — pinned by making Event allocation explode
        engine = deepspeed_tpu.init_inference(
            tiny_model(), dtype="fp32", telemetry=True,
            serving={"block_size": 8, "max_running": 2})
        assert engine._events is None

        def boom(*a, **k):
            raise AssertionError("Event allocated with events disabled")

        monkeypatch.setattr(events_mod, "Event", boom)
        prompts = [np.arange(1, 6, dtype=np.int32),
                   np.arange(10, 21, dtype=np.int32)]
        outs = engine.generate_batch(prompts, max_new_tokens=6)
        assert len(outs) == 2
        assert len(get_flight_recorder()) == 0
        with pytest.raises(ValueError, match="telemetry.events"):
            engine.export_serving_trace("/tmp/nope.json")

    def test_full_prefix_rehit_emits_cow_and_chunk(self, tmp_path):
        # a fully-cached re-served prompt: COW split + exactly one tail
        # chunk ride the event stream and the exported trace
        engine = serving_engine()
        prompt = np.arange(16, dtype=np.int32)      # 2 full blocks
        engine.generate_batch([prompt], max_new_tokens=4)
        get_flight_recorder().clear()
        engine.generate_batch([prompt], max_new_tokens=4)
        evs = get_flight_recorder().snapshot()
        kinds = Counter(e.kind for e in evs)
        assert kinds["req.cache_hit"] == 1
        assert kinds["req.cow_copy"] == 1
        assert kinds["req.prefill_chunk"] == 1
        assert kinds["req.prefill"] == 0
        hit = next(e for e in evs if e.kind == "req.cache_hit")
        assert hit.data["tokens"] == 15             # target - 1
        path = engine.export_serving_trace(str(tmp_path / "rehit.json"))
        assert validate_trace.validate_path(path) == []


# --------------------------------------------------------------------- #
# training + checkpoint events, bundles


class TestTrainingEvents:

    def test_train_step_and_ckpt_phase_events(self, tmp_path):
        engine, batch = train_engine({"enabled": True, "events": True})
        for _ in range(3):
            float(engine.train_batch(batch()))
        engine.save_checkpoint(str(tmp_path / "ckpt"), asynchronous=False)
        evs = get_flight_recorder().snapshot()
        kinds = Counter(e.kind for e in evs)
        assert kinds["train.step"] == 3
        assert kinds["ckpt.snapshot"] == 1
        assert kinds["ckpt.serialize"] == 1
        assert kinds["ckpt.commit"] == 1
        steps = [e.step for e in evs if e.kind == "train.step"]
        assert steps == [1, 2, 3]
        commit = next(e for e in evs if e.kind == "ckpt.commit")
        assert commit.data["bytes"] > 0 and commit.data["tag"]
        engine.destroy()

    def test_ckpt_retry_event_on_transient_fault(self, tmp_path):
        from deepspeed_tpu.utils import fault_injection
        engine, batch = train_engine({"enabled": True, "events": True})
        float(engine.train_batch(batch()))
        engine._config.checkpoint_config.retry_backoff_s = 0.0
        inj = fault_injection.FaultInjector()
        inj.fail_writes(errno_code=28, path_substr="state.npz", count=1)
        with fault_injection.inject(inj):
            engine.save_checkpoint(str(tmp_path / "ckpt"),
                                   asynchronous=False)
        retries = [e for e in get_flight_recorder().snapshot()
                   if e.kind == "ckpt.retry"]
        assert len(retries) == 1
        assert retries[0].data["attempt"] == 1
        assert "28" in retries[0].data["error"] \
            or "space" in retries[0].data["error"].lower()
        engine.destroy()

    def test_emergency_save_ships_events_jsonl(self, tmp_path):
        engine, batch = train_engine({"enabled": True, "events": True})
        float(engine.train_batch(batch()))
        save_dir = str(tmp_path / "emergency")
        engine.emergency_save(save_dir)
        p = os.path.join(save_dir, "events.jsonl")
        assert os.path.isfile(p)
        assert validate_trace.validate_path(p) == []
        kinds = [json.loads(line)["kind"]
                 for line in Path(p).read_text().splitlines()]
        assert "train.step" in kinds and "ckpt.snapshot" in kinds
        engine.destroy()

    def test_events_off_training_hot_path_allocates_nothing(
            self, monkeypatch):
        engine, batch = train_engine({"enabled": True})   # events off
        assert engine._tel_events is None

        def boom(*a, **k):
            raise AssertionError("Event allocated with events disabled")

        monkeypatch.setattr(events_mod, "Event", boom)
        float(engine.train_batch(batch()))
        assert len(get_flight_recorder()) == 0
        engine.destroy()

    def test_anomaly_bundle_contains_events_jsonl(self, tmp_path):
        from deepspeed_tpu.monitor.config import HealthConfig
        from deepspeed_tpu.monitor.health import HealthMonitor, StepHealth
        from deepspeed_tpu.monitor.metrics import MetricsRegistry
        rec = get_flight_recorder()
        rec.enable()
        rec.emit("train.step", step=1, dur_ns=1000)
        cfg = HealthConfig(enabled=True, action="dump",
                           dump_dir=str(tmp_path / "dumps"))
        mon = HealthMonitor(cfg, registry=MetricsRegistry())
        fired = mon.observe_step(StepHealth(step=1, loss=float("nan")))
        assert "nonfinite" in fired
        bundles = list((tmp_path / "dumps").iterdir())
        assert len(bundles) == 1
        p = bundles[0] / "events.jsonl"
        assert p.is_file()
        assert validate_trace.validate_path(str(p)) == []


# --------------------------------------------------------------------- #
# on-demand device profiling


class TestProfileWindow:

    def _patch_profiler(self, monkeypatch):
        calls = []
        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda d, **k: calls.append(("start", d)))
        monkeypatch.setattr(jax.profiler, "stop_trace",
                            lambda: calls.append(("stop",)))
        return calls

    def test_window_arms_starts_and_stops(self, monkeypatch):
        calls = self._patch_profiler(monkeypatch)
        w = ProfileWindow("/tmp/prof_a")
        w.tick()                       # nothing armed: no-op
        w.arm(2, log_dir="/tmp/prof_b")
        for _ in range(4):
            w.tick()
        assert calls == [("start", "/tmp/prof_b"), ("stop",)]
        assert w.captures == 1 and not w.active
        with pytest.raises(ValueError, match=">= 1"):
            w.arm(0)

    def test_config_armed_window_with_start_step(self, monkeypatch):
        calls = self._patch_profiler(monkeypatch)
        w = ProfileWindow("/tmp/prof_c", start_step=2, num_steps=1)
        w.tick(); w.tick()             # steps 0, 1: before the window
        assert calls == []
        w.tick()                       # step 2: start
        assert calls == [("start", "/tmp/prof_c")] and w.active
        w.tick()                       # step 3: window over -> stop
        assert calls[-1] == ("stop",)

    def test_engine_profile_arms_via_train_batch(self, monkeypatch):
        calls = self._patch_profiler(monkeypatch)
        engine, batch = train_engine()           # telemetry OFF: still works
        assert engine._profiler is None
        engine.profile(steps=2, log_dir="/tmp/prof_d")
        for _ in range(4):
            float(engine.train_batch(batch()))
        assert calls == [("start", "/tmp/prof_d"), ("stop",)]
        engine.destroy()

    def test_config_profile_block_builds_window(self):
        engine, _ = train_engine({"enabled": True,
                                  "profile": {"start_step": 1,
                                              "num_steps": 2,
                                              "dir": "/tmp/prof_e"}})
        assert engine._profiler is not None
        assert engine._profiler._armed == {"start": 1, "steps": 2,
                                           "dir": "/tmp/prof_e"}
        engine.destroy()

    def test_destroy_stops_dangling_capture(self, monkeypatch):
        calls = self._patch_profiler(monkeypatch)
        engine, batch = train_engine()
        engine.profile(steps=100)
        float(engine.train_batch(batch()))       # start, never finishes
        assert calls[-1][0] == "start"
        engine.destroy()
        assert calls[-1] == ("stop",)


# --------------------------------------------------------------------- #
# CLI surfaces


class TestCli:

    def test_dscli_trace_validate(self, tmp_path, capsys):
        from deepspeed_tpu.cli import _trace
        good = tmp_path / "good.json"
        good.write_text(json.dumps({"traceEvents": [
            {"ph": "X", "name": "s", "ts": 0, "dur": 1,
             "pid": 0, "tid": 0}]}))
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"traceEvents": [{"ph": "Z"}]}))
        assert _trace(["--validate", str(good)]) == 0
        assert _trace(["--validate", str(good), str(bad)]) == 1
        out = capsys.readouterr().out
        assert "OK" in out and "unknown ph" in out

    def test_dscli_profile_chrome_summary(self, tmp_path, capsys):
        from deepspeed_tpu.cli import _profile
        trace = tmp_path / "t.json"
        trace.write_text(json.dumps({"traceEvents": [
            {"ph": "X", "name": "train_batch", "ts": 0, "dur": 2000,
             "pid": 0, "tid": 0},
            {"ph": "X", "name": "train_batch", "ts": 3000, "dur": 1000,
             "pid": 0, "tid": 0},
            {"ph": "X", "name": "fwd", "ts": 0, "dur": 500,
             "pid": 0, "tid": 0}]}))
        assert _profile([str(trace)]) == 0
        out = capsys.readouterr().out
        assert "train_batch" in out and "2 " in out

    def test_dscli_profile_logdir_inventory(self, tmp_path, capsys):
        from deepspeed_tpu.cli import _profile
        run = tmp_path / "plugins" / "profile" / "2026_08_03_12_00_00"
        run.mkdir(parents=True)
        (run / "host0.xplane.pb").write_bytes(b"\0" * 128)
        assert _profile([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "1 profiler run(s)" in out and "host0.xplane.pb" in out
        empty = tmp_path / "empty"
        empty.mkdir()
        assert _profile([str(empty)]) == 1

    def test_dscli_health_json(self, tmp_path, capsys):
        from deepspeed_tpu.monitor.health import health_cli
        sink = tmp_path / "telemetry.jsonl"
        rec = {"ts": 1000.0, "step": 7,
               "counters": {"train/steps": 7,
                            'health/anomalies{type="loss_spike"}': 2},
               "gauges": {"train/loss": 3.5, "train/mfu": 0.4,
                          "mem/host_rss_bytes": 1024},
               "histograms": {"train/step_time_ms":
                              {"count": 7, "mean": 100.0, "p50": 99.0,
                               "p99": 120.0}}}
        prev = {"ts": 990.0, "step": 5, "counters": {"train/steps": 5},
                "gauges": {}, "histograms": {}}
        sink.write_text(json.dumps(prev) + "\n" + json.dumps(rec) + "\n")
        assert health_cli(["--json", str(sink)]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["step"] == 7
        assert out["train"]["steps"] == 7 and out["train"]["mfu"] == 0.4
        assert out["train"]["steps_per_sec"] == pytest.approx(0.2)
        assert out["loss"]["loss"] == 3.5
        assert out["anomalies"] == {"loss_spike": 2}
        assert out["memory"]["host_rss_bytes"] == 1024
        assert out["snapshot"]["step"] == 7
        # missing sink: machine-readable error, rc 1
        assert health_cli(["--json", str(tmp_path / "nope.jsonl")]) == 1
        assert "error" in json.loads(capsys.readouterr().out)


# --------------------------------------------------------------------- #
# StepTracer metadata + bench skip records (satellites)


class TestStepTracerMetadata:

    def test_export_names_pid_and_tid_tracks(self, tmp_path):
        tracer = StepTracer(use_accelerator=False)
        with tracer.span("fwd"):
            pass
        path = tracer.export_chrome_trace(str(tmp_path / "host.json"))
        assert validate_trace.validate_path(path) == []
        doc = json.loads(Path(path).read_text())
        metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        procs = [e for e in metas if e["name"] == "process_name"]
        threads = [e for e in metas if e["name"] == "thread_name"]
        assert procs[0]["args"]["name"] == "deepspeed_tpu host"
        assert threads and threads[0]["args"]["name"] == "MainThread"
        span = next(e for e in doc["traceEvents"] if e["ph"] == "X")
        assert span["tid"] == threads[0]["tid"]


class TestBenchSkipRecords:

    def test_skip_records_carry_stage_and_error_text(self, capsys):
        import bench
        err = {"stage": "backend_init_timeout",
               "summary": "device backend did not initialize within 240s",
               "error": "TimeoutExpired: Command '...' timed out\n"
                        "RuntimeError: relay unreachable"}
        bench._emit_skip_records(err)
        lines = capsys.readouterr().out.strip().splitlines()
        assert len(lines) == len(bench._enabled_metrics())
        for line in lines:
            rec = json.loads(line)
            assert rec["skipped"] is True
            assert rec["skip_stage"] == "backend_init_timeout"
            assert "relay unreachable" in rec["skip_error"]
            assert "did not initialize" in rec["unit"]

    def test_legacy_string_error_still_works(self, capsys):
        import bench
        bench._emit_skip_records("boom\ndetail")
        rec = json.loads(capsys.readouterr().out.strip().splitlines()[0])
        assert rec["skip_stage"] == "backend_probe"
        assert rec["unit"].endswith("(skipped: boom)")
