"""Optimizer micro-benchmarks (reference ``tests/perf/adam_test.py``).

Run directly (not collected by pytest):

    python tests/perf/perf_optimizers.py [--n 25000000]

Times the native C++ cpu_adam against a numpy reference on host, and the
fused Pallas Adam against the optax chain on the current jax backend.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def bench_cpu_adam(n: int, iters: int = 10):
    from deepspeed_tpu.ops import native
    from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
    if not native.available():
        print("cpu_adam: native library unavailable, skipped")
        return
    rng = np.random.default_rng(0)
    p = rng.normal(size=n).astype(np.float32)
    g = rng.normal(size=n).astype(np.float32)
    opt = DeepSpeedCPUAdam(lr=1e-3)
    opt.register("p0", n)
    opt.step("p0", p, g)  # warmup
    t0 = time.perf_counter()
    for _ in range(iters):
        opt.step("p0", p, g)
    dt = (time.perf_counter() - t0) / iters
    print(f"cpu_adam (C++ SIMD): {n/1e6:.0f}M params, {dt*1e3:.1f} ms/step, "
          f"{n/dt/1e9:.2f} Gparam/s")


def bench_fused_adam(n: int, iters: int = 10):
    import jax
    import jax.numpy as jnp
    import optax

    from deepspeed_tpu.ops.adam.fused_adam_kernel import fused_adam_step

    key = jax.random.key(0)
    p = jax.random.normal(key, (n,), jnp.float32)
    g = jax.random.normal(key, (n,), jnp.float32)
    m = jnp.zeros(n, jnp.float32)
    v = jnp.zeros(n, jnp.float32)

    def run_fused():
        return fused_adam_step(p, g, m, v, step=2, lr=1e-3)

    tx = optax.adamw(1e-3)
    st = tx.init(p)

    @jax.jit
    def run_optax(p, g, st):
        u, st = tx.update(g, st, p)
        return optax.apply_updates(p, u), st

    for name, fn in (("fused pallas", lambda: run_fused()[0]),
                     ("optax chain", lambda: run_optax(p, g, st)[0])):
        fn().block_until_ready()
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        out.block_until_ready()
        dt = (time.perf_counter() - t0) / iters
        print(f"{name} ({jax.default_backend()}): {n/1e6:.0f}M params, "
              f"{dt*1e3:.2f} ms/step, {n/dt/1e9:.2f} Gparam/s")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=25_000_000)
    args = ap.parse_args()
    bench_cpu_adam(args.n)
    bench_fused_adam(args.n)
