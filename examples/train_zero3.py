"""Train a llama-style model with ZeRO-3 + bf16 on every available chip.

The condensed form of docs/tutorials/getting-started.md, runnable as-is:

    python examples/train_zero3.py [--steps 50] [--size tiny]

(On CPU for a quick look: JAX_PLATFORMS=cpu DS_ACCELERATOR=cpu
XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/train_zero3.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="tiny", help="llama preset size")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--micro-batch", type=int, default=1)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--save", default=None, help="checkpoint dir")
    args = ap.parse_args()

    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import llama

    model = llama(args.size, max_seq=args.seq, remat="dots", loss_chunk=args.seq)
    params = model.init_params(jax.random.key(0))

    engine, _, _, scheduler = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": args.micro_batch,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 3e-4, "weight_decay": 0.1}},
            "scheduler": {"type": "WarmupLR",
                          "params": {"warmup_max_lr": 3e-4,
                                     "warmup_num_steps": 10}},
            "zero_optimization": {"stage": 3},
            "bf16": {"enabled": True},
            "gradient_clipping": 1.0,
            "mesh": {"dp": -1},
        })

    vocab = model.config.vocab_size
    bs = engine.train_batch_size()
    rng = np.random.default_rng(0)
    for step in range(args.steps):
        batch = {"input_ids": rng.integers(0, vocab, (bs, args.seq)).astype(np.int32)}
        loss = engine.train_batch(batch)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {float(loss):.4f}  lr {engine.get_lr()[0]:.2e}")
    if args.save:
        engine.save_checkpoint(args.save, tag="final")
        print(f"saved checkpoint to {args.save}/final")


if __name__ == "__main__":
    main()
