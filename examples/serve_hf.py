"""Serve a HuggingFace checkpoint directory through init_inference.

    python examples/serve_hf.py /path/to/hf-checkpoint [--dtype bf16|int8]
        [--prompt-len 32] [--gen 32]

Works with any supported architecture (gpt2/llama/bloom/opt/gpt-neox/gptj/
gpt-neo for generation; bert/distilbert/clip-text serve hidden states or
MLM logits through engine.forward instead).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("checkpoint", help="HF checkpoint directory")
    ap.add_argument("--dtype", default="bf16")
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--gen", type=int, default=32)
    args = ap.parse_args()

    import numpy as np

    import deepspeed_tpu

    engine = deepspeed_tpu.init_inference(args.checkpoint, dtype=args.dtype)
    vocab = getattr(engine.module.config, "vocab_size", 50257)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, vocab, (1, args.prompt_len)).astype(np.int32)

    try:
        out = np.asarray(engine.generate(prompt, max_new_tokens=args.gen))
        print(f"generated {out.shape[1] - args.prompt_len} tokens; "
              f"last 8 ids: {out[0, -8:].tolist()}")
        return
    except ValueError as e:
        if "requires a causal LM" not in str(e):
            raise  # real error (length checks etc.), not an encoder family
    out = np.asarray(engine.forward(prompt))
    print(f"forward output shape {out.shape}, finite={np.isfinite(out).all()}")


if __name__ == "__main__":
    main()
