"""Pipeline-parallel training (1F1B) over a pp x dp mesh.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    DS_ACCELERATOR=cpu python examples/train_pipeline.py --pp 2 --steps 10

On a real pod slice, drop the env overrides and size the mesh to the
hardware (pp * dp must equal the device count).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--seq", type=int, default=128)
    args = ap.parse_args()

    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.pipeline import PipelinedCausalLM
    from deepspeed_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(vocab_size=1024, n_layer=args.layers, n_head=4,
                            d_model=128, max_seq=args.seq)
    model = PipelinedCausalLM(cfg, num_stages=args.pp)
    params = model.init_params(jax.random.key(0))

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 2,   # pipeline micro-batches
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "bf16": {"enabled": True},
            "mesh": {"pp": args.pp, "dp": -1},
        })

    bs = engine.train_batch_size()
    rng = np.random.default_rng(0)
    for step in range(args.steps):
        batch = {"input_ids": rng.integers(0, 1024, (bs, args.seq)).astype(np.int32)}
        loss = engine.train_batch(batch)
        print(f"step {step:3d}  loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
