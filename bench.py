"""Benchmark: causal-LM training throughput on one TPU chip.

Prints one JSON line per metric: {"metric", "value", "unit", "vs_baseline"}.

1. GPT-2 125M, MHA, ZeRO-1 — the historical bench config (every round).
2. A llama-style GQA model (rope/rmsnorm/swiglu, n_kv_head < n_head) under
   ZeRO-3 — the BASELINE.md north-star shape (Llama-7B ZeRO-3), sized to
   the largest that fits one chip, so the driver measures the GQA flash
   index maps and ZeRO-3 gather-on-use paths, not just the easy config.
   Disable with BENCH_LLAMA=0.

Baseline: the reference's single-GPU fused-kernel result — BERT-large at
>50% of V100 peak (docs/_posts/2020-05-28-fastest-bert-training.md, see
BASELINE.md). vs_baseline = achieved MFU / 0.50, i.e. >1.0 means this
framework exceeds the reference's best published hardware efficiency class.

Env knobs (defaults are the chip-measured fast path):
  BENCH_STEPS=10           timed steps per window (best of two windows)
  BENCH_GPT2/LLAMA=1       enable metric 1 / 2; BENCH_BERT=1 enables the
                           bert-large MLM metric (un-gated now that the
                           fused CE kernel removes the head bottleneck)
  BENCH_BATCH=64 BENCH_SEQ=1024            gpt2 metric shape
  BENCH_LLAMA_BATCH=4 BENCH_LLAMA_SEQ=2048 llama metric shape
  BENCH_BERT_BATCH=32 BENCH_BERT_SEQ=512   bert metric shape (bs48+ OOMs)
  BENCH_BERT_REMAT=none    bert-only remat (falls back to BENCH_REMAT;
                           measured fastest: none — fits at bs32)
  BENCH_BERT_SCAN=0        bert layer stacking (unrolled measured +12%)
  BENCH_BERT_GATHER=0.25   MLM masked-position gather budget (fraction of
                           B*S routed through the vocab head; 0 = full)
  BENCH_REMAT=dots         1/true/full | 0/false/none | dots | selective...
  BENCH_FUSED_CE=auto      vocab-head CE path: auto = fused logits-free
                           Pallas kernel on TPU, XLA loss_chunk streaming
                           elsewhere | on | off
  BENCH_LOSS_CHUNK=2048    vocab-head streaming chunk when the fused kernel
                           is off/unavailable (0 = off; the bert metric
                           defaults to 4096, its measured best)
  BENCH_ATTN=auto          auto | flash | xla
  BENCH_OPT=AdamW          AdamW | FusedAdam | ...
  BENCH_SCAN=0             gpt2 layer stacking (0 = unrolled, measured
                           ~12% faster); BENCH_LLAMA_SCAN=0 for metric 2
                           (unrolled measured 13.5% faster on-chip)
  BENCH_BLOCK_Q/K=0        flash kernel block override (0 = tuned default)
  BENCH_DECODE_DENSE/PAGED=1  serving decode metrics: the same mixed
                           prompt set through the static generate path vs
                           the paged continuous-batching generate_batch
                           (the paged record's vs_baseline = speedup over
                           dense); BENCH_DECODE_REQS=16 BENCH_DECODE_NEW=128
                           BENCH_DECODE_BLOCK=128 BENCH_DECODE_RUNNING=8
  BENCH_SERVE_PREFIX=1     shared-system-prompt TTFT probe: prefix caching
                           off vs on (vs_baseline = off/on TTFT ratio);
                           BENCH_SERVE_REQS=8 BENCH_SERVE_PREFIX_LEN=768
                           BENCH_SERVE_NEW=16
  BENCH_KV_TIER=1          tiered-KV re-hit probe: shared-prefix TTFT at
                           forced cache pressure, host spill on vs
                           destroy-on-reclaim (vs_baseline = off/on);
                           BENCH_KV_TIER_PREFIX_LEN=512
                           BENCH_KV_TIER_BLOCKS=24
  BENCH_SERVE_SPEC=1       speculative-decode probe: p50 TPOT on repetitive
                           motif prompts, serving.speculative off vs ngram
                           (vs_baseline = off/on p50 ratio; accepted
                           tokens/step in the telemetry blob);
                           BENCH_SERVE_SPEC_REQS=8 BENCH_SERVE_SPEC_K=4
                           BENCH_SERVE_SPEC_NEW=64 BENCH_SERVE_SPEC_MOTIF=48
  BENCH_SERVE_CHUNKED=1    decode-interference probe: p99 TPOT with long
                           prompts prefilling whole vs chunked
                           (vs_baseline = whole/chunked p99 ratio);
                           BENCH_SERVE_LONG_LEN=896 BENCH_SERVE_CHUNK=256
  BENCH_SERVE_TP=1         multi-chip tensor-parallel serving probe: paged
                           decode tokens/s at serving.tp=1 vs tp=N on the
                           same prompt set (vs_baseline = scaling
                           efficiency, (tpN/tp1)/N); skip record on a
                           single-device backend; BENCH_SERVE_TP_N=auto
                           BENCH_SERVE_TP_REQS=8 BENCH_SERVE_TP_NEW=64
  BENCH_SERVE_ASYNC=1      open-loop async serving probe: Poisson arrivals
                           through the always-on AsyncServingEngine, value
                           = GOODPUT (generated tokens/s from requests
                           whose own p99 TPOT met the target), vs_baseline
                           = goodput/throughput (SLO attainment, <= 1);
                           BENCH_SERVE_ASYNC_RATE=8 (req/s)
                           BENCH_SERVE_ASYNC_REQS=24
                           BENCH_SERVE_ASYNC_NEW=32
                           BENCH_SERVE_ASYNC_TPOT_MS=50 (p99 target)
  BENCH_SERVE_CHAOS=1      serving fault-tolerance probe: the Poisson
                           async run re-run under a seeded injection
                           schedule (one engine-fatal fault + scattered
                           per-request step faults), value = faulted-run
                           goodput, vs_baseline = GOODPUT RETENTION
                           (faulted/clean); restart/retry/quarantine
                           counters ride the telemetry blob;
                           BENCH_SERVE_CHAOS_RATE=8 (req/s)
                           BENCH_SERVE_CHAOS_REQS=16
                           BENCH_SERVE_CHAOS_NEW=32
  BENCH_SERVE_DP=1         replica scale-out probe: the same seeded Poisson
                           trace through one AsyncServingEngine (dp=1) and
                           through a two-replica ReplicaRouter with session
                           affinity (dp=2), value = dp=2 goodput,
                           vs_baseline = SCALING EFFICIENCY
                           ((goodput_dp2/goodput_dp1)/2, 1.0 = linear);
                           BENCH_SERVE_DP_RATE=8 (req/s)
                           BENCH_SERVE_DP_REQS=16 BENCH_SERVE_DP_NEW=32
  BENCH_CTL=1              adaptive-autopilot spike probe: one engine, the
                           same seeded Poisson trace with a mid-trace
                           arrival SPIKE, driven twice — controller OFF
                           (static config posture) then ON (the
                           monitor/controller.py SLO-burn autopilot,
                           dscli serve --adaptive); value = adaptive-run
                           goodput at the p99 TPOT target, vs_baseline =
                           adaptive/static goodput; per-run SLO breach /
                           shed / knob-action counts and the decision
                           ledger ride the telemetry blob;
                           BENCH_CTL_RATE=6 (req/s) BENCH_CTL_REQS=18
                           BENCH_CTL_NEW=32 BENCH_CTL_TPOT_MS=50
                           BENCH_CTL_SPIKE=6 (spike factor)
  BENCH_SKIP_PROBE=0       skip the subprocess backend probe
  BENCH_PROBE_RETRIES=1    probe retries before giving up on the backend
  BENCH_ALLOW_CPU=0        on probe failure, run a tiny CPU smoke metric
                           instead of just emitting the skip record

A failed backend probe is NOT an error exit: the bench emits one parseable
JSON skip record per enabled metric ({"metric": ..., "value": 0.0,
"skipped": true, ...}) and exits 0, so the bench trajectory always has a
machine-readable data point even on a TPU-less box.
"""

import json
import os
import subprocess
import sys
import time


def _probe_backend(timeout_s: int = 240):
    """Probe device init in a SUBPROCESS: a dead TPU relay hangs backend
    setup indefinitely inside C++ (uninterruptible in-process), which would
    hang the whole bench run. A bounded probe fails fast instead. Returns
    None on success, else a failure dict: ``{"stage", "summary", "error"}``
    — the init stage that failed and the actual exception text, so the
    skip records emitted from it are diagnosable from the JSON alone
    (ROADMAP r03-r05: relay failures surfaced only as ``parsed: null``)."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired as e:
        # an indefinite hang inside backend init is the r03-r05 relay-outage
        # signature (ports up, C++ init never returns) — tag the records so
        # the trajectory analyzer can bucket these rounds without regexing
        # the summary text
        return {"stage": "backend_init_timeout",
                "summary": f"device backend did not initialize within "
                           f"{timeout_s}s (hung init — TPU relay down?)",
                "error": str(e),
                "hint": "relay_down"}
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-15:]
        return {"stage": "backend_init_error",
                "summary": f"device backend init failed (rc={r.returncode}): "
                           + (tail[-1] if tail else "no stderr"),
                "error": "\n".join(tail),
                "returncode": r.returncode}
    return None


def _parse_remat(env: str):
    """BENCH_REMAT accepts 1/true/full/0/false/none or a policy name —
    shared by every bench builder."""
    return {"1": True, "true": True, "full": True,
            "0": False, "false": False, "none": False}.get(env.lower(), env)


def _reset_telemetry():
    """Fresh registry/watchdog per metric so each record's embedded
    telemetry blob describes THAT metric's run only. Must run before the
    engine is constructed (families created at init would be orphaned)."""
    from deepspeed_tpu.monitor.metrics import get_registry
    from deepspeed_tpu.monitor.trace import get_compile_watchdog
    get_compile_watchdog().reset()
    get_registry().reset()


def _bench_telemetry():
    """The train metrics' shared telemetry block: health on in "record"
    mode with device sentinels OFF — the host detectors (spike / stall /
    overflow) and anomaly counters ride along without perturbing the
    measured step (no in-step reductions beyond the grad-norm reuse
    telemetry records anyway). Fresh dict per call: the engine parses the
    raw config and a shared literal could alias across builders."""
    return {"enabled": True,
            "health": {"enabled": True, "sentinels": False,
                       "action": "record"}}


def _telemetry_blob(engine):
    """Compact telemetry summary for the result record: compile counts,
    MFU/step-time (training engines), serving histograms (decode bench)."""
    snap = engine.telemetry_snapshot() \
        if hasattr(engine, "telemetry_snapshot") else {}
    if not snap:
        return None
    blob = {"compile_counts": snap.get("compile", {}).get("by_fn", {})}
    g, h, c = (snap.get("gauges", {}), snap.get("histograms", {}),
               snap.get("counters", {}))
    for k in ("train/mfu", "train/tokens_per_sec",
              "train/achieved_tflops_per_chip", "train/data_stall_fraction",
              "serving/queue_depth", "serving/kv_block_utilization",
              "serving/kv_fragmentation", "serving/running",
              "serving/kv_host_blocks", "serving/kv_host_bytes"):
        if k in g:
            blob[k] = round(g[k], 6)
    for k in ("train/step_time_ms", "serving/ttft_ms", "serving/tpot_ms",
              "serving/queue_wait_ms",
              "checkpoint/save_ms", "checkpoint/snapshot_ms",
              "checkpoint/bytes"):
        if k in h:
            blob[k] = {kk: round(float(vv), 3) for kk, vv in h[k].items()}
    for k in ("serving/preemptions", "serving/recompute_tokens",
              "serving/prefill_steps", "serving/decode_steps",
              "serving/generated_tokens", "serving/spec_verify_steps",
              "serving/spec_proposed_tokens", "serving/spec_accepted_tokens",
              "serving/spec_rollbacks", "serving/rejected_requests",
              "serving/kv_spills", "serving/kv_fetch_hits",
              "serving/kv_fetch_tokens", "serving/kv_host_errors",
              "serving/engine_restarts", "serving/request_retries",
              "serving/timeouts", "serving/shed_requests",
              "checkpoint/saves",
              "checkpoint/failures"):
        if k in c:
            blob[k] = c[k]
    # request latency anatomy: per-phase p50/p99 (fleet-summed counts
    # keep the record compact — per-replica detail stays in /metrics)
    # and the wasted-token causes, so BENCH records carry TTFT anatomy
    from deepspeed_tpu.monitor.health import multilabel_series
    phases = {}
    for labels, v in multilabel_series(h, "serving/phase_ms"):
        p = labels.get("phase")
        if p is None or not (v or {}).get("count"):
            continue
        agg = phases.setdefault(p, {"count": 0, "p50": 0.0, "p99": 0.0})
        agg["count"] += int(v["count"])
        agg["p50"] = round(max(agg["p50"], float(v.get("p50", 0.0))), 3)
        agg["p99"] = round(max(agg["p99"], float(v.get("p99", 0.0))), 3)
    if phases:
        blob["serving/phase_ms"] = phases
    wasted = {}
    for labels, v in multilabel_series(c, "serving/wasted_tokens"):
        cause = labels.get("cause")
        if cause is not None and v:
            wasted[cause] = wasted.get(cause, 0) + int(v)
    if wasted:
        blob["serving/wasted_tokens"] = wasted
    # health summary: detector firings (zero-valued on a clean run)
    from deepspeed_tpu.monitor.health import labeled_series
    faults = {k: int(v)
              for k, v in labeled_series(c, "serving/step_faults").items()}
    if faults:
        blob["serving/step_faults"] = faults
    anoms = {k: int(v)
             for k, v in labeled_series(c, "health/anomalies").items()}
    if anoms:
        blob["health_anomalies"] = anoms
    # SLO burn-rate alerts + flight-recorder ring loss, when the plane ran
    slo_fired = {k: int(v)
                 for k, v in labeled_series(c, "slo/breaches").items() if v}
    if slo_fired:
        blob["slo_breaches"] = slo_fired
    if g.get("events/dropped"):
        blob["events/dropped"] = int(g["events/dropped"])
    # peak HBM straight from the accelerator — device truth, present even
    # when gauge sampling never ran (e.g. telemetry flush cadence 0)
    try:
        from deepspeed_tpu.accelerator import get_accelerator
        acc = get_accelerator()
        peaks = [acc.max_memory_allocated(i)
                 for i in range(acc.local_device_count())]
        if any(peaks):
            blob["peak_hbm_bytes"] = int(max(peaks))
    except Exception:
        pass
    return blob


def build_bench_engine():
    """The bench's env knobs → (engine, model, batch_fn, knobs dict). Shared
    with benchmarks/profile_bench.py so the profile always measures the
    exact configuration the bench reports."""
    _reset_telemetry()
    import jax
    import numpy as np

    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models import gpt2

    BATCH = int(os.environ.get("BENCH_BATCH", 64))  # bs64 ≈ +0.6% over bs32
    SEQ = int(os.environ.get("BENCH_SEQ", 1024))

    # Memory/speed knobs (see models/transformer.py): the default is the
    # tuned fast path — "dots" remat (save matmul outputs, recompute the
    # cheap elementwise parts; the packed flash kernel is fast enough to
    # recompute) + chunked cross-entropy (never materialises the
    # [B, S, vocab] fp32 logits) + unrolled layers.
    remat_env = os.environ.get("BENCH_REMAT", "dots")
    REMAT = _parse_remat(remat_env)
    LOSS_CHUNK = int(os.environ.get("BENCH_LOSS_CHUNK", 2048))
    FUSED_CE = os.environ.get("BENCH_FUSED_CE", "auto")
    ATTN = os.environ.get("BENCH_ATTN", "auto")
    SCAN = os.environ.get("BENCH_SCAN", "0") == "1"  # unrolled: XLA schedules
    # the 12 blocks better than a lax.scan (measured ~12% faster)
    model = gpt2("125m", remat=REMAT, loss_chunk=LOSS_CHUNK, attention_backend=ATTN,
                 scan_layers=SCAN, fused_cross_entropy=FUSED_CE)
    params = model.init_params(jax.random.key(0))

    dist.set_mesh(None)
    # BENCH_OPT=FusedAdam selects the Pallas fused single-pass optimizer
    OPT = os.environ.get("BENCH_OPT", "AdamW")
    config = {
        "train_micro_batch_size_per_gpu": BATCH,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": OPT, "params": {"lr": 6e-4, "weight_decay": 0.1}},
        "zero_optimization": {"stage": 1},
        "bf16": {"enabled": True},
        "mesh": {"dp": -1},
        "steps_per_print": 0,
        "telemetry": _bench_telemetry(),
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params, config=config)

    rng = np.random.default_rng(0)

    def batch_fn():
        return {"input_ids": rng.integers(0, 50257, size=(BATCH, SEQ)).astype(np.int32)}

    return engine, model, batch_fn, dict(BATCH=BATCH, SEQ=SEQ,
                                         remat_env=remat_env,
                                         LOSS_CHUNK=LOSS_CHUNK,
                                         FUSED_CE=FUSED_CE)


def build_llama_bench_engine():
    """Llama-style GQA + ZeRO-3 bench config (north-star shape, one chip).

    ~500M params: d_model 1536, 12 q heads over 4 kv heads (head_dim 128 —
    the flash kernel's native GQA envelope), swiglu/rmsnorm/rope, seq 2048.
    ZeRO-3 so the driver exercises parameter sharding + gather-on-use even
    at world size 1 (the sharding rules, master-param update, and donation
    paths are identical; only the collective extent changes)."""
    _reset_telemetry()
    import jax
    import numpy as np

    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models import llama

    BATCH = int(os.environ.get("BENCH_LLAMA_BATCH", 4))
    SEQ = int(os.environ.get("BENCH_LLAMA_SEQ", 2048))
    blk_q = int(os.environ.get("BENCH_BLOCK_Q", 0)) or None
    blk_k = int(os.environ.get("BENCH_BLOCK_K", 0)) or None
    model = llama("tiny", n_layer=16, n_head=12, n_kv_head=4, d_model=1536,
                  d_ff=4096, max_seq=SEQ,
                  remat=_parse_remat(os.environ.get("BENCH_REMAT", "dots")),
                  loss_chunk=int(os.environ.get("BENCH_LOSS_CHUNK", 2048)),
                  fused_cross_entropy=os.environ.get("BENCH_FUSED_CE", "auto"),
                  attention_backend=os.environ.get("BENCH_ATTN", "auto"),
                  scan_layers=os.environ.get("BENCH_LLAMA_SCAN", "0") == "1",
                  attn_block_q=blk_q, attn_block_k=blk_k)
    params = model.init_params(jax.random.key(0))

    dist.set_mesh(None)
    config = {
        "train_micro_batch_size_per_gpu": BATCH,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": os.environ.get("BENCH_OPT", "AdamW"),
                      "params": {"lr": 3e-4, "weight_decay": 0.1}},
        "zero_optimization": {"stage": 3},
        "bf16": {"enabled": True},
        "mesh": {"dp": -1},
        "steps_per_print": 0,
        "telemetry": _bench_telemetry(),
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params, config=config)

    rng = np.random.default_rng(0)

    def batch_fn():
        return {"input_ids": rng.integers(0, 32000, size=(BATCH, SEQ)).astype(np.int32)}

    return engine, model, batch_fn, dict(BATCH=BATCH, SEQ=SEQ)


def build_bert_bench_engine():
    """BERT-large MLM (the reference's headline fastest-BERT-training
    benchmark: 53 TFLOPS = >50% of V100 peak at seq 512,
    docs/_posts/2020-05-28-fastest-bert-training.md): 24L/1024d/16h,
    seq 512, ZeRO-2, bf16. On by default (BENCH_BERT=0 gates it) now that
    the fused logits-free CE kernel removes the vocab-head bottleneck the
    metric was gated on."""
    _reset_telemetry()
    import jax
    import numpy as np

    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models.bert import BertConfig, BertModel

    BATCH = int(os.environ.get("BENCH_BERT_BATCH", 32))
    SEQ = int(os.environ.get("BENCH_BERT_SEQ", 512))
    # chip-measured fastest knobs (bs32, no remat, 4096 CE chunks, unrolled
    # layers, 0.25 masked-gather budget): 48.3k tok/s = MFU 0.496 on v5e
    model = BertModel(BertConfig(vocab_size=30522, max_seq=SEQ, n_layer=24,
                                 n_head=16, d_model=1024, d_ff=4096,
                                 remat=_parse_remat(os.environ.get(
                                     "BENCH_BERT_REMAT",
                                     os.environ.get("BENCH_REMAT", "none"))),
                                 loss_chunk=int(os.environ.get("BENCH_LOSS_CHUNK", 4096)),
                                 fused_cross_entropy=os.environ.get("BENCH_FUSED_CE", "auto"),
                                 scan_layers=os.environ.get("BENCH_BERT_SCAN", "0") == "1",
                                 mlm_gather_budget=float(os.environ.get("BENCH_BERT_GATHER", "0.25"))),
                      with_mlm_head=True)
    params = model.init_params(jax.random.key(0))

    dist.set_mesh(None)
    config = {
        "train_micro_batch_size_per_gpu": BATCH,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": os.environ.get("BENCH_OPT", "AdamW"),
                      "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "zero_optimization": {"stage": 2},
        "bf16": {"enabled": True},
        "mesh": {"dp": -1},
        "steps_per_print": 0,
        "telemetry": _bench_telemetry(),
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params, config=config)

    rng = np.random.default_rng(0)

    def batch_fn():
        ids = rng.integers(0, 30522, size=(BATCH, SEQ)).astype(np.int32)
        labels = np.full_like(ids, -100)
        pos = rng.random((BATCH, SEQ)) < 0.15
        labels[pos] = ids[pos]
        ids[pos] = 103  # [MASK]
        return {"input_ids": ids, "labels": labels}

    return engine, model, batch_fn, dict(BATCH=BATCH, SEQ=SEQ)


def _run_metric(name, engine, model, batch, BATCH, SEQ, steps, extra_unit):
    import jax
    import time as _t

    float(engine.train_batch(batch()))  # warmup/compile; host fetch = sync
    # best of two timed windows: device throughput is stable but transient
    # host contention (another process, tunnel hiccup) can pollute a single
    # window; the max is the hardware's number
    dt = None
    for _ in range(2):
        t0 = _t.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(batch())
        loss_val = float(loss)  # chained state => this syncs every step
        w = _t.perf_counter() - t0
        dt = w if dt is None else min(dt, w)

    tokens_per_sec = BATCH * SEQ * steps / dt
    achieved_tflops = tokens_per_sec * model.flops_per_token(SEQ) / 1e12

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "unknown").lower()
    # one peak table for the whole system (accelerator device-kind map +
    # DS_PEAK_TFLOPS override — the same denominator the telemetry MFU
    # gauge uses); 197 keeps the historical default for unknown kinds
    from deepspeed_tpu.accelerator import get_accelerator
    peak = get_accelerator().peak_tflops() or 197.0
    mfu = achieved_tflops / peak

    rec = {
        "metric": name,
        "value": round(tokens_per_sec, 1),
        "unit": f"tokens/s (bf16, bs{BATCH}xseq{SEQ}, {extra_unit}, {kind}, "
                f"{achieved_tflops:.1f} TFLOPs, MFU {mfu:.3f}, loss {loss_val:.3f})",
        "vs_baseline": round(mfu / 0.50, 3),
    }
    tel = _telemetry_blob(engine)
    if tel:
        rec["telemetry"] = tel
    print(json.dumps(rec), flush=True)


# single registry: (env gate, default, metric name) — consumed by BOTH the
# run loop in main() and the probe-failure skip records, so the two can
# never drift apart on names or gate defaults
BENCH_METRICS = [
    ("BENCH_GPT2", "1", "gpt2_125m_train_tokens_per_sec_per_chip"),
    ("BENCH_LLAMA", "1", "llama_gqa_500m_zero3_train_tokens_per_sec_per_chip"),
    ("BENCH_BERT", "1", "bert_large_mlm_train_tokens_per_sec_per_chip"),
    ("BENCH_DECODE_DENSE", "1", "gpt2_decode_dense_tokens_per_sec_per_chip"),
    ("BENCH_DECODE_PAGED", "1", "gpt2_decode_paged_tokens_per_sec_per_chip"),
    ("BENCH_SERVE_PREFIX", "1", "gpt2_serving_prefix_cache_ttft_ms"),
    ("BENCH_KV_TIER", "1", "gpt2_serving_kv_tier_ttft_ms"),
    ("BENCH_SERVE_CHUNKED", "1", "gpt2_serving_chunked_prefill_tpot_p99_ms"),
    ("BENCH_SERVE_SPEC", "1", "gpt2_serving_spec_decode_tpot_ms"),
    ("BENCH_SERVE_ASYNC", "1", "gpt2_serving_async_goodput_tokens_per_sec"),
    ("BENCH_SERVE_CHAOS", "1", "gpt2_serving_chaos_goodput_tokens_per_sec"),
    ("BENCH_SERVE_DP", "1", "gpt2_serving_dp_goodput_tokens_per_sec"),
    ("BENCH_CTL", "1", "gpt2_serving_adaptive_goodput_tokens_per_sec"),
    ("BENCH_SERVE_TP", "1", "gpt2_serving_tp_tokens_per_sec"),
    ("BENCH_CKPT", "1", "gpt2_ckpt_async_stall_ms_per_step"),
]


def _metric_enabled(env: str) -> bool:
    default = next(d for e, d, _ in BENCH_METRICS if e == env)
    return os.environ.get(env, default) != "0"


def _metric_name(env: str) -> str:
    return next(n for e, _, n in BENCH_METRICS if e == env)


def _enabled_metrics():
    return [name for env, _, name in BENCH_METRICS if _metric_enabled(env)]


def run_decode_bench():
    """Serving decode throughput: the same mixed-length prompt set through
    the static per-request ``generate`` path (dense KV workspace) and the
    paged continuous-batching ``generate_batch`` path. The paged record's
    vs_baseline is its speedup over the dense record — the serving layer's
    trajectory number (BENCH is empty for inference before this)."""
    import time as _t

    import jax
    import numpy as np

    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models import gpt2

    dist.set_mesh(None)
    NREQ = int(os.environ.get("BENCH_DECODE_REQS", 16))
    MAX_NEW = int(os.environ.get("BENCH_DECODE_NEW", 128))
    BLOCK = int(os.environ.get("BENCH_DECODE_BLOCK", 128))
    RUNNING = int(os.environ.get("BENCH_DECODE_RUNNING", 8))
    model = gpt2("125m", remat=False,
                 attention_backend=os.environ.get("BENCH_ATTN", "auto"))
    _reset_telemetry()
    engine = deepspeed_tpu.init_inference(
        model, dtype="bf16", telemetry=True,
        serving={"block_size": BLOCK, "max_running": RUNNING,
                 # cache off: this metric tracks the PR-2 paged-decode
                 # trajectory — a warm-call cache hit skipping timed prefill
                 # would silently change what it measures (the prefix-cache
                 # win has its own BENCH_SERVE_PREFIX probe)
                 "prefix_caching": "off"})
    rng = np.random.default_rng(0)
    # mixed prompt lengths: the tail-convoy shape continuous batching wins on
    prompts = [rng.integers(0, 50257, size=int(n)).astype(np.int32)
               for n in rng.integers(32, 256, size=NREQ)]

    results = {}
    for gate, mode in (("BENCH_DECODE_DENSE", "off"),
                       ("BENCH_DECODE_PAGED", "auto")):
        if not _metric_enabled(gate):
            continue
        name = _metric_name(gate)
        # per-mode reset: the dense record's blob must not leak into the
        # paged one (warm-up compiles after the reset are part of that
        # mode's run and stay). Safe mid-engine: every telemetry handle on
        # the inference path re-resolves its registry family per use.
        _reset_telemetry()
        engine._config.serving.paged = mode
        # warm ONE prompt per 128-bucket present in the mix (the prefill
        # program compiles per bucket) with a max_new in the SAME 128-bucket
        # as the timed MAX_NEW (the dense decode loop's out buffer is keyed
        # by it) — an uncovered compile landing inside the timed window
        # would skew the metric
        buckets = {}
        for p in prompts:
            buckets.setdefault(-(-p.size // 128), p)
        # cheapest max_new in the SAME 128-bucket as MAX_NEW
        warm_new = 128 * ((MAX_NEW - 1) // 128) + 1
        warm = engine.generate_batch(list(buckets.values()),
                                     max_new_tokens=warm_new)
        jax.block_until_ready(warm)
        t0 = _t.perf_counter()
        outs = engine.generate_batch(prompts, max_new_tokens=MAX_NEW)
        gen_tokens = sum(int(o.shape[0]) - p.size
                         for p, o in zip(prompts, outs))
        dt = _t.perf_counter() - t0
        results[mode] = gen_tokens / dt
        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", "unknown").lower()
        vs = (round(results["auto"] / results["off"], 3)
              if mode == "auto" and results.get("off") else 0.0)
        rec = {
            "metric": name,
            "value": round(gen_tokens / dt, 1),
            "unit": f"generated tokens/s (bf16, {NREQ} reqs x {MAX_NEW} new, "
                    f"prompts 32-256, block={BLOCK}, running={RUNNING}, "
                    f"{kind})",
            "vs_baseline": vs,
        }
        tel = _telemetry_blob(engine)
        if tel:
            rec["telemetry"] = tel
        print(json.dumps(rec), flush=True)


def _serve_hist(engine, name, key):
    """One serving-histogram stat from the engine's telemetry snapshot."""
    h = engine.telemetry_snapshot().get("histograms", {}).get(name, {})
    return float(h.get(key, 0.0))


def run_prefix_cache_bench():
    """Shared-system-prompt serving probe: NREQ requests whose prompts all
    start with the same long prefix, prefix caching OFF vs ON. The ON
    record's value is its p50 TTFT and vs_baseline the OFF/ON TTFT ratio
    (>1 = caching cut time-to-first-token): request 1 prefills the shared
    blocks, every later admission hits them with zero prefill compute."""
    import numpy as np

    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models import gpt2

    dist.set_mesh(None)
    NREQ = int(os.environ.get("BENCH_SERVE_REQS", 8))
    SYS = int(os.environ.get("BENCH_SERVE_PREFIX_LEN", 768))
    TAIL, MAX_NEW = 32, int(os.environ.get("BENCH_SERVE_NEW", 16))
    model = gpt2("125m", remat=False,
                 attention_backend=os.environ.get("BENCH_ATTN", "auto"))
    rng = np.random.default_rng(0)
    system = rng.integers(0, 50257, size=SYS).astype(np.int32)
    prompts = [np.concatenate([system, rng.integers(0, 50257, size=TAIL)
                               .astype(np.int32)]) for _ in range(NREQ)]

    results = {}
    for mode in ("off", "auto"):
        _reset_telemetry()
        engine = deepspeed_tpu.init_inference(
            model, dtype="bf16", telemetry=True,
            serving={"block_size": 128, "max_running": 8,
                     "prefix_caching": mode})
        engine.generate_batch(prompts, max_new_tokens=MAX_NEW)   # warm:
        # compiles, and (ON mode) the steady-state populated cache
        _reset_telemetry()
        engine.generate_batch(prompts, max_new_tokens=MAX_NEW)
        results[mode] = _serve_hist(engine, "serving/ttft_ms", "p50")
        if mode == "auto":
            rec = {
                "metric": _metric_name("BENCH_SERVE_PREFIX"),
                "value": round(results["auto"], 2),
                "unit": f"p50 TTFT ms (bf16, {NREQ} reqs sharing a {SYS}-tok "
                        f"prefix +{TAIL} tail, prefix cache on; off = "
                        f"{results['off']:.1f} ms)",
                # >1 = prefix caching sped TTFT up by this factor
                "vs_baseline": (round(results["off"] / results["auto"], 3)
                                if results["auto"] else 0.0),
            }
            tel = _telemetry_blob(engine)
            if tel:
                rec["telemetry"] = tel
            print(json.dumps(rec), flush=True)


def run_kv_tier_bench():
    """Tiered-KV re-hit probe at FORCED cache pressure: NREQ requests
    share a long prefix, then a scratch burst floods the (deliberately
    small) device pool so the shared prefix's cold blocks are reclaimed
    before the requests return. With ``kv_host`` off, reclaim destroys —
    the re-hit re-prefills the whole prefix; on, reclaim demotes to host
    RAM and the re-hit re-materializes it H2D. Value = p50 re-hit TTFT
    with tiering ON, vs_baseline = OFF/ON (>1 = spilling beat
    destroy-on-reclaim)."""
    import numpy as np

    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models import gpt2

    dist.set_mesh(None)
    NREQ = int(os.environ.get("BENCH_SERVE_REQS", 4))
    SYS = int(os.environ.get("BENCH_KV_TIER_PREFIX_LEN", 512))
    TAIL, MAX_NEW = 32, int(os.environ.get("BENCH_SERVE_NEW", 8))
    POOL = int(os.environ.get("BENCH_KV_TIER_BLOCKS", 24))
    model = gpt2("125m", remat=False,
                 attention_backend=os.environ.get("BENCH_ATTN", "auto"))
    rng = np.random.default_rng(0)
    system = rng.integers(0, 50257, size=SYS).astype(np.int32)
    prompts = [np.concatenate([system, rng.integers(0, 50257, size=TAIL)
                               .astype(np.int32)]) for _ in range(NREQ)]
    # the pressure burst: enough cold-block churn to reclaim every shared
    # block between re-hits (the tier's whole reason to exist)
    scratch = [rng.integers(0, 50257, size=SYS + 128).astype(np.int32)
               for _ in range(6)]

    results = {}
    for mode in (False, True):
        _reset_telemetry()
        engine = deepspeed_tpu.init_inference(
            model, dtype="bf16", telemetry=True,
            serving={"block_size": 128, "max_running": 4,
                     "max_num_blocks": POOL,
                     "kv_host": {"enabled": mode}})
        engine.generate_batch(prompts, max_new_tokens=MAX_NEW)   # warm +
        # populate; the burst then reclaims (destroys or demotes) the
        # shared prefix's cold blocks
        engine.generate_batch(scratch, max_new_tokens=MAX_NEW)
        _reset_telemetry()
        engine.generate_batch(prompts, max_new_tokens=MAX_NEW)   # re-hit
        results[mode] = _serve_hist(engine, "serving/ttft_ms", "p50")
        if mode:
            snap = engine.telemetry_snapshot().get("counters", {})
            rec = {
                "metric": _metric_name("BENCH_KV_TIER"),
                "value": round(results[True], 2),
                "unit": f"p50 re-hit TTFT ms (bf16, {NREQ} reqs sharing a "
                        f"{SYS}-tok prefix, {POOL}-block pool + scratch "
                        f"burst; destroy-on-reclaim = "
                        f"{results[False]:.1f} ms; "
                        f"fetch_hits={int(snap.get('serving/kv_fetch_hits', 0))}"
                        f" spills={int(snap.get('serving/kv_spills', 0))})",
                # >1 = demote+fetch cut re-hit TTFT by this factor
                "vs_baseline": (round(results[False] / results[True], 3)
                                if results[True] else 0.0),
            }
            tel = _telemetry_blob(engine)
            if tel:
                rec["telemetry"] = tel
            print(json.dumps(rec), flush=True)
        del engine


def run_chunked_prefill_bench():
    """Decode-throughput interference probe: short requests decode while
    long prompts keep arriving and prefilling. Whole-prompt prefill stalls
    every running decode for the full prompt (TPOT tail spike); chunked
    prefill interleaves one chunk per decode step. Value = p99 TPOT with
    chunking ON, vs_baseline = OFF/ON p99 ratio (>1 = chunking cut the
    decode stall)."""
    import numpy as np

    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models import gpt2

    dist.set_mesh(None)
    LONG = int(os.environ.get("BENCH_SERVE_LONG_LEN", 896))
    CHUNK = int(os.environ.get("BENCH_SERVE_CHUNK", 256))
    MAX_NEW = int(os.environ.get("BENCH_SERVE_NEW", 16))
    model = gpt2("125m", remat=False,
                 attention_backend=os.environ.get("BENCH_ATTN", "auto"))
    rng = np.random.default_rng(0)
    # FIFO admission: the short prompts admit first and decode while each
    # long prompt prefills into a freed slot mid-run
    prompts = [rng.integers(0, 50257, size=64).astype(np.int32)
               for _ in range(3)]
    prompts += [rng.integers(0, 50257, size=LONG).astype(np.int32)
                for _ in range(4)]

    results = {}
    for chunk in (0, CHUNK):
        _reset_telemetry()
        engine = deepspeed_tpu.init_inference(
            model, dtype="bf16", telemetry=True,
            serving={"block_size": 128, "max_running": 4,
                     "prefix_caching": "off", "prefill_chunk_tokens": chunk})
        engine.generate_batch(prompts, max_new_tokens=MAX_NEW)   # warm
        _reset_telemetry()
        engine.generate_batch(prompts, max_new_tokens=MAX_NEW)
        results[chunk] = _serve_hist(engine, "serving/tpot_ms", "p99")
        if chunk:
            rec = {
                "metric": _metric_name("BENCH_SERVE_CHUNKED"),
                "value": round(results[chunk], 2),
                "unit": f"p99 TPOT ms (bf16, 3 short decodes vs 4x{LONG}-tok "
                        f"prefills, chunk={chunk}; whole-prompt = "
                        f"{results[0]:.1f} ms)",
                "vs_baseline": (round(results[0] / results[chunk], 3)
                                if results[chunk] else 0.0),
            }
            tel = _telemetry_blob(engine)
            if tel:
                rec["telemetry"] = tel
            print(json.dumps(rec), flush=True)


def run_spec_decode_bench():
    """Speculative-decode probe: a repetitive / shared-pattern prompt set
    (the n-gram self-speculation sweet spot — templated text where the
    continuation has literally been seen before) decoded with
    ``serving.speculative`` OFF vs ON at the same greedy settings. Value =
    p50 TPOT with speculation on; vs_baseline = OFF/ON p50 TPOT ratio
    (>1 = fewer fused steps per emitted token); the same run's
    ``accepted_tokens_per_step`` and spec counters ride in the record's
    telemetry blob, so the acceptance rate that produced the speedup is
    part of the data point."""
    import numpy as np

    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models import gpt2

    dist.set_mesh(None)
    NREQ = int(os.environ.get("BENCH_SERVE_SPEC_REQS", 8))
    K = int(os.environ.get("BENCH_SERVE_SPEC_K", 4))
    MAX_NEW = int(os.environ.get("BENCH_SERVE_SPEC_NEW", 64))
    MOTIF = int(os.environ.get("BENCH_SERVE_SPEC_MOTIF", 48))
    model = gpt2("125m", remat=False,
                 attention_backend=os.environ.get("BENCH_ATTN", "auto"))
    rng = np.random.default_rng(0)
    # repetitive prompts: a short unique PREFIX then a motif tiled several
    # times — the prompt's tail n-gram recurs earlier in the tiling, so
    # the proposer speculates from the very first decode turn (a unique
    # suffix would leave the tail unmatchable and measure nothing); greedy
    # loops then extend the win into generated text
    prompts = []
    for _ in range(NREQ):
        motif = rng.integers(0, 50257, size=MOTIF).astype(np.int32)
        prompts.append(np.concatenate(
            [rng.integers(0, 50257, size=8).astype(np.int32),
             np.tile(motif, 5)]))

    results, stats = {}, {}
    for mode in ("off", "ngram"):
        _reset_telemetry()
        engine = deepspeed_tpu.init_inference(
            model, dtype="bf16", telemetry=True,
            serving={"block_size": 128, "max_running": 8,
                     # cache off: both modes pay identical prefill, so the
                     # TPOT delta is the multi-token decode win alone
                     "prefix_caching": "off",
                     "speculative": {"mode": mode, "k": K}})
        engine.generate_batch(prompts, max_new_tokens=MAX_NEW)   # warm
        _reset_telemetry()
        engine.generate_batch(prompts, max_new_tokens=MAX_NEW)
        results[mode] = _serve_hist(engine, "serving/tpot_ms", "p50")
        stats[mode] = dict(getattr(engine, "_last_serve_stats", {}) or {})
        if mode == "ngram":
            st = stats[mode]
            steps = st.get("decode_steps", 0) + st.get("verify_steps", 0)
            rec = {
                "metric": _metric_name("BENCH_SERVE_SPEC"),
                "value": round(results["ngram"], 3),
                "unit": f"p50 TPOT ms (bf16, {NREQ} reqs x {MAX_NEW} new, "
                        f"5x{MOTIF}-tok motif prompts, ngram k={K}; off = "
                        f"{results['off']:.2f} ms)",
                # >1 = speculation cut per-token latency by this factor
                "vs_baseline": (round(results["off"] / results["ngram"], 3)
                                if results["ngram"] else 0.0),
            }
            tel = _telemetry_blob(engine) or {}
            tel["accepted_tokens_per_step"] = (
                round(st.get("emitted_tokens", 0) / steps, 3) if steps
                else 0.0)
            tel["spec_stats"] = st
            rec["telemetry"] = tel
            print(json.dumps(rec), flush=True)
        # free this mode's engine (params + pools + executables) BEFORE
        # building the next one: both resident at once doubles peak HBM
        # and perturbs the very TPOT number the probe measures
        del engine


def _drive_open_loop(engine, prompts, gaps, max_new, consume,
                     injector=None, serving=None, sessions=None):
    """Shared Poisson open-loop driver for the async/chaos/dp serving
    probes: submit the seeded arrival trace (`sleep(gap)` then
    `add_request`) to a fresh ``AsyncServingEngine``, fan one
    ``consume(handle, rec)`` thread per request, join, drain — so the
    probes' goodput accounting can never drift methodologically.
    ``injector`` (a ``FaultInjector``) is installed for the run's
    duration. ``serving`` overrides the engine-wrapping default (the dp
    probe passes a ``ReplicaRouter`` — same ``add_request``/``shutdown``
    surface); ``sessions`` is an optional per-request session-key list
    (drives the router's affinity hash). Returns ``(recs, wall_seconds,
    serving)``; ``serving`` is already shut down (aborted if the drain
    failed)."""
    import threading
    import time as _t

    from deepspeed_tpu.inference.serve import AsyncServingEngine
    from deepspeed_tpu.utils import fault_injection as fi

    if serving is None:
        serving = AsyncServingEngine(engine, max_new_tokens=max_new)
    recs, threads = [], []
    t0 = _t.perf_counter()
    try:
        if injector is not None:
            fi.install(injector)
        for i, (p, gap) in enumerate(zip(prompts, gaps)):
            _t.sleep(gap)
            h = serving.add_request(
                p, session=sessions[i] if sessions else None)
            rec = {"tpot": [], "tokens": 0}
            th = threading.Thread(target=consume, args=(h, rec),
                                  daemon=True)
            th.start()
            recs.append(rec)
            threads.append(th)
        for th in threads:
            th.join(600)
        serving.shutdown(drain=True, timeout=600)
    finally:
        if injector is not None:
            fi.clear()
        if not serving._stopped:
            try:
                serving.shutdown(drain=False, timeout=60)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
    return recs, _t.perf_counter() - t0, serving


def run_async_serving_bench():
    """Open-loop async serving probe: Poisson arrivals (exponential
    inter-arrival gaps at BENCH_SERVE_ASYNC_RATE req/s, seeded — the
    trace replays) submitted to the always-on ``AsyncServingEngine``
    while earlier requests are mid-decode — the arrival pattern
    ``generate_batch`` benches can never produce. Value = GOODPUT at a
    p99 TPOT target: generated tokens/s counted only from requests whose
    own p99 per-token latency met BENCH_SERVE_ASYNC_TPOT_MS;
    vs_baseline = goodput / raw throughput (SLO attainment, 1.0 = every
    request met the target). The same run exercises the open-loop
    telemetry (TTFT/TPOT/queue-wait histograms ride the record's blob)
    and the flight recorder — the per-request chrome trace is exported
    next to the tempdir and its path embedded. Failures degrade to the
    standard skip record (skip_stage/skip_error), never an rc!=0."""
    import tempfile
    import time as _t

    import numpy as np

    RATE = float(os.environ.get("BENCH_SERVE_ASYNC_RATE", 8.0))
    NREQ = int(os.environ.get("BENCH_SERVE_ASYNC_REQS", 24))
    MAX_NEW = int(os.environ.get("BENCH_SERVE_ASYNC_NEW", 32))
    TARGET = float(os.environ.get("BENCH_SERVE_ASYNC_TPOT_MS", 50.0))
    engine = sampler = None
    try:
        import deepspeed_tpu
        import deepspeed_tpu.comm as dist
        from deepspeed_tpu.models import gpt2

        dist.set_mesh(None)
        _reset_telemetry()
        model = gpt2("125m", remat=False,
                     attention_backend=os.environ.get("BENCH_ATTN", "auto"))
        engine = deepspeed_tpu.init_inference(
            model, dtype="bf16", telemetry={"events": True},
            serving={"block_size": 128, "max_running": 8,
                     "prefix_caching": "off"})
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 50257, size=int(n)).astype(np.int32)
                   for n in rng.integers(64, 192, size=NREQ)]
        gaps = rng.exponential(1.0 / max(RATE, 1e-6), size=NREQ)
        # warm the fused programs CLOSED-loop — the open loop reuses them
        # (the serving_async_steady contract), so compile time never
        # pollutes the measured arrival window
        engine.generate_batch(prompts[:2], max_new_tokens=MAX_NEW)
        _reset_telemetry()

        def consume(h, rec):
            last = None
            for burst in h.stream():
                now = _t.perf_counter()
                if last is not None:
                    rec["tpot"] += [(now - last) / len(burst)] * len(burst)
                last = now
                rec["tokens"] += len(burst)
            rec["status"] = h.status

        # the SLO plane rides the run: default serving objectives at the
        # probe's own TPOT target, evaluated on background sampler ticks
        # (zero compiles — the serving_metrics_steady contract), so the
        # record can report whether the burn-rate alerts fired
        from deepspeed_tpu.monitor.sampler import MetricsSampler
        from deepspeed_tpu.monitor.slo import (SloEngine, parse_objectives,
                                               serving_objectives)
        slo = SloEngine(
            parse_objectives(serving_objectives(tpot_p99_ms=TARGET),
                             default_windows=[16, 4]),
            events=engine._events)
        sampler = MetricsSampler(interval_s=0.25, slo=slo).start()

        recs, wall, _serving = _drive_open_loop(engine, prompts, gaps,
                                                MAX_NEW, consume)
        sampler.stop()                  # final tick lands shutdown state

        good = total = met = 0
        for rec in recs:
            total += rec["tokens"]
            p99_ms = (float(np.percentile(rec["tpot"], 99)) * 1e3
                      if rec["tpot"] else 0.0)
            if rec.get("status") == "finished" and p99_ms <= TARGET:
                good += rec["tokens"]
                met += 1
        goodput = good / wall if wall > 0 else 0.0
        throughput = total / wall if wall > 0 else 0.0
        out = {
            "metric": _metric_name("BENCH_SERVE_ASYNC"),
            "value": round(goodput, 1),
            "unit": f"goodput tokens/s (bf16 open loop, Poisson {RATE}/s x "
                    f"{NREQ} reqs x {MAX_NEW} new, p99 TPOT target "
                    f"{TARGET:.0f} ms: {met}/{NREQ} requests met it; raw "
                    f"throughput = {throughput:.1f} tok/s)",
            # SLO attainment: 1.0 = every request inside the TPOT target
            "vs_baseline": (round(goodput / throughput, 3)
                            if throughput else 0.0),
        }
        tel = _telemetry_blob(engine) or {}
        tel["slo_met_requests"] = met
        tel["throughput_tokens_per_sec"] = round(throughput, 1)
        # final registry snapshot (the sampler's last tick) + any SLO
        # breach events the burn-rate engine fired during the run
        if sampler.ring:
            final = dict(sampler.ring[-1])
            final.pop("ts", None)
            tel["final_metrics_snapshot"] = final
        from deepspeed_tpu.monitor.health import labeled_series
        breaches = {k: int(v) for k, v in labeled_series(
            (engine.telemetry_snapshot() or {}).get("counters", {}),
            "slo/breaches").items() if v}
        if breaches:
            tel["slo_breaches"] = breaches
        ev = engine._events
        if ev is not None:
            breach_events = [e.to_dict() for e in ev.snapshot()
                             if e.kind == "slo.breach"]
            if breach_events:
                tel["slo_breach_events"] = breach_events
        trace_path = os.path.join(tempfile.gettempdir(),
                                  "bench_serve_async_trace.json")
        try:
            # the open-loop per-request chrome trace, finally exercised
            # under realistic arrivals (ROADMAP item 1's telemetry ask)
            tel["serving_trace"] = engine.export_serving_trace(trace_path)
        except Exception:  # noqa: BLE001 — trace export is best-effort
            pass
        out["telemetry"] = tel
        print(json.dumps(out), flush=True)
    except Exception as e:  # noqa: BLE001 — probe failure => skip record
        print(json.dumps({
            "metric": _metric_name("BENCH_SERVE_ASYNC"),
            "value": 0.0,
            "unit": "goodput tokens/s (skipped: async serving probe "
                    "failed)",
            "vs_baseline": 0.0,
            "skipped": True,
            "skip_stage": "serve_async_run",
            "skip_error": f"{type(e).__name__}: {e}",
        }), flush=True)
    finally:
        # the open-loop driver owns the serving teardown
        if sampler is not None:
            sampler.stop(final_tick=False)
        del engine


def run_serve_chaos_bench():
    """Serving fault-tolerance probe: the Poisson-arrival async goodput
    run executed twice on one engine — CLEAN, then again under a SEEDED
    fault-injection schedule (one engine-fatal fault that forces a
    crash-safe engine restart, plus scattered per-request step faults that
    exercise retry/backoff containment). Value = the faulted run's goodput
    (generated tokens/s over FINISHED requests); vs_baseline = GOODPUT
    RETENTION, faulted/clean — 1.0 means the fault-tolerance spine cost
    nothing, 0 means the loop died (it must not: a crashed loop fails the
    probe into a skip record). Restart/retry/quarantine counters and the
    step-fault breakdown ride the record's telemetry blob."""
    import numpy as np

    RATE = float(os.environ.get("BENCH_SERVE_CHAOS_RATE", 8.0))
    NREQ = int(os.environ.get("BENCH_SERVE_CHAOS_REQS", 16))
    MAX_NEW = int(os.environ.get("BENCH_SERVE_CHAOS_NEW", 32))
    engine = None
    try:
        import deepspeed_tpu
        import deepspeed_tpu.comm as dist
        from deepspeed_tpu.models import gpt2
        from deepspeed_tpu.utils import fault_injection as fi

        dist.set_mesh(None)
        _reset_telemetry()
        model = gpt2("125m", remat=False,
                     attention_backend=os.environ.get("BENCH_ATTN", "auto"))
        engine = deepspeed_tpu.init_inference(
            model, dtype="bf16", telemetry={"events": True},
            serving={"block_size": 128, "max_running": 8,
                     "prefix_caching": "off"})
        rng = np.random.default_rng(7)
        prompts = [rng.integers(0, 50257, size=int(n)).astype(np.int32)
                   for n in rng.integers(64, 192, size=NREQ)]
        gaps = rng.exponential(1.0 / max(RATE, 1e-6), size=NREQ)
        # closed-loop warm-up so neither run pays compile time in its
        # arrival window (the faulted run recompiles once mid-run by
        # design — that recovery cost IS part of what it measures)
        engine.generate_batch(prompts[:2], max_new_tokens=MAX_NEW)

        def consume(h, rec):
            for burst in h.stream():
                rec["tokens"] += len(burst)
            rec["status"] = h.status

        def one_run(injector):
            recs, wall, serving = _drive_open_loop(
                engine, prompts, gaps, MAX_NEW, consume, injector=injector)
            good = sum(r["tokens"] for r in recs
                       if r.get("status") == "finished")
            done = sum(r.get("status") == "finished" for r in recs)
            return (good / wall if wall > 0 else 0.0, done,
                    serving.restarts)

        clean, clean_done, _ = one_run(None)
        _reset_telemetry()       # the record's blob describes the faulted run
        # the seeded schedule: an engine-fatal mid-run + per-request
        # faults scattered through the action stream (deterministic given
        # the injector's step counter)
        inj = fi.FaultInjector()
        inj.fail_step("decode", at_step=max(NREQ, 8), count=1, phase="post")
        inj.fail_step("prefill", at_step=3, count=1)
        inj.fail_step("decode", at_step=2 * max(NREQ, 8), count=1)
        faulted, faulted_done, restarts = one_run(inj)

        out = {
            "metric": _metric_name("BENCH_SERVE_CHAOS"),
            "value": round(faulted, 1),
            "unit": f"goodput tokens/s under injected faults (bf16 open "
                    f"loop, Poisson {RATE}/s x {NREQ} reqs x {MAX_NEW} "
                    f"new; 1 engine-fatal + 2 per-request faults; "
                    f"{faulted_done}/{NREQ} finished vs {clean_done}/"
                    f"{NREQ} clean at {clean:.1f} tok/s)",
            # goodput retention: how much serving capacity survives the
            # fault schedule (restart recompiles + recompute retries)
            "vs_baseline": round(faulted / clean, 3) if clean else 0.0,
        }
        tel = _telemetry_blob(engine) or {}
        tel["engine_restarts"] = restarts
        out["telemetry"] = tel
        print(json.dumps(out), flush=True)
    except Exception as e:  # noqa: BLE001 — probe failure => skip record
        print(json.dumps({
            "metric": _metric_name("BENCH_SERVE_CHAOS"),
            "value": 0.0,
            "unit": "goodput tokens/s under injected faults (skipped: "
                    "serving chaos probe failed)",
            "vs_baseline": 0.0,
            "skipped": True,
            "skip_stage": "serve_chaos_run",
            "skip_error": f"{type(e).__name__}: {e}",
        }), flush=True)
    finally:
        del engine


def run_serve_adaptive_bench():
    """Adaptive-autopilot spike probe: one engine, the same seeded
    Poisson arrival trace with a MID-TRACE ARRIVAL SPIKE (the middle
    third's inter-arrival gaps divided by BENCH_CTL_SPIKE), driven twice
    — STATIC first (controller off: the config posture rides the spike),
    then ADAPTIVE (the monitor/controller.py burn-rate autopilot ticking
    on a background sampler, actions applied between engine steps — the
    ``dscli serve --adaptive`` wiring). Value = the adaptive run's
    goodput at the p99 TPOT target (the async probe's definition:
    tokens/s from finished requests whose own p99 TPOT met it);
    vs_baseline = adaptive/static goodput — above 1.0 the autopilot
    bought goodput under the spike. Per-run SLO breach / shed /
    knob-action counts plus the decision ledger's audit lines ride the
    telemetry blob. Failures degrade to the standard skip record."""
    import time as _t

    import numpy as np

    RATE = float(os.environ.get("BENCH_CTL_RATE", 6.0))
    NREQ = int(os.environ.get("BENCH_CTL_REQS", 18))
    MAX_NEW = int(os.environ.get("BENCH_CTL_NEW", 32))
    TARGET = float(os.environ.get("BENCH_CTL_TPOT_MS", 50.0))
    SPIKE = max(float(os.environ.get("BENCH_CTL_SPIKE", 6.0)), 1.0)
    engine = None
    try:
        import deepspeed_tpu
        import deepspeed_tpu.comm as dist
        from deepspeed_tpu.inference.serve import AsyncServingEngine
        from deepspeed_tpu.models import gpt2
        from deepspeed_tpu.monitor.controller import (AdaptiveController,
                                                      explain_decisions,
                                                      knobs_from_serving)
        from deepspeed_tpu.monitor.health import (labeled_series,
                                                  multilabel_series)
        from deepspeed_tpu.monitor.sampler import MetricsSampler
        from deepspeed_tpu.monitor.slo import (SloEngine, parse_objectives,
                                               serving_objectives)

        dist.set_mesh(None)
        _reset_telemetry()
        model = gpt2("125m", remat=False,
                     attention_backend=os.environ.get("BENCH_ATTN", "auto"))
        # chunked prefill gives the controller a real prefill_chunk
        # ladder; admission/shed knobs bootstrap from the default policy
        engine = deepspeed_tpu.init_inference(
            model, dtype="bf16", telemetry={"events": True},
            serving={"block_size": 128, "max_running": 8,
                     "prefix_caching": "off",
                     "prefill_chunk_tokens": 256})
        rng = np.random.default_rng(19)
        prompts = [rng.integers(0, 50257, size=int(n)).astype(np.int32)
                   for n in rng.integers(64, 192, size=NREQ)]
        gaps = rng.exponential(1.0 / max(RATE, 1e-6), size=NREQ)
        # the spike: the middle third arrives SPIKE x faster than the
        # steady Poisson rate — the burn the autopilot is built to read
        lo, hi = NREQ // 3, 2 * NREQ // 3
        gaps[lo:hi] /= SPIKE
        # closed-loop warm-up: both runs reuse the warm programs, and
        # every knob-ladder rung stays inside the compiled buckets (the
        # serving_adaptive_steady contract), so neither run pays compile
        # time inside its measured arrival window
        engine.generate_batch(prompts[:2], max_new_tokens=MAX_NEW)

        def consume(h, rec):
            last = None
            for burst in h.stream():
                now = _t.perf_counter()
                if last is not None:
                    rec["tpot"] += [(now - last) / len(burst)] * len(burst)
                last = now
                rec["tokens"] += len(burst)
            rec["status"] = h.status

        def one_run(adaptive):
            _reset_telemetry()
            serving = AsyncServingEngine(engine, max_new_tokens=MAX_NEW)
            slo = SloEngine(
                parse_objectives(serving_objectives(tpot_p99_ms=TARGET),
                                 default_windows=[16, 4]),
                events=engine._events)
            ctl = None
            if adaptive:
                ctl = AdaptiveController(
                    knobs_from_serving(engine.config.serving,
                                       policy=serving.policy),
                    events=engine._events,
                    apply_fn=serving.apply_knobs)
            sampler = MetricsSampler(interval_s=0.2, slo=slo,
                                     ctl=ctl).start()
            try:
                recs, wall, _serving = _drive_open_loop(
                    engine, prompts, gaps, MAX_NEW, consume,
                    serving=serving)
            finally:
                sampler.stop(final_tick=False)
            good = met = 0
            for rec in recs:
                p99_ms = (float(np.percentile(rec["tpot"], 99)) * 1e3
                          if rec["tpot"] else 0.0)
                if rec.get("status") == "finished" and p99_ms <= TARGET:
                    good += rec["tokens"]
                    met += 1
            counters = (engine.telemetry_snapshot() or {}).get(
                "counters", {})
            return {
                "goodput": good / wall if wall > 0 else 0.0,
                "met": met,
                "breaches": int(sum(labeled_series(
                    counters, "slo/breaches").values())),
                "shed": int(counters.get("serving/shed_requests", 0)),
                "actions": int(sum(v for _, v in multilabel_series(
                    counters, "ctl/actions"))),
            }

        static = one_run(adaptive=False)
        adapt = one_run(adaptive=True)

        out = {
            "metric": _metric_name("BENCH_CTL"),
            "value": round(adapt["goodput"], 1),
            "unit": f"goodput tokens/s under a {SPIKE:.0f}x arrival spike "
                    f"(bf16 open loop, Poisson {RATE}/s x {NREQ} reqs x "
                    f"{MAX_NEW} new, p99 TPOT target {TARGET:.0f} ms; "
                    f"adaptive {adapt['met']}/{NREQ} met it with "
                    f"{adapt['breaches']} SLO breaches vs static "
                    f"{static['met']}/{NREQ} with {static['breaches']} "
                    f"at {static['goodput']:.1f} tok/s)",
            # the autopilot's value: goodput bought (or lost) vs riding
            # the spike in the static config posture
            "vs_baseline": (round(adapt["goodput"] / static["goodput"], 3)
                            if static["goodput"] else 0.0),
        }
        tel = _telemetry_blob(engine) or {}
        for label, run in (("static", static), ("adaptive", adapt)):
            tel[label] = {"goodput_tokens_per_sec": round(run["goodput"], 1),
                          "slo_met_requests": run["met"],
                          "slo_breaches": run["breaches"],
                          "shed_requests": run["shed"]}
        tel["ctl_actions"] = adapt["actions"]
        ev = engine._events
        if ev is not None:
            ledger = explain_decisions(
                e.to_dict() for e in ev.snapshot())
            if ledger:
                tel["ctl_ledger"] = ledger[:40]
        out["telemetry"] = tel
        print(json.dumps(out), flush=True)
    except Exception as e:  # noqa: BLE001 — probe failure => skip record
        print(json.dumps({
            "metric": _metric_name("BENCH_CTL"),
            "value": 0.0,
            "unit": "goodput tokens/s under an arrival spike (skipped: "
                    "adaptive serving probe failed)",
            "vs_baseline": 0.0,
            "skipped": True,
            "skip_stage": "serve_adaptive_run",
            "skip_error": f"{type(e).__name__}: {e}",
        }), flush=True)
    finally:
        del engine


def run_serve_dp_bench():
    """Replica scale-out probe: the SAME seeded Poisson arrival trace
    through one ``AsyncServingEngine`` (dp=1) and through a two-replica
    ``ReplicaRouter`` with session affinity (dp=2, replicas share the
    model params — per-replica state is just the KV pools). Value = the
    dp=2 run's goodput (generated tokens/s over FINISHED requests);
    vs_baseline = SCALING EFFICIENCY, (goodput_dp2 / goodput_dp1) / 2 —
    1.0 means a second serving replica doubles goodput, and on a
    single-chip box the number quantifies how much of the dp axis is
    compute-bound (replicas time-slice one chip) vs queue-bound (open-
    loop arrivals wait less when two intakes drain the backlog).
    Per-replica routing counters ride the record's telemetry blob."""
    import numpy as np

    RATE = float(os.environ.get("BENCH_SERVE_DP_RATE", 8.0))
    NREQ = int(os.environ.get("BENCH_SERVE_DP_REQS", 16))
    MAX_NEW = int(os.environ.get("BENCH_SERVE_DP_NEW", 32))
    engines = []
    try:
        import deepspeed_tpu
        import deepspeed_tpu.comm as dist
        from deepspeed_tpu.inference.router import ReplicaRouter
        from deepspeed_tpu.inference.serve import AsyncServingEngine
        from deepspeed_tpu.models import gpt2

        dist.set_mesh(None)
        _reset_telemetry()
        model = gpt2("125m", remat=False,
                     attention_backend=os.environ.get("BENCH_ATTN", "auto"))
        serving_cfg = {"block_size": 128, "max_running": 8,
                       "prefix_caching": "off"}
        engines.append(deepspeed_tpu.init_inference(
            model, dtype="bf16", telemetry={"events": True},
            serving=serving_cfg))
        engines.append(deepspeed_tpu.init_inference(
            model, params=engines[0].params, dtype="bf16",
            telemetry={"events": True}, serving=serving_cfg))
        rng = np.random.default_rng(11)
        prompts = [rng.integers(0, 50257, size=int(n)).astype(np.int32)
                   for n in rng.integers(64, 192, size=NREQ)]
        gaps = rng.exponential(1.0 / max(RATE, 1e-6), size=NREQ)
        # one session per request: the affinity hash spreads fresh
        # sessions over the ring deterministically
        sessions = [f"dp-bench-{i}" for i in range(NREQ)]
        # closed-loop warm-up on BOTH replicas so neither run pays
        # compile time inside its measured arrival window
        for e in engines:
            e.generate_batch(prompts[:2], max_new_tokens=MAX_NEW)
        _reset_telemetry()

        def consume(h, rec):
            for burst in h.stream():
                rec["tokens"] += len(burst)
            rec["status"] = h.status

        def one_run(serving):
            recs, wall, serving = _drive_open_loop(
                engines[0], prompts, gaps, MAX_NEW, consume,
                serving=serving, sessions=sessions)
            good = sum(r["tokens"] for r in recs
                       if r.get("status") == "finished")
            done = sum(r.get("status") == "finished" for r in recs)
            return (good / wall if wall > 0 else 0.0), done

        dp1, dp1_done = one_run(
            AsyncServingEngine(engines[0], max_new_tokens=MAX_NEW))
        _reset_telemetry()       # the record's blob describes the dp=2 run
        dp2, dp2_done = one_run(ReplicaRouter(
            [AsyncServingEngine(e, max_new_tokens=MAX_NEW)
             for e in engines]))

        eff = (dp2 / dp1) / 2 if dp1 else 0.0
        out = {
            "metric": _metric_name("BENCH_SERVE_DP"),
            "value": round(dp2, 1),
            "unit": f"goodput tokens/s at dp=2 (bf16 open loop, Poisson "
                    f"{RATE}/s x {NREQ} reqs x {MAX_NEW} new, session-"
                    f"affine router; {dp2_done}/{NREQ} finished vs "
                    f"{dp1_done}/{NREQ} at dp=1, {dp1:.1f} tok/s)",
            # replica scaling efficiency: 1.0 = second replica doubles
            # goodput (expect << 1.0 when both time-slice one chip)
            "vs_baseline": round(eff, 3),
        }
        tel = _telemetry_blob(engines[0]) or {}
        from deepspeed_tpu.monitor.health import labeled_series
        counters = (engines[0].telemetry_snapshot() or {}).get(
            "counters", {})
        routed = {k: int(v) for k, v in labeled_series(
            counters, "router/requests").items()}
        if routed:
            tel["router_requests"] = routed
        out["telemetry"] = tel
        print(json.dumps(out), flush=True)
    except Exception as e:  # noqa: BLE001 — probe failure => skip record
        print(json.dumps({
            "metric": _metric_name("BENCH_SERVE_DP"),
            "value": 0.0,
            "unit": "goodput tokens/s at dp=2 (skipped: replica scale-out "
                    "probe failed)",
            "vs_baseline": 0.0,
            "skipped": True,
            "skip_stage": "serve_dp_run",
            "skip_error": f"{type(e).__name__}: {e}",
        }), flush=True)
    finally:
        del engines


def run_serving_tp_bench():
    """Tensor-parallel serving scaling probe: the same mixed prompt set
    through the paged engine at serving.tp=1 and serving.tp=N on one
    slice. Value = paged decode throughput (generated tokens/s) at tp=N;
    vs_baseline = SCALING EFFICIENCY, (tpN tokens/s ÷ tp1 tokens/s) ÷ N —
    1.0 means decode scales linearly with the slice, and anything near it
    means one model's max size scales with the slice too (params and KV
    pools are really sharded: per-chip bytes drop to 1/N). Emits a skip
    record on a single-device backend (nothing to shard over)."""
    import time as _t

    import numpy as np

    import deepspeed_tpu
    import deepspeed_tpu.comm as dist

    import jax
    n_dev = jax.device_count()
    if n_dev < 2:
        print(json.dumps({
            "metric": _metric_name("BENCH_SERVE_TP"),
            "value": 0.0,
            "unit": "tokens/s (skipped: single-device backend, nothing to "
                    "shard over)",
            "vs_baseline": 0.0,
            "skipped": True,
            "skip_stage": "single_device",
            "skip_error": f"jax.device_count()={n_dev}",
        }), flush=True)
        return

    from deepspeed_tpu.models import gpt2
    model = gpt2("125m", remat=False,
                 attention_backend=os.environ.get("BENCH_ATTN", "auto"))
    heads = model.config.kv_heads
    tp_env = os.environ.get("BENCH_SERVE_TP_N", "auto")
    if tp_env == "auto":
        # largest tp <= min(devices, 4) that divides BOTH the device count
        # and the KV heads (gpt2-125m: 12 heads -> 2, 3, 4 all legal);
        # no legal degree (e.g. 5 devices) -> skip record, not a crash
        TP = max((t for t in range(2, min(n_dev, 4) + 1)
                  if n_dev % t == 0 and heads % t == 0), default=0)
    else:
        TP = int(tp_env)
    if TP < 2:
        print(json.dumps({
            "metric": _metric_name("BENCH_SERVE_TP"),
            "value": 0.0,
            "unit": "tokens/s (skipped: no tp in 2..4 divides both "
                    f"device count {n_dev} and kv heads {heads})",
            "vs_baseline": 0.0,
            "skipped": True,
            "skip_stage": "no_divisible_tp",
            "skip_error": f"devices={n_dev}, kv_heads={heads}",
        }), flush=True)
        return
    NREQ = int(os.environ.get("BENCH_SERVE_TP_REQS", 8))
    MAX_NEW = int(os.environ.get("BENCH_SERVE_TP_NEW", 64))
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 50257, size=int(n)).astype(np.int32)
               for n in rng.integers(64, 192, size=NREQ)]

    results = {}
    for tp in (1, TP):
        dist.set_mesh(None)
        _reset_telemetry()
        engine = deepspeed_tpu.init_inference(
            model, dtype="bf16", telemetry=True,
            serving={"block_size": 128, "max_running": 8,
                     # cold decode both times: the cache win is
                     # BENCH_SERVE_PREFIX's story, this one is scaling
                     "prefix_caching": "off", "tp": tp})
        engine.generate_batch(prompts, max_new_tokens=MAX_NEW)   # warm
        t0 = _t.perf_counter()
        outs = engine.generate_batch(prompts, max_new_tokens=MAX_NEW)
        dt = _t.perf_counter() - t0
        gen = sum(int(o.shape[0]) - len(p) for o, p in zip(outs, prompts))
        results[tp] = gen / dt
        if tp == TP:
            rec = {
                "metric": _metric_name("BENCH_SERVE_TP"),
                "value": round(results[TP], 1),
                "unit": f"generated tokens/s (bf16 paged decode, tp={TP} "
                        f"over {n_dev} devices, {NREQ} reqs x {MAX_NEW} "
                        f"new; tp=1 = {results[1]:.1f} tok/s)",
                # scaling efficiency: 1.0 = linear decode scaling
                "vs_baseline": (round(results[TP] / results[1] / TP, 3)
                                if results[1] else 0.0),
            }
            tel = _telemetry_blob(engine)
            if tel:
                rec["telemetry"] = tel
            print(json.dumps(rec), flush=True)
        del engine


def run_checkpoint_bench():
    """Async-checkpoint stall probe: the same training loop with and
    without a two-phase async save in flight. Phase 1 (device->host
    snapshot) runs on the training thread; phase 2 (serialize+fsync+commit)
    on the background writer — the metric is the per-step stall the whole
    mechanism adds, with checkpoint/save_ms + /bytes from the same run
    embedded in the record's telemetry blob. BENCH_CKPT_STEPS overrides the
    window; BENCH_CKPT_EVERY the save cadence (steps per async save)."""
    import shutil
    import tempfile
    import time as _t

    steps = max(4, int(os.environ.get("BENCH_CKPT_STEPS",
                                      os.environ.get("BENCH_STEPS", 10))))
    every = max(1, int(os.environ.get("BENCH_CKPT_EVERY", 2)))
    engine, model, batch, knobs = build_bench_engine()
    # bound the probe's disk footprint: retention keeps the 2 newest tags
    engine._config.checkpoint_config.keep_last = 2
    save_dir = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        float(engine.train_batch(batch()))  # warmup/compile

        def _window(save: bool):
            times = []
            for i in range(steps):
                t0 = _t.perf_counter()
                loss = engine.train_batch(batch())
                if save and i % every == 0:
                    engine.save_checkpoint(save_dir, asynchronous=True)
                float(loss)  # host fetch = the only reliable sync point
                times.append((_t.perf_counter() - t0) * 1e3)
            return sum(times) / len(times)

        base_ms = _window(save=False)
        with_ms = _window(save=True)
        engine.flush_checkpoints()
        stall = with_ms - base_ms
        rec = {
            "metric": _metric_name("BENCH_CKPT"),
            "value": round(stall, 3),
            "unit": f"ms/step added by async save every {every} steps "
                    f"(base {base_ms:.1f} -> {with_ms:.1f} ms/step, "
                    f"{steps}-step windows)",
            # <=1.0 means the async save is (near-)stall-free
            "vs_baseline": round(with_ms / base_ms, 4),
        }
        tel = _telemetry_blob(engine)
        if tel:
            rec["telemetry"] = tel
        print(json.dumps(rec), flush=True)
    finally:
        try:
            engine.destroy()   # stop the writer thread so the engine can GC
        except Exception:
            pass
        shutil.rmtree(save_dir, ignore_errors=True)


def _emit_skip_records(err):
    """One parseable JSON record per enabled metric so the bench trajectory
    is never empty: a dead TPU relay is a data point ("skipped"), not a
    silent rc=1 hole the driver records as ``parsed: null``. ``err`` is
    the probe's failure dict (or a bare string from older callers); each
    record carries the init stage and the ACTUAL exception text so the
    failure is diagnosable from the JSON alone."""
    if isinstance(err, str) or err is None:
        first = (err or "").strip().splitlines() or ["backend probe failed"]
        err = {"stage": "backend_probe", "summary": first[0],
               "error": err or ""}
    for name in _enabled_metrics():
        rec = {
            "metric": name,
            "value": 0.0,
            "unit": f"tokens/s (skipped: {err['summary']})",
            "vs_baseline": 0.0,
            "skipped": True,
            "skip_stage": err["stage"],
            "skip_error": err.get("error", ""),
        }
        if err.get("hint"):
            # e.g. "relay_down" on the backend-init-timeout signature
            rec["skip_hint"] = err["hint"]
        print(json.dumps(rec), flush=True)


def _run_cpu_smoke(steps: int):
    """BENCH_ALLOW_CPU=1 fallback when the device backend is down: a tiny
    causal-LM config on the CPU backend. Not an MFU number (vs_baseline 0) —
    it proves the train loop end-to-end and gives the round a real loss."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np

    import deepspeed_tpu
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.models import CausalLM
    from deepspeed_tpu.models.transformer import TransformerConfig

    BATCH, SEQ = 4, 64
    model = CausalLM(TransformerConfig(vocab_size=512, n_layer=2, n_head=2,
                                       d_model=64, max_seq=SEQ, remat=False,
                                       attention_backend="xla"))
    import jax
    params = model.init_params(jax.random.key(0))
    dist.set_mesh(None)
    config = {
        "train_micro_batch_size_per_gpu": BATCH,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "mesh": {"dp": 1},
        "steps_per_print": 0,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config=config)
    rng = np.random.default_rng(0)

    def batch():
        return {"input_ids": rng.integers(0, 512, size=(BATCH, SEQ)).astype(np.int32)}

    float(engine.train_batch(batch()))  # compile
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch())
    loss_val = float(loss)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "cpu_smoke_train_tokens_per_sec",
        "value": round(BATCH * SEQ * steps / dt, 1),
        "unit": f"tokens/s (cpu fallback, bs{BATCH}xseq{SEQ}, tiny model, "
                f"loss {loss_val:.3f}; NOT an MFU metric)",
        "vs_baseline": 0.0,
    }), flush=True)


def main():
    if os.environ.get("BENCH_SKIP_PROBE") != "1":
        # one retry after a short pause: a relay mid-restart (ports up,
        # backend briefly unresponsive) should not cost the round's number
        retries = int(os.environ.get("BENCH_PROBE_RETRIES", 1))
        err = _probe_backend()
        while err is not None and retries > 0:
            print(f"bench: probe failed ({err['summary']}); retrying in 60s",
                  file=sys.stderr)
            time.sleep(60)
            retries -= 1
            err = _probe_backend()
        if err is not None:
            # degrade gracefully: parseable skip records (and optionally a
            # CPU smoke metric), rc=0 — never an empty bench round
            print(f"bench: [{err['stage']}] {err['summary']}\n"
                  f"{err.get('error', '')}", file=sys.stderr)
            _emit_skip_records(err)
            if os.environ.get("BENCH_ALLOW_CPU") == "1":
                # best effort only: the skip records above are already the
                # round's parseable data points, so a broken CPU fallback
                # must not turn this back into an rc!=0 empty round
                try:
                    _run_cpu_smoke(max(1, int(os.environ.get("BENCH_STEPS", 10)) // 5))
                except Exception as e:  # noqa: BLE001 - never fail the round
                    print(f"bench: cpu smoke fallback failed: {e}", file=sys.stderr)
            sys.exit(0)
    import jax

    STEPS = int(os.environ.get("BENCH_STEPS", 10))
    if STEPS < 1:
        print("bench: BENCH_STEPS must be >= 1", file=sys.stderr)
        sys.exit(1)
    engine = None
    if _metric_enabled("BENCH_GPT2"):
        engine, model, batch, knobs = build_bench_engine()
        # warmup/compile inside _run_metric; float() forces a host fetch —
        # the only reliable sync point over remote-tunnel device transports
        # (block_until_ready/effects_barrier return before remote execution
        # finishes)
        _run_metric(_metric_name("BENCH_GPT2"), engine, model,
                    batch, knobs["BATCH"], knobs["SEQ"], STEPS,
                    f"ZeRO-1, remat={knobs['remat_env']}, "
                    f"fused_ce={knobs['FUSED_CE']}, "
                    f"loss_chunk={knobs['LOSS_CHUNK']}")

    if _metric_enabled("BENCH_LLAMA"):
        # free the first engine's device state before the larger model lands
        if engine is not None:
            del engine, model, batch
        import gc
        gc.collect()
        engine, model, batch, knobs = build_llama_bench_engine()
        _run_metric(_metric_name("BENCH_LLAMA"),
                    engine, model, batch, knobs["BATCH"], knobs["SEQ"],
                    STEPS, "GQA 12q/4kv hd128, ZeRO-3, remat=dots")

    if _metric_enabled("BENCH_BERT"):
        if engine is not None:
            del engine, model, batch
        import gc
        gc.collect()
        engine, model, batch, knobs = build_bert_bench_engine()
        _run_metric(_metric_name("BENCH_BERT"),
                    engine, model, batch, knobs["BATCH"], knobs["SEQ"],
                    STEPS, "MLM, ZeRO-2")

    if _metric_enabled("BENCH_CKPT"):
        if engine is not None:
            del engine, model, batch
        import gc
        gc.collect()
        run_checkpoint_bench()
        engine = None

    if any(_metric_enabled(g) for g in
           ("BENCH_DECODE_DENSE", "BENCH_DECODE_PAGED",
            "BENCH_SERVE_PREFIX", "BENCH_KV_TIER", "BENCH_SERVE_CHUNKED",
            "BENCH_SERVE_SPEC", "BENCH_SERVE_ASYNC", "BENCH_SERVE_CHAOS",
            "BENCH_SERVE_DP", "BENCH_CTL", "BENCH_SERVE_TP")):
        # free the last training engine's device state before serving
        if engine is not None:
            del engine, model, batch
        import gc
        gc.collect()
        if _metric_enabled("BENCH_DECODE_DENSE") \
                or _metric_enabled("BENCH_DECODE_PAGED"):
            run_decode_bench()
            gc.collect()
        if _metric_enabled("BENCH_SERVE_PREFIX"):
            run_prefix_cache_bench()
            gc.collect()
        if _metric_enabled("BENCH_KV_TIER"):
            run_kv_tier_bench()
            gc.collect()
        if _metric_enabled("BENCH_SERVE_CHUNKED"):
            run_chunked_prefill_bench()
            gc.collect()
        if _metric_enabled("BENCH_SERVE_SPEC"):
            run_spec_decode_bench()
            gc.collect()
        if _metric_enabled("BENCH_SERVE_ASYNC"):
            run_async_serving_bench()
            gc.collect()
        if _metric_enabled("BENCH_SERVE_CHAOS"):
            run_serve_chaos_bench()
            gc.collect()
        if _metric_enabled("BENCH_SERVE_DP"):
            run_serve_dp_bench()
            gc.collect()
        if _metric_enabled("BENCH_CTL"):
            run_serve_adaptive_bench()
            gc.collect()
        if _metric_enabled("BENCH_SERVE_TP"):
            run_serving_tp_bench()


if __name__ == "__main__":
    main()
