// Host-side vectorized Adagrad for ZeRO-Offload.
//
// Reference parity: csrc/adagrad/cpu_adagrad.cpp:238 + cpu_adagrad.h —
// same SIMD/OpenMP pattern as cpu_adam, exported over a C ABI for ctypes.

#include <cmath>
#include <cstdint>
#include <cstring>

namespace {

inline uint16_t f32_to_bf16(float f) {
    uint32_t bits;
    std::memcpy(&bits, &f, sizeof(bits));
    uint32_t lsb = (bits >> 16) & 1u;
    bits += 0x7fffu + lsb;
    return static_cast<uint16_t>(bits >> 16);
}

}  // namespace

extern "C" {

void ds_adagrad_step(float* params, const float* grads, float* exp_avg_sq,
                     int64_t n, float lr, float eps, float weight_decay) {
#pragma omp parallel for simd schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float grad = grads[i];
        if (weight_decay > 0.0f) grad += weight_decay * params[i];
        exp_avg_sq[i] += grad * grad;
        params[i] -= lr * grad / (std::sqrt(exp_avg_sq[i]) + eps);
    }
}

void ds_adagrad_step_plus_copy(float* params, const float* grads,
                               float* exp_avg_sq, uint16_t* param_out_bf16,
                               int64_t n, float lr, float eps,
                               float weight_decay) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float grad = grads[i];
        if (weight_decay > 0.0f) grad += weight_decay * params[i];
        exp_avg_sq[i] += grad * grad;
        params[i] -= lr * grad / (std::sqrt(exp_avg_sq[i]) + eps);
        param_out_bf16[i] = f32_to_bf16(params[i]);
    }
}

}  // extern "C"
