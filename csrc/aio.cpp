// Async tensor I/O engine for NVMe offload (ZeRO-Infinity).
//
// Reference parity: csrc/aio/ — `aio_handle` (deepspeed_py_aio_handle.cpp:14-40)
// exposes a thread-pool + libaio queue doing O_DIRECT reads/writes of tensors;
// swappers above it stream param/optimizer partitions to NVMe.
//
// TPU-native rebuild: a dependency-free C++17 thread pool where every request
// is split into per-thread file chunks served with pread/pwrite. O_DIRECT is
// used when buffer/size/offset alignment permits (callers allocate 4096-aligned
// padded buffers via the Python helper), falling back to page-cache I/O
// otherwise. C ABI for ctypes; no torch, no pybind11.

#include <fcntl.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr int64_t kAlign = 4096;

struct Chunk {
    int op;  // 0 = read, 1 = write
    void* buf;
    std::string path;
    int64_t offset;
    int64_t nbytes;
    bool try_direct;
    std::atomic<int>* remaining;  // owned by the request
};

struct Handle {
    int n_threads;
    int64_t block_size;
    std::vector<std::thread> workers;
    std::deque<Chunk> queue;
    std::mutex mu;
    std::condition_variable cv;
    std::atomic<int64_t> inflight{0};
    std::atomic<int64_t> errors{0};
    std::atomic<int> last_errno{0};
    std::atomic<bool> stop{false};

    explicit Handle(int threads, int64_t block) : n_threads(threads), block_size(block) {
        for (int i = 0; i < n_threads; ++i) workers.emplace_back([this] { run(); });
    }

    ~Handle() {
        {
            std::lock_guard<std::mutex> lk(mu);
            stop = true;
        }
        cv.notify_all();
        for (auto& w : workers) w.join();
    }

    void run() {
        for (;;) {
            Chunk c;
            {
                std::unique_lock<std::mutex> lk(mu);
                cv.wait(lk, [this] { return stop || !queue.empty(); });
                if (stop && queue.empty()) return;
                c = std::move(queue.front());
                queue.pop_front();
            }
            if (!do_io(c)) {
                errors.fetch_add(1);
                last_errno.store(errno);
            }
            if (c.remaining->fetch_sub(1) == 1) delete c.remaining;
            {
                // decrement under the lock: otherwise a waiter that just saw
                // inflight==1 can miss the notify and sleep forever
                std::lock_guard<std::mutex> lk(mu);
                inflight.fetch_sub(1);
            }
            cv.notify_all();
        }
    }

    static bool do_io(const Chunk& c) {
        int flags = c.op == 0 ? O_RDONLY : (O_WRONLY | O_CREAT);
        bool direct = c.try_direct &&
                      (reinterpret_cast<uintptr_t>(c.buf) % kAlign == 0) &&
                      (c.offset % kAlign == 0) && (c.nbytes % kAlign == 0);
        int fd = -1;
#ifdef O_DIRECT
        if (direct) fd = ::open(c.path.c_str(), flags | O_DIRECT, 0644);
#endif
        if (fd < 0) fd = ::open(c.path.c_str(), flags, 0644);
        if (fd < 0) return false;
        char* p = static_cast<char*>(c.buf);
        int64_t left = c.nbytes, off = c.offset;
        bool ok = true;
        while (left > 0) {
            ssize_t n = c.op == 0 ? ::pread(fd, p, static_cast<size_t>(left), off)
                                  : ::pwrite(fd, p, static_cast<size_t>(left), off);
            if (n <= 0) {
                ok = false;
                break;
            }
            p += n;
            off += n;
            left -= n;
        }
        ::close(fd);
        return ok;
    }

    void submit(int op, void* buf, const char* path, int64_t nbytes, bool try_direct) {
        // split into block_size chunks across the pool (reference block_size
        // semantics: per-aio-call granularity)
        int64_t nchunks = (nbytes + block_size - 1) / block_size;
        if (nchunks < 1) nchunks = 1;
        auto* remaining = new std::atomic<int>(static_cast<int>(nchunks));
        {
            std::lock_guard<std::mutex> lk(mu);
            for (int64_t i = 0; i < nchunks; ++i) {
                int64_t off = i * block_size;
                int64_t len = std::min(block_size, nbytes - off);
                inflight.fetch_add(1);
                queue.push_back(Chunk{op, static_cast<char*>(buf) + off, path, off,
                                      len, try_direct, remaining});
            }
        }
        cv.notify_all();
    }

    int64_t wait() {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [this] { return inflight.load() == 0; });
        return errors.exchange(0);
    }
};

}  // namespace

extern "C" {

void* ds_aio_handle_new(int64_t block_size, int n_threads) {
    if (block_size <= 0) block_size = 1 << 20;
    if (n_threads <= 0) n_threads = 8;
    return new Handle(n_threads, block_size);
}

void ds_aio_handle_free(void* h) { delete static_cast<Handle*>(h); }

// async submit; completion via ds_aio_wait
void ds_aio_pread(void* h, void* buf, const char* path, int64_t nbytes) {
    static_cast<Handle*>(h)->submit(0, buf, path, nbytes, true);
}

void ds_aio_pwrite(void* h, void* buf, const char* path, int64_t nbytes) {
    static_cast<Handle*>(h)->submit(1, buf, path, nbytes, true);
}

// blocks until all inflight I/O completes; returns error count since last wait
int64_t ds_aio_wait(void* h) { return static_cast<Handle*>(h)->wait(); }

int64_t ds_aio_inflight(void* h) { return static_cast<Handle*>(h)->inflight.load(); }

int ds_aio_last_errno(void* h) { return static_cast<Handle*>(h)->last_errno.exchange(0); }

}  // extern "C"
