// Host-side vectorized Adam/AdamW for ZeRO-Offload.
//
// Reference parity: csrc/adam/cpu_adam.cpp:304 + csrc/includes/cpu_adam.h
// (AVX intrinsics + OpenMP, exports ds_adam_step / ds_adam_step_plus_copy).
// TPU-native rebuild: plain C++ with OpenMP worksharing and `omp simd`
// auto-vectorization (compiled -O3 -march=native, so the compiler emits
// AVX2/AVX-512 or NEON for the TPU-VM host CPU without hand intrinsics),
// plus a fused bf16 store of updated params into the device-bound staging
// buffer (the reference's `_plus_copy` overlap, csrc/adam/cpu_adam.cpp:290).
//
// All entry points use a C ABI and are loaded via ctypes (no torch, no
// pybind11). Buffers are caller-owned; bf16 is passed as uint16 words.

#include <cmath>
#include <cstddef>
#include <cstdint>
#include <cstring>

namespace {

inline float bf16_to_f32(uint16_t h) {
    uint32_t bits = static_cast<uint32_t>(h) << 16;
    float f;
    std::memcpy(&f, &bits, sizeof(f));
    return f;
}

// round-to-nearest-even, matching XLA's f32->bf16 convert
inline uint16_t f32_to_bf16(float f) {
    uint32_t bits;
    std::memcpy(&bits, &f, sizeof(bits));
    uint32_t lsb = (bits >> 16) & 1u;
    bits += 0x7fffu + lsb;
    return static_cast<uint16_t>(bits >> 16);
}

// One fused Adam update for element i. bias_c1/bias_c2 are the caller's
// precomputed 1-beta^t corrections so the inner loop stays branch-free.
inline float adam_update(float param, float grad, float& m, float& v,
                         float beta1, float beta2, float eps, float lr,
                         float weight_decay, int adamw_mode, float bias_c1,
                         float bias_c2) {
    if (!adamw_mode && weight_decay > 0.0f) grad += weight_decay * param;
    m = beta1 * m + (1.0f - beta1) * grad;
    v = beta2 * v + (1.0f - beta2) * grad * grad;
    float mhat = m / bias_c1;
    float vhat = v / bias_c2;
    float update = mhat / (std::sqrt(vhat) + eps);
    if (adamw_mode && weight_decay > 0.0f) update += weight_decay * param;
    return param - lr * update;
}

}  // namespace

extern "C" {

// fp32 params/grads in place.
void ds_adam_step(float* params, const float* grads, float* exp_avg,
                  float* exp_avg_sq, int64_t n, float lr, float beta1,
                  float beta2, float eps, float weight_decay, int adamw_mode,
                  float bias_c1, float bias_c2) {
#pragma omp parallel for simd schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        params[i] = adam_update(params[i], grads[i], exp_avg[i], exp_avg_sq[i],
                                beta1, beta2, eps, lr, weight_decay, adamw_mode,
                                bias_c1, bias_c2);
    }
}

// fp32 master params, bf16 grads (as produced on device), fused bf16 store of
// the updated params into `param_out_bf16` — the staging buffer the engine
// transfers back to HBM, overlapping convert+copy with the update itself.
// A null `param_out_bf16` skips the store (update-only).
void ds_adam_step_bf16(float* params, const uint16_t* grads_bf16,
                       float* exp_avg, float* exp_avg_sq,
                       uint16_t* param_out_bf16, int64_t n, float lr,
                       float beta1, float beta2, float eps, float weight_decay,
                       int adamw_mode, float bias_c1, float bias_c2) {
    if (param_out_bf16 != nullptr) {
#pragma omp parallel for schedule(static)
        for (int64_t i = 0; i < n; ++i) {
            float p = adam_update(params[i], bf16_to_f32(grads_bf16[i]), exp_avg[i],
                                  exp_avg_sq[i], beta1, beta2, eps, lr,
                                  weight_decay, adamw_mode, bias_c1, bias_c2);
            params[i] = p;
            param_out_bf16[i] = f32_to_bf16(p);
        }
    } else {
#pragma omp parallel for schedule(static)
        for (int64_t i = 0; i < n; ++i) {
            params[i] = adam_update(params[i], bf16_to_f32(grads_bf16[i]), exp_avg[i],
                                    exp_avg_sq[i], beta1, beta2, eps, lr,
                                    weight_decay, adamw_mode, bias_c1, bias_c2);
        }
    }
}

// fp32 update + fused bf16 copy-out (reference ds_adam_step_plus_copy).
void ds_adam_step_plus_copy(float* params, const float* grads, float* exp_avg,
                            float* exp_avg_sq, uint16_t* param_out_bf16,
                            int64_t n, float lr, float beta1, float beta2,
                            float eps, float weight_decay, int adamw_mode,
                            float bias_c1, float bias_c2) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float p = adam_update(params[i], grads[i], exp_avg[i], exp_avg_sq[i],
                              beta1, beta2, eps, lr, weight_decay, adamw_mode,
                              bias_c1, bias_c2);
        params[i] = p;
        param_out_bf16[i] = f32_to_bf16(p);
    }
}

}  // extern "C"
