// Parallel flatten/unflatten of host tensor lists.
//
// Reference parity: csrc/utils/flatten_unflatten.cpp (UtilsBuilder) — the
// reference re-exports torch's _flatten_dense_tensors; here the host-offload
// buffers are raw numpy memory, so this is a parallel gather/scatter memcpy.

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Copy `count` source buffers (byte sizes in `sizes`) back-to-back into `dst`.
void ds_flatten(const void** srcs, const int64_t* sizes, int64_t count,
                void* dst) {
    std::vector<int64_t> offs(static_cast<size_t>(count));
    int64_t off = 0;
    for (int64_t i = 0; i < count; ++i) {
        offs[static_cast<size_t>(i)] = off;
        off += sizes[i];
    }
#pragma omp parallel for schedule(dynamic)
    for (int64_t i = 0; i < count; ++i) {
        std::memcpy(static_cast<char*>(dst) + offs[static_cast<size_t>(i)],
                    srcs[i], static_cast<size_t>(sizes[i]));
    }
}

// Scatter a flat buffer back out into `count` destination buffers.
void ds_unflatten(void* const* dsts, const int64_t* sizes, int64_t count,
                  const void* src) {
    std::vector<int64_t> offs(static_cast<size_t>(count));
    int64_t off = 0;
    for (int64_t i = 0; i < count; ++i) {
        offs[static_cast<size_t>(i)] = off;
        off += sizes[i];
    }
#pragma omp parallel for schedule(dynamic)
    for (int64_t i = 0; i < count; ++i) {
        std::memcpy(dsts[i], static_cast<const char*>(src) + offs[static_cast<size_t>(i)],
                    static_cast<size_t>(sizes[i]));
    }
}

// Parallel single memcpy for large pinned-buffer moves
// (reference csrc/aio/py_lib/deepspeed_py_copy.cpp deepspeed_memcpy).
void ds_memcpy(void* dst, const void* src, int64_t nbytes) {
    const int64_t chunk = 1 << 22;  // 4 MiB per task
    int64_t nchunks = (nbytes + chunk - 1) / chunk;
#pragma omp parallel for schedule(static)
    for (int64_t c = 0; c < nchunks; ++c) {
        int64_t off = c * chunk;
        int64_t len = nbytes - off < chunk ? nbytes - off : chunk;
        std::memcpy(static_cast<char*>(dst) + off,
                    static_cast<const char*>(src) + off,
                    static_cast<size_t>(len));
    }
}

}  // extern "C"
