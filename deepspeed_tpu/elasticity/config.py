"""Elasticity config (reference: deepspeed/elasticity/config.py)."""

from __future__ import annotations

import json

from deepspeed_tpu.config import constants as C


class ElasticityError(Exception):
    """Base exception for elasticity errors."""


class ElasticityConfigError(ElasticityError):
    """Elasticity configuration error."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """World size is not compatible with the elastic config."""


class ElasticityConfig:
    """Elastic config object: which batch sizes are valid across which
    device-count ranges, so checkpoints stay consistent as world size changes.

    JSON schema (same as reference)::

        "elasticity": {
            "enabled": true,
            "max_train_batch_size": 2000,
            "micro_batch_sizes": [2,4,6],
            "min_gpus": 1, "max_gpus": 10000,
            "min_time": 20,
            "prefer_larger_batch": true,
            "ignore_non_elastic_batch_info": false,
            "version": 0.2
        }
    """

    def __init__(self, param_dict: dict):
        self.enabled = param_dict.get(C.ENABLED, C.ENABLED_DEFAULT)
        if self.enabled:
            if C.MAX_ACCEPTABLE_BATCH_SIZE in param_dict:
                self.max_acceptable_batch_size = param_dict[C.MAX_ACCEPTABLE_BATCH_SIZE]
            else:
                raise ElasticityConfigError(f"Elasticity config missing {C.MAX_ACCEPTABLE_BATCH_SIZE}")
            if C.MICRO_BATCHES in param_dict:
                self.micro_batches = param_dict[C.MICRO_BATCHES]
            else:
                raise ElasticityConfigError(f"Elasticity config missing {C.MICRO_BATCHES}")
        else:
            self.max_acceptable_batch_size = param_dict.get(C.MAX_ACCEPTABLE_BATCH_SIZE,
                                                            C.MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT)
            self.micro_batches = param_dict.get(C.MICRO_BATCHES, C.MICRO_BATCHES_DEFAULT)

        if not isinstance(self.micro_batches, list):
            raise ElasticityConfigError(
                f"Elasticity expected value of {C.MICRO_BATCHES} to be a "
                f"list of micro batches, instead is: {type(self.micro_batches)}, containing: {self.micro_batches}")
        if not all(map(lambda m: isinstance(m, int), self.micro_batches)):
            raise ElasticityConfigError(f"Elasticity expected {C.MICRO_BATCHES} to only contain a list of integers, "
                                        f"instead contains: f{self.micro_batches}")
        if not all(map(lambda m: m > 0, self.micro_batches)):
            raise ElasticityConfigError(f"Elasticity expected {C.MICRO_BATCHES} to only contain positive integers, "
                                        f"instead contains: f{self.micro_batches}")

        self.min_gpus = param_dict.get(C.MIN_GPUS, C.MIN_GPUS_DEFAULT)
        self.max_gpus = param_dict.get(C.MAX_GPUS, C.MAX_GPUS_DEFAULT)
        if self.min_gpus < 1 or self.max_gpus < 1:
            raise ElasticityConfigError("Elasticity min/max gpus must be > 0, "
                                        f"given min_gpus: {self.min_gpus}, max_gpus: {self.max_gpus}")
        if self.max_gpus < self.min_gpus:
            raise ElasticityConfigError("Elasticity min_gpus cannot be greater than max_gpus, "
                                        f"given min_gpus: {self.min_gpus}, max_gpus: {self.max_gpus}")

        self.model_parallel_size = param_dict.get(C.MODEL_PARALLEL_SIZE, C.MODEL_PARALLEL_SIZE_DEFAULT)
        if self.model_parallel_size < 1:
            raise ElasticityConfigError("Model-Parallel size cannot be less than 1, "
                                        f"given model-parallel size: {self.model_parallel_size}")

        self.num_gpus_per_node = param_dict.get(C.NUM_GPUS_PER_NODE, C.NUM_GPUS_PER_NODE_DEFAULT)
        if self.num_gpus_per_node < 1:
            raise ElasticityConfigError("Number of GPUs per node cannot be less than 1, "
                                        f"given number of GPUs per node: {self.num_gpus_per_node}")

        self.min_time = param_dict.get(C.MIN_TIME, C.MIN_TIME_DEFAULT)
        if self.min_time < 0:
            raise ElasticityConfigError(f"Elasticity min time needs to be >= 0: given {self.min_time}")

        self.version = param_dict.get(C.VERSION, C.ELASTICITY_DEFAULT_VERSION)
        self.prefer_larger_batch_size = param_dict.get(C.PREFER_LARGER_BATCH, C.PREFER_LARGER_BATCH_DEFAULT)
        self.ignore_non_elastic_batch_info = param_dict.get(C.IGNORE_NON_ELASTIC_BATCH_INFO,
                                                            C.IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)

    def repr(self):
        return self.__dict__

    def __repr__(self):
        return json.dumps(self.__dict__, sort_keys=True, indent=4)
