"""Elastic agent: worker monitoring + restart on failure/membership change.

Reference parity: ``deepspeed/elasticity/elastic_agent.py:25,115``
(``DSElasticAgent(LocalElasticAgent)`` — torch-elastic integration that
monitors local workers, restarts the group when membership changes, and
injects the DeepSpeed env; enabled from ``launcher/launch.py`` when
torch-elastic compatible).

TPU redesign: there is no torch-elastic runtime to subclass, and TPU pods
restart at slice granularity — so the agent is a self-contained supervisor:

- spawn ``local_world_size`` worker processes with the full distributed env
  (same block :func:`deepspeed_tpu.launcher.launch.build_rank_env` builds);
- poll at ``monitor_interval``; all-zero exits → SUCCEEDED;
- on any failure: kill the group, re-evaluate capacity via ``capacity_fn``
  (healthy local slots — the analogue of the rendezvous membership set),
  validate the new world against the elastic plan
  (:func:`deepspeed_tpu.elasticity.compute_elastic_config` — batch sizes
  stay consistent across scale events, reference ``elasticity.py:231``),
  and restart. Scale-DOWN events do not count against ``max_restarts``
  (the failure is explained by lost capacity); everything else does,
  mirroring the reference's "scaling events get the same attempt #".
"""

from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import sys
import time
from enum import Enum
from typing import Callable, Dict, List, Optional, Sequence

from deepspeed_tpu.launcher.launch import build_rank_env
from deepspeed_tpu.utils.logging import logger


class WorkerState(str, Enum):
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"


@dataclasses.dataclass
class RunResult:
    state: WorkerState
    return_codes: List[int]
    restarts: int


@dataclasses.dataclass
class WorkerSpec:
    """What to run (reference ``WorkerSpec``): ``entrypoint`` argv; the
    agent appends nothing — rank identity arrives via env."""
    entrypoint: Sequence[str]
    local_world_size: int
    max_restarts: int = 3
    monitor_interval: float = 0.2
    master_addr: str = "127.0.0.1"
    master_port: int = 29500


class DSElasticAgent:
    """Single-node elastic supervisor (multi-node composition happens at the
    runner level, one agent per node, like the reference's per-node
    LocalElasticAgent)."""

    def __init__(self, spec: WorkerSpec, env: Optional[Dict[str, str]] = None,
                 ds_config: Optional[dict] = None,
                 capacity_fn: Optional[Callable[[], int]] = None):
        self.spec = spec
        self.ds_env = dict(env or {})
        self.ds_config = ds_config
        # membership probe: how many local workers can run right now
        self.capacity_fn = capacity_fn or (lambda: spec.local_world_size)
        self._procs: List[subprocess.Popen] = []

    # -------------------- group lifecycle -------------------- #

    def _admissible_world(self, capacity: int) -> int:
        """Largest world size <= capacity valid under the elastic plan."""
        if capacity < 1:
            # a zero-worker group would vacuously "succeed" without running
            raise RuntimeError(f"no capacity ({capacity}) to run any worker")
        if not self.ds_config:
            return capacity
        from deepspeed_tpu.elasticity import compute_elastic_config
        _, valid_worlds = compute_elastic_config(self.ds_config)
        fitting = [w for w in valid_worlds if w <= capacity]
        if not fitting:
            raise RuntimeError(
                f"no elastic-valid world size fits capacity {capacity} "
                f"(valid: {valid_worlds})")
        return max(fitting)

    def _start_group(self, world: int, restart_count: int) -> None:
        world_info = {"localhost": list(range(world))}
        self._procs = []
        for lr in range(world):
            env = os.environ.copy()
            env.update(self.ds_env)
            env.update(build_rank_env(world_info, 0, lr,
                                      self.spec.master_addr,
                                      self.spec.master_port))
            env["DSTPU_RESTART_COUNT"] = str(restart_count)
            env["DSTPU_MAX_RESTARTS"] = str(self.spec.max_restarts)
            self._procs.append(subprocess.Popen(
                list(self.spec.entrypoint), env=env))
        logger.info(f"elastic agent: started {world} workers "
                    f"(attempt {restart_count})")

    def _stop_group(self) -> None:
        for p in self._procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except ProcessLookupError:
                    pass
        deadline = time.time() + 5.0
        for p in self._procs:
            timeout = max(0.0, deadline - time.time())
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()  # reap, so poll() reports the kill instead of None

    def _monitor(self) -> Optional[List[int]]:
        """None while running; exit codes once every worker has exited or
        any worker has failed (the group is then stopped)."""
        codes = [p.poll() for p in self._procs]
        if any(c not in (None, 0) for c in codes):
            self._stop_group()
            return [p.poll() for p in self._procs]
        if all(c is not None for c in codes):
            return codes
        return None

    # -------------------- run loop -------------------- #

    def run(self) -> RunResult:
        restart_count = 0
        capacity = self.capacity_fn()  # probe errors propagate (caller bug)
        try:
            world = self._admissible_world(capacity)
        except RuntimeError as e:
            # no admissible world at startup -> a failed result, not a crash
            logger.error(f"elastic agent: {e}")
            return RunResult(WorkerState.FAILED, [], 0)
        self._start_group(world, restart_count)
        while True:
            time.sleep(self.spec.monitor_interval)
            codes = self._monitor()
            if codes is None:
                continue
            if all(c == 0 for c in codes):
                return RunResult(WorkerState.SUCCEEDED, codes, restart_count)

            new_capacity = self.capacity_fn()
            try:
                new_world = self._admissible_world(new_capacity)
            except RuntimeError:
                logger.error("elastic agent: no admissible world size left")
                return RunResult(WorkerState.FAILED, codes, restart_count)

            # only a genuine scale-DOWN is a free attempt (the failure is
            # explained by lost capacity); anything else — same-capacity
            # crashes, flapping, scale-up — consumes restart budget, so a
            # crashing job can't loop forever behind capacity noise
            scaled = new_world < world
            if not scaled:
                restart_count += 1
            if restart_count > self.spec.max_restarts:
                logger.error(f"elastic agent: exceeded max_restarts "
                             f"({self.spec.max_restarts})")
                return RunResult(WorkerState.FAILED, codes, restart_count)

            logger.warning(
                f"elastic agent: workers failed {codes}; "
                f"{'rescaling to ' + str(new_world) if scaled else 'restarting'}"
                f" (attempt {restart_count}/{self.spec.max_restarts})")
            world = new_world
            self._start_group(world, restart_count)
