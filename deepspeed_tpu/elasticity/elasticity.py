"""Elastic batch-size planning (reference ``elasticity/elasticity.py``).

Given a maximum acceptable global batch size and a set of valid micro-batch
sizes, enumerate the composite global batch sizes that stay valid across a
range of chip counts — so training can resume after a world-size change
without changing effective hyperparameters. Algorithms follow the
reference's v0.1 (``:81``) and v0.2 (``:124``, adds
``num_gpus_per_node``-divisibility) semantics.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.config import constants as C
from deepspeed_tpu.elasticity.config import (ElasticityConfig, ElasticityConfigError,
                                             ElasticityError, ElasticityIncompatibleWorldSize)

# The 38 smallest highly composite numbers — enough to scale candidate
# batch sizes up to ~720K (the reference plans over the same constant set)
HCN_LIST = [
    1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260, 1680,
    2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360, 50400, 55440,
    83160, 110880, 166320, 221760, 277200, 332640, 498960, 554400, 665280,
    720720,
]


def get_candidate_batch_sizes(base_list: List[int], max_acceptable_batch_size: int) -> List[int]:
    """One candidate per base: the base scaled by the largest highly
    composite number that keeps it ≤ max (reference ``:25-37``) — HCN
    scaling maximizes the divisor count, hence the valid chip counts."""
    candidates = set()
    for base in base_list:
        if base >= max_acceptable_batch_size:
            candidates.add(base)
            continue
        limit = max_acceptable_batch_size // base
        hcn = max(h for h in HCN_LIST if h <= limit)
        candidates.add(hcn * base)
    return sorted(candidates)


def get_valid_gpus(batch_size: int, micro_batches: List[int],
                   min_valid_gpus: int, max_valid_gpus: int) -> List[int]:
    """Chip counts g in range where batch = m * gas * g for some micro-batch
    m — i.e. the divisors of batch/m (factor search in the reference,
    ``:39-58``; identical set, enumerated by range here)."""
    valid = []
    for g in range(min_valid_gpus, max_valid_gpus + 1):
        if any(batch_size % (g * m) == 0 for m in micro_batches):
            valid.append(g)
    return valid


def get_best_candidates(candidate_batch_sizes: List[int], micro_batches: List[int],
                        min_gpus: int, max_gpus: int, prefer_larger: bool):
    """The candidate with the most valid chip counts (ties → batch-size
    preference), reference ``:61-80``."""
    max_valid_gpus = 0
    best_batch = int(min(micro_batches))
    best_gpus = None
    for batch in candidate_batch_sizes:
        valid = get_valid_gpus(batch, micro_batches, min_gpus, max_gpus)
        if (len(valid) > max_valid_gpus
                or (len(valid) == max_valid_gpus
                    and ((prefer_larger and batch > best_batch)
                         or (not prefer_larger and batch < best_batch)))):
            max_valid_gpus = len(valid)
            best_batch = batch
            best_gpus = valid
    return best_batch, best_gpus


def _get_compatible_gpus_v01(micro_batches: List[int], max_acceptable_batch_size: int,
                             min_gpus: int, max_gpus: int, prefer_larger: bool):
    import math
    min_gpus = min_gpus or 1
    max_gpus = max_gpus or max_acceptable_batch_size // min(micro_batches)
    if not all(mb <= max_acceptable_batch_size for mb in micro_batches):
        raise ElasticityConfigError(
            f"All micro batches {micro_batches} must be <= "
            f"max_acceptable_batch_size {max_acceptable_batch_size}")
    # bases: each micro batch AND their lcm (reference ``:110-114``)
    lcm = math.lcm(*micro_batches)
    candidates = get_candidate_batch_sizes(list(micro_batches) + [lcm],
                                           max_acceptable_batch_size)
    return get_best_candidates(candidates, micro_batches, min_gpus, max_gpus, prefer_larger)


def _get_compatible_gpus_v02(micro_batches: List[int], max_acceptable_batch_size: int,
                             min_gpus: int, max_gpus: int, prefer_larger: bool,
                             num_gpus_per_node: int, model_parallel_size: int):
    """v0.2: chip counts are whole multiples of chips-per-node. The search
    runs at NODE granularity on a per-node-DP-scaled max batch, then the
    result is scaled back up — so the final batch stays divisible by every
    valid chip-level DP count (reference ``:124-188``)."""
    if num_gpus_per_node % model_parallel_size != 0:
        raise ElasticityConfigError(
            f"model_parallel_size {model_parallel_size} must divide "
            f"num_gpus_per_node {num_gpus_per_node}")
    dp_size_per_node = num_gpus_per_node // model_parallel_size

    per_node_batch, valid_nodes = _get_compatible_gpus_v01(
        micro_batches,
        max_acceptable_batch_size // dp_size_per_node,
        min_gpus=max(1, min_gpus // num_gpus_per_node),
        max_gpus=max(1, max_gpus // num_gpus_per_node),
        prefer_larger=prefer_larger)
    if not valid_nodes:
        return per_node_batch, []
    final_batch = per_node_batch * dp_size_per_node
    valid_gpus = [n * num_gpus_per_node for n in (valid_nodes or [])]
    return final_batch, valid_gpus


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "",
                           world_size: int = 0, return_microbatch: bool = False):
    """Resolve the elastic plan (reference ``:231``).

    Without ``world_size``: returns ``(final_batch_size, valid_world_sizes)``.
    With ``world_size``: returns ``(final_batch_size, micro_batch, gas)`` —
    or with ``return_microbatch`` the chosen micro batch alone.
    """
    elastic_config_dict = ds_config.get(C.ELASTICITY, {})
    elastic_config = ElasticityConfig(elastic_config_dict)
    if not elastic_config.enabled:
        raise ElasticityError("Elasticity is not enabled in the provided config")

    if float(elastic_config.version) == 0.1:
        final_batch_size, valid_gpus = _get_compatible_gpus_v01(
            elastic_config.micro_batches, elastic_config.max_acceptable_batch_size,
            elastic_config.min_gpus, elastic_config.max_gpus,
            elastic_config.prefer_larger_batch_size)
    elif float(elastic_config.version) == 0.2:
        final_batch_size, valid_gpus = _get_compatible_gpus_v02(
            elastic_config.micro_batches, elastic_config.max_acceptable_batch_size,
            elastic_config.min_gpus, elastic_config.max_gpus,
            elastic_config.prefer_larger_batch_size, elastic_config.num_gpus_per_node,
            elastic_config.model_parallel_size)
    else:
        raise ElasticityConfigError(f"Unknown elasticity version {elastic_config.version}")

    if final_batch_size is None or not valid_gpus:
        raise ElasticityError(
            f"No valid batch size found for micro batches {elastic_config.micro_batches} "
            f"within max batch {elastic_config.max_acceptable_batch_size}")

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"World size {world_size} is not valid for this elastic config; "
                f"valid world sizes: {valid_gpus}")
        # pick the largest micro batch that divides the per-replica batch
        dp = world_size // elastic_config.model_parallel_size if float(
            elastic_config.version) == 0.2 else world_size
        if final_batch_size % dp != 0:
            raise ElasticityIncompatibleWorldSize(
                f"batch {final_batch_size} does not divide across dp={dp}")
        per_replica = final_batch_size // dp
        candidates = [m for m in elastic_config.micro_batches if per_replica % m == 0]
        if not candidates:
            raise ElasticityIncompatibleWorldSize(
                f"no micro batch in {elastic_config.micro_batches} divides the "
                f"per-replica batch {per_replica} at world size {world_size}")
        micro = (max(candidates) if elastic_config.prefer_larger_batch_size
                 else min(candidates))
        gas = per_replica // micro
        if return_microbatch:
            return micro
        return final_batch_size, micro, gas

    return final_batch_size, valid_gpus


def ensure_immutable_elastic_config(runtime_elastic_config_dict: Dict) -> None:
    """Guard against changing the elastic config mid-job via env-propagated
    snapshot (reference ``:202-230``)."""
    import json
    import os

    env_key = "DEEPSPEED_ELASTICITY_CONFIG"
    if env_key in os.environ:
        scheduler_config = json.loads(os.environ[env_key])
        if scheduler_config != runtime_elastic_config_dict:
            raise ElasticityConfigError(
                "Elastic config changed between scheduler and runtime; "
                "this would corrupt elastic checkpoints")
    else:
        os.environ[env_key] = json.dumps(runtime_elastic_config_dict)
