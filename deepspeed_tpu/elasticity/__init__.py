"""Elasticity: batch-size planning valid across changing chip counts
(reference ``deepspeed/elasticity/``)."""

from deepspeed_tpu.elasticity.config import (ElasticityConfig, ElasticityConfigError,
                                             ElasticityError,
                                             ElasticityIncompatibleWorldSize)
from deepspeed_tpu.elasticity.elasticity import (compute_elastic_config,
                                                 ensure_immutable_elastic_config,
                                                 get_candidate_batch_sizes,
                                                 get_valid_gpus)
from deepspeed_tpu.elasticity.elastic_agent import (DSElasticAgent, RunResult,
                                                    WorkerSpec, WorkerState)

__all__ = [
    "ElasticityConfig", "ElasticityError", "ElasticityConfigError",
    "ElasticityIncompatibleWorldSize", "compute_elastic_config",
    "ensure_immutable_elastic_config", "get_candidate_batch_sizes", "get_valid_gpus",
    "DSElasticAgent", "WorkerSpec", "WorkerState", "RunResult",
]
