"""Compression entry points (reference ``compression/compress.py``:
``init_compression`` ``:92`` / ``redundancy_clean`` ``:120``).

The reference rewrites ``nn.Module``s in place; here compression is a
functional wrapper: :func:`init_compression` returns a model whose loss/
forward transparently applies the configured QAT fake-quant + pruning to
matching parameters (matched by dotted-path substring, the analogue of the
reference's ``different_groups`` module-name patterns), and
:func:`redundancy_clean` burns the transforms into the param tree for
deployment.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from deepspeed_tpu.compression import functional as F
from deepspeed_tpu.compression.config import (ACTIVATION_QUANTIZATION, CHANNEL_PRUNING,
                                              DIFFERENT_GROUPS, HEAD_PRUNING, ROW_PRUNING,
                                              SHARED_PARAMETERS, SPARSE_PRUNING,
                                              WEIGHT_QUANTIZATION, get_compression_config)
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.pytree import leaf_key

_TECHNIQUES = (WEIGHT_QUANTIZATION, SPARSE_PRUNING, ROW_PRUNING, HEAD_PRUNING, CHANNEL_PRUNING)


class _GroupRule:
    """One ``different_groups`` entry: which params + which technique params."""

    def __init__(self, technique: str, name: str, params: Dict, modules: List[str]):
        self.technique = technique
        self.name = name
        self.params = params
        self.modules = modules  # substring patterns over dotted param paths; ["*"] = all

    def matches(self, dotted: str) -> bool:
        return any(m == "*" or m in dotted for m in self.modules)


def _collect_rules(compression_config: Dict) -> List[_GroupRule]:
    rules: List[_GroupRule] = []
    act = compression_config.get(ACTIVATION_QUANTIZATION, {})
    if act.get(SHARED_PARAMETERS, act).get("enabled", False):
        logger.warning(
            "activation_quantization is configured but not applied: functional "
            "param-tree compression cannot inject activation hooks from outside "
            "the model. Call compression.functional.quantize_activation inside "
            "the model's forward (or request it via TransformerConfig) instead.")
    for technique in _TECHNIQUES:
        tcfg = compression_config.get(technique, {})
        shared = tcfg.get(SHARED_PARAMETERS, tcfg)
        if not shared.get("enabled", False):
            continue
        groups = tcfg.get(DIFFERENT_GROUPS, {})
        if not groups:
            continue
        for gname, gcfg in groups.items():
            params = dict(gcfg.get("params", {}))
            params["schedule_offset"] = shared.get("schedule_offset", 0)
            params.update({k: v for k, v in shared.items()
                           if k not in ("enabled", DIFFERENT_GROUPS)})
            modules = gcfg.get("modules", ["*"])
            rules.append(_GroupRule(technique, gname, params, modules))
    return rules


def _apply_rule(technique: str, w, params: Dict):
    if technique == WEIGHT_QUANTIZATION:
        bits = int(params.get("start_bits", params.get("target_bits", 8)))
        sym = params.get("quantization_type", "symmetric") == "symmetric"
        groups = int(params.get("quantize_groups", 1))
        return F.fake_quantize(w, bits, sym, groups)
    if technique == SPARSE_PRUNING:
        return F.prune(w, "sparse", float(params.get("dense_ratio", 0.5)))
    if technique == ROW_PRUNING:
        return F.prune(w, "row", float(params.get("dense_ratio", 0.5)))
    if technique == CHANNEL_PRUNING:
        return F.prune(w, "channel", float(params.get("dense_ratio", 0.5)))
    if technique == HEAD_PRUNING:
        return F.prune(w, "head", float(params.get("dense_ratio", 0.5)),
                       num_heads=int(params.get("num_heads", 1)))
    return w


class CompressedModel:
    """Wraps a model: the configured transforms are applied to matching
    params (per the scheduler's active set) before every forward/loss."""

    def __init__(self, model, compression_config: Dict):
        self.model = model
        self.config = compression_config
        self.rules = _collect_rules(compression_config)
        self._active = {id(r): True for r in self.rules}  # scheduler toggles
        if hasattr(model, "config"):
            self.config_model = model.config

    def set_active(self, rule: _GroupRule, active: bool) -> None:
        self._active[id(rule)] = active

    def compress_params(self, params):
        """Apply every active transform to its matching leaves."""
        active_rules = [r for r in self.rules if self._active.get(id(r), True)]
        if not active_rules:
            return params

        def transform(path, leaf):
            dotted = leaf_key(path)
            for rule in active_rules:
                if rule.matches(dotted) and leaf.ndim >= 2:
                    leaf = _apply_rule(rule.technique, leaf, rule.params)
            return leaf

        return jax.tree_util.tree_map_with_path(transform, params)

    # model-protocol passthrough. The engine adapts to the model's arity
    # (some losses take an rng, some don't) — forward only what the wrapped
    # model accepts so the adapter sees the true signature through us.
    def loss(self, params, batch, *args, **kwargs):
        import inspect
        try:
            n_extra = len(inspect.signature(self.model.loss).parameters) - 2
        except (TypeError, ValueError):
            n_extra = len(args)
        return self.model.loss(self.compress_params(params), batch,
                               *args[:max(0, n_extra)], **kwargs)

    def forward(self, params, *args, **kwargs):
        return self.model.forward(self.compress_params(params), *args, **kwargs)

    def __call__(self, params, *args, **kwargs):
        return self.forward(params, *args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.model, name)


def init_compression(model, deepspeed_config, mpu=None):
    """Reference ``init_compression`` (``compress.py:92``): returns the
    compression-wrapped model. ``deepspeed_config``: dict or path."""
    import json
    if isinstance(deepspeed_config, str):
        with open(deepspeed_config) as f:
            deepspeed_config = json.load(f)
    ccfg = get_compression_config(deepspeed_config)
    wrapped = CompressedModel(model, ccfg)
    logger.info(f"init_compression: {len(wrapped.rules)} compression group(s) active")
    return wrapped


def redundancy_clean(model_or_params, deepspeed_config, mpu=None):
    """Reference ``redundancy_clean`` (``compress.py:120``): burn the
    transforms into the params for deployment. Takes the raw param tree +
    the ds config (NOT a CompressedModel — pass ``engine.state.params``)."""
    import json
    if isinstance(deepspeed_config, str):
        with open(deepspeed_config) as f:
            deepspeed_config = json.load(f)
    if isinstance(model_or_params, CompressedModel):
        raise ValueError("pass the param tree: redundancy_clean(params, config)")
    ccfg = get_compression_config(deepspeed_config)
    shell = CompressedModel(model=None, compression_config=ccfg)
    return shell.compress_params(model_or_params)
