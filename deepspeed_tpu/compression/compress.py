"""Compression entry points (reference ``compression/compress.py``:
``init_compression`` ``:92`` / ``redundancy_clean`` ``:120``).

The reference rewrites ``nn.Module``s in place; here compression is a
functional wrapper: :func:`init_compression` returns a model whose loss/
forward transparently applies the configured QAT fake-quant + pruning to
matching parameters (matched by dotted-path substring, the analogue of the
reference's ``different_groups`` module-name patterns), and
:func:`redundancy_clean` burns the transforms into the param tree for
deployment.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from deepspeed_tpu.compression import functional as F
from deepspeed_tpu.compression.config import (ACTIVATION_QUANTIZATION, CHANNEL_PRUNING,
                                              DIFFERENT_GROUPS, HEAD_PRUNING,
                                              LAYER_REDUCTION, ROW_PRUNING,
                                              SHARED_PARAMETERS, SPARSE_PRUNING,
                                              WEIGHT_QUANTIZATION, get_compression_config)
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.pytree import leaf_key

_TECHNIQUES = (WEIGHT_QUANTIZATION, SPARSE_PRUNING, ROW_PRUNING, HEAD_PRUNING, CHANNEL_PRUNING)


class _GroupRule:
    """One ``different_groups`` entry: which params + which technique params."""

    def __init__(self, technique: str, name: str, params: Dict, modules: List[str]):
        self.technique = technique
        self.name = name
        self.params = params
        self.modules = modules  # substring patterns over dotted param paths; ["*"] = all

    def matches(self, dotted: str) -> bool:
        return any(m == "*" or m in dotted for m in self.modules)


def _collect_rules(compression_config: Dict) -> List[_GroupRule]:
    rules: List[_GroupRule] = []
    for technique in _TECHNIQUES:
        tcfg = compression_config.get(technique, {})
        shared = tcfg.get(SHARED_PARAMETERS, tcfg)
        if not shared.get("enabled", False):
            continue
        groups = tcfg.get(DIFFERENT_GROUPS, {})
        if not groups:
            continue
        for gname, gcfg in groups.items():
            params = dict(gcfg.get("params", {}))
            params["schedule_offset"] = shared.get("schedule_offset", 0)
            params.update({k: v for k, v in shared.items()
                           if k not in ("enabled", DIFFERENT_GROUPS)})
            modules = gcfg.get("modules", ["*"])
            rules.append(_GroupRule(technique, gname, params, modules))
    return rules


def _apply_rule(technique: str, w, params: Dict):
    if technique == WEIGHT_QUANTIZATION:
        bits = int(params.get("start_bits", params.get("target_bits", 8)))
        sym = params.get("quantization_type", "symmetric") == "symmetric"
        groups = int(params.get("quantize_groups", 1))
        return F.fake_quantize(w, bits, sym, groups)
    if technique == SPARSE_PRUNING:
        return F.prune(w, "sparse", float(params.get("dense_ratio", 0.5)))
    if technique == ROW_PRUNING:
        return F.prune(w, "row", float(params.get("dense_ratio", 0.5)))
    if technique == CHANNEL_PRUNING:
        return F.prune(w, "channel", float(params.get("dense_ratio", 0.5)))
    if technique == HEAD_PRUNING:
        return F.prune(w, "head", float(params.get("dense_ratio", 0.5)),
                       num_heads=int(params.get("num_heads", 1)))
    return w


def _load_config(deepspeed_config):
    if isinstance(deepspeed_config, str):
        import json
        with open(deepspeed_config) as f:
            return json.load(f)
    return deepspeed_config


class CompressedModel:
    """Wraps a model: the configured transforms are applied to matching
    params (per the scheduler's active set) before every forward/loss."""

    def __init__(self, model, compression_config: Dict):
        self.model = model
        self.config = compression_config
        self.rules = _collect_rules(compression_config)
        self._active = {id(r): True for r in self.rules}  # scheduler toggles
        self.compression_epoch = 0
        self._act_rule = None
        if model is not None:
            # structural rewiring first (layer reduction is not scheduled)
            model = self._rewire(model, self._layer_reduction_changes(compression_config))
            self._plain_model = model
            act_changes, act_rule = self._act_quant_changes(compression_config)
            if act_changes:
                # activation quant is a scheduled technique like the others:
                # it rides self.rules so CompressionScheduler honors its
                # schedule_offset by flipping between the two model variants
                self._act_model = self._rewire(model, act_changes)
                self._act_rule = act_rule
                self.rules.append(act_rule)
                self._active[id(act_rule)] = True
                model = self._act_model
            self.model = model
        if hasattr(model, "config"):
            self.config_model = model.config

    @staticmethod
    def _act_quant_changes(compression_config: Dict):
        """Config-section → TransformerConfig field changes for activation
        fake-quant (reference QuantAct layers, basic_layer.py:118-860)."""
        act = compression_config.get(ACTIVATION_QUANTIZATION, {})
        shared = act.get(SHARED_PARAMETERS, act)
        if not shared.get("enabled", False):
            return {}, None
        groups = act.get(DIFFERENT_GROUPS, {})
        bit_set = {int(g.get("params", {}).get("bits", 8)) for g in groups.values()} or {8}
        if len(bit_set) > 1:
            raise ValueError(
                f"activation_quantization groups request different bit widths "
                f"{sorted(bit_set)}; per-module scoped activation quant is not "
                "supported (the fake-quant applies at every attention/MLP "
                "input) — use one bit width")
        scoped = [m for g in groups.values() for m in g.get("modules", ["*"])
                  if m != "*"]
        if scoped:
            from deepspeed_tpu.utils.logging import warn_once
            warn_once(f"activation_quantization 'modules' patterns {scoped} are "
                      "applied GLOBALLY (every attention/MLP input) — scoped "
                      "activation quant is not supported")
        if str(shared.get("range_calibration", "dynamic")) == "static":
            from deepspeed_tpu.utils.logging import warn_once
            warn_once("activation_quantization range_calibration='static' "
                      "uses dynamic per-tensor ranges here (no calibration "
                      "momentum state in the functional design)")
        changes = dict(act_quant_bits=next(iter(bit_set)),
                       act_quant_sym=shared.get("quantization_type",
                                                "symmetric") == "symmetric")
        rule_params = {k: v for k, v in shared.items()
                       if k not in ("enabled", DIFFERENT_GROUPS)}
        rule_params.setdefault("schedule_offset", 0)
        rule = _GroupRule(ACTIVATION_QUANTIZATION, "activation_quantization",
                          rule_params, ["*"])
        return changes, rule

    @staticmethod
    def _layer_reduction_changes(compression_config: Dict) -> Dict:
        lr = compression_config.get(LAYER_REDUCTION, {})
        if not lr.get("enabled", False):
            return {}
        teacher_layer = list(lr.get("teacher_layer") or [])
        keep = int(lr.get("keep_number_layer", len(teacher_layer)))
        if keep <= 0:
            raise ValueError("layer_reduction needs keep_number_layer "
                             "(or teacher_layer) in the config")
        if teacher_layer and keep != len(teacher_layer):
            raise ValueError(
                f"layer_reduction keep_number_layer={keep} inconsistent with "
                f"teacher_layer (length {len(teacher_layer)}): "
                "student_initialization would reject this config later")
        return {"n_layer": keep}

    @staticmethod
    def _rewire(model, changes: Dict):
        """Apply TransformerConfig field changes on a COPY of the model."""
        import copy
        import dataclasses

        if not changes:
            return model
        if not (hasattr(model, "config")
                and all(hasattr(model.config, k) for k in changes)):
            raise ValueError(
                f"compression config requests model-side rewrites {changes} "
                "but the model has no compatible TransformerConfig; zoo "
                "models (or a config with these fields) are required")
        model = copy.copy(model)
        model.config = dataclasses.replace(model.config, **changes)
        if hasattr(model, "zoo_cfg"):
            # models caching a derived config (BertModel.zoo_cfg) would
            # silently keep computing with the stale one
            if not hasattr(model.config, "zoo"):
                raise ValueError(
                    f"cannot rewire {type(model).__name__}: it caches a "
                    "derived zoo_cfg its config cannot rebuild")
            model.zoo_cfg = model.config.zoo()
        return model

    def set_active(self, rule: _GroupRule, active: bool) -> None:
        if self._active.get(id(rule)) != active:
            # compiled programs captured the old active set at trace time;
            # bumping the epoch tells the engine to drop them (train_batch
            # checks client_model.compression_epoch)
            self.compression_epoch += 1
        self._active[id(rule)] = active
        if rule is self._act_rule:
            self.model = self._act_model if active else self._plain_model

    def compress_params(self, params):
        """Apply every active transform to its matching leaves."""
        active_rules = [r for r in self.rules if self._active.get(id(r), True)]
        if not active_rules:
            return params

        def transform(path, leaf):
            dotted = leaf_key(path)
            for rule in active_rules:
                if rule.matches(dotted) and leaf.ndim >= 2:
                    leaf = _apply_rule(rule.technique, leaf, rule.params)
            return leaf

        return jax.tree_util.tree_map_with_path(transform, params)

    # model-protocol passthrough. The engine adapts to the model's arity
    # (some losses take an rng, some don't) — forward only what the wrapped
    # model accepts so the adapter sees the true signature through us.
    def loss(self, params, batch, *args, **kwargs):
        import inspect
        try:
            n_extra = len(inspect.signature(self.model.loss).parameters) - 2
        except (TypeError, ValueError):
            n_extra = len(args)
        return self.model.loss(self.compress_params(params), batch,
                               *args[:max(0, n_extra)], **kwargs)

    def forward(self, params, *args, **kwargs):
        return self.model.forward(self.compress_params(params), *args, **kwargs)

    def __call__(self, params, *args, **kwargs):
        return self.forward(params, *args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.model, name)


def init_compression(model, deepspeed_config, mpu=None):
    """Reference ``init_compression`` (``compress.py:92``): returns the
    compression-wrapped model. ``deepspeed_config``: dict or path."""
    ccfg = get_compression_config(_load_config(deepspeed_config))
    wrapped = CompressedModel(model, ccfg)
    logger.info(f"init_compression: {len(wrapped.rules)} compression group(s) active")
    return wrapped


def redundancy_clean(model_or_params, deepspeed_config, mpu=None):
    """Reference ``redundancy_clean`` (``compress.py:120``): burn the
    transforms into the params for deployment. Takes the raw param tree +
    the ds config (NOT a CompressedModel — pass ``engine.state.params``)."""
    deepspeed_config = _load_config(deepspeed_config)
    if isinstance(model_or_params, CompressedModel):
        raise ValueError("pass the param tree: redundancy_clean(params, config)")
    ccfg = get_compression_config(deepspeed_config)
    shell = CompressedModel(model=None, compression_config=ccfg)
    return shell.compress_params(model_or_params)


def student_initialization(student_params, teacher_params, deepspeed_config):
    """Layer-reduction student init (reference ``compression/compress.py:164``
    ``student_initialization``): re-initialize the student's stacked layers
    from the configured teacher layers, and copy the non-layer modules.

    Works on zoo param TREES (layers stacked on the leading axis) instead of
    nn.Modules: ``teacher_layer`` indexes the teacher's layer axis;
    ``other_module_name`` lists top-level subtrees to copy verbatim
    (default: every non-"layers" top-level entry, i.e. embed/ln_f/lm_head).
    Returns a new student tree; inputs are not mutated.
    """
    import numpy as np

    lr = get_compression_config(_load_config(deepspeed_config)).get(LAYER_REDUCTION, {})
    if not lr.get("enabled", False):
        raise ValueError("student_initialization needs compression_training."
                         "layer_reduction.enabled=true")
    teacher_layer = list(lr.get("teacher_layer") or [])
    if not teacher_layer:
        raise ValueError("layer_reduction.teacher_layer is required")
    if "layers" not in student_params or "layers" not in teacher_params:
        raise ValueError("student_initialization expects zoo param trees "
                         "with a stacked 'layers' subtree")
    n_student = jax.tree.leaves(student_params["layers"])[0].shape[0]
    if n_student != len(teacher_layer):
        raise ValueError(f"student has {n_student} layers but teacher_layer "
                         f"names {len(teacher_layer)} source layers")

    idx = np.asarray(teacher_layer, np.int64)
    out = dict(student_params)
    out["layers"] = jax.tree.map(lambda a: np.asarray(a)[idx],
                                 teacher_params["layers"])
    others = lr.get("other_module_name")
    if others is None:
        others = [k for k in teacher_params if k != "layers"]
    for name in others:
        if name not in teacher_params:
            raise KeyError(f"other_module_name entry {name!r} not in the "
                           f"teacher tree (has {sorted(teacher_params)})")
        if name not in student_params:
            raise KeyError(f"other_module_name entry {name!r} not in the "
                           f"student tree (has {sorted(student_params)}); "
                           "a silently skipped module would train from "
                           "random init")
        out[name] = teacher_params[name]
    return out
