"""Compression primitives (reference ``compression/basic_layer.py`` +
``compression/utils.py`` re-designed functionally).

The reference implements compression as stateful ``nn.Module`` subclasses
(``LinearLayer_Compress`` etc., ``basic_layer.py:118-860``). In a functional
param-tree world the same math becomes pure transforms:

- QAT fake quantization (symmetric/asymmetric, per-tensor or grouped) with a
  straight-through-estimator gradient (``custom_vjp``: identity backward)
- magnitude pruning masks: unstructured (sparse), row, channel (column),
  and attention-head granularity

All are jittable; XLA fuses the quant/dequant into adjacent matmuls on TPU.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


# ------------------------------------------------------------------ #
# fake quantization (QAT) with straight-through estimator

def _quant_dequant(w, bits: int, symmetric: bool, groups: int):
    """Quantize → dequantize in fp32 (the non-differentiable core)."""
    orig_shape = w.shape
    flat = w.astype(jnp.float32).reshape(groups, -1)
    qmax = 2.0 ** (bits - 1) - 1  # symmetric range
    if symmetric:
        scale = jnp.max(jnp.abs(flat), axis=1, keepdims=True) / qmax
        scale = jnp.where(scale == 0, 1.0, scale)
        q = jnp.clip(jnp.round(flat / scale), -qmax - 1, qmax)
        out = q * scale
    else:
        lo = jnp.min(flat, axis=1, keepdims=True)
        hi = jnp.max(flat, axis=1, keepdims=True)
        levels = 2.0**bits - 1
        scale = jnp.where(hi > lo, (hi - lo) / levels, 1.0)
        q = jnp.clip(jnp.round((flat - lo) / scale), 0, levels)
        out = q * scale + lo
    return out.reshape(orig_shape)


import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def fake_quantize(w, bits: int = 8, symmetric: bool = True, groups: int = 1):
    return _quant_dequant(w, bits, symmetric, groups).astype(w.dtype)


def _fq_fwd(w, bits, symmetric, groups):
    return fake_quantize(w, bits, symmetric, groups), None


def _fq_bwd(bits, symmetric, groups, _, g):
    # straight-through estimator: gradient passes through the rounding
    return (g,)


fake_quantize.defvjp(_fq_fwd, _fq_bwd)


def quantize_activation(x, bits: int = 8, symmetric: bool = True):
    """Dynamic-range activation fake-quant (per-tensor)."""
    return fake_quantize(x, bits, symmetric, 1)


# ------------------------------------------------------------------ #
# pruning masks (all return same-shape 0/1 masks; "l1" = magnitude)

def sparse_mask(w, dense_ratio: float) -> jnp.ndarray:
    """Unstructured magnitude mask keeping the top ``dense_ratio`` fraction."""
    flat = jnp.abs(w).ravel()
    k = max(1, int(flat.size * dense_ratio))
    threshold = jnp.sort(flat)[-k]
    return (jnp.abs(w) >= threshold).astype(w.dtype)


def row_mask(w, dense_ratio: float) -> jnp.ndarray:
    """Keep the top rows (output neurons) by L1 norm; w [in, out] → mask over
    dim 1 broadcast to w's shape (reference row pruning prunes weight rows
    feeding the next layer)."""
    norms = jnp.sum(jnp.abs(w), axis=0)
    k = max(1, int(norms.size * dense_ratio))
    threshold = jnp.sort(norms)[-k]
    keep = (norms >= threshold).astype(w.dtype)
    return jnp.broadcast_to(keep[None, :], w.shape)


def channel_mask(w, dense_ratio: float) -> jnp.ndarray:
    """Keep the top input channels by L1 norm; mask over dim 0."""
    norms = jnp.sum(jnp.abs(w), axis=tuple(range(1, w.ndim)))
    k = max(1, int(norms.size * dense_ratio))
    threshold = jnp.sort(norms)[-k]
    keep = (norms >= threshold).astype(w.dtype)
    return jnp.broadcast_to(keep.reshape((-1,) + (1,) * (w.ndim - 1)), w.shape)


def head_mask(w, num_heads: int, dense_ratio: float) -> jnp.ndarray:
    """Keep the top attention heads by L1 norm of their output-projection
    slices; w [H*Hd, D] (attention output weight) → per-head mask."""
    in_dim = w.shape[0]
    head_dim = in_dim // num_heads
    per_head = jnp.sum(jnp.abs(w.reshape(num_heads, head_dim, -1)), axis=(1, 2))
    k = max(1, int(num_heads * dense_ratio))
    threshold = jnp.sort(per_head)[-k]
    keep = (per_head >= threshold).astype(w.dtype)
    return jnp.broadcast_to(keep[:, None, None], (num_heads, head_dim, w.shape[1])).reshape(w.shape)


_MASK_FNS = {"sparse": sparse_mask, "row": row_mask, "channel": channel_mask}


def prune(w, method: str, dense_ratio: float, num_heads: Optional[int] = None):
    """Apply a pruning mask (STE-free: masks are recomputed each call during
    training, then frozen by redundancy_clean)."""
    if method == "head":
        return w * head_mask(w, num_heads, dense_ratio)
    return w * _MASK_FNS[method](w, dense_ratio)
