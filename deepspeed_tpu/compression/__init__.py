"""Compression (reference ``deepspeed/compression/``): QAT quantization,
structured/unstructured pruning, schedule-gated activation, and
redundancy_clean for deployment."""

from deepspeed_tpu.compression.compress import (CompressedModel, init_compression,
                                                redundancy_clean,
                                                student_initialization)
from deepspeed_tpu.compression.config import get_compression_config
from deepspeed_tpu.compression.functional import (channel_mask, fake_quantize, head_mask,
                                                  prune, quantize_activation, row_mask,
                                                  sparse_mask)
from deepspeed_tpu.compression.scheduler import CompressionScheduler

__all__ = [
    "init_compression", "redundancy_clean", "student_initialization",
    "CompressedModel", "CompressionScheduler",
    "get_compression_config", "fake_quantize", "quantize_activation", "prune",
    "sparse_mask", "row_mask", "channel_mask", "head_mask",
]
