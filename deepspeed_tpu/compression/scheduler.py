"""Compression scheduler (reference ``compression/scheduler.py``): activates
each technique group once training passes its ``schedule_offset`` (and
deactivates after ``schedule_offset_end`` when set). Stepped from the engine
every global step (reference hook ``runtime/engine.py:1668,1974``).
"""

from __future__ import annotations

from typing import Dict

from deepspeed_tpu.compression.compress import CompressedModel
from deepspeed_tpu.utils.logging import logger


class CompressionScheduler:

    def __init__(self, model: CompressedModel):
        if not isinstance(model, CompressedModel):
            raise TypeError("CompressionScheduler requires an init_compression()-wrapped model")
        self.model = model
        self.training_steps = 0
        self._announced: Dict[int, bool] = {}
        self._refresh()

    def _refresh(self) -> None:
        for rule in self.model.rules:
            offset = int(rule.params.get("schedule_offset", 0))
            end = rule.params.get("schedule_offset_end")
            active = self.training_steps >= offset and (
                end is None or self.training_steps <= int(end))
            self.model.set_active(rule, active)
            if active and not self._announced.get(id(rule)):
                logger.info(f"compression group '{rule.name}' ({rule.technique}) "
                            f"activated at step {self.training_steps}")
                self._announced[id(rule)] = True

    def step(self, step_zero_check: bool = False) -> None:
        if not step_zero_check:
            self.training_steps += 1
        self._refresh()

    def state_dict(self) -> Dict:
        return {"training_steps": self.training_steps}

    def load_state_dict(self, sd: Dict) -> None:
        self.training_steps = sd["training_steps"]
        self._refresh()
