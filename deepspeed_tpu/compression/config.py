"""Compression config (reference: deepspeed/compression/config.py).

Returns nested dicts keyed like the reference JSON schema (weight
quantization, activation quantization, sparse/row/head/channel pruning,
layer reduction) with defaults filled in.
"""

from __future__ import annotations

import copy

COMPRESSION_TRAINING = "compression_training"

WEIGHT_QUANTIZATION = "weight_quantization"
ACTIVATION_QUANTIZATION = "activation_quantization"
SPARSE_PRUNING = "sparse_pruning"
ROW_PRUNING = "row_pruning"
HEAD_PRUNING = "head_pruning"
CHANNEL_PRUNING = "channel_pruning"
LAYER_REDUCTION = "layer_reduction"

SHARED_PARAMETERS = "shared_parameters"
DIFFERENT_GROUPS = "different_groups"

TECHNIQUE_ENABLED = "enabled"
TECHNIQUE_SCHEDULE_OFFSET = "schedule_offset"
TECHNIQUE_SCHEDULE_OFFSET_END = "schedule_offset_end"

_SHARED_DEFAULTS = {
    WEIGHT_QUANTIZATION: {
        "enabled": False,
        "quantizer_kernel": False,
        "schedule_offset": 0,
        "quantize_groups": 1,
        "quantize_verbose": False,
        "quantization_type": "symmetric",
        "quantize_weight_in_forward": False,
        "rounding": "nearest",
        "fp16_mixed_quantize": {
            "enabled": False,
            "quantize_change_ratio": 0.001,
        },
    },
    ACTIVATION_QUANTIZATION: {
        "enabled": False,
        "quantization_type": "symmetric",
        "range_calibration": "dynamic",
        "schedule_offset": 1000,
    },
    SPARSE_PRUNING: {
        "enabled": False,
        "method": "l1",
        "schedule_offset": 1000,
    },
    ROW_PRUNING: {
        "enabled": False,
        "method": "l1",
        "schedule_offset": 1000,
    },
    HEAD_PRUNING: {
        "enabled": False,
        "method": "topk",
        "schedule_offset": 1000,
    },
    CHANNEL_PRUNING: {
        "enabled": False,
        "method": "l1",
        "schedule_offset": 1000,
    },
}


from deepspeed_tpu.config.config_utils import deep_update as _deep_update


def get_compression_config(param_dict: dict) -> dict:
    compression = param_dict.get(COMPRESSION_TRAINING, {})
    out = {LAYER_REDUCTION: {"enabled": False, **compression.get(LAYER_REDUCTION, {})}}
    for technique, defaults in _SHARED_DEFAULTS.items():
        section = compression.get(technique, {})
        out[technique] = {
            SHARED_PARAMETERS: _deep_update(defaults, section.get(SHARED_PARAMETERS, {})),
            DIFFERENT_GROUPS: copy.deepcopy(section.get(DIFFERENT_GROUPS, {})),
        }
    return out
