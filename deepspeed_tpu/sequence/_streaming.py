"""Shared chunked streaming-softmax attention core for the SP paths.

One implementation of the numerically sensitive flash-softmax math used by
both sequence-parallel programs (``ring.py`` per ring step, ``ulysses.py``
over the full gathered sequence), with a **custom VJP**: the backward pass
recomputes per-chunk probabilities from the saved logsumexp instead of
letting AD stack per-chunk residuals — residual memory is O(S·Hd)
(q/k/v/out/lse) and live memory O(Sq·chunk) in BOTH directions. Same
recompute strategy as the Pallas flash kernel's bwd
(``ops/pallas/flash_attention.py``), expressed in XLA for the places a bare
kernel cannot go (inside sp shard_map bodies).

Key chunks are PADDED to a multiple of ``chunk`` with fully-masked tails
(no divisor search — shard sizes with no good divisor would otherwise
collapse to tiny chunks and thousands of sequential steps).

GQA: k/v may carry KV = H/rep heads; they broadcast per CHUNK inside the
loop, so the rep-expanded kv never materializes.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_NEG_INF = -1e9  # matches ops.attention masking constant


def _float0_like(x):
    return np.zeros(np.shape(x), jax.dtypes.float0)


def _pad_kv(k, v, mask_bias, chunk):
    """Pad keys to a chunk multiple. The pad tail rides a TRUE -inf bias
    (not _NEG_INF): its weight is exactly 0 even for degenerate rows whose
    every real key is -1e9-masked, keeping fully-masked-row outputs equal
    to the dense reference's uniform-over-real-keys. Safe from exp(-inf+inf)
    NaNs because pad < chunk, so every chunk holds >= 1 key whose logit is
    > -inf."""
    Sk = k.shape[1]
    pad = (-Sk) % chunk
    if pad == 0:
        return k, v, mask_bias, Sk
    if mask_bias is None:
        mask_bias = jnp.zeros((k.shape[0], Sk), jnp.float32)
    k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
    v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    mask_bias = jnp.pad(mask_bias, ((0, 0), (0, pad)),
                        constant_values=-jnp.inf)
    return k, v, mask_bias, Sk


def _chunk_logits(q32, kc, maskc, qpos, kposc, causal, slopes, scale, rep):
    """fp32 logits for one key chunk: GQA broadcast, scale, alibi, causal
    and key-mask bias. q32 [B,H,Sq,Hd], kc [B,Ck,KV,Hd] → [B,H,Sq,Ck]."""
    if rep != 1:
        kc = jnp.repeat(kc, rep, axis=2)
    logits = jnp.einsum("bhqd,bkhd->bhqk", q32, kc.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale
    if slopes is not None:
        dist = (kposc[None, :] - qpos[:, None]).astype(jnp.float32)
        logits = logits + slopes[None, :, None, None] * dist[None, None]
    if causal:
        logits = jnp.where((qpos[:, None] >= kposc[None, :])[None, None],
                           logits, _NEG_INF)
    if maskc is not None:
        logits = logits + maskc[:, None, None, :]
    return logits


# qpos0/kpos0 are TRACED int32 scalars (ring passes axis_index-derived block
# offsets), so they are regular operands with float0 cotangents — only the
# genuinely static knobs are nondiff.
@partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def chunked_attention(q, k, v, mask_bias, slopes, qpos0, kpos0,
                      causal: bool, chunk: int, out_dtype, scale=None):
    """Exact softmax attention, streamed over key chunks.

    q [B, Sq, H, Hd]; k/v [B, Sk, KV, Hd] with KV | H; mask_bias [B, Sk]
    additive key bias or None; slopes [H] alibi or None; qpos0/kpos0 [] int32
    global offsets of the local q/k blocks; ``scale`` (static float) defaults
    to Hd**-0.5. Returns ``(out [B,Sq,H,Hd] in out_dtype, lse [B,H,Sq])``.
    BOTH outputs are differentiable (ring's cross-step softmax combination
    differentiates through lse).
    """
    return _fwd_impl(q, k, v, mask_bias, slopes, qpos0, kpos0,
                     causal, chunk, out_dtype, scale)


def _fwd_impl(q, k, v, mask_bias, slopes, qpos0, kpos0, causal, chunk,
              out_dtype, scale=None):
    B, Sq, H, Hd = q.shape
    rep = H // k.shape[2]
    scale = Hd**-0.5 if scale is None else scale
    chunk = min(chunk, k.shape[1])  # small shards run exact-size, unpadded
    k, v, mask_bias, _ = _pad_kv(k, v, mask_bias, chunk)
    n = k.shape[1] // chunk
    q32 = jnp.transpose(q.astype(jnp.float32), (0, 2, 1, 3))
    qpos = qpos0 + jnp.arange(Sq)

    def step(carry, c):
        m, l, o = carry
        kc = jax.lax.dynamic_slice_in_dim(k, c * chunk, chunk, 1)
        vc = jax.lax.dynamic_slice_in_dim(v, c * chunk, chunk, 1)
        mc = (jax.lax.dynamic_slice_in_dim(mask_bias, c * chunk, chunk, 1)
              if mask_bias is not None else None)
        kposc = kpos0 + c * chunk + jnp.arange(chunk)
        logits = _chunk_logits(q32, kc, mc, qpos, kposc, causal, slopes,
                               scale, rep)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[..., None])
        l_new = l * alpha + p.sum(axis=-1)
        vc32 = (jnp.repeat(vc, rep, axis=2) if rep != 1 else vc).astype(jnp.float32)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vc32, preferred_element_type=jnp.float32)
        return (m_new, l_new, o_new), None

    init = (jnp.full((B, H, Sq), -jnp.inf, jnp.float32),
            jnp.zeros((B, H, Sq), jnp.float32),
            jnp.zeros((B, H, Sq, Hd), jnp.float32))
    (m, l, o), _ = jax.lax.scan(step, init, jnp.arange(n, dtype=jnp.int32))
    l_safe = jnp.maximum(l, 1e-30)
    out = jnp.transpose(o / l_safe[..., None], (0, 2, 1, 3)).astype(out_dtype)
    lse = m + jnp.log(l_safe)
    return out, lse


def _fwd_rule(q, k, v, mask_bias, slopes, qpos0, kpos0, causal, chunk,
              out_dtype, scale=None):
    out, lse = _fwd_impl(q, k, v, mask_bias, slopes, qpos0, kpos0,
                         causal, chunk, out_dtype, scale)
    return (out, lse), (q, k, v, mask_bias, slopes, qpos0, kpos0, out, lse)


def _bwd_rule(causal, chunk, out_dtype, scale, res, cts):
    q, k, v, mask_bias, slopes, qpos0, kpos0, out, lse = res
    do, dlse = cts  # d lse / d logits = p, folded into ds below
    B, Sq, H, Hd = q.shape
    KV = k.shape[2]
    rep = H // KV
    scale = Hd**-0.5 if scale is None else scale
    Sk_orig = k.shape[1]
    chunk = min(chunk, k.shape[1])  # mirror _fwd_impl's small-shard clamp
    k_p, v_p, mask_p, _ = _pad_kv(k, v, mask_bias, chunk)
    n = k_p.shape[1] // chunk

    q32 = jnp.transpose(q.astype(jnp.float32), (0, 2, 1, 3))
    do32 = jnp.transpose(do.astype(jnp.float32), (0, 2, 1, 3))
    o32 = jnp.transpose(out.astype(jnp.float32), (0, 2, 1, 3))
    D = jnp.sum(do32 * o32, axis=-1)                              # [B,H,Sq]
    dlse32 = dlse.astype(jnp.float32)
    qpos = qpos0 + jnp.arange(Sq)

    def step(carry, c):
        dq, dslopes_acc = carry
        kc = jax.lax.dynamic_slice_in_dim(k_p, c * chunk, chunk, 1)
        vc = jax.lax.dynamic_slice_in_dim(v_p, c * chunk, chunk, 1)
        mc = (jax.lax.dynamic_slice_in_dim(mask_p, c * chunk, chunk, 1)
              if mask_p is not None else None)
        kposc = kpos0 + c * chunk + jnp.arange(chunk)
        logits = _chunk_logits(q32, kc, mc, qpos, kposc, causal, slopes,
                               scale, rep)
        # normalized probabilities recomputed from the saved lse (fully
        # masked rows recompute the same uniform weights the forward used;
        # -inf pad keys recompute exactly 0)
        p = jnp.exp(logits - lse[..., None])
        vc_r = (jnp.repeat(vc, rep, axis=2) if rep != 1 else vc).astype(jnp.float32)
        kc_r = (jnp.repeat(kc, rep, axis=2) if rep != 1 else kc).astype(jnp.float32)
        dv_c = jnp.einsum("bhqk,bhqd->bkhd", p, do32,
                          preferred_element_type=jnp.float32)
        dp = jnp.einsum("bhqd,bkhd->bhqk", do32, vc_r,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - D[..., None] + dlse32[..., None])          # [B,H,Sq,Ck]
        dq = dq + jnp.einsum("bhqk,bkhd->bhqd", ds, kc_r,
                             preferred_element_type=jnp.float32) * scale
        dk_c = jnp.einsum("bhqk,bhqd->bkhd", ds, q32,
                          preferred_element_type=jnp.float32) * scale
        if rep != 1:  # fold query-head grads onto the shared kv head
            dk_c = dk_c.reshape(B, chunk, KV, rep, Hd).sum(axis=3)
            dv_c = dv_c.reshape(B, chunk, KV, rep, Hd).sum(axis=3)
        dm_c = ds.sum(axis=(1, 2)) if mask_bias is not None else None
        if slopes is not None:
            dist = (kposc[None, :] - qpos[:, None]).astype(jnp.float32)
            dslopes_acc = dslopes_acc + jnp.einsum(
                "bhqk,qk->h", ds, dist, preferred_element_type=jnp.float32)
        return (dq, dslopes_acc), (dk_c, dv_c, dm_c)

    dq0 = jnp.zeros((B, H, Sq, Hd), jnp.float32)
    ds0 = jnp.zeros((H,), jnp.float32) if slopes is not None else jnp.zeros((0,))
    (dq, dslopes), (dk_chunks, dv_chunks, dm_chunks) = jax.lax.scan(
        step, (dq0, ds0), jnp.arange(n, dtype=jnp.int32))
    dk = jnp.moveaxis(dk_chunks, 0, 1).reshape(B, n * chunk, KV, Hd)[:, :Sk_orig]
    dv = jnp.moveaxis(dv_chunks, 0, 1).reshape(B, n * chunk, KV, Hd)[:, :Sk_orig]
    dq = jnp.transpose(dq, (0, 2, 1, 3)).astype(q.dtype)
    dmask = None
    if mask_bias is not None:
        dmask = jnp.moveaxis(dm_chunks, 0, 1).reshape(B, n * chunk)[:, :Sk_orig]
        dmask = dmask.astype(mask_bias.dtype)
    dslopes_out = None if slopes is None else dslopes.astype(slopes.dtype)
    return (dq, dk.astype(k.dtype), dv.astype(v.dtype), dmask, dslopes_out,
            _float0_like(qpos0), _float0_like(kpos0))


chunked_attention.defvjp(_fwd_rule, _bwd_rule)
