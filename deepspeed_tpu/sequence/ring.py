"""Ring attention over the ``sp`` mesh axis.

Flash-style streaming softmax over K/V blocks that rotate around the ring
with ``lax.ppermute``: at ring step ``s`` a device holding query block ``i``
attends to key/value block ``(i - s) mod sp``. The running (max, sum, out)
accumulators make the result exactly equal to full softmax attention while
every chip only ever holds S/sp keys — O(S/sp) memory and ppermute traffic
that XLA overlaps with each step's matmuls on the MXU.

Causality is handled per block-pair from *global* positions (query block i,
key block j: j>i fully masked, j==i triangular, j<i dense), so the math
matches :func:`deepspeed_tpu.ops.attention.mha_attention` bit-for-bit in
fp32.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.sequence._program import run_sp_program

_NEG_INF = -1e9  # matches ops.attention masking constant

# per-ring-step key-chunk size: local shards larger than this stream their
# softmax in chunks (bounds logits memory to O(Sq * RING_KEY_CHUNK)).
# Import-time knob: the compiled sp programs are cached WITHOUT this in the
# key, so set it before the first ring_attention call of the process.
RING_KEY_CHUNK = 1024


def ring_attention_local(q, k, v, *, axis: str, causal: bool = True, mask_bias=None,
                         alibi_slopes=None, scale: Optional[float] = None):
    """Per-shard body (call inside ``shard_map`` over ``axis``).

    q, k, v: LOCAL [B, Sq, H, Hd] / [B, Sk, KV, Hd] blocks (KV may be a
    divisor of H — GQA kv rides the ring UNREPEATED, H/KV× less ppermute
    traffic); mask_bias: local additive key mask [B, Sk] or None. Returns
    local [B, Sq, H, Hd].
    """
    B, Sq, H, Hd = q.shape
    Sk, KV = k.shape[1], k.shape[2]
    rep = H // KV
    sp = jax.lax.axis_size(axis)
    my_block = jax.lax.axis_index(axis)
    scale = scale if scale is not None else Hd**-0.5

    q32 = q.astype(jnp.float32)
    qpos = my_block * Sq + jnp.arange(Sq)  # global query positions

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    # inner key-chunking bounds per-ring-step logits to O(Sq·chunk): at real
    # long context the LOCAL shard is still big (512k/16 = 32k keys → a
    # 32k×32k logits block is GBs per head), so the shard-local softmax
    # must itself stream
    if Sk > RING_KEY_CHUNK:
        # smallest chunk count >= Sk/RING_KEY_CHUNK that divides Sk, so the
        # memory bound holds for non-multiple shard sizes too (worst case a
        # prime Sk degrades to n_chunks == Sk, never to unchunked)
        n_chunks = -(-Sk // RING_KEY_CHUNK)
        while Sk % n_chunks:
            n_chunks += 1
    else:
        n_chunks = 1
    Ck = Sk // n_chunks

    def _update(kb, vb, maskb, kvpos, m, l, o):
        """Streaming-softmax update against one key chunk at global kvpos.
        GQA kv arrives unrepeated and broadcasts here, per CHUNK — the full
        rep-expanded shard never materializes."""
        if rep != 1:
            kb = jnp.repeat(kb, rep, axis=2)
            vb = jnp.repeat(vb, rep, axis=2)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q32, kb.astype(jnp.float32),
                            preferred_element_type=jnp.float32) * scale
        if alibi_slopes is not None:
            dist = (kvpos[None, :] - qpos[:, None]).astype(jnp.float32)
            logits = logits + alibi_slopes[None, :, None, None] * dist[None, None, :, :]
        if causal:
            logits = jnp.where((qpos[:, None] >= kvpos[None, :])[None, None], logits, _NEG_INF)
        if maskb is not None:
            logits = logits + maskb[:, None, None, :]

        m_new = jnp.maximum(m, logits.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[..., None])
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, vb.astype(jnp.float32),
                                                  preferred_element_type=jnp.float32)
        return m_new, l_new, o_new

    def accumulate(kb, vb, maskb, m, l, o, s):
        """One flash-softmax update against kv block (my_block - s) mod sp."""
        pos0 = ((my_block - s) % sp) * Sk

        if n_chunks == 1:
            return _update(kb, vb, maskb, pos0 + jnp.arange(Sk), m, l, o)

        def chunk_step(carry, c):
            m, l, o = carry
            kc = jax.lax.dynamic_slice_in_dim(kb, c * Ck, Ck, 1)
            vc = jax.lax.dynamic_slice_in_dim(vb, c * Ck, Ck, 1)
            mc = (jax.lax.dynamic_slice_in_dim(maskb, c * Ck, Ck, 1)
                  if maskb is not None else None)
            return _update(kc, vc, mc, pos0 + c * Ck + jnp.arange(Ck), m, l, o), None

        # remat: without it AD stacks each chunk's softmax residuals and the
        # O(Sq*S) footprint the chunking exists to avoid comes right back in
        # the backward pass
        chunk_step = jax.checkpoint(chunk_step, prevent_cse=False)
        (m, l, o), _ = jax.lax.scan(chunk_step, (m, l, o),
                                    jnp.arange(n_chunks, dtype=jnp.int32))
        return m, l, o

    m0 = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)
    o0 = jnp.zeros((B, H, Sq, Hd), jnp.float32)

    # step 0 on the resident block, then permute-then-accumulate for the
    # remaining sp-1 steps (no dead permute after the last accumulate)
    m, l, o = accumulate(k, v, mask_bias, m0, l0, o0, 0)

    def step(carry, s):
        kb, vb, maskb, m, l, o = carry
        kb = jax.lax.ppermute(kb, axis, perm)
        vb = jax.lax.ppermute(vb, axis, perm)
        if maskb is not None:
            maskb = jax.lax.ppermute(maskb, axis, perm)
        m, l, o = accumulate(kb, vb, maskb, m, l, o, s)
        return (kb, vb, maskb, m, l, o), None

    (_, _, _, m, l, o), _ = jax.lax.scan(step, (k, v, mask_bias, m, l, o),
                                         jnp.arange(1, sp))

    out = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ring_attention(q, k, v, *, mesh, axis: str = "sp", causal: bool = True, mask_bias=None,
                   alibi_slopes=None, scale: Optional[float] = None):
    """Global-view ring attention: shard_map over ``axis`` (seq dim), all
    other dims (batch→dp, heads→tp) stay auto-sharded."""
    return run_sp_program(ring_attention_local, q, k, v, mesh=mesh, axis=axis,
                          causal=causal, mask_bias=mask_bias,
                          alibi_slopes=alibi_slopes, scale=scale)
