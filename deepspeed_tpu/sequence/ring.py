"""Ring attention over the ``sp`` mesh axis.

Flash-style streaming softmax over K/V blocks that rotate around the ring
with ``lax.ppermute``: at ring step ``s`` a device holding query block ``i``
attends to key/value block ``(i - s) mod sp``. Per-step attention runs the
shared chunked streaming core (``sequence/_streaming.py`` — custom-VJP
recompute backward, O(Sq·chunk) live memory in both directions); the
partial ``(out_s, lse_s)`` results combine across ring steps in the log
domain, so the total is exactly full softmax attention while every chip
only ever holds S/sp keys. GQA kv rides the ring UNREPEATED (H/KV× less
ppermute traffic) and broadcasts per chunk inside the core.

Causality is handled from *global* positions inside the core (query block
i, key block j: j>i fully masked, j==i triangular, j<i dense), so the math
matches :func:`deepspeed_tpu.ops.attention.mha_attention` bit-for-bit in
fp32.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

import numpy as np

from deepspeed_tpu.sequence._program import run_sp_program
from deepspeed_tpu.sequence._streaming import chunked_attention

# per-ring-step key-chunk size inside the shared streaming core. Mutable
# module knob; the compiled sp program is keyed on its current value.
RING_KEY_CHUNK = 1024

# ring-flash: run the Pallas flash kernel on the shard-local blocks inside
# the sp shard_map body (the kernel itself is not shard_mappable from the
# model dispatch, but a pallas_call composes fine INSIDE a shard body).
# None = auto (TPU: kernel; elsewhere: XLA streaming core). Tests force True
# (interpret mode). Keyed into the compiled-program cache like RING_KEY_CHUNK.
RING_USE_FLASH = None

_LN2 = float(np.log(2.0))


def _use_flash() -> bool:
    from deepspeed_tpu.sequence._program import resolve_use_flash
    return resolve_use_flash(RING_USE_FLASH)


def ring_attention_local(q, k, v, *, axis: str, causal: bool = True, mask_bias=None,
                         alibi_slopes=None, scale: Optional[float] = None):
    """Per-shard body (call inside ``shard_map`` over ``axis``).

    q, k, v: LOCAL [B, Sq, H, Hd] / [B, Sk, KV, Hd] blocks (KV may be a
    divisor of H); mask_bias: local additive key mask [B, Sk] or None.
    Returns local [B, Sq, H, Hd].
    """
    B, Sq, H, Hd = q.shape
    Sk = k.shape[1]
    from deepspeed_tpu.comm import bound_axis_size
    sp = bound_axis_size(axis)
    my_block = jax.lax.axis_index(axis)

    perm = [(i, (i + 1) % sp) for i in range(sp)]
    qpos0 = (my_block * Sq).astype(jnp.int32)
    use_flash = _use_flash()

    def flash_block(kb, vb, maskb, kpos0, diag):
        """One ring step through the Pallas kernel. Sq == Sk and offsets are
        block-aligned, so a step is either the causal diagonal (diag), fully
        visible, or fully masked (gated by the caller via lse) — never a
        partial triangle, which is why the kernel's LOCAL causal mask
        suffices. Alibi's global-position term slope*(kpos0-qpos0) is
        constant within the block: softmax-invariant for o, a per-head lse
        shift applied after."""
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
        o, lse2 = flash_attention(q, kb, vb, mask_bias=maskb,
                                  causal=bool(diag) and causal,
                                  alibi_slopes=alibi_slopes, scale=scale,
                                  return_lse=True)
        # log2 → natural; the kernel's +1e30 empty-row marker becomes -1e30
        # so an empty block contributes zero weight to the combine
        lse = jnp.where(lse2 > 1e29, jnp.float32(-1e30), lse2 * _LN2)
        if alibi_slopes is not None:
            shift = (jnp.asarray(alibi_slopes, jnp.float32)
                     * (kpos0 - qpos0).astype(jnp.float32))
            lse = jnp.where(lse > -1e29, lse + shift[None, :, None], lse)
        return o, lse

    def block_attn(kb, vb, maskb, s, diag):
        kpos0 = (((my_block - s) % sp) * Sk).astype(jnp.int32)
        if use_flash:
            return flash_block(kb, vb, maskb, kpos0, diag)
        return chunked_attention(q, kb, vb, maskb, alibi_slopes, qpos0, kpos0,
                                 causal, RING_KEY_CHUNK, jnp.float32, scale)

    def combine(M, L, O, o_s, lse_s):
        """Log-domain merge of a normalized partial (o_s, lse_s) into the
        running (M, L, O); the final output is O / L."""
        M_new = jnp.maximum(M, lse_s)
        a = jnp.exp(M - M_new)
        b = jnp.exp(lse_s - M_new)
        O_new = O * a[..., None] + jnp.transpose(o_s, (0, 2, 1, 3)) * b[..., None]
        L_new = L * a + b
        return M_new, L_new, O_new

    o0, lse0 = block_attn(k, v, mask_bias, jnp.int32(0), True)
    M = lse0
    L = jnp.ones_like(lse0)
    O = jnp.transpose(o0.astype(jnp.float32), (0, 2, 1, 3))  # [B, H, Sq, Hd]

    def step(carry, s):
        kb, vb, maskb, M, L, O = carry
        kb = jax.lax.ppermute(kb, axis, perm)
        vb = jax.lax.ppermute(vb, axis, perm)
        if maskb is not None:
            maskb = jax.lax.ppermute(maskb, axis, perm)
        o_s, lse_s = block_attn(kb, vb, maskb, s, False)
        if use_flash and causal:
            # the kernel computed the block dense (off-diagonal steps are
            # all-or-nothing); gate invisible blocks out via lse = -inf so
            # their combine weight exp(lse_s - M) is exactly 0 EVEN when the
            # running max M is itself the -1e30 empty-row marker (a -1e30
            # sentinel here would give exp(0)=1 and leak future keys into
            # fully-masked-prefix rows)
            visible = ((my_block - s) % sp) < my_block
            lse_s = jnp.where(visible, lse_s, -jnp.inf)
        M, L, O = combine(M, L, O, o_s, lse_s)
        return (kb, vb, maskb, M, L, O), None

    (_, _, _, M, L, O), _ = jax.lax.scan(step, (k, v, mask_bias, M, L, O),
                                         jnp.arange(1, sp))

    out = O / jnp.maximum(L, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ring_attention(q, k, v, *, mesh, axis: str = "sp", causal: bool = True, mask_bias=None,
                   alibi_slopes=None, scale: Optional[float] = None):
    """Global-view ring attention: shard_map over ``axis`` (seq dim), all
    other dims (batch→dp, heads→tp) stay auto-sharded."""
    return run_sp_program(ring_attention_local, q, k, v, mesh=mesh, axis=axis,
                          causal=causal, mask_bias=mask_bias,
                          alibi_slopes=alibi_slopes, scale=scale,
                          knobs=(RING_KEY_CHUNK, _use_flash()))
