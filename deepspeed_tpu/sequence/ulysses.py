"""DeepSpeed-Ulysses-style sequence parallelism: all-to-all head↔seq.

Around the attention core, seq-sharded q/k/v [B, S/sp, H, Hd] are re-sharded
with ``lax.all_to_all`` into head-sharded [B, S, H/sp, Hd]; each chip then
runs attention for its H/sp heads over the FULL sequence, and a second
all-to-all restores sequence sharding. Communication volume is O(B·S·D/sp)
per direction — the all-to-alls ride ICI on the innermost mesh axes.

Long context: above ``ULYSSES_KEY_CHUNK`` the local attention runs the
shared chunked streaming core (``sequence/_streaming.py`` — custom-VJP
recompute backward), so neither direction materializes the S×S logits and
GQA kv is broadcast per chunk, never as a full rep-expanded copy.

Reference analogue: none at this version (SURVEY.md §2.3 — SP absent);
this implements the capability the reference later shipped as
``DistributedAttention``, expressed as XLA collectives instead of NCCL
all-to-alls.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.sequence._program import run_sp_program
from deepspeed_tpu.sequence._streaming import chunked_attention

# key-chunk size for the head-sharded local attention: above this the local
# softmax streams over key chunks (bounds logits to O(S·chunk) instead of
# S²). Mutable module knob; the compiled sp program is keyed on its value.
ULYSSES_KEY_CHUNK = 2048

# run the Pallas flash kernel for the head-sharded local attention (after
# the all-to-all each chip holds the FULL sequence for H/sp heads — plain
# kernel territory). None = auto (TPU only). Cache-keyed like the chunk knob.
ULYSSES_USE_FLASH = None


def _use_flash() -> bool:
    from deepspeed_tpu.sequence._program import resolve_use_flash
    return resolve_use_flash(ULYSSES_USE_FLASH)


def ulysses_attention_local(q, k, v, *, axis: str, causal: bool = True, mask_bias=None,
                            alibi_slopes=None, scale: Optional[float] = None):
    """Per-shard body (inside ``shard_map`` over ``axis``).

    q [B, Sq_loc, H, Hd], k/v [B, Sk_loc, H_or_KV, Hd] (GQA kv may carry
    KV < H heads: when KV divides the axis size it rides the all-to-all
    unrepeated — H/KV× less wire; otherwise it is repeated first),
    mask_bias local [B, Sk_loc] additive. H must be divisible by the axis
    size.
    """
    from deepspeed_tpu.comm import bound_axis_size
    sp = bound_axis_size(axis)
    H, KV = q.shape[2], k.shape[2]
    if H % sp != 0:
        raise ValueError(f"Ulysses SP needs heads ({H}) divisible by sp axis size ({sp})")

    # seq-sharded -> head-sharded (gather seq, scatter heads)
    def to_heads(x):
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    if KV != H and KV % sp != 0:
        # can't head-scatter fewer kv heads than chips: fall back to
        # repeating before the transfer
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)
        KV = H
    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    if mask_bias is not None:
        mask_bias = jax.lax.all_gather(mask_bias, axis, axis=1, tiled=True)  # [B, S]

    my = jax.lax.axis_index(axis)
    slopes = None
    if alibi_slopes is not None:
        h_loc = H // sp
        slopes = jax.lax.dynamic_slice_in_dim(alibi_slopes, my * h_loc, h_loc)

    S, Hd = qh.shape[1], qh.shape[3]
    if _use_flash():
        # Pallas flash on the full-sequence local attention: O(S·Hd) HBM
        # like the streaming core, kernel-grade VPU/MXU utilisation, GQA kv
        # native (unrepeated)
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
        out = flash_attention(qh, kh, vh, mask_bias=mask_bias, causal=causal,
                              alibi_slopes=slopes, scale=scale)
    elif S > ULYSSES_KEY_CHUNK:
        # long context: dense attention would materialize an S×S logits
        # block — stream through the shared core (unrepeated GQA kv goes in
        # directly; the core broadcasts per chunk)
        out, _ = chunked_attention(qh, kh, vh, mask_bias, slopes,
                                   jnp.int32(0), jnp.int32(0),
                                   causal, ULYSSES_KEY_CHUNK, qh.dtype, scale)
    else:
        # dense path: mha_attention is GQA-native (grouped-head einsum), and
        # the head-scatter preserves grouping — local query head g still
        # reads local kv head g // (H/KV) — so kv stays unrepeated here too
        from deepspeed_tpu.ops.attention import mha_attention
        out = mha_attention(qh, kh, vh,
                            mask_bias=None if mask_bias is None else mask_bias[:, None, None, :],
                            causal=causal, alibi_slopes=slopes, scale=scale)

    # head-sharded -> seq-sharded (gather heads, scatter seq)
    return jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(q, k, v, *, mesh, axis: str = "sp", causal: bool = True, mask_bias=None,
                      alibi_slopes=None, scale: Optional[float] = None):
    """Global-view Ulysses attention: shard_map over ``axis`` only; batch and
    head dims stay auto-sharded (dp/tp compose via partial-auto)."""
    return run_sp_program(ulysses_attention_local, q, k, v, mesh=mesh, axis=axis,
                          causal=causal, mask_bias=mask_bias,
                          alibi_slopes=alibi_slopes, scale=scale,
                          knobs=(ULYSSES_KEY_CHUNK, _use_flash()))
