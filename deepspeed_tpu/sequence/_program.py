"""Shared shard_map program builder for the SP attention implementations.

ring.py and ulysses.py differ only in the per-shard body; the cached
(mesh, static-args) → jitted shard_map program machinery lives here once.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.utils.jax_compat import shard_map


@functools.lru_cache(maxsize=128)
def _cached_program(local_fn: Callable, mesh, axis: str, causal: bool, has_mask: bool,
                    has_alibi: bool, scale: Optional[float], knobs: tuple = ()):
    """Build + jit the shard_map program once per (body, mesh, static-arg)
    combo so eager callers hit the jit cache instead of recompiling.
    ``knobs`` carries the caller's module-level tuning globals (chunk sizes,
    kernel toggles) purely as cache-key salt: the body reads the globals at
    trace time, so keying on their current values makes mutating a knob
    after first compile take effect instead of silently hitting a stale
    program."""
    qkv_spec = P(None, axis, None, None)
    in_specs = [qkv_spec, qkv_spec, qkv_spec]
    if has_mask:
        in_specs.append(P(None, axis))
    if has_alibi:
        in_specs.append(P(None))  # replicated [H] slopes

    def body(*xs):
        qq, kk, vv = xs[:3]
        rest = list(xs[3:])
        mb = rest.pop(0) if has_mask else None
        slopes = rest.pop(0) if has_alibi else None
        return local_fn(qq, kk, vv, axis=axis, causal=causal, mask_bias=mb,
                        alibi_slopes=slopes, scale=scale)

    fn = shard_map(body, mesh=mesh, in_specs=tuple(in_specs), out_specs=qkv_spec,
                       axis_names={axis}, check_vma=False)
    # partial-auto shard_map must run under jit; nested jit inlines when traced
    return jax.jit(fn)


def run_sp_program(local_fn: Callable, q, k, v, *, mesh, axis: str, causal: bool,
                   mask_bias, alibi_slopes, scale: Optional[float], knobs: tuple = ()):
    """Dispatch q/k/v (+ optional mask/slopes) through the cached shard_map
    program built around ``local_fn``. ``knobs``: the caller's current
    tuning-global values (cache-key salt, see _cached_program)."""
    args = [q, k, v]
    if mask_bias is not None:
        args.append(mask_bias)
    if alibi_slopes is not None:
        args.append(jnp.asarray(alibi_slopes))
    fn = _cached_program(local_fn, mesh, axis, causal, mask_bias is not None,
                         alibi_slopes is not None, scale, knobs)
    return fn(*args)


def resolve_use_flash(override) -> bool:
    """Shared auto-detection for the SP bodies' Pallas-kernel toggles
    (ring.RING_USE_FLASH / ulysses.ULYSSES_USE_FLASH): explicit override
    wins, else kernel on TPU, XLA streaming core elsewhere."""
    if override is not None:
        return bool(override)
    return jax.default_backend() == "tpu"
