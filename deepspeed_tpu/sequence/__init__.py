"""Sequence / context parallelism (long-context training & inference).

The reference at this version has no sequence-parallel path (verified in
SURVEY.md §2.3: no Ulysses/ring/context-parallel in ``deepspeed/``) and
serves long sequences with block-sparse attention and activation-checkpoint
offload instead. The TPU build provides SP as a first-class mesh axis
(``sp``) with two interchangeable attention programs:

- :func:`ring_attention` — blockwise flash attention whose K/V blocks rotate
  around the ``sp`` ring with ``lax.ppermute`` (communication hidden behind
  each block's matmuls). Memory per chip is O(S/sp); no single device ever
  materialises the full sequence. This is the TPU-idiomatic equivalent of
  the later reference versions' ring/"DistributedAttention" designs and of
  the blocksparse "scale to long sequences" capability
  (``deepspeed/ops/sparse_attention/``).
- :func:`ulysses_attention` — all-to-all head↔sequence re-sharding around a
  dense local attention (DeepSpeed-Ulysses style): seq-sharded activations
  become head-sharded just for the attention core, so each chip computes
  full-sequence attention for H/sp heads.

Both are pure ``shard_map`` programs over the global mesh: batch/head dims
stay auto-sharded (dp/tp compose transparently via partial-auto mode).
"""

from deepspeed_tpu.sequence.ring import ring_attention, ring_attention_local
from deepspeed_tpu.sequence.ulysses import ulysses_attention, ulysses_attention_local

__all__ = [
    "ring_attention",
    "ring_attention_local",
    "ulysses_attention",
    "ulysses_attention_local",
    "sp_attention",
]


def sp_attention(q, k, v, *, mesh, impl: str = "ring", axis: str = "sp", causal: bool = True,
                 mask_bias=None, alibi_slopes=None, scale=None):
    """Dispatch to the configured sequence-parallel attention implementation.

    q, k, v: GLOBAL-shaped [B, S, H, Hd] arrays (under jit, logically sharded
    over ``axis`` on the sequence dim). mask_bias: optional additive [B, S]
    key-side bias (0 keep / -1e9 drop).
    """
    if impl == "ring":
        return ring_attention(q, k, v, mesh=mesh, axis=axis, causal=causal,
                              mask_bias=mask_bias, alibi_slopes=alibi_slopes, scale=scale)
    if impl in ("ulysses", "all_to_all", "alltoall"):
        return ulysses_attention(q, k, v, mesh=mesh, axis=axis, causal=causal,
                                 mask_bias=mask_bias, alibi_slopes=alibi_slopes, scale=scale)
    raise ValueError(f"Unknown sequence-parallel impl {impl!r} (expected 'ring' or 'ulysses')")
