"""Architecture presets for the model zoo.

Coverage target: the model families the reference injects kernels for
(``deepspeed/module_inject/containers/*.py`` — gpt2, gptj, gptneo, gptneox,
opt, bloom, megatron) plus Llama-class models (the BASELINE.json north-star
config). Sizes follow the published architecture tables.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax.numpy as jnp

from deepspeed_tpu.models.causal_lm import CausalLM
from deepspeed_tpu.models.transformer import TransformerConfig


def gpt2(size: str = "125m", **over) -> CausalLM:
    dims = {
        "125m": dict(n_layer=12, n_head=12, d_model=768),
        "350m": dict(n_layer=24, n_head=16, d_model=1024),
        "774m": dict(n_layer=36, n_head=20, d_model=1280),
        "1.5b": dict(n_layer=48, n_head=25, d_model=1600),
    }[size]
    cfg = TransformerConfig(vocab_size=50257, max_seq=1024, pos_embedding="learned", norm="layernorm",
                            activation="gelu", tie_embeddings=True, attn_bias=True, **dims, **over)
    return CausalLM(cfg)


def gpt2_medium(**over) -> CausalLM:
    return gpt2("350m", **over)


def gpt2_large(**over) -> CausalLM:
    return gpt2("774m", **over)


def gpt2_xl(**over) -> CausalLM:
    return gpt2("1.5b", **over)


def llama_7b(**over) -> CausalLM:
    cfg = TransformerConfig(vocab_size=32000, n_layer=32, n_head=32, d_model=4096, d_ff=11008, max_seq=2048,
                            pos_embedding="rope", norm="rmsnorm", activation="swiglu", tie_embeddings=False,
                            norm_eps=1e-6, **over)
    return CausalLM(cfg)


def llama(size: str = "7b", **over) -> CausalLM:
    dims = {
        "tiny": dict(n_layer=4, n_head=8, d_model=512, d_ff=1408, vocab_size=32000, max_seq=512),
        "7b": dict(n_layer=32, n_head=32, d_model=4096, d_ff=11008, vocab_size=32000, max_seq=2048),
        "13b": dict(n_layer=40, n_head=40, d_model=5120, d_ff=13824, vocab_size=32000, max_seq=2048),
        "70b": dict(n_layer=80, n_head=64, d_model=8192, d_ff=28672, n_kv_head=8, vocab_size=32000, max_seq=4096),
    }[size]
    cfg = TransformerConfig(pos_embedding="rope", norm="rmsnorm", activation="swiglu", tie_embeddings=False,
                            norm_eps=1e-6, **{**dims, **over})
    return CausalLM(cfg)


def bloom(size: str = "560m", **over) -> CausalLM:
    dims = {
        "560m": dict(n_layer=24, n_head=16, d_model=1024),
        "1b7": dict(n_layer=24, n_head=16, d_model=2048),
        "7b1": dict(n_layer=30, n_head=32, d_model=4096),
        "176b": dict(n_layer=70, n_head=112, d_model=14336),
    }[size]
    cfg = TransformerConfig(vocab_size=250880, max_seq=2048, pos_embedding="alibi", norm="layernorm",
                            activation="gelu", tie_embeddings=True, embed_layernorm=True,
                            attn_bias=True, **dims, **over)
    return CausalLM(cfg)


def opt(size: str = "125m", **over) -> CausalLM:
    dims = {
        "125m": dict(n_layer=12, n_head=12, d_model=768),
        "1.3b": dict(n_layer=24, n_head=32, d_model=2048),
        "6.7b": dict(n_layer=32, n_head=32, d_model=4096),
        "13b": dict(n_layer=40, n_head=40, d_model=5120),
        "30b": dict(n_layer=48, n_head=56, d_model=7168),
        "66b": dict(n_layer=64, n_head=72, d_model=9216),
    }[size]
    cfg = TransformerConfig(vocab_size=50272, max_seq=2048, pos_embedding="learned", norm="layernorm",
                            activation="relu", tie_embeddings=True, attn_bias=True, **dims, **over)
    return CausalLM(cfg)


def gpt_neox(size: str = "20b", **over) -> CausalLM:
    dims = {
        "tiny": dict(n_layer=4, n_head=8, d_model=512),
        "20b": dict(n_layer=44, n_head=64, d_model=6144),
    }[size]
    cfg = TransformerConfig(vocab_size=50432, max_seq=2048, pos_embedding="rope", norm="layernorm",
                            activation="gelu", parallel_residual=True, tie_embeddings=False,
                            attn_bias=True, **dims, **over)
    return CausalLM(cfg)


MODEL_PRESETS: Dict[str, Callable] = {
    "gpt2": gpt2,
    "llama": llama,
    "bloom": bloom,
    "opt": opt,
    "gpt_neox": gpt_neox,
}


def get_model(family: str, size: str = None, **over) -> CausalLM:
    fn = MODEL_PRESETS[family]
    return fn(size, **over) if size else fn(**over)
