"""TPU-first decoder/encoder transformer backbone shared by the model zoo.

This is the training-side analogue of the reference's fused transformer
kernels (``csrc/transformer/``, ``deepspeed/ops/transformer/transformer.py``)
re-designed for XLA rather than translated: one stacked-parameter layer block
executed with ``lax.scan`` (single compile for all layers, the layout
ZeRO-3/FSDP wants: gathering one layer's params per scan step bounds live
memory exactly like the reference's fetch/release coordinator), optional
``jax.checkpoint`` rematerialisation (activation checkpointing), einsum-form
attention XLA fuses onto the MXU, and TP/SP sharding expressed as
PartitionSpecs.

Model families configure the block: GPT-2 (learned pos + LN + gelu),
Llama (RoPE + RMSNorm + SwiGLU), BLOOM (alibi), OPT, GPT-NeoX, BERT
(bidirectional). See the thin wrappers in ``deepspeed_tpu/models/``.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: Optional[int] = None           # default 4*d_model (or 8/3 for swiglu)
    max_seq: int = 1024
    n_kv_head: Optional[int] = None      # GQA; default n_head
    # block style
    pos_embedding: str = "learned"       # learned | rope | alibi | none
    norm: str = "layernorm"              # layernorm | rmsnorm
    activation: str = "gelu"             # gelu (tanh) | gelu_exact | quick_gelu | swiglu | relu
    parallel_residual: bool = False      # gpt-neox style
    norm_position: str = "pre"           # pre (GPT) | post (BERT add&norm)
    causal: bool = True
    tie_embeddings: bool = True
    embed_layernorm: bool = False        # BLOOM word_embeddings_layernorm
    attn_bias: bool = False              # qkv/out biases (gpt2/opt/bloom/neox)
    # numerics
    rope_theta: float = 10000.0
    rope_dim: int = 0                    # 0 = full head dim; else partial
    rope_interleaved: bool = False       # GPT-J pairing vs NeoX half-split
    lm_head_bias: bool = False           # GPT-J's lm_head carries a bias
    norm_eps: float = 1e-5
    # hidden dropout (embedding sum + both residual-branch outputs, GPT-2
    # placement), applied only when the loss path is given an rng — eval and
    # inference paths pass none and stay deterministic. Attention-PROBS
    # dropout is deliberately not implemented: the flash kernel family
    # cannot apply it and a silent einsum-only fallback would change
    # numerics between paths (modern recipes train attention undropped).
    dropout: float = 0.0
    # memory: activation checkpointing per layer. False/"none" = save all
    # activations; True/"full" = save only layer inputs (reference
    # CheckpointFunction semantics); "dots" = save matmul outputs, recompute
    # the cheap elementwise/attention parts (best MFU when it fits HBM);
    # "offload_dots" = save matmul outputs to pinned host memory.
    remat: Any = True
    scan_layers: bool = True
    # sequence/context parallelism over the "sp" mesh axis
    sequence_parallel: str = "none"      # none | ring | ulysses
    # attention kernel: auto = Pallas flash on TPU, XLA einsum elsewhere
    attention_backend: str = "auto"      # auto | flash | xla
    # flash kernel block sizes on the direct / batch-head-sharded kernel
    # paths; None = the kernel's measured defaults (whole-sequence blocks at
    # S <= 1024, 512x512 above). The sp (ring/ulysses) paths keep their own
    # shard-local block tuning and warn if these are set.
    attn_block_q: Optional[int] = None
    attn_block_k: Optional[int] = None
    # block-sparse attention: a SparsityConfig (ops/sparse_attention) whose
    # layout replaces dense attention in every layer — the model-level
    # integration the reference does by module surgery
    # (ops/sparse_attention/sparse_attention_utils.py
    # replace_model_self_attention_with_sparse_self_attention). TPU runs the
    # block-sparse flash kernel; elsewhere the exact dense token-bias form.
    sparse_attention: Optional[Any] = None
    # cross-entropy in sequence chunks of this many tokens: never
    # materialises the full [B, S, vocab] logits (0 = unchunked). Only
    # consulted when the fused CE kernel below is off / unavailable.
    loss_chunk: int = 0
    # vocab-head loss kernel: "auto" = the fused logits-free Pallas
    # cross-entropy kernel (ops/pallas/fused_cross_entropy) on TPU, the XLA
    # loss_chunk streaming path elsewhere; "on" forces the kernel (interpret
    # mode off-TPU — the CPU test tier); "off" keeps the XLA path
    fused_cross_entropy: str = "auto"
    # attention logit scale; None = head_dim**-0.5. GPT-Neo-family models
    # use UNSCALED attention (1.0)
    attn_scale: Optional[float] = None
    # QAT activation fake-quant (dynamic range, straight-through bwd) applied
    # to the attention and MLP inputs; 0 = off. Wired automatically by
    # compression.init_compression from the activation_quantization config
    # section (reference compression/basic_layer.py:118-860 QuantAct)
    act_quant_bits: int = 0
    act_quant_sym: bool = True
    # Megatron-style MANUAL tensor parallelism: the mesh axis name over which
    # attention/mlp weights arrive pre-sliced (column-parallel qkv/up,
    # row-parallel out/down) and the blocks insert the f/g collectives
    # explicitly (_mtp_in/_mtp_out). Set only by the pipeline engine's
    # manual-tp stage factory (models/pipeline.py manual_tp_stage_fn) for
    # execution inside a fully-manual (pp × dp × tp) stage shard_map, where
    # the SPMD partitioner — which otherwise inserts these collectives from
    # the sharding specs — is not available. Reference capability: fused
    # kernels + TP run unchanged under PP (csrc/transformer/inference/csrc/
    # pt_binding.cpp:1668-1793 via deepspeed/runtime/pipe/engine.py:596).
    manual_tp: Optional[str] = None
    # init
    init_std: float = 0.02

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @property
    def kv_heads(self) -> int:
        return self.n_kv_head or self.n_head

    @property
    def ff_dim(self) -> int:
        if self.d_ff is not None:
            return self.d_ff
        if self.activation == "swiglu":
            # keep matmul dims MXU-friendly (multiple of 128)
            d = int(8 * self.d_model / 3)
            return (d + 127) // 128 * 128
        return 4 * self.d_model


# --------------------------------------------------------------------- #
# parameter init

def init_params(cfg: TransformerConfig, rng, dtype=jnp.float32) -> Dict[str, Any]:
    """Stacked-layer parameter pytree. Layer weights carry a leading
    ``n_layer`` dim so ``lax.scan`` runs one compiled block for all layers."""
    k_emb, k_pos, k_layers, k_head = jax.random.split(rng, 4)
    std = cfg.init_std
    L, D, F = cfg.n_layer, cfg.d_model, cfg.ff_dim
    H, KV, Hd = cfg.n_head, cfg.kv_heads, cfg.head_dim

    def norm_params():
        scale = jnp.ones((L, D), dtype)
        if cfg.norm == "layernorm":
            return {"scale": scale, "bias": jnp.zeros((L, D), dtype)}
        return {"scale": scale}

    def dense(key, shape, scale=std):
        return (jax.random.normal(key, shape) * scale).astype(dtype)

    ks = jax.random.split(k_layers, 8)
    # attention out & mlp down get depth-scaled init (gpt-2 style)
    out_std = std / math.sqrt(2 * L)
    params: Dict[str, Any] = {
        "embed": {"tokens": dense(k_emb, (cfg.vocab_size, D))},
        "layers": {
            "ln_attn": norm_params(),
            "attn": {
                "wq": dense(ks[0], (L, D, H * Hd)),
                "wk": dense(ks[1], (L, D, KV * Hd)),
                "wv": dense(ks[2], (L, D, KV * Hd)),
                "wo": dense(ks[3], (L, H * Hd, D), out_std),
                **({"bq": jnp.zeros((L, H * Hd), dtype),
                    "bk": jnp.zeros((L, KV * Hd), dtype),
                    "bv": jnp.zeros((L, KV * Hd), dtype),
                    "bo": jnp.zeros((L, D), dtype)} if cfg.attn_bias else {}),
            },
            "ln_mlp": norm_params(),
            "mlp": ({
                "w_gate": dense(ks[4], (L, D, F)),
                "w_up": dense(ks[5], (L, D, F)),
                "w_down": dense(ks[6], (L, F, D), out_std),
            } if cfg.activation == "swiglu" else {
                "w_up": dense(ks[5], (L, D, F)),
                "b_up": jnp.zeros((L, F), dtype),
                "w_down": dense(ks[6], (L, F, D), out_std),
                "b_down": jnp.zeros((L, D), dtype),
            }),
        },
        "ln_f": ({"scale": jnp.ones((D,), dtype), "bias": jnp.zeros((D,), dtype)}
                 if cfg.norm == "layernorm" else {"scale": jnp.ones((D,), dtype)}),
    }
    if cfg.pos_embedding == "learned":
        params["embed"]["positions"] = dense(k_pos, (cfg.max_seq, D))
    if cfg.embed_layernorm:
        params["embed"]["ln"] = ({"scale": jnp.ones((D,), dtype), "bias": jnp.zeros((D,), dtype)}
                                 if cfg.norm == "layernorm" else {"scale": jnp.ones((D,), dtype)})
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(k_head, (D, cfg.vocab_size))
        if cfg.lm_head_bias:
            params["lm_head_bias"] = jnp.zeros((cfg.vocab_size,), dtype)
    return params


def tp_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """Tensor-parallel PartitionSpecs: column-shard qkv/up, row-shard out/down
    (Megatron layout over the ``tp`` mesh axis); vocab-shard embeddings.
    ZeRO sharding composes on the remaining free dims."""
    ln = {"scale": P(None, None), "bias": P(None, None)} if cfg.norm == "layernorm" else {"scale": P(None, None)}
    specs = {
        "embed": {"tokens": P("tp", None)},
        "layers": {
            "ln_attn": ln,
            "attn": {
                "wq": P(None, None, "tp"),
                "wk": P(None, None, "tp"),
                "wv": P(None, None, "tp"),
                "wo": P(None, "tp", None),
                **({"bq": P(None, "tp"), "bk": P(None, "tp"),
                    "bv": P(None, "tp"), "bo": P(None, None)} if cfg.attn_bias else {}),
            },
            "ln_mlp": ln,
            "mlp": ({
                "w_gate": P(None, None, "tp"),
                "w_up": P(None, None, "tp"),
                "w_down": P(None, "tp", None),
            } if cfg.activation == "swiglu" else {
                "w_up": P(None, None, "tp"),
                "b_up": P(None, "tp"),
                "w_down": P(None, "tp", None),
                "b_down": P(None, None),
            }),
        },
        "ln_f": {"scale": P(None), "bias": P(None)} if cfg.norm == "layernorm" else {"scale": P(None)},
    }
    if cfg.pos_embedding == "learned":
        specs["embed"]["positions"] = P(None, None)
    if cfg.embed_layernorm:
        specs["embed"]["ln"] = ({"scale": P(None), "bias": P(None)}
                                if cfg.norm == "layernorm" else {"scale": P(None)})
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tp")
        if cfg.lm_head_bias:
            specs["lm_head_bias"] = P("tp")
    return specs


# --------------------------------------------------------------------- #
# forward


def _w(entry, like):
    """Weight access: transparently dequantises int8 ``Quantized8`` leaves
    (weight-only inference quantisation) to ``like``'s dtype."""
    from deepspeed_tpu.ops.quant import maybe_dequant
    return maybe_dequant(entry, like.dtype)


def _norm(cfg: TransformerConfig, x, p):
    x32 = x.astype(jnp.float32)
    if cfg.norm == "rmsnorm":
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        out = x32 * jax.lax.rsqrt(var + cfg.norm_eps) * p["scale"].astype(jnp.float32)
    else:
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        out = (x32 - mean) * jax.lax.rsqrt(var + cfg.norm_eps)
        out = out * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return out.astype(x.dtype)


def _rope(x, positions, theta: float, rope_dim: int = 0,
          interleaved: bool = False):
    """Rotary position embedding.

    ``rope_dim`` 0/None rotates the full head dim; otherwise only the first
    ``rope_dim`` dims rotate and the tail passes through (GPT-NeoX
    ``rotary_pct < 1`` / GPT-J ``rotary_dim``). ``interleaved`` selects the
    GPT-J pairing (dims (0,1),(2,3),...) instead of the NeoX/Llama
    half-split pairing (dims (i, i+half)).
    """
    B, S, H, Hd = x.shape
    rd = rope_dim or Hd
    xr, tail = x[..., :rd], x[..., rd:]
    half = rd // 2
    freqs = jnp.exp(-math.log(theta) * jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, :, None].astype(jnp.float32) * freqs[None, None, :]  # [B,S,half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    if interleaved:
        x1, x2 = xr[..., 0::2], xr[..., 1::2]
        r1 = x1 * cos - x2 * sin
        r2 = x2 * cos + x1 * sin
        rotated = jnp.stack([r1, r2], axis=-1).reshape(xr.shape)
    else:
        x1, x2 = xr[..., :half], xr[..., half:]
        rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                                  axis=-1)
    if rd != Hd:
        rotated = jnp.concatenate([rotated, tail.astype(rotated.dtype)], axis=-1)
    return rotated.astype(x.dtype)


def _alibi_slopes(n_head: int):
    # standard alibi slope schedule
    start = 2.0**(-8.0 / n_head)
    return jnp.asarray([start**(i + 1) for i in range(n_head)], jnp.float32)


def key_mask_bias(attn_mask):
    """[B, S] 1=keep attention mask → additive key-side bias [B, S]
    (0 keep / -1e9 drop); None passes through. Single producer for every
    attention path (dense, ring, ulysses)."""
    if attn_mask is None:
        return None
    return jnp.where(attn_mask > 0, 0.0, -1e9).astype(jnp.float32)


# sequence length beyond which the XLA fallback attention streams its
# softmax (sequence/_streaming.py) instead of materialising S x S logits;
# the chunk size is deliberately smaller so just-over-threshold sequences
# don't pad a near-full chunk of dead keys
DENSE_STREAM_THRESHOLD = 4096
DENSE_STREAM_CHUNK = 1024


def _dropout(cfg: TransformerConfig, x, key):
    """Inverted dropout; identity when the rate is 0 or no key is given
    (eval / inference). Reference capability: the fused training layer's
    hidden-dropout ratios (csrc/transformer/ds_transformer_cuda.cpp
    dropout kernels; config attn_dropout_ratio/hidden_dropout_ratio)."""
    if not cfg.dropout or key is None:
        return x
    keep = 1.0 - cfg.dropout
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0).astype(x.dtype)


def _mtp_in(x, axis):
    """Megatron's ``f`` operator: identity forward, psum backward. Inside a
    manual-tp region the cotangents arriving from the column-parallel
    consumers (qkv / up projections) are per-shard partials; summing them
    here hands the replicated upstream land (residual, LN, embed) a full
    gradient."""
    @jax.custom_vjp
    def f(x):
        return x
    f.defvjp(lambda x: (x, None), lambda _, g: (jax.lax.psum(g, axis),))
    return f(x)


def _mtp_out(x, axis):
    """Megatron's ``g`` operator: psum forward (complete the row-parallel
    matmul's contraction over the sharded inner dim), identity backward (the
    downstream cotangent is already replicated over the axis)."""
    @jax.custom_vjp
    def g(x):
        return jax.lax.psum(x, axis)
    g.defvjp(lambda x: (jax.lax.psum(x, axis), None), lambda _, ct: (ct,))
    return g(x)


def attention(cfg: TransformerConfig, x, lp, positions, mask_bias):
    """Einsum-form multi-head attention; XLA maps the batched matmuls onto
    the MXU and fuses softmax. (A Pallas flash-attention kernel can be slotted
    in via deepspeed_tpu.ops — see ops/transformer.)

    With ``cfg.manual_tp`` set the weights arrive pre-sliced over the tp
    mesh axis (whole heads per shard) and the block runs Megatron-style:
    f at the input, local-head attention (which reaches the bare flash
    kernel — the context is fully manual), g after the out projection."""
    B, S, D = x.shape
    H, KV, Hd = cfg.n_head, cfg.kv_heads, cfg.head_dim
    if cfg.manual_tp:
        from deepspeed_tpu.comm import bound_axis_size
        tp = bound_axis_size(cfg.manual_tp)
        H //= tp
        KV //= tp
        x = _mtp_in(x, cfg.manual_tp)

    from jax.ad_checkpoint import checkpoint_name
    x = _maybe_act_quant(cfg, x)
    # attn_bias=True REQUIRES all four bias tensors (loud KeyError on a
    # params tree saved without them, consistent with the bo access below)
    bq = lp["bq"] if cfg.attn_bias else 0
    bk = lp["bk"] if cfg.attn_bias else 0
    bv = lp["bv"] if cfg.attn_bias else 0
    q = checkpoint_name((x @ _w(lp["wq"], x) + bq).reshape(B, S, H, Hd), "q_proj")
    k = checkpoint_name((x @ _w(lp["wk"], x) + bk).reshape(B, S, KV, Hd), "k_proj")
    v = checkpoint_name((x @ _w(lp["wv"], x) + bv).reshape(B, S, KV, Hd), "v_proj")

    if cfg.pos_embedding == "rope":
        q = _rope(q, positions, cfg.rope_theta, cfg.rope_dim, cfg.rope_interleaved)
        k = _rope(k, positions, cfg.rope_theta, cfg.rope_dim, cfg.rope_interleaved)

    if cfg.pos_embedding == "alibi":
        # slope values follow the GLOBAL head index; a manual-tp shard
        # carries heads [r*H, (r+1)*H) of the full set
        slopes = _alibi_slopes(cfg.n_head)
        if cfg.manual_tp:
            r = jax.lax.axis_index(cfg.manual_tp)
            slopes = jax.lax.dynamic_slice_in_dim(slopes, r * H, H)
    else:
        slopes = None

    if cfg.sparse_attention is not None:
        if cfg.manual_tp:
            raise NotImplementedError(
                "sparse attention does not compose with manual-tp pipeline "
                "stages (the stage factory refuses this config; pp×tp runs "
                "the vmap/SPMD path instead)")
        out = _sparse_model_attention(cfg, q, k, v, mask_bias, slopes)
        out = checkpoint_name(out.reshape(B, S, H * Hd), "attn_out")
        proj = out @ _w(lp["wo"], out) + (lp["bo"] if cfg.attn_bias else 0)
        return checkpoint_name(proj, "wo_out")

    sp_mesh = _sp_mesh(cfg)
    out = None
    if sp_mesh is not None:
        # GQA kv stays UNREPEATED through the sp collectives (ring ppermute /
        # ulysses all-to-all move H/KV-times less data); the shard bodies
        # broadcast kv heads locally
        from deepspeed_tpu.sequence import sp_attention
        if cfg.attn_block_q or cfg.attn_block_k:
            from deepspeed_tpu.utils.logging import warn_once
            warn_once("attn_block_q/attn_block_k apply to the direct and "
                      "batch/head-sharded flash paths; the sequence-parallel "
                      "kernels keep their own shard-local block tuning")
        out = sp_attention(q, k, v, mesh=sp_mesh, impl=cfg.sequence_parallel,
                           causal=cfg.causal, mask_bias=mask_bias,
                           alibi_slopes=slopes, scale=cfg.attn_scale)
    else:
        # kernel paths first — the Pallas kernel beats the XLA streaming
        # core at every length it can run
        use_direct = _use_flash(cfg)
        fmesh = None if use_direct else _flash_mesh(cfg)
        if use_direct or fmesh is not None:
            # GQA kv goes in UNREPEATED — the flash kernel index-maps query
            # head h to kv head h // (H/KV), so HBM/VMEM kv traffic stays at
            # KV heads (H/KV× less on llama-style GQA)
            if use_direct:
                from deepspeed_tpu.ops.pallas import flash_attention
                out = flash_attention(q, k, v, mask_bias=mask_bias,
                                      causal=cfg.causal, alibi_slopes=slopes,
                                      scale=cfg.attn_scale,
                                      block_q=cfg.attn_block_q,
                                      block_k=cfg.attn_block_k)
            else:
                out = _flash_sharded(cfg, q, k, v, mask_bias, slopes, fmesh)
        if out is None and S > DENSE_STREAM_THRESHOLD:
            # long sequences off the kernel paths (pipeline stage vmap,
            # sp-less CPU, shapes outside the kernel envelope): stream the
            # softmax through the shared chunked core instead of
            # materialising the S x S logits — pure jnp, so it vmaps over
            # pipeline stages and partitions under pp where a Pallas call
            # cannot go. GQA kv goes in unrepeated when no kernel was tried
            # (the core broadcasts per chunk).
            from deepspeed_tpu.sequence._streaming import chunked_attention
            mb = None if mask_bias is None else mask_bias.astype(jnp.float32)
            out, _ = chunked_attention(q, k, v, mb, slopes, jnp.int32(0),
                                       jnp.int32(0), cfg.causal,
                                       DENSE_STREAM_CHUNK, q.dtype,
                                       cfg.attn_scale)
    if out is None:
        # GQA kv goes in UNREPEATED — mha_attention contracts grouped query
        # heads [KV, G] against the raw kv, no H/KV× copy
        from deepspeed_tpu.ops.attention import mha_attention
        out = mha_attention(q, k, v,
                            mask_bias=None if mask_bias is None else mask_bias[:, None, None, :],
                            causal=cfg.causal, alibi_slopes=slopes,
                            scale=cfg.attn_scale)
    out = checkpoint_name(out.reshape(B, S, H * Hd), "attn_out")
    proj = out @ _w(lp["wo"], out)
    if cfg.manual_tp:
        # row-parallel wo: each shard contracted its local heads only —
        # complete the sum, then add the replicated bias ONCE
        proj = _mtp_out(proj, cfg.manual_tp)
    proj = proj + (lp["bo"] if cfg.attn_bias else 0)
    return checkpoint_name(proj, "wo_out")


def _sparse_model_attention(cfg: TransformerConfig, q, k, v, mask_bias, slopes):
    """Model-level block-sparse attention (cfg.sparse_attention set): every
    layer computes softmax over the sparsity layout's support only. TPU
    single-device/full-manual contexts run the block-sparse flash kernel
    (zero blocks skipped fwd+bwd); everywhere else the exact dense
    token-bias einsum, which vmaps and partitions like the other fallbacks.
    Reference capability: sparse_attention_utils.py module surgery swapping
    BertSparseSelfAttention into the encoder."""
    from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
        sparse_attention_core)
    B, S, H, Hd = q.shape
    if k.shape[2] != H:
        raise NotImplementedError(
            "sparse attention requires n_kv_head == n_head (MHA)")
    if slopes is not None:
        raise NotImplementedError("sparse attention does not compose with alibi")
    if _sp_mesh(cfg) is not None:
        raise NotImplementedError(
            "sparse attention does not compose with sequence parallelism")
    sc = cfg.sparse_attention
    # Dense/base configs carry no directionality — cfg.causal alone governs
    mode = getattr(sc, "attention", None)
    if mode is not None and (mode == "unidirectional") != bool(cfg.causal):
        raise ValueError(f"sparsity config attention={mode!r} disagrees with "
                         f"the model's causal={cfg.causal}")
    layout = sc.make_layout(S)
    if layout.shape[0] != H:
        raise ValueError(f"sparsity config num_heads={layout.shape[0]} != "
                         f"model n_head={H}")
    # the kernel wants layout blocks that are legal VMEM tiles; smaller
    # blocks (or CPU) take the exact dense form (make_layout already
    # rejected S not divisible by the block; the core rejects dense
    # fallbacks past its DENSE_SPARSE_MAX_SEQ — single guard, single
    # message)
    mb = None if mask_bias is None else mask_bias.astype(jnp.float32)
    if sc.block >= 128 and sc.block % 8 == 0:  # legal VMEM tile sizes only
        if _use_flash(cfg):
            return sparse_attention_core(q, k, v, layout, sc.block,
                                         bool(cfg.causal), mb,
                                         scale=cfg.attn_scale, use_pallas=True)
        fmesh = _flash_mesh(cfg)
        if fmesh is not None:
            # multi-chip dp/fsdp×tp(×ep) mesh: the layout rides the head
            # axis through the shard_map so every shard keeps the
            # block-sparse kernel
            out = _flash_sharded(cfg, q, k, v, mb, None, fmesh,
                                 block_layout=layout)
            if out is not None:
                return out
    return sparse_attention_core(q, k, v, layout, sc.block, bool(cfg.causal),
                                 mb, scale=cfg.attn_scale, use_pallas=False)


def _inside_full_manual(mesh) -> bool:
    """True when every mesh axis of size > 1 is a manual axis of the current
    trace — i.e. we are inside a shard_map over all partitioned axes, so
    array data is fully device-local and a bare ``pallas_call`` is legal.
    This is how attention under the pipeline engine's stage shard_map
    reaches the flash kernel (runtime/pipe/engine.py)."""
    for name, size in mesh.shape.items():
        if size > 1:
            try:
                # probe only: axis_index raises NameError iff the axis is
                # not bound in the current trace (works on every jax
                # version; lax.axis_size does not exist on older ones)
                jax.lax.axis_index(name)
            except NameError:
                return False
    return True


def _bare_pallas_legal() -> bool:
    """Whether a bare (unwrapped) ``pallas_call`` is legal here: single-device
    meshes, or a fully-manual shard_map context (every partitioned mesh axis
    already local, e.g. the pipeline engine's stage bodies). Elsewhere XLA's
    SPMD partitioner would have to partition the call, which it cannot —
    the single invariant behind both the flash-attention and fused-CE
    dispatches."""
    import deepspeed_tpu.comm as dist
    return not (dist.has_mesh() and dist.get_mesh().devices.size > 1
                and not _inside_full_manual(dist.get_mesh()))


def _use_flash(cfg: TransformerConfig) -> bool:
    """Direct (unwrapped) Pallas flash attention where a bare pallas_call is
    legal (:func:`_bare_pallas_legal`). Other multi-device meshes go through
    :func:`_flash_sharded` (shard_map over batch/head axes) instead."""
    if cfg.attention_backend not in ("flash", "auto"):
        return False
    if not _bare_pallas_legal():
        return False
    if cfg.attention_backend == "flash":
        return True
    return jax.default_backend() == "tpu"


def _flash_mesh(cfg: TransformerConfig):
    """The active mesh when the shard_map-wrapped flash kernel applies:
    every axis of size > 1 must be one the kernel can shard without
    communication — batch over dp/fsdp, heads over tp — or one attention is
    replicated over (ep: expert parallelism shards only the expert MLPs, so
    attention math is identical across the axis). Pipeline / sequence axes
    fall back to the einsum form (attention there runs under the stage vmap /
    the sp paths, where a shard_map cannot be placed)."""
    if cfg.attention_backend not in ("flash", "auto"):
        return None
    if cfg.attention_backend == "auto" and jax.default_backend() != "tpu":
        return None
    import deepspeed_tpu.comm as dist
    if not dist.has_mesh():
        return None
    mesh = dist.get_mesh()
    if mesh.devices.size == 1:
        return None
    for name, size in mesh.shape.items():
        if size > 1 and name not in ("dp", "fsdp", "tp", "ep"):
            return None
        if size > 1:
            # already inside a shard_map/pmap over this axis (e.g. the 1-bit
            # optimizer step)? a nested shard_map is illegal — use einsum
            # (axis_index as the bound-axis probe, see _inside_full_manual)
            try:
                jax.lax.axis_index(name)
                return None
            except NameError:
                pass
    return mesh


def _shard_axes(mesh, B: int, H: int, KV: int = None):
    """Batch/head mesh-axis split shared by the shard_map-wrapped kernels:
    returns (batch_axes, head_axis, nb, nh), or None when the sizes don't
    divide the axes."""
    batch_axes = tuple(a for a in ("dp", "fsdp") if mesh.shape.get(a, 1) > 1)
    head_axis = "tp" if mesh.shape.get("tp", 1) > 1 else None
    nb = 1
    for a in batch_axes:
        nb *= mesh.shape[a]
    nh = mesh.shape["tp"] if head_axis else 1
    if B % nb or H % nh or (KV is not None and KV % nh):
        return None
    return batch_axes, head_axis, nb, nh


def _flash_sharded(cfg: TransformerConfig, q, k, v, mask_bias, slopes, mesh,
                   block_layout=None):
    """Flash attention under a dp/fsdp×tp mesh: shard_map over the batch and
    head axes (no cross-shard communication — attention is pointwise in batch
    and head), so the Pallas kernel runs per-shard instead of silently
    falling back to O(S²) einsum attention on multi-chip meshes.
    ``block_layout`` [H, nb, nb] rides the head axis, so block-SPARSE
    attention keeps the kernel on multi-chip meshes too.
    Returns None when the shard sizes don't divide (caller falls back)."""
    from deepspeed_tpu.utils.jax_compat import shard_map

    B, S, H, Hd = q.shape
    KV = k.shape[2]
    split = _shard_axes(mesh, B, H, KV)
    if split is None and KV != H and _shard_axes(mesh, B, H) is not None:
        # KV heads don't divide the tp axis (e.g. 8 kv heads, tp=16): repeat
        # kv to H heads so each shard still runs the kernel — pays the GQA
        # repeat copy but keeps the flash path
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)
        KV = H
        split = _shard_axes(mesh, B, H)
    if split is None:
        return None
    batch_axes, head_axis, nb, nh = split

    qspec = P(batch_axes or None, None, head_axis, None)
    mspec = P(batch_axes or None, None)
    sspec = P(head_axis)

    from deepspeed_tpu.ops.pallas import flash_attention

    # None mask/slopes stay None INSIDE the shard_map (instead of zero
    # arrays) so the kernel's plain-causal fast path engages per shard
    operands = [q, k, v]
    specs = [qspec, qspec, qspec]
    if mask_bias is not None:
        operands.append(mask_bias.astype(jnp.float32))
        specs.append(mspec)
    if slopes is not None:
        operands.append(jnp.asarray(slopes, jnp.float32).reshape(H))
        specs.append(sspec)
    if block_layout is not None:
        operands.append(jnp.asarray(block_layout, jnp.float32))
        specs.append(P(head_axis))

    def inner(qs, ks, vs, *rest):
        rest = list(rest)
        ms = rest.pop(0) if mask_bias is not None else None
        ss = rest.pop(0) if slopes is not None else None
        bl = rest.pop(0) if block_layout is not None else None
        return flash_attention(qs, ks, vs, mask_bias=ms, causal=cfg.causal,
                               alibi_slopes=ss, scale=cfg.attn_scale,
                               block_q=cfg.attn_block_q,
                               block_k=cfg.attn_block_k,
                               block_layout=bl)

    wrapped = shard_map(inner, mesh=mesh, in_specs=tuple(specs),
                       out_specs=qspec, check_vma=False)
    return wrapped(*operands)


def _decode_sharded(q1, ck, cv, pos, pad_bias, slopes, mesh, scale=None):
    """Decode-attention kernel under a dp/fsdp×tp mesh: shard_map over batch
    (q/cache/pad_bias) and heads (q + KV cache + slopes) — decode attention
    is pointwise in batch and head, so shards need no communication and the
    multi-chip TP serving path keeps the fused kernel instead of the
    O(B·H·Smax) einsum with a repeated GQA cache.
    Returns None when shard sizes don't divide or the per-shard shape is
    outside the kernel envelope (caller falls back)."""
    from deepspeed_tpu.utils.jax_compat import shard_map

    B, H, Hd = q1.shape
    Smax, KV = ck.shape[1], ck.shape[2]
    split = _shard_axes(mesh, B, H, KV)
    if split is None:
        return None
    batch_axes, head_axis, nb, nh = split
    # per-shard kernel envelope, checked here because the shard_map body
    # cannot fall back per-shard
    if (H // nh) % (KV // nh) or Hd % 64 or Smax % 128:
        return None

    from deepspeed_tpu.ops.pallas.decode_attention import decode_attention

    qspec = P(batch_axes or None, head_axis, None)
    cspec = P(batch_axes or None, None, head_axis, None)
    operands = [q1, ck, cv, jnp.asarray(pos, jnp.int32)]
    specs = [qspec, cspec, cspec, P()]
    if pad_bias is not None:
        operands.append(pad_bias.astype(jnp.float32))
        specs.append(P(batch_axes or None, None))
    if slopes is not None:
        operands.append(jnp.asarray(slopes, jnp.float32).reshape(H))
        specs.append(P(head_axis))

    def inner(qs, cks, cvs, ps, *rest):
        rest = list(rest)
        ms = rest.pop(0) if pad_bias is not None else None
        ss = rest.pop(0) if slopes is not None else None
        return decode_attention(qs, cks, cvs, ps, pad_bias=ms, alibi_slopes=ss,
                                scale=scale)

    wrapped = shard_map(inner, mesh=mesh, in_specs=tuple(specs),
                        out_specs=qspec, check_vma=False)
    return wrapped(*operands)


def _paged_shard_ok(mesh, H: int, KV: int, Hd: int, bs: int) -> bool:
    """Whether the shard_map'd paged kernel applies on ``mesh``: heads and
    KV heads must divide the tp axis, and the PER-SHARD shape must sit
    inside the kernel envelope (a shard_map body cannot fall back
    per-shard, so the check happens out here)."""
    from deepspeed_tpu.ops.pallas.paged_decode_attention import \
        paged_envelope_ok
    nh = mesh.shape.get("tp", 1)
    if H % nh or KV % nh:
        return False
    return paged_envelope_ok(H // nh, KV // nh, Hd, bs)


def _paged_decode_sharded(q1, kp, vp, block_tables, pos, pad_bias, slopes,
                          mesh, scale=None):
    """Paged decode-attention kernel under an SPMD mesh: shard_map over the
    KV-HEAD axis — q and the block pools split over ``tp``, while block
    tables, positions and the logical-position bias stay REPLICATED
    (per-shard block indices are identical; the head split is the only
    partition, so shards need no communication). dp/fsdp/ep axes replicate
    the whole fused step: continuous batching is ONE program over all
    running rows and the pool is shared state, not batch data. This is how
    multi-chip TP serving keeps the scalar-prefetched Pallas kernel
    instead of falling back to the gather + einsum path.
    Returns None when :func:`_paged_shard_ok` rejects the split."""
    from deepspeed_tpu.utils.jax_compat import shard_map

    B, H, Hd = q1.shape
    bs, KV = kp.shape[1], kp.shape[2]
    if not _paged_shard_ok(mesh, H, KV, Hd, bs):
        return None
    head_axis = "tp" if mesh.shape.get("tp", 1) > 1 else None

    from deepspeed_tpu.ops.pallas.paged_decode_attention import \
        paged_decode_attention

    qspec = P(None, head_axis, None)
    pspec = P(None, None, head_axis, None)
    operands = [q1, kp, vp, jnp.asarray(block_tables, jnp.int32),
                jnp.asarray(pos, jnp.int32)]
    specs = [qspec, pspec, pspec, P(), P()]
    if pad_bias is not None:
        operands.append(pad_bias.astype(jnp.float32))
        specs.append(P(None, None))
    if slopes is not None:
        # contiguous head chunks of H/nh = G * (KV/nh) heads: each shard's
        # slopes regroup to its own (KV_shard, G) exactly like q does
        operands.append(jnp.asarray(slopes, jnp.float32).reshape(H))
        specs.append(P(head_axis))

    def inner(qs, kps, vps, bts, ps, *rest):
        rest = list(rest)
        ms = rest.pop(0) if pad_bias is not None else None
        ss = rest.pop(0) if slopes is not None else None
        return paged_decode_attention(qs, kps, vps, bts, ps, pad_bias=ms,
                                      alibi_slopes=ss, scale=scale)

    wrapped = shard_map(inner, mesh=mesh, in_specs=tuple(specs),
                        out_specs=qspec, check_vma=False)
    return wrapped(*operands)


def _sp_mesh(cfg: TransformerConfig):
    """The active mesh when sequence parallelism is configured AND the mesh
    carries an sp axis of size > 1; else None (dense attention)."""
    if cfg.sequence_parallel == "none":
        return None
    import deepspeed_tpu.comm as dist
    if not dist.has_mesh():
        return None
    mesh = dist.get_mesh()
    if "sp" in mesh.shape and mesh.shape["sp"] > 1:
        return mesh
    return None


def _remat_policy(remat):
    """Map the config's remat setting to a jax.checkpoint policy (None =
    full remat, the reference's save-only-inputs CheckpointFunction)."""
    if remat is True or remat == "full":
        return None
    pols = jax.checkpoint_policies
    if remat == "dots":
        return pols.dots_with_no_batch_dims_saveable
    if remat == "selective":
        # save only the [tokens, D]-sized projections (cheap to store) plus
        # the flash kernel's (o, lse) residuals — so backward runs the flash
        # backward kernels WITHOUT re-running the forward kernel — and
        # recompute the d_ff-sized up/gate activations in backward
        return pols.save_only_these_names(
            "q_proj", "k_proj", "v_proj", "attn_out", "wo_out", "ff_down",
            "flash_o", "flash_lse")
    if remat == "offload_dots":
        return pols.offload_dot_with_no_batch_dims("device", "pinned_host")
    raise ValueError(f"unknown remat policy {remat!r} (expected True/'full', "
                     "'dots', 'selective', 'offload_dots', False/'none')")


def _maybe_act_quant(cfg: TransformerConfig, x):
    """QAT activation fake-quant at the matmul inputs (the reference's
    QuantAct placement); dynamic per-tensor range, STE backward."""
    if cfg.act_quant_bits:
        from deepspeed_tpu.compression.functional import quantize_activation
        return quantize_activation(x, cfg.act_quant_bits, cfg.act_quant_sym)
    return x


def mlp(cfg: TransformerConfig, x, lp):
    from jax.ad_checkpoint import checkpoint_name
    x = _maybe_act_quant(cfg, x)
    if cfg.manual_tp:
        x = _mtp_in(x, cfg.manual_tp)
    if cfg.activation == "swiglu":
        out = (jax.nn.silu(x @ _w(lp["w_gate"], x)) * (x @ _w(lp["w_up"], x))) @ _w(lp["w_down"], x)
        if cfg.manual_tp:
            out = _mtp_out(out, cfg.manual_tp)
        return checkpoint_name(out, "ff_down")
    h = x @ _w(lp["w_up"], x) + lp["b_up"]
    if cfg.activation == "gelu":
        h = jax.nn.gelu(h, approximate=True)
    elif cfg.activation == "gelu_exact":
        h = jax.nn.gelu(h, approximate=False)  # BERT's erf gelu
    elif cfg.activation == "quick_gelu":
        h = h * jax.nn.sigmoid(1.702 * h)  # CLIP's QuickGELU
    else:
        h = jax.nn.relu(h)
    out = h @ _w(lp["w_down"], x)
    if cfg.manual_tp:
        # row-parallel w_down: sum the per-shard partials, replicated bias once
        out = _mtp_out(out, cfg.manual_tp)
    return checkpoint_name(out + lp["b_down"], "ff_down")


def block(cfg: TransformerConfig, x, lp, positions, mask_bias, rng=None):
    ka = km = None
    if rng is not None and cfg.dropout:
        ka, km = jax.random.split(rng)
    if cfg.norm_position == "post":
        # BERT-style add&norm: residual first, LN after (reference's fused
        # encoder layer, csrc/transformer/ds_transformer_cuda.cpp pre/post
        # layernorm modes)
        a = _dropout(cfg, attention(cfg, x, lp["attn"], positions, mask_bias), ka)
        x = _norm(cfg, x + a, lp["ln_attn"])
        return _norm(cfg, x + _dropout(cfg, mlp(cfg, x, lp["mlp"]), km), lp["ln_mlp"])
    a = _dropout(cfg, attention(cfg, _norm(cfg, x, lp["ln_attn"]), lp["attn"],
                                positions, mask_bias), ka)
    if cfg.parallel_residual:
        m = _dropout(cfg, mlp(cfg, _norm(cfg, x, lp["ln_mlp"]), lp["mlp"]), km)
        return x + a + m
    x = x + a
    m = _dropout(cfg, mlp(cfg, _norm(cfg, x, lp["ln_mlp"]), lp["mlp"]), km)
    return x + m


def forward(cfg: TransformerConfig, params, tokens, attn_mask=None):
    """tokens [B, S] int32 → logits [B, S, vocab]."""
    x = hidden_states(cfg, params, tokens, attn_mask)
    return x @ _head_weight(cfg, params) + _head_bias(params)


# --------------------------------------------------------------------- #
# KV-cache inference path (reference: preallocated workspace + KV append,
# csrc/transformer/inference/includes/inference_context.h:49, softmax_context
# csrc/transformer/inference/csrc/pt_binding.cpp:1668-1793, layer-past
# handling deepspeed/model_implementations/transformers/ds_transformer.py:18).
# TPU design: a donated fixed-shape [L, B, Smax, KV, Hd] cache updated with
# dynamic_update_slice inside one jitted program per (prefill, decode) shape
# — no per-token recompilation, O(Smax) attention per generated token.

def init_kv_cache(cfg: TransformerConfig, batch_size: int, max_len: Optional[int] = None,
                  dtype=jnp.bfloat16) -> Dict[str, Any]:
    """Preallocated KV cache: k/v [n_layer, B, max_len, kv_heads, head_dim]."""
    Smax = max_len or cfg.max_seq
    shape = (cfg.n_layer, batch_size, Smax, cfg.kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _qkv_project(cfg: TransformerConfig, x, lp, positions):
    """Shared decode-side q/k/v projection: act-quant (QAT parity with the
    training path — or prefill/decode logits diverge from forward()),
    optional attn biases (attn_bias=True REQUIRES all four bias tensors —
    loud KeyError on a params tree saved without them), head reshape, rope.
    Returns (q [B,T,H,Hd], k [B,T,KV,Hd], v [B,T,KV,Hd])."""
    B, T, D = x.shape
    H, KV, Hd = cfg.n_head, cfg.kv_heads, cfg.head_dim
    x = _maybe_act_quant(cfg, x)
    bq = lp["bq"] if cfg.attn_bias else 0
    bk = lp["bk"] if cfg.attn_bias else 0
    bv = lp["bv"] if cfg.attn_bias else 0
    q = (x @ _w(lp["wq"], x) + bq).reshape(B, T, H, Hd)
    k = (x @ _w(lp["wk"], x) + bk).reshape(B, T, KV, Hd)
    v = (x @ _w(lp["wv"], x) + bv).reshape(B, T, KV, Hd)
    if cfg.pos_embedding == "rope":
        q = _rope(q, positions, cfg.rope_theta, cfg.rope_dim, cfg.rope_interleaved)
        k = _rope(k, positions, cfg.rope_theta, cfg.rope_dim, cfg.rope_interleaved)
    return q, k, v


def _grouped_cache_einsum(cfg: TransformerConfig, q, ck, cv, positions,
                          pad_bias):
    """Grouped-head einsum of q [B,T,H,Hd] against an UNREPEATED cache
    ck/cv [B,S,KV,Hd] with per-row causal masking at ``positions`` (query
    heads reshaped [KV, G]: head h reads kv head h // G, matching the
    kernels' index maps — off-kernel decode skips the H/KV× cache copy).
    The single masked-softmax core shared by the dense-workspace and paged
    fallback paths. Returns [B, T, H*Hd]."""
    B, T, H, Hd = q.shape
    S, KV = ck.shape[1], ck.shape[2]
    G = H // KV
    scale = Hd**-0.5 if cfg.attn_scale is None else cfg.attn_scale
    q5 = q.reshape(B, T, KV, G, Hd)
    scores = jnp.einsum("btcgd,bscd->bcgts", q5, ck,
                        preferred_element_type=jnp.float32) * scale
    kpos = jnp.arange(S, dtype=jnp.int32)[None, None, None, None, :]  # [1,1,1,1,S]
    qpos = positions[:, None, None, :, None]                          # [B,1,1,T,1]
    valid = kpos <= qpos                                              # causal + cache bound
    if cfg.pos_embedding == "alibi":
        slopes5 = _alibi_slopes(H).reshape(KV, G)
        scores = scores + slopes5[None, :, :, None, None] * (kpos - qpos).astype(jnp.float32)
    scores = jnp.where(valid, scores, -1e30)
    if pad_bias is not None:
        scores = scores + pad_bias[:, None, None, None, :]
    probs = jax.nn.softmax(scores, axis=-1).astype(cv.dtype)
    return jnp.einsum("bcgts,bscd->btcgd", probs, cv).reshape(B, T, H * Hd)


def _cached_attention(cfg: TransformerConfig, x, lp, positions, pos, ck, cv, pad_bias):
    """Attention for T new tokens against the (updated) KV cache.

    x [B, T, D]; positions [B, T] global positions of the new tokens —
    the engine contract is ``positions == pos + arange(T)`` per row (rope
    uses the array; causal/alibi geometry in both the streaming and dense
    branches assumes that contiguous layout); pos [] int32 tokens already
    cached; ck/cv [B, Smax, KV, Hd]. Returns (out [B, T, D], new ck, cv)."""
    B, T, D = x.shape
    H, KV, Hd = cfg.n_head, cfg.kv_heads, cfg.head_dim
    Smax = ck.shape[1]

    q, k, v = _qkv_project(cfg, x, lp, positions)

    ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, pos, 0, 0))
    cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, pos, 0, 0))

    if T == 1:
        # fused decode kernel: streams the cache once, no GQA repeat copy
        # (reference softmax_context, pt_binding.cpp:1668-1793) — direct on
        # one device, shard_map over batch/head axes on dp/fsdp×tp meshes
        slopes = _alibi_slopes(H) if cfg.pos_embedding == "alibi" else None
        o = None
        if _use_flash(cfg):
            from deepspeed_tpu.ops.pallas.decode_attention import decode_attention
            o = decode_attention(q[:, 0], ck, cv, pos, pad_bias=pad_bias,
                                 alibi_slopes=slopes, scale=cfg.attn_scale)
        else:
            dmesh = _flash_mesh(cfg)
            if dmesh is not None:
                o = _decode_sharded(q[:, 0], ck, cv, pos, pad_bias,
                                    slopes, dmesh, scale=cfg.attn_scale)
        if o is not None:
            out = o.reshape(B, 1, H * Hd)
            out = out @ _w(lp["wo"], out) + (lp["bo"] if cfg.attn_bias else 0)
            return out, ck, cv

    if Smax > DENSE_STREAM_THRESHOLD:
        # long-workspace prefill AND kernel-less decode: stream the softmax
        # over cache chunks (O(T·chunk) live memory, no rep-expanded cache
        # copy) instead of the O(T·Smax) einsum below. The core derives
        # query positions as pos + arange(T) — identical to the engine
        # contract this function documents (positions = pos + arange), which
        # the dense path below also assumes per batch row.
        from deepspeed_tpu.sequence._streaming import chunked_attention
        slopes = _alibi_slopes(H) if cfg.pos_embedding == "alibi" else None
        pb = None if pad_bias is None else pad_bias.astype(jnp.float32)
        o, _ = chunked_attention(q, ck, cv, pb, slopes,
                                 jnp.asarray(pos, jnp.int32), jnp.int32(0),
                                 True, DENSE_STREAM_CHUNK, q.dtype,
                                 cfg.attn_scale)
        out = o.reshape(B, T, H * Hd)
        out = out @ _w(lp["wo"], out) + (lp["bo"] if cfg.attn_bias else 0)
        return out, ck, cv

    out = _grouped_cache_einsum(cfg, q, ck, cv, positions, pad_bias)
    out = out @ _w(lp["wo"], out) + (lp["bo"] if cfg.attn_bias else 0)
    return out, ck, cv


def cached_embed(cfg: TransformerConfig, params, tokens, pos, dtype):
    """Embedding for the cached path: tokens [B, T] at cache offset ``pos``
    — a scalar (whole-batch offset, the dense workspace path) or a [B]
    vector (per-request offsets, the paged continuous-batching path)."""
    B, T = tokens.shape
    x = params["embed"]["tokens"][tokens].astype(dtype)
    positions = jnp.asarray(pos, jnp.int32).reshape(-1, 1) \
        + jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    if cfg.pos_embedding == "learned":
        x = x + params["embed"]["positions"][positions].astype(x.dtype)
    if cfg.embed_layernorm:
        x = _norm(cfg, x, params["embed"]["ln"])
    return x, positions


def _decode_block(cfg: TransformerConfig, h, lp, attn_fn, mlp_fn=None):
    """The ONE pre-LN residual wiring of every cache-decode block (dense
    workspace via :func:`cached_block`, paged prefill and paged decode):
    ``attn_fn(x_normed)`` returns (attn_out, new cache k, new cache v);
    ``mlp_fn(cfg, x_normed, lp)`` overrides the dense MLP (MoE)."""
    mfn = mlp_fn if mlp_fn is not None else (
        lambda c, xx, lpp: mlp(c, xx, lpp["mlp"]))
    a, nkp, nvp = attn_fn(_norm(cfg, h, lp["ln_attn"]))
    if cfg.parallel_residual:
        m = mfn(cfg, _norm(cfg, h, lp["ln_mlp"]), lp)
        return h + a + m, nkp, nvp
    h = h + a
    m = mfn(cfg, _norm(cfg, h, lp["ln_mlp"]), lp)
    return h + m, nkp, nvp


def cached_block(cfg: TransformerConfig, h, lp, ck, cv, positions, pos,
                 pad_bias=None, mlp_fn=None):
    """ONE layer of the KV-cache path: pre-LN attention against + append to
    the layer's cache. Shared by the compiled scan in :func:`forward_cached`
    and ZeRO-Inference weight streaming (per-layer host→device loop,
    ``inference/engine.py``). ``mlp_fn(cfg, x_normed, lp)`` overrides the
    dense MLP (the MoE zoo passes its routed experts)."""
    return _decode_block(
        cfg, h, lp,
        lambda xn: _cached_attention(cfg, xn, lp["attn"], positions, pos,
                                     ck, cv, pad_bias),
        mlp_fn)


def cached_head(cfg: TransformerConfig, params, x):
    """Final norm + logits projection of the cached path."""
    x = _norm(cfg, x, params["ln_f"])
    return x @ _head_weight(cfg, params) + _head_bias(params)


def forward_cached(cfg: TransformerConfig, params, tokens, cache, pos, pad_bias=None,
                   mlp_fn=None):
    """tokens [B, T] (T static: prompt chunk or 1) attended against + appended
    to ``cache`` at offset ``pos`` ([] int32). Returns (logits [B, T, vocab],
    new cache). ``pad_bias`` [B, Smax] additive f32 masks cache slots of
    left-padded prompts; ``mlp_fn`` see :func:`cached_block`."""
    if cfg.norm_position == "post":
        raise ValueError("norm_position='post' is not supported by the "
                         "KV-cache decode path (pre-LN only)")
    if cfg.sparse_attention is not None:
        # decoding attends position-by-position against the whole cache; a
        # training-time block layout does not transfer — reject rather than
        # silently decode dense and diverge from forward()
        raise NotImplementedError(
            "sparse_attention is not supported by the KV-cache decode path; "
            "serve with the dense forward() or drop the sparsity config")
    x, positions = cached_embed(cfg, params, tokens, pos, cache["k"].dtype)

    def run_block(h, xs):
        lp, ck, cv = xs
        h, nck, ncv = cached_block(cfg, h, lp, ck, cv, positions, pos, pad_bias,
                                   mlp_fn)
        return h, (nck, ncv)

    x, (nk, nv) = jax.lax.scan(run_block, x, (params["layers"], cache["k"], cache["v"]))
    logits = cached_head(cfg, params, x)
    return logits, {"k": nk, "v": nv}


# --------------------------------------------------------------------- #
# Paged KV cache (vLLM PagedAttention / Orca continuous batching, TPU form):
# KV lives in fixed-size block POOLS [n_layer, num_blocks, block_size, KV, Hd]
# shared by every in-flight request; each request owns a block table mapping
# its logical blocks to pool blocks. Memory is bounded by tokens in flight
# (not B × Smax), requests at different depths decode in one fused step, and
# retiring a request frees its blocks for the next admission.

def init_paged_kv_cache(cfg: TransformerConfig, num_blocks: int,
                        block_size: int, dtype=jnp.bfloat16) -> Dict[str, Any]:
    """Paged KV pools: k/v [n_layer, num_blocks, block_size, kv_heads, Hd].
    Block 0 is conventionally the allocator's dummy block (padding tokens
    and inactive decode rows write there; nothing ever reads it)."""
    shape = (cfg.n_layer, num_blocks, block_size, cfg.kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _pool_scatter(pool, kv_new, slots):
    """Write per-token k or v [N, KV, Hd] into one layer's pool
    [num_blocks, bs, KV, Hd] at flat slots [N] (block_id * bs + offset)."""
    Nb, bs = pool.shape[0], pool.shape[1]
    flat = pool.reshape(Nb * bs, *pool.shape[2:])
    return flat.at[slots].set(kv_new.astype(pool.dtype)).reshape(pool.shape)


def _paged_gather(pool, block_tables):
    """Dense [B, max_blocks*bs, KV, Hd] gather of each request's cache via
    its block table — the einsum fallback when the paged kernel is
    off-envelope or the mesh/SPMD context forbids a bare pallas_call."""
    Nb, bs = pool.shape[0], pool.shape[1]
    flat = pool.reshape(Nb * bs, *pool.shape[2:])
    B = block_tables.shape[0]
    idx = (block_tables[:, :, None] * bs
           + jnp.arange(bs, dtype=jnp.int32)[None, None, :])
    return flat[idx.reshape(B, -1)]


def _paged_decode_attention(cfg: TransformerConfig, x, lp, positions, pos,
                            kp, vp, block_tables, pad_bias):
    """One fused decode step over all running requests against the paged
    pools: x [B, 1, D] (one new token per request), pos [B] per-request
    cache depths, kp/vp [num_blocks, bs, KV, Hd], block_tables
    [B, max_blocks]. Returns (out [B, 1, D], new kp, vp)."""
    B, T, D = x.shape
    H = cfg.n_head
    bs = kp.shape[1]

    q, k, v = _qkv_project(cfg, x, lp, positions)

    # each request's new k/v lands at its block-table slot; inactive rows
    # carry a zeroed table and write into the dummy block
    slots = block_tables[jnp.arange(B), pos // bs] * bs + pos % bs
    kp = _pool_scatter(kp, k[:, 0], slots)
    vp = _pool_scatter(vp, v[:, 0], slots)

    slopes = _alibi_slopes(H) if cfg.pos_embedding == "alibi" else None
    o = None
    if _use_flash(cfg):
        from deepspeed_tpu.ops.pallas.paged_decode_attention import \
            paged_decode_attention
        o = paged_decode_attention(q[:, 0], kp, vp, block_tables, pos,
                                   pad_bias=pad_bias, alibi_slopes=slopes,
                                   scale=cfg.attn_scale)
    else:
        # SPMD mesh (a bare pallas_call is illegal): shard_map the kernel
        # over the KV-head/tp axis — the head-sharded pool's shards each
        # stream their local heads, tables stay replicated
        pmesh = _flash_mesh(cfg)
        if pmesh is not None:
            o = _paged_decode_sharded(q[:, 0], kp, vp, block_tables, pos,
                                      pad_bias, slopes, pmesh,
                                      scale=cfg.attn_scale)
    if o is not None:
        out = o.reshape(B, 1, H * cfg.head_dim)
    else:
        # gather + grouped einsum (the dense cache path's masked-softmax
        # core with per-request qpos) — partitionable, the CPU tier default
        out = _grouped_cache_einsum(cfg, q, _paged_gather(kp, block_tables),
                                    _paged_gather(vp, block_tables),
                                    positions, pad_bias)
    out = out @ _w(lp["wo"], out) + (lp["bo"] if cfg.attn_bias else 0)
    return out, kp, vp


def _paged_prefill_attention(cfg: TransformerConfig, x, lp, positions,
                             kp, vp, slots):
    """Prefill attention of ONE fresh request: causal self-attention over
    its own prompt (a fresh request has no prior context to read), with the
    prompt's k/v scattered into the request's pool blocks. x [1, T, D];
    slots [T] flat pool slots (pad positions routed to the dummy block)."""
    B, T, D = x.shape
    H, KV, Hd = cfg.n_head, cfg.kv_heads, cfg.head_dim

    q, k, v = _qkv_project(cfg, x, lp, positions)

    kp = _pool_scatter(kp, k.reshape(T, KV, Hd), slots)
    vp = _pool_scatter(vp, v.reshape(T, KV, Hd), slots)

    slopes = _alibi_slopes(H) if cfg.pos_embedding == "alibi" else None
    out = None
    if _use_flash(cfg):
        from deepspeed_tpu.ops.pallas import flash_attention
        out = flash_attention(q, k, v, causal=True, alibi_slopes=slopes,
                              scale=cfg.attn_scale, block_q=cfg.attn_block_q,
                              block_k=cfg.attn_block_k)
    if out is None:
        from deepspeed_tpu.ops.attention import mha_attention
        out = mha_attention(q, k, v, causal=True, alibi_slopes=slopes,
                            scale=cfg.attn_scale)
    out = out.reshape(B, T, H * Hd)
    out = out @ _w(lp["wo"], out) + (lp["bo"] if cfg.attn_bias else 0)
    return out, kp, vp


def _paged_chunk_attention(cfg: TransformerConfig, x, lp, positions,
                           kp, vp, block_tables, slots):
    """Prefill-chunk attention of ONE request that already has cached
    context: the chunk's k/v are scattered into the request's pool blocks
    at ``slots``, then its queries attend causally over EVERYTHING the
    request has cached — the prefix-cache hit / earlier chunks PLUS this
    chunk — via the paged gather path and the shared masked-softmax core
    (``_grouped_cache_einsum`` with per-row query positions; the same
    machinery the off-kernel paged decode uses, so numerics match it).
    x [1, T, D] (T the chunk bucket, pads routed to the dummy block);
    positions [1, T] global positions ``start + arange(T)``."""
    B, T, D = x.shape
    H, KV, Hd = cfg.n_head, cfg.kv_heads, cfg.head_dim

    q, k, v = _qkv_project(cfg, x, lp, positions)

    kp = _pool_scatter(kp, k.reshape(T, KV, Hd), slots)
    vp = _pool_scatter(vp, v.reshape(T, KV, Hd), slots)

    # gather the request's whole block table (static width) and let the
    # causal mask (kpos <= qpos) hide everything beyond the chunk's last
    # real token — unwritten tail blocks and dummy-mapped table slots all
    # sit at higher logical positions than any live query
    out = _grouped_cache_einsum(cfg, q, _paged_gather(kp, block_tables),
                                _paged_gather(vp, block_tables),
                                positions, None)
    out = out @ _w(lp["wo"], out) + (lp["bo"] if cfg.attn_bias else 0)
    return out, kp, vp


def _check_paged_config(cfg: TransformerConfig):
    if cfg.norm_position == "post" or not cfg.causal:
        raise ValueError("the paged KV path serves pre-LN causal LMs only")
    if cfg.sparse_attention is not None:
        raise NotImplementedError(
            "sparse_attention is not supported by the paged KV decode path")



def forward_paged_prefill(cfg: TransformerConfig, params, tokens, pools,
                          slots, last_idx, mlp_fn=None):
    """Prefill ONE admitted request into its allocated blocks.

    tokens [1, T] right-padded prompt (T the compile bucket); slots [T]
    flat pool slots per prompt position (block_table[t // bs] * bs + t % bs,
    pads routed to the dummy block); last_idx [] int32 index of the last
    real prompt token. Returns (logits [1, vocab] at last_idx, new pools) —
    junk pad positions are causally invisible to the sampled position."""
    _check_paged_config(cfg)
    x, positions = cached_embed(cfg, params, tokens, jnp.int32(0),
                                pools["k"].dtype)

    def run_block(h, xs):
        lp, kp, vp = xs
        h, nkp, nvp = _decode_block(
            cfg, h, lp,
            lambda xn: _paged_prefill_attention(cfg, xn, lp["attn"], positions,
                                                kp, vp, slots),
            mlp_fn)
        return h, (nkp, nvp)

    x, (nk, nv) = jax.lax.scan(run_block, x,
                               (params["layers"], pools["k"], pools["v"]))
    # head on the sampled position only: the [1, vocab] projection, not
    # the whole bucket's [T, vocab]
    xl = jax.lax.dynamic_slice_in_dim(x, last_idx, 1, axis=1)
    return cached_head(cfg, params, xl)[:, 0, :], {"k": nk, "v": nv}


def forward_paged_prefill_chunk(cfg: TransformerConfig, params, tokens,
                                pools, block_tables, slots, start_pos,
                                last_idx, mlp_fn=None):
    """Prefill ONE CHUNK of a request that already has ``start_pos`` tokens
    cached in its blocks (a prefix-cache hit, or earlier chunks of a
    Sarathi-style chunked prefill).

    tokens [1, T] the chunk, right-padded to the compile bucket;
    block_tables [1, max_blocks] the request's table (unused entries 0 =
    dummy); slots [T] flat pool slots per chunk position
    (block_table[(start+t) // bs] * bs + (start+t) % bs, pads routed to the
    dummy block); start_pos [] int32 tokens already cached; last_idx []
    int32 index WITHIN the chunk of its last real token. Returns
    (logits [1, vocab] at last_idx, new pools) — intermediate chunks
    discard the logits, the final chunk samples from them."""
    _check_paged_config(cfg)
    x, positions = cached_embed(cfg, params, tokens, start_pos,
                                pools["k"].dtype)

    def run_block(h, xs):
        lp, kp, vp = xs
        h, nkp, nvp = _decode_block(
            cfg, h, lp,
            lambda xn: _paged_chunk_attention(cfg, xn, lp["attn"], positions,
                                              kp, vp, block_tables, slots),
            mlp_fn)
        return h, (nkp, nvp)

    x, (nk, nv) = jax.lax.scan(run_block, x,
                               (params["layers"], pools["k"], pools["v"]))
    xl = jax.lax.dynamic_slice_in_dim(x, last_idx, 1, axis=1)
    return cached_head(cfg, params, xl)[:, 0, :], {"k": nk, "v": nv}


def copy_paged_block(pools, src, dst):
    """Device copy of one pool block across every layer (the scheduler's
    copy-on-write split: a request restarting mid-block inside a SHARED
    block gets a private copy before it writes). src/dst [] int32."""
    return {"k": pools["k"].at[:, dst].set(pools["k"][:, src]),
            "v": pools["v"].at[:, dst].set(pools["v"][:, src])}


def _paged_verify_attention(cfg: TransformerConfig, x, lp, positions,
                            kp, vp, block_tables, slots):
    """Verify attention over ALL running requests at once: each row's
    speculation window (its pending last token + proposed candidates) has
    its k/v scattered into the row's pool blocks at ``slots`` ([B, W] flat
    slots, pads and inactive rows routed to the dummy block), then every
    window query attends causally over the row's whole table with per-row
    position WINDOWS ``positions[b, t] = pos_b + t``.

    Token-identity with plain decode requires the SAME attention
    implementation the decode step dispatches to — an argmax near-tie
    resolved differently between two numerically-equivalent kernels would
    flip an accepted token. So where the decode step takes the Pallas
    paged kernel, verify runs the kernel once per window position
    (scatter position t, query position t — exactly the t sequential
    decode steps it replaces, still one compiled program); everywhere
    else both use the gather + grouped-einsum masked-softmax core (W = 1
    degenerates to the off-kernel decode exactly)."""
    B, W, D = x.shape
    H, KV, Hd = cfg.n_head, cfg.kv_heads, cfg.head_dim

    q, k, v = _qkv_project(cfg, x, lp, positions)

    # kernel dispatch mirrors the decode step's exactly (direct where a
    # bare pallas_call is legal, shard_map over the KV-head axis on SPMD
    # meshes) — token identity demands verify resolve argmax near-ties
    # with the SAME implementation decode would have used
    direct = _use_flash(cfg)
    pmesh = None
    if not direct:
        pmesh = _flash_mesh(cfg)
        if pmesh is not None and not _paged_shard_ok(
                pmesh, H, KV, Hd, kp.shape[1]):
            pmesh = None
    if direct or pmesh is not None:
        from deepspeed_tpu.ops.pallas.paged_decode_attention import \
            paged_decode_attention
        slopes = _alibi_slopes(H) if cfg.pos_embedding == "alibi" else None
        outs = []
        for t in range(W):
            kp = _pool_scatter(kp, k[:, t], slots[:, t])
            vp = _pool_scatter(vp, v[:, t], slots[:, t])
            if direct:
                o = paged_decode_attention(q[:, t], kp, vp, block_tables,
                                           positions[:, t],
                                           alibi_slopes=slopes,
                                           scale=cfg.attn_scale)
            else:
                o = _paged_decode_sharded(q[:, t], kp, vp, block_tables,
                                          positions[:, t], None, slopes,
                                          pmesh, scale=cfg.attn_scale)
            if o is None:
                break          # off-envelope: the einsum core below
            outs.append(o)
        if len(outs) == W:
            out = jnp.stack(outs, axis=1).reshape(B, W, H * Hd)
            out = out @ _w(lp["wo"], out) + (lp["bo"] if cfg.attn_bias else 0)
            return out, kp, vp

    # re-scattering already-written positions is idempotent (same values
    # to the same slots), so the off-envelope break above lands here clean
    kp = _pool_scatter(kp, k.reshape(B * W, KV, Hd), slots.reshape(-1))
    vp = _pool_scatter(vp, v.reshape(B * W, KV, Hd), slots.reshape(-1))

    # causal mask (kpos <= qpos) bounds each window query at its own
    # position: candidate t sees the cached context plus window tokens
    # <= t, junk pad queries see junk but nothing reads their logits
    out = _grouped_cache_einsum(cfg, q, _paged_gather(kp, block_tables),
                                _paged_gather(vp, block_tables),
                                positions, None)
    out = out @ _w(lp["wo"], out) + (lp["bo"] if cfg.attn_bias else 0)
    return out, kp, vp


def forward_paged_verify(cfg: TransformerConfig, params, tokens, pools,
                         block_tables, slots, pos, mlp_fn=None):
    """One fused VERIFY step of speculative decoding over all running
    requests: the paged-decode math over ``W = k + 1`` positions per
    request in one program.

    tokens [B, W] — row b is its pending last sampled token followed by
    its proposed candidate continuation, right-padded to the window
    bucket; slots [B, W] flat pool slots per window position
    (block_table[(pos+t) // bs] * bs + (pos+t) % bs, pads and inactive
    rows routed to the dummy block); pos [B] per-request cache depths.
    Returns (logits [B, W, vocab] at EVERY window position, new pools).

    Greedy acceptance is host-side: argmax at window offset t is the
    token plain greedy decode would emit after candidates 1..t, so the
    longest candidate prefix matched plus the first-mismatch token is
    token-identical to t+1 sequential decode steps. Rejected candidates'
    k/v stay in the pools beyond the committed position — never read
    (attention masks at each row's pos) and overwritten as decode
    advances; the scheduler handles pos rewind + prefix-cache rollback."""
    _check_paged_config(cfg)
    x, positions = cached_embed(cfg, params, tokens, pos, pools["k"].dtype)

    def run_block(h, xs):
        lp, kp, vp = xs
        h, nkp, nvp = _decode_block(
            cfg, h, lp,
            lambda xn: _paged_verify_attention(cfg, xn, lp["attn"], positions,
                                               kp, vp, block_tables, slots),
            mlp_fn)
        return h, (nkp, nvp)

    x, (nk, nv) = jax.lax.scan(run_block, x,
                               (params["layers"], pools["k"], pools["v"]))
    return cached_head(cfg, params, x), {"k": nk, "v": nv}


def forward_paged_decode(cfg: TransformerConfig, params, tokens, pools,
                         block_tables, pos, pad_bias=None, mlp_fn=None):
    """One fused decode step over ALL running requests: tokens [B, 1] (each
    request's last sampled token), block_tables [B, max_blocks], pos [B]
    per-request cache depths. Returns (logits [B, vocab], new pools)."""
    _check_paged_config(cfg)
    x, positions = cached_embed(cfg, params, tokens, pos, pools["k"].dtype)

    def run_block(h, xs):
        lp, kp, vp = xs
        h, nkp, nvp = _decode_block(
            cfg, h, lp,
            lambda xn: _paged_decode_attention(cfg, xn, lp["attn"], positions,
                                               pos, kp, vp, block_tables,
                                               pad_bias),
            mlp_fn)
        return h, (nkp, nvp)

    x, (nk, nv) = jax.lax.scan(run_block, x,
                               (params["layers"], pools["k"], pools["v"]))
    return cached_head(cfg, params, x)[:, 0, :], {"k": nk, "v": nv}


def run_layers(cfg: TransformerConfig, x, layer_params, positions, mask_bias,
               rng=None):
    """Run the stacked layer blocks over ``x`` with the config's remat policy
    and scan/unroll choice — shared by :func:`hidden_states` and non-token
    encoders (e.g. the CLIP vision tower). ``rng`` (training loss paths
    only) seeds per-layer dropout keys; None keeps every path deterministic
    and the traced program identical to the dropout-free form."""
    with_keys = rng is not None and bool(cfg.dropout)
    n_layer = jax.tree.leaves(layer_params)[0].shape[0]

    def run_block(h, xs):
        lp, key = xs if with_keys else (xs, None)
        out = block(cfg, h, lp, positions, mask_bias, rng=key)
        return out, None

    if cfg.remat and cfg.remat != "none":
        run_block = jax.checkpoint(run_block, policy=_remat_policy(cfg.remat),
                                   prevent_cse=False)

    xs = (layer_params, jax.random.split(rng, n_layer)) if with_keys else layer_params
    if cfg.scan_layers:
        x, _ = jax.lax.scan(run_block, x, xs)
    else:
        for i in range(n_layer):
            x, _ = run_block(x, jax.tree.map(lambda a: a[i], xs))
    return x


def hidden_states(cfg: TransformerConfig, params, tokens, attn_mask=None,
                  rng=None):
    """tokens [B, S] int32 → final normed hidden states [B, S, D] (the
    forward body without the vocab projection). ``rng`` enables dropout
    (training loss paths); None — the default for forward/inference —
    is deterministic."""
    if cfg.norm_position == "post":
        # post-LN stacks end inside the last block and have no ln_f; the
        # LM paths here are pre-LN only — build on run_layers directly
        # (see models/bert.py) instead of silently mixing the two schemes
        raise ValueError("norm_position='post' is not supported by the LM "
                         "forward paths; use run_layers (e.g. BertModel)")
    B, S = tokens.shape
    x = params["embed"]["tokens"][tokens]
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
    if cfg.pos_embedding == "learned":
        x = x + params["embed"]["positions"][:S][None, :, :]
    if cfg.embed_layernorm:
        x = _norm(cfg, x, params["embed"]["ln"])
    k_embed = k_layers = None
    if rng is not None and cfg.dropout:
        k_embed, k_layers = jax.random.split(rng)
    x = _dropout(cfg, x, k_embed)

    x = run_layers(cfg, x, params["layers"], positions, key_mask_bias(attn_mask),
                   rng=k_layers)
    return _norm(cfg, x, params["ln_f"])


def _head_weight(cfg: TransformerConfig, params):
    """[D, vocab] projection (tied embedding transpose or lm_head)."""
    if cfg.tie_embeddings:
        return params["embed"]["tokens"].T
    return _w(params["lm_head"], params["embed"]["tokens"])


def _head_bias(params):
    """Optional [vocab] logits bias (GPT-J's lm_head carries one)."""
    return params.get("lm_head_bias", 0)


def _token_ce(logits, labels, valid):
    """Per-token nll and valid count from [N, V] f32 logits."""
    lse = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.sum((lse - gold) * valid), jnp.sum(valid)


def chunked_vocab_ce(h, w, hb, safe_labels, valid, chunk: int):
    """Mean token cross-entropy for a vocab head ``h @ w + hb`` from
    [B, S, D] features. With ``chunk > 0`` dividing B*S, the projection +
    CE stream over token chunks inside a rematerialised scan, so the
    [B, S, vocab] fp32 logits are never materialised — shared by the
    causal ``lm_loss`` and the BERT MLM loss."""
    B, S, D = h.shape
    vf = valid.astype(jnp.float32)
    if chunk <= 0 or (B * S) % chunk != 0:
        logits = (h @ w + hb).astype(jnp.float32)
        nll, n = _token_ce(logits.reshape(B * S, -1),
                           safe_labels.reshape(-1), vf.reshape(-1))
        return nll / jnp.maximum(n, 1)

    nc = (B * S) // chunk
    hf = h.reshape(nc, chunk, D)
    lf = safe_labels.reshape(nc, chunk)
    vff = vf.reshape(nc, chunk)

    def body(carry, inp):
        hc, lc, vc = inp
        logits = (hc @ w + hb).astype(jnp.float32)
        nll, n = _token_ce(logits, lc, vc)
        s_nll, s_n = carry
        return (s_nll + nll, s_n + n), None

    # full remat: the chunk logits are recomputed in backward, never stored
    body = jax.checkpoint(body, prevent_cse=False)
    (nll, n), _ = jax.lax.scan(body, (jnp.float32(0), jnp.float32(0)),
                               (hf, lf, vff))
    return nll / jnp.maximum(n, 1)


def _use_fused_ce(cfg) -> bool:
    """Whether the vocab head should run the fused logits-free Pallas CE
    kernel. ``cfg`` is any config carrying ``fused_cross_entropy`` (the zoo's
    TransformerConfig or BertConfig). "auto" mirrors the flash-attention
    dispatch: TPU only, and only where a bare ``pallas_call`` is legal —
    single-device meshes or a fully-manual shard_map context; multi-device
    SPMD land falls back to the partitionable XLA streaming path."""
    mode = getattr(cfg, "fused_cross_entropy", "auto")
    if mode == "off":
        return False
    if mode == "on":
        return True
    if mode != "auto":
        raise ValueError(f"fused_cross_entropy={mode!r} (expected "
                         "'auto', 'on' or 'off')")
    return jax.default_backend() == "tpu" and _bare_pallas_legal()


def vocab_head_ce(cfg, h, w, hb, safe_labels, valid):
    """Mean token CE for a vocab head ``h @ w + hb`` — the single dispatch
    every zoo loss head goes through. With ``cfg.fused_cross_entropy``
    selecting the kernel (see :func:`_use_fused_ce`), the fused logits-free
    Pallas CE runs the projection + loss without ever materialising the
    [tokens, vocab] logits in ANY precision; otherwise the XLA
    :func:`chunked_vocab_ce` streaming path (``cfg.loss_chunk``) applies."""
    if _use_fused_ce(cfg):
        from deepspeed_tpu.ops.pallas.fused_cross_entropy import (
            fused_cross_entropy)
        bias = None if isinstance(hb, (int, float)) else hb
        return fused_cross_entropy(h, w, safe_labels, bias=bias, valid=valid)
    return chunked_vocab_ce(h, w, hb, safe_labels, valid,
                            getattr(cfg, "loss_chunk", 0))


def lm_loss(cfg: TransformerConfig, params, batch, rng=None,
            ignore_index: int = -100):
    """Next-token cross-entropy. batch: dict(input_ids[B,S], optional
    labels[B,S], optional attention_mask[B,S]).

    The vocab head goes through :func:`vocab_head_ce`: by default the fused
    logits-free Pallas CE kernel on TPU (the analogue of the reference's
    fused softmax-xent kernels — HBM traffic O(B·S·D) instead of O(B·S·V)),
    else the ``cfg.loss_chunk`` XLA streaming scan."""
    tokens = batch["input_ids"]
    labels = batch.get("labels")
    if labels is None:
        labels = jnp.concatenate([tokens[:, 1:], jnp.full_like(tokens[:, :1], ignore_index)], axis=1)
    x = hidden_states(cfg, params, tokens, batch.get("attention_mask"), rng=rng)
    w = _head_weight(cfg, params)
    B, S, D = x.shape

    valid = (labels != ignore_index)
    safe_labels = jnp.where(valid, labels, 0)

    hb = _head_bias(params)
    return vocab_head_ce(cfg, x, w, hb, safe_labels, valid)
