"""TPU-first decoder/encoder transformer backbone shared by the model zoo.

This is the training-side analogue of the reference's fused transformer
kernels (``csrc/transformer/``, ``deepspeed/ops/transformer/transformer.py``)
re-designed for XLA rather than translated: one stacked-parameter layer block
executed with ``lax.scan`` (single compile for all layers, the layout
ZeRO-3/FSDP wants: gathering one layer's params per scan step bounds live
memory exactly like the reference's fetch/release coordinator), optional
``jax.checkpoint`` rematerialisation (activation checkpointing), einsum-form
attention XLA fuses onto the MXU, and TP/SP sharding expressed as
PartitionSpecs.

Model families configure the block: GPT-2 (learned pos + LN + gelu),
Llama (RoPE + RMSNorm + SwiGLU), BLOOM (alibi), OPT, GPT-NeoX, BERT
(bidirectional). See the thin wrappers in ``deepspeed_tpu/models/``.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: Optional[int] = None           # default 4*d_model (or 8/3 for swiglu)
    max_seq: int = 1024
    n_kv_head: Optional[int] = None      # GQA; default n_head
    # block style
    pos_embedding: str = "learned"       # learned | rope | alibi | none
    norm: str = "layernorm"              # layernorm | rmsnorm
    activation: str = "gelu"             # gelu | swiglu | relu
    parallel_residual: bool = False      # gpt-neox style
    causal: bool = True
    tie_embeddings: bool = True
    # numerics
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dropout: float = 0.0
    # memory
    remat: bool = True                   # activation checkpointing per layer
    scan_layers: bool = True
    # sequence/context parallelism over the "sp" mesh axis
    sequence_parallel: str = "none"      # none | ring | ulysses
    # attention kernel: auto = Pallas flash on TPU, XLA einsum elsewhere
    attention_backend: str = "auto"      # auto | flash | xla
    # init
    init_std: float = 0.02

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @property
    def kv_heads(self) -> int:
        return self.n_kv_head or self.n_head

    @property
    def ff_dim(self) -> int:
        if self.d_ff is not None:
            return self.d_ff
        if self.activation == "swiglu":
            # keep matmul dims MXU-friendly (multiple of 128)
            d = int(8 * self.d_model / 3)
            return (d + 127) // 128 * 128
        return 4 * self.d_model


# --------------------------------------------------------------------- #
# parameter init

def init_params(cfg: TransformerConfig, rng, dtype=jnp.float32) -> Dict[str, Any]:
    """Stacked-layer parameter pytree. Layer weights carry a leading
    ``n_layer`` dim so ``lax.scan`` runs one compiled block for all layers."""
    k_emb, k_pos, k_layers, k_head = jax.random.split(rng, 4)
    std = cfg.init_std
    L, D, F = cfg.n_layer, cfg.d_model, cfg.ff_dim
    H, KV, Hd = cfg.n_head, cfg.kv_heads, cfg.head_dim

    def norm_params():
        scale = jnp.ones((L, D), dtype)
        if cfg.norm == "layernorm":
            return {"scale": scale, "bias": jnp.zeros((L, D), dtype)}
        return {"scale": scale}

    def dense(key, shape, scale=std):
        return (jax.random.normal(key, shape) * scale).astype(dtype)

    ks = jax.random.split(k_layers, 8)
    # attention out & mlp down get depth-scaled init (gpt-2 style)
    out_std = std / math.sqrt(2 * L)
    params: Dict[str, Any] = {
        "embed": {"tokens": dense(k_emb, (cfg.vocab_size, D))},
        "layers": {
            "ln_attn": norm_params(),
            "attn": {
                "wq": dense(ks[0], (L, D, H * Hd)),
                "wk": dense(ks[1], (L, D, KV * Hd)),
                "wv": dense(ks[2], (L, D, KV * Hd)),
                "wo": dense(ks[3], (L, H * Hd, D), out_std),
            },
            "ln_mlp": norm_params(),
            "mlp": ({
                "w_gate": dense(ks[4], (L, D, F)),
                "w_up": dense(ks[5], (L, D, F)),
                "w_down": dense(ks[6], (L, F, D), out_std),
            } if cfg.activation == "swiglu" else {
                "w_up": dense(ks[5], (L, D, F)),
                "b_up": jnp.zeros((L, F), dtype),
                "w_down": dense(ks[6], (L, F, D), out_std),
                "b_down": jnp.zeros((L, D), dtype),
            }),
        },
        "ln_f": ({"scale": jnp.ones((D,), dtype), "bias": jnp.zeros((D,), dtype)}
                 if cfg.norm == "layernorm" else {"scale": jnp.ones((D,), dtype)}),
    }
    if cfg.pos_embedding == "learned":
        params["embed"]["positions"] = dense(k_pos, (cfg.max_seq, D))
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(k_head, (D, cfg.vocab_size))
    return params


def tp_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """Tensor-parallel PartitionSpecs: column-shard qkv/up, row-shard out/down
    (Megatron layout over the ``tp`` mesh axis); vocab-shard embeddings.
    ZeRO sharding composes on the remaining free dims."""
    ln = {"scale": P(None, None), "bias": P(None, None)} if cfg.norm == "layernorm" else {"scale": P(None, None)}
    specs = {
        "embed": {"tokens": P("tp", None)},
        "layers": {
            "ln_attn": ln,
            "attn": {
                "wq": P(None, None, "tp"),
                "wk": P(None, None, "tp"),
                "wv": P(None, None, "tp"),
                "wo": P(None, "tp", None),
            },
            "ln_mlp": ln,
            "mlp": ({
                "w_gate": P(None, None, "tp"),
                "w_up": P(None, None, "tp"),
                "w_down": P(None, "tp", None),
            } if cfg.activation == "swiglu" else {
                "w_up": P(None, None, "tp"),
                "b_up": P(None, "tp"),
                "w_down": P(None, "tp", None),
                "b_down": P(None, None),
            }),
        },
        "ln_f": {"scale": P(None), "bias": P(None)} if cfg.norm == "layernorm" else {"scale": P(None)},
    }
    if cfg.pos_embedding == "learned":
        specs["embed"]["positions"] = P(None, None)
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


# --------------------------------------------------------------------- #
# forward

def _norm(cfg: TransformerConfig, x, p):
    x32 = x.astype(jnp.float32)
    if cfg.norm == "rmsnorm":
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        out = x32 * jax.lax.rsqrt(var + cfg.norm_eps) * p["scale"].astype(jnp.float32)
    else:
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        out = (x32 - mean) * jax.lax.rsqrt(var + cfg.norm_eps)
        out = out * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return out.astype(x.dtype)


def _rope(x, positions, theta: float):
    """Rotary position embedding over the last dim (pairs)."""
    B, S, H, Hd = x.shape
    half = Hd // 2
    freqs = jnp.exp(-math.log(theta) * jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, :, None].astype(jnp.float32) * freqs[None, None, :]  # [B,S,half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(x.dtype)


def _alibi_slopes(n_head: int):
    # standard alibi slope schedule
    start = 2.0**(-8.0 / n_head)
    return jnp.asarray([start**(i + 1) for i in range(n_head)], jnp.float32)


def key_mask_bias(attn_mask):
    """[B, S] 1=keep attention mask → additive key-side bias [B, S]
    (0 keep / -1e9 drop); None passes through. Single producer for every
    attention path (dense, ring, ulysses)."""
    if attn_mask is None:
        return None
    return jnp.where(attn_mask > 0, 0.0, -1e9).astype(jnp.float32)


def attention(cfg: TransformerConfig, x, lp, positions, mask_bias):
    """Einsum-form multi-head attention; XLA maps the batched matmuls onto
    the MXU and fuses softmax. (A Pallas flash-attention kernel can be slotted
    in via deepspeed_tpu.ops — see ops/transformer.)"""
    B, S, D = x.shape
    H, KV, Hd = cfg.n_head, cfg.kv_heads, cfg.head_dim

    q = (x @ lp["wq"]).reshape(B, S, H, Hd)
    k = (x @ lp["wk"]).reshape(B, S, KV, Hd)
    v = (x @ lp["wv"]).reshape(B, S, KV, Hd)

    if cfg.pos_embedding == "rope":
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)

    if KV != H:  # GQA: repeat kv heads
        rep = H // KV
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    slopes = _alibi_slopes(H) if cfg.pos_embedding == "alibi" else None

    sp_mesh = _sp_mesh(cfg)
    if sp_mesh is not None:
        from deepspeed_tpu.sequence import sp_attention
        out = sp_attention(q, k, v, mesh=sp_mesh, impl=cfg.sequence_parallel,
                           causal=cfg.causal, mask_bias=mask_bias, alibi_slopes=slopes)
    elif _use_flash(cfg):
        from deepspeed_tpu.ops.pallas import flash_attention
        out = flash_attention(q, k, v, mask_bias=mask_bias, causal=cfg.causal,
                              alibi_slopes=slopes)
    else:
        from deepspeed_tpu.ops.attention import mha_attention
        out = mha_attention(q, k, v,
                            mask_bias=None if mask_bias is None else mask_bias[:, None, None, :],
                            causal=cfg.causal, alibi_slopes=slopes)
    out = out.reshape(B, S, H * Hd)
    return out @ lp["wo"]


def _use_flash(cfg: TransformerConfig) -> bool:
    """Pallas flash attention is a per-shard kernel: XLA cannot partition a
    pallas_call inside a multi-device auto-sharded program, so fall back to
    the einsum form whenever the active mesh spans >1 device. (Multi-device
    long-context runs should use ``sequence_parallel`` — sharded streaming
    attention via shard_map.)"""
    if cfg.attention_backend not in ("flash", "auto"):
        return False
    import deepspeed_tpu.comm as dist
    if dist.has_mesh() and dist.get_mesh().devices.size > 1:
        if cfg.attention_backend == "flash":
            from deepspeed_tpu.utils.logging import logger
            logger.warning("attention_backend='flash' on a >1-device mesh: "
                           "falling back to XLA einsum attention (pallas_call "
                           "is not partitionable; use sequence_parallel='ring' "
                           "for sharded O(S/sp)-memory attention)")
        return False
    if cfg.attention_backend == "flash":
        return True
    return jax.default_backend() == "tpu"


def _sp_mesh(cfg: TransformerConfig):
    """The active mesh when sequence parallelism is configured AND the mesh
    carries an sp axis of size > 1; else None (dense attention)."""
    if cfg.sequence_parallel == "none":
        return None
    import deepspeed_tpu.comm as dist
    if not dist.has_mesh():
        return None
    mesh = dist.get_mesh()
    if "sp" in mesh.shape and mesh.shape["sp"] > 1:
        return mesh
    return None


def mlp(cfg: TransformerConfig, x, lp):
    if cfg.activation == "swiglu":
        return (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
    h = x @ lp["w_up"] + lp["b_up"]
    h = jax.nn.gelu(h, approximate=True) if cfg.activation == "gelu" else jax.nn.relu(h)
    return h @ lp["w_down"] + lp["b_down"]


def block(cfg: TransformerConfig, x, lp, positions, mask_bias):
    a = attention(cfg, _norm(cfg, x, lp["ln_attn"]), lp["attn"], positions, mask_bias)
    if cfg.parallel_residual:
        m = mlp(cfg, _norm(cfg, x, lp["ln_mlp"]), lp["mlp"])
        return x + a + m
    x = x + a
    m = mlp(cfg, _norm(cfg, x, lp["ln_mlp"]), lp["mlp"])
    return x + m


def forward(cfg: TransformerConfig, params, tokens, attn_mask=None):
    """tokens [B, S] int32 → logits [B, S, vocab]."""
    B, S = tokens.shape
    x = params["embed"]["tokens"][tokens]
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
    if cfg.pos_embedding == "learned":
        x = x + params["embed"]["positions"][:S][None, :, :]

    mask_bias = key_mask_bias(attn_mask)

    layer_params = params["layers"]

    def run_block(h, lp):
        out = block(cfg, h, lp, positions, mask_bias)
        return out, None

    if cfg.remat:
        run_block = jax.checkpoint(run_block, prevent_cse=False)

    if cfg.scan_layers:
        x, _ = jax.lax.scan(run_block, x, layer_params)
    else:
        for i in range(cfg.n_layer):
            lp = jax.tree.map(lambda a: a[i], layer_params)
            x, _ = run_block(x, lp)

    x = _norm(cfg, x, params["ln_f"])
    if cfg.tie_embeddings:
        logits = x @ params["embed"]["tokens"].T
    else:
        logits = x @ params["lm_head"]
    return logits


def lm_loss(cfg: TransformerConfig, params, batch, ignore_index: int = -100):
    """Next-token cross-entropy. batch: dict(input_ids[B,S], optional
    labels[B,S], optional attention_mask[B,S])."""
    tokens = batch["input_ids"]
    labels = batch.get("labels")
    if labels is None:
        labels = jnp.concatenate([tokens[:, 1:], jnp.full_like(tokens[:, :1], ignore_index)], axis=1)
    logits = forward(cfg, params, tokens, batch.get("attention_mask"))
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1)
