"""Generic causal-LM wrapper over the shared transformer backbone."""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models import transformer as T


class CausalLM:
    """A causal language model ready for ``deepspeed_tpu.initialize``.

    batch: dict(input_ids[B,S] int32, optional labels, attention_mask).
    """

    def __init__(self, config: T.TransformerConfig, param_dtype=jnp.float32):
        self.config = config
        self.param_dtype = param_dtype

    def init_params(self, rng) -> Dict[str, Any]:
        from deepspeed_tpu.runtime import zero
        from deepspeed_tpu.utils.init_on_device import materialize_params
        ctx = zero.active_init()
        init = lambda r: T.init_params(self.config, r, dtype=self.param_dtype)
        if ctx is not None:
            # inside `with zero.Init(...)`: materialise ZeRO-3-sharded, the
            # full tree never exists on any single device/host
            return ctx.materialize(init, rng, tp_specs=self.tp_specs())
        return materialize_params(init, rng)

    def forward(self, params, tokens, attn_mask=None):
        return T.forward(self.config, params, tokens, attn_mask)

    def __call__(self, params, tokens, attn_mask=None):
        return self.forward(params, tokens, attn_mask)

    def loss(self, params, batch, rng=None):
        """Training loss; ``rng`` (threaded by the engine's train path)
        enables cfg.dropout — eval/inference paths pass None and stay
        deterministic. The vocab head dispatches per
        ``cfg.fused_cross_entropy``: the fused logits-free Pallas CE kernel
        by default on TPU, the ``cfg.loss_chunk`` XLA streaming path
        elsewhere (transformer.py ``vocab_head_ce``)."""
        return T.lm_loss(self.config, params, batch, rng=rng)

    def tp_specs(self) -> Dict[str, Any]:
        return T.tp_specs(self.config)

    # ---- KV-cache inference (see transformer.forward_cached) ----

    def init_cache(self, batch_size: int, max_len: Optional[int] = None,
                   dtype=jnp.bfloat16) -> Dict[str, Any]:
        return T.init_kv_cache(self.config, batch_size, max_len, dtype)

    def forward_cached(self, params, tokens, cache, pos, pad_bias=None):
        return T.forward_cached(self.config, params, tokens, cache, pos, pad_bias)

    # ---- paged KV serving (see transformer.forward_paged_*) ----

    def init_paged_cache(self, num_blocks: int, block_size: int,
                         dtype=jnp.bfloat16) -> Dict[str, Any]:
        return T.init_paged_kv_cache(self.config, num_blocks, block_size, dtype)

    def forward_paged_prefill(self, params, tokens, pools, slots, last_idx):
        return T.forward_paged_prefill(self.config, params, tokens, pools,
                                       slots, last_idx)

    def forward_paged_prefill_chunk(self, params, tokens, pools,
                                    block_tables, slots, start_pos, last_idx):
        return T.forward_paged_prefill_chunk(self.config, params, tokens,
                                             pools, block_tables, slots,
                                             start_pos, last_idx)

    def forward_paged_decode(self, params, tokens, pools, block_tables, pos,
                             pad_bias=None):
        return T.forward_paged_decode(self.config, params, tokens, pools,
                                      block_tables, pos, pad_bias)

    def forward_paged_verify(self, params, tokens, pools, block_tables,
                             slots, pos):
        return T.forward_paged_verify(self.config, params, tokens, pools,
                                      block_tables, slots, pos)

    @property
    def num_parameters(self) -> int:
        cfg = self.config
        embed = cfg.vocab_size * cfg.d_model + (cfg.max_seq * cfg.d_model if cfg.pos_embedding == "learned" else 0)
        attn = cfg.d_model * cfg.head_dim * (cfg.n_head + 2 * cfg.kv_heads) + cfg.n_head * cfg.head_dim * cfg.d_model
        if cfg.attn_bias:
            attn += cfg.head_dim * (cfg.n_head + 2 * cfg.kv_heads) + cfg.d_model
        if cfg.activation == "swiglu":
            mlp = 3 * cfg.d_model * cfg.ff_dim
        else:
            mlp = 2 * cfg.d_model * cfg.ff_dim + cfg.ff_dim + cfg.d_model
        norms = (4 if cfg.norm == "layernorm" else 2) * cfg.d_model
        final_norm = (2 if cfg.norm == "layernorm" else 1) * cfg.d_model
        if cfg.embed_layernorm:
            final_norm += (2 if cfg.norm == "layernorm" else 1) * cfg.d_model
        head = 0 if cfg.tie_embeddings else cfg.d_model * cfg.vocab_size
        return embed + cfg.n_layer * (attn + mlp + norms) + final_norm + head

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """Approximate training FLOPs/token (6N + attention term)."""
        cfg = self.config
        s = seq_len or cfg.max_seq
        n = self.num_parameters
        return 6.0 * n + 12.0 * cfg.n_layer * cfg.d_model * s
