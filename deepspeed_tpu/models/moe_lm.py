"""MoE causal LM: transformer backbone with mixture-of-experts MLPs.

The model-zoo analogue of DeepSpeed-MoE models (reference ``deepspeed/moe/``
integrated into Megatron-style GPT). Every ``moe_freq``-th block replaces its
dense MLP with an expert-parallel MoE; the load-balancing aux loss is
accumulated across layers and added to the LM loss.

Layers are stacked and scanned like the dense backbone; expert weights carry
dims ``[n_moe_layers, num_experts, ...]`` sharded ``P(None, "ep", ...)``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models import transformer as T
from deepspeed_tpu.utils.init_on_device import honors_on_device
from deepspeed_tpu.moe.sharded_moe import dispatch_combine, top1gating, top2gating


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    k: int = 1
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    aux_loss_coef: float = 0.01
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True
    expert_ff_mult: int = 4
    # Residual (PR-)MoE, arXiv:2201.05596: each MoE MLP is blended with a
    # dense MLP through a learned 2-way softmax coefficient (reference
    # moe/layer.py use_residual + inference moe_type='residual')
    use_residual: bool = False


class MoECausalLM:
    """Causal LM where every block's MLP is an MoE layer."""

    def __init__(self, config: T.TransformerConfig, moe_config: MoEConfig = MoEConfig(),
                 param_dtype=jnp.float32, mesh=None):
        self.config = config
        self.moe = moe_config
        self.param_dtype = param_dtype
        self.mesh = mesh
        self.num_experts = moe_config.num_experts

    # -------------------- params -------------------- #

    @honors_on_device
    def init_params(self, rng) -> Dict[str, Any]:
        cfg, moe = self.config, self.moe
        base = T.init_params(cfg, rng, dtype=self.param_dtype)
        L, D = cfg.n_layer, cfg.d_model
        E = moe.num_experts
        F = moe.expert_ff_mult * D
        k1, k2, k3 = jax.random.split(jax.random.fold_in(rng, 999), 3)
        s_in, s_out = 0.02, 0.02 / math.sqrt(2 * L)
        base["layers"]["mlp"] = {
            "gate_w": (jax.random.normal(k1, (L, D, E)) / math.sqrt(D)).astype(self.param_dtype),
            "w_up": (jax.random.normal(k2, (L, E, D, F)) * s_in).astype(self.param_dtype),
            "b_up": jnp.zeros((L, E, F), self.param_dtype),
            "w_down": (jax.random.normal(k3, (L, E, F, D)) * s_out).astype(self.param_dtype),
            "b_down": jnp.zeros((L, E, D), self.param_dtype),
        }
        if moe.use_residual:
            k4, k5, k6 = jax.random.split(jax.random.fold_in(rng, 1001), 3)
            base["layers"]["mlp"].update({
                "res_w_up": (jax.random.normal(k4, (L, D, F)) * s_in).astype(self.param_dtype),
                "res_b_up": jnp.zeros((L, F), self.param_dtype),
                "res_w_down": (jax.random.normal(k5, (L, F, D)) * s_out).astype(self.param_dtype),
                "res_b_down": jnp.zeros((L, D), self.param_dtype),
                "coef_w": (jax.random.normal(k6, (L, D, 2)) * 0.02).astype(self.param_dtype),
                "coef_b": jnp.zeros((L, 2), self.param_dtype),
            })
        return base

    def tp_specs(self) -> Dict[str, Any]:
        specs = T.tp_specs(self.config)
        specs["layers"]["mlp"] = {
            "gate_w": P(None, None, None),
            "w_up": P(None, "ep", None, "tp"),
            "b_up": P(None, "ep", "tp"),
            "w_down": P(None, "ep", "tp", None),
            "b_down": P(None, "ep", None),
        }
        if self.moe.use_residual:
            specs["layers"]["mlp"].update({
                "res_w_up": P(None, None, "tp"), "res_b_up": P(None, "tp"),
                "res_w_down": P(None, "tp", None), "res_b_down": P(None, None),
                "coef_w": P(None, None, None), "coef_b": P(None, None),
            })
        return specs

    # -------------------- forward -------------------- #

    def _moe_mlp(self, lp, x, rng, train: bool, used_token=None):
        """x [B,S,D] → ([B,S,D], l_aux) via top-k expert routing.
        ``used_token`` [B*S] 1/0 keeps masked tokens out of capacity (top-1
        only; the reference's top-2 gate has no mask either)."""
        moe = self.moe
        B, S, D = x.shape
        tokens = x.reshape(-1, D)
        if train and moe.noisy_gate_policy == "Jitter" and rng is not None:
            tokens = tokens * jax.random.uniform(rng, tokens.shape, minval=0.99, maxval=1.01)
        logits = tokens.astype(jnp.float32) @ lp["gate_w"].astype(jnp.float32)
        cf = moe.capacity_factor if train else moe.eval_capacity_factor
        if moe.k == 1:
            l_aux, combine, dispatch, _ = top1gating(
                logits, cf, moe.min_capacity, used_token,
                moe.noisy_gate_policy if train else None, moe.drop_tokens,
                # RTS is a TRAINING regularizer: eval/serving routes
                # deterministically (positional capacity priority), matching
                # the reference's inference kernels — and without the
                # no-rng fallback warning in every serving process
                moe.use_rts and train, rng=rng)
        else:
            l_aux, combine, dispatch, _ = top2gating(logits, cf, moe.min_capacity,
                                                     moe.drop_tokens, rng=rng)

        def expert(p, xe):
            # T._w dequantises int8 Quantized8 expert weights transparently
            h = xe @ T._w(p["w_up"], xe) + p["b_up"]
            return jax.nn.gelu(h, approximate=True) @ T._w(p["w_down"], xe) + p["b_down"]

        eps = {k: lp[k] for k in ("w_up", "b_up", "w_down", "b_down")}
        combined = dispatch_combine(tokens, combine, dispatch, expert, eps, mesh=self.mesh)
        if moe.use_residual:
            # PR-MoE blend (reference moe/layer.py:115-123): dense MLP +
            # 2-way softmax coefficient over [moe, dense]
            h = jax.nn.gelu(tokens @ T._w(lp["res_w_up"], tokens) + lp["res_b_up"],
                            approximate=True)
            res = h @ T._w(lp["res_w_down"], tokens) + lp["res_b_down"]
            coef = jax.nn.softmax(tokens @ lp["coef_w"] + lp["coef_b"], axis=-1)
            combined = combined * coef[..., 0:1] + res * coef[..., 1:2]
        return combined.reshape(B, S, D), l_aux

    def _block(self, x, lp, positions, mask_bias, rng, train: bool):
        cfg = self.config
        k_route = ka = km = None
        if rng is not None:
            if cfg.dropout and train:
                k_route, ka, km = jax.random.split(rng, 3)
            else:
                k_route = rng
        a = T.attention(cfg, T._norm(cfg, x, lp["ln_attn"]), lp["attn"], positions, mask_bias)
        x = x + T._dropout(cfg, a, ka)
        m, l_aux = self._moe_mlp(lp["mlp"], T._norm(cfg, x, lp["ln_mlp"]), k_route, train)
        return x + T._dropout(cfg, m, km), l_aux

    def forward(self, params, tokens, attn_mask=None, rng=None, train: bool = True):
        cfg = self.config
        B, S = tokens.shape
        x = params["embed"]["tokens"][tokens]
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
        if cfg.pos_embedding == "learned":
            x = x + params["embed"]["positions"][:S][None, :, :]
        mask_bias = T.key_mask_bias(attn_mask)
        # No rng means no stochastic routing: RTS/Jitter would otherwise draw
        # the same permutation every step from a constant key, silently biasing
        # which tokens get dropped at capacity (top1gating's own rng=None path
        # makes the same choice).
        def run_block(carry, scan_in):
            h, aux = carry
            lp, i = scan_in
            block_rng = None if rng is None else jax.random.fold_in(rng, i)
            h, l_aux = self._block(h, lp, positions, mask_bias, block_rng, train)
            return (h, aux + l_aux), None

        if cfg.remat:
            run_block = jax.checkpoint(run_block, prevent_cse=False)
        (x, aux_total), _ = jax.lax.scan(run_block, (x, jnp.zeros((), jnp.float32)),
                                         (params["layers"], jnp.arange(cfg.n_layer)))

        x = T._norm(cfg, x, params["ln_f"])
        if cfg.tie_embeddings:
            logits = x @ params["embed"]["tokens"].T
        else:
            logits = x @ T._w(params["lm_head"], x)
        return logits, aux_total / cfg.n_layer

    # -------------------- KV-cache serving path -------------------- #

    def init_cache(self, batch_size: int, max_len: Optional[int] = None,
                   dtype=jnp.bfloat16) -> Dict[str, Any]:
        return T.init_kv_cache(self.config, batch_size, max_len, dtype)

    def forward_cached(self, params, tokens, cache, pos, pad_bias=None,
                       valid=None):
        """Incremental MoE decode (reference DeepSpeedMoEInference serving,
        ops/transformer/inference/moe_inference.py) on the shared cached
        path with the MoE MLP slotted in: attention runs against the KV
        cache, the MLP routes the step's tokens with eval capacity.
        ``valid`` [B, T] (1 = real token) keeps prefill bucket PADDING out
        of the expert-capacity competition (top1 used_token; top-2 has no
        mask, same as the reference). Routing capacity is per call, so with
        drop_tokens at tight capacity a decoded step can drop differently
        than the same token inside one long forward — the reference's
        per-forward capacity semantics."""
        used = None if valid is None else valid.reshape(-1)

        def moe_mlp_fn(cfg, x_normed, lp):
            out, _ = self._moe_mlp(lp["mlp"], x_normed, None, train=False,
                                   used_token=used)
            return out

        return T.forward_cached(self.config, params, tokens, cache, pos,
                                pad_bias, mlp_fn=moe_mlp_fn)

    def loss(self, params, batch, rng=None):
        logits, aux = self.forward(params, batch["input_ids"], batch.get("attention_mask"),
                                   rng=rng, train=True)
        tokens = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate([tokens[:, 1:], jnp.full_like(tokens[:, :1], -100)], axis=1)
        logits = logits.astype(jnp.float32)
        valid = labels != -100
        safe = jnp.where(valid, labels, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        lm = jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1)
        return lm + self.moe.aux_loss_coef * aux

    @property
    def num_parameters(self) -> int:
        cfg, moe = self.config, self.moe
        D, E = cfg.d_model, moe.num_experts
        F = moe.expert_ff_mult * D
        embed = cfg.vocab_size * D + (cfg.max_seq * D if cfg.pos_embedding == "learned" else 0)
        attn = D * cfg.head_dim * (cfg.n_head + 2 * cfg.kv_heads) + cfg.n_head * cfg.head_dim * D
        moe_mlp = D * E + E * (2 * D * F + F + D)
        norms = (4 if cfg.norm == "layernorm" else 2) * D
        final_norm = (2 if cfg.norm == "layernorm" else 1) * D
        head = 0 if cfg.tie_embeddings else D * cfg.vocab_size
        return embed + cfg.n_layer * (attn + moe_mlp + norms) + final_norm + head
