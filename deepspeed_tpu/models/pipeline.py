"""Pipeline-stage organisation of the causal-LM backbone.

This is the homogeneous-stage model consumed by
``deepspeed_tpu.runtime.pipe.engine.PipelineEngine``: the transformer's
``n_layer`` blocks are grouped into ``num_stages`` stages whose parameters
are stacked on a leading stage axis (sharded over the ``pp`` mesh axis).
Equivalent reference pattern: building a GPT with ``PipelineModule`` +
per-layer ``LayerSpec``s (``deepspeed/runtime/pipe/module.py:82``), with the
embedding optionally tied to the LM head (``TiedLayerSpec``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models import transformer as T
from deepspeed_tpu.models.causal_lm import CausalLM
from deepspeed_tpu.utils.init_on_device import honors_on_device


class PipelinedCausalLM(CausalLM):
    """Causal LM with parameters organised as {"embed", "stages", "head"}.

    ``stages`` leaves have shape ``[num_stages, layers_per_stage, ...]``;
    ``n_layer`` must divide evenly. Attention masks travel with activations
    through the pipeline (``carry_keys``); labels are consumed on the last
    stage only.
    """

    def __init__(self, config: T.TransformerConfig, num_stages: int, param_dtype=jnp.float32):
        super().__init__(config, param_dtype)
        if config.n_layer % num_stages != 0:
            raise ValueError(f"n_layer {config.n_layer} not divisible by num_stages {num_stages}")
        self.num_stages = num_stages
        self.layers_per_stage = config.n_layer // num_stages

    # -------------------- params -------------------- #

    @honors_on_device
    def init_params(self, rng) -> Dict[str, Any]:
        p = T.init_params(self.config, rng, dtype=self.param_dtype)
        S, Lps = self.num_stages, self.layers_per_stage
        stages = jax.tree.map(lambda a: a.reshape((S, Lps) + a.shape[1:]), p["layers"])
        head = {"ln_f": p["ln_f"]}
        if not self.config.tie_embeddings:
            head["lm_head"] = p["lm_head"]
        return {"embed": p["embed"], "stages": stages, "head": head}

    def tp_specs(self) -> Dict[str, Any]:
        t = T.tp_specs(self.config)
        stages = jax.tree.map(lambda s: P(*(("pp",) + tuple(s))), t["layers"],
                              is_leaf=lambda x: isinstance(x, P))
        head = {"ln_f": t["ln_f"]}
        if not self.config.tie_embeddings:
            head["lm_head"] = t["lm_head"]
        return {"embed": t["embed"], "stages": stages, "head": head}

    # -------------------- pipeline stage functions -------------------- #

    def _embed(self, params, mb, rng):
        cfg = self.config
        tokens = mb["input_ids"]
        B, S = tokens.shape
        x = params["embed"]["tokens"][tokens]
        if cfg.pos_embedding == "learned":
            x = x + params["embed"]["positions"][:S][None, :, :]
        return T._dropout(cfg, x, rng if cfg.dropout else None)

    def _stage(self, stage_params, x, aux, rng):
        """One pipeline stage: scan over its layers_per_stage blocks."""
        return self._stage_with(self.config, stage_params, x, aux, rng)

    def manual_tp_stage_fn(self, axis: str, size: int):
        """Stage body for the pipeline engine's manual (pp × dp × tp)
        shard_map: weights enter pre-sliced over ``axis`` (whole heads /
        ff columns per shard) and the blocks run Megatron-style with
        explicit f/g collectives (transformer.py ``manual_tp``) — so
        attention still reaches the bare Pallas flash kernel inside the
        fully-manual stage bodies. Returns None when this config cannot
        shard that way (the engine then keeps the vmap/SPMD path)."""
        import dataclasses
        cfg = self.config
        if (cfg.sparse_attention is not None
                or cfg.sequence_parallel != "none"
                or cfg.n_head % size or cfg.kv_heads % size
                or cfg.ff_dim % size):
            return None
        mcfg = dataclasses.replace(cfg, manual_tp=axis)

        def stage_fn(stage_params, x, aux, rng):
            return self._stage_with(mcfg, stage_params, x, aux, rng)

        return stage_fn

    def _stage_with(self, cfg, stage_params, x, aux, rng):
        """One stage = run_layers over this stage's layers_per_stage stacked
        blocks — the same key-threaded scan/remat machinery as the
        non-pipelined path (transformer.py), so dropout placement and remat
        policies cannot diverge between them."""
        B, S, D = x.shape
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
        mask_bias = T.key_mask_bias(aux.get("attention_mask"))
        rng = rng if cfg.dropout else None
        return T.run_layers(cfg, x, stage_params, positions, mask_bias, rng=rng)

    def _head_loss(self, params, x, mb, rng, ignore_index: int = -100):
        cfg = self.config
        x = T._norm(cfg, x, params["head"]["ln_f"])
        if cfg.tie_embeddings:
            logits = x @ params["embed"]["tokens"].T
        else:
            logits = x @ params["head"]["lm_head"]
        logits = logits.astype(jnp.float32)
        tokens = mb["input_ids"]
        labels = mb.get("labels")
        if labels is None:
            labels = jnp.concatenate([tokens[:, 1:], jnp.full_like(tokens[:, :1], ignore_index)], axis=1)
        valid = labels != ignore_index
        safe = jnp.where(valid, labels, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        return jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1)

    def pipeline_spec(self) -> Dict[str, Any]:
        return {
            "embed_fn": self._embed,
            "stage_fn": self._stage,
            "head_loss_fn": self._head_loss,
            "num_stages": self.num_stages,
            "carry_keys": ("attention_mask",),
            # manual-tp hooks: let the stage shard_map cover a tp axis too
            # (runtime/pipe/engine.py _stage_map_builder)
            "stage_fn_tp": self.manual_tp_stage_fn,
            "stage_tp_specs": self.tp_specs()["stages"],
        }

    # -------------------- sequential path (eval / pp=1) -------------------- #

    def loss(self, params, batch):
        """Non-pipelined loss with identical math — used for eval_batch and
        correctness tests against the pipelined path. No rng: dropout (if
        configured) is OFF here, matching reference module.eval()."""
        aux = {k: batch[k] for k in ("attention_mask",) if k in batch}
        x = self._embed(params, batch, None)
        Lps = self.layers_per_stage
        flat = jax.tree.map(lambda a: a.reshape((self.num_stages * Lps,) + a.shape[2:]),
                            params["stages"])
        for s in range(self.num_stages):
            sp = jax.tree.map(lambda a: a[s * Lps:(s + 1) * Lps], flat)
            x = self._stage(sp, x, aux, None)
        return self._head_loss(params, x, batch, None)

    def forward(self, params, tokens, attn_mask=None):
        raise NotImplementedError("PipelinedCausalLM exposes loss()/pipeline_spec(); "
                                  "use CausalLM for logits-level forward")
