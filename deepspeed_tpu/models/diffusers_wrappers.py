"""Diffusers model wrappers (UNet / VAE) — reference
``deepspeed/model_implementations/diffusers/unet.py`` and ``vae.py``:
thin modules that capture a CUDA graph of the wrapped denoiser/decoder so
the diffusion loop replays a fixed graph instead of re-launching kernels.

TPU equivalent: ``jax.jit`` IS the captured graph. Each wrapper owns one
compiled program per input shape; the denoising loop's repeated calls
replay it. The wrappers also pin the NHWC layout (TPU's preferred conv
layout — the reference's spatial kernels exist for the same reason, see
``ops/spatial/kernels.py``) and donate the latent buffer so the loop
updates in place.
"""

from __future__ import annotations

from typing import Any, Callable

import jax

__all__ = ["DSUNet", "DSVAE"]


class DSUNet:
    """Wrap a functional UNet ``apply(params, latents, timestep, context)``.

    ``donate_latents=True`` reuses the latents HBM buffer for the output
    (the reference's static-graph-buffer effect, ``diffusers/unet.py``
    ``_graph_replay``) — only safe when the caller does NOT read latents
    after the call (i.e. ``latents = unet(...)`` style loops). The standard
    ``noise_pred = unet(...); scheduler.step(noise_pred, t, latents)`` loop
    reads latents again, so donation is OFF by default.
    """

    def __init__(self, apply_fn: Callable, donate_latents: bool = False):
        self.apply_fn = apply_fn
        argnums = (1,) if donate_latents else ()
        self._jit = jax.jit(apply_fn, donate_argnums=argnums)

    def __call__(self, params, latents, timestep, context=None, **kw):
        return self._jit(params, latents, timestep, context, **kw)


class DSVAE:
    """Wrap a functional VAE with separate jitted encode/decode programs
    (the reference captures two graphs, ``vae.py``)."""

    def __init__(self, encode_fn: Callable = None, decode_fn: Callable = None):
        self._encode = jax.jit(encode_fn) if encode_fn else None
        self._decode = jax.jit(decode_fn) if decode_fn else None

    def encode(self, params, images, *a, **kw):
        if self._encode is None:
            raise ValueError("no encode_fn configured")
        return self._encode(params, images, *a, **kw)

    def decode(self, params, latents, *a, **kw):
        if self._decode is None:
            raise ValueError("no decode_fn configured")
        return self._decode(params, latents, *a, **kw)
