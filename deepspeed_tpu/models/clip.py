"""CLIP text/vision encoders + the DSClipEncoder wrapper.

Reference parity: ``deepspeed/model_implementations/transformers/
clip_encoder.py:9`` (``DSClipEncoder`` — wraps the HF CLIP text encoder,
rebuilds its causal mask, and captures per-branch CUDA graphs for repeated
diffusion-loop calls).

TPU redesign: the encoders are functional zoo models reusing
:func:`deepspeed_tpu.models.transformer.block` (pre-LN, QuickGELU, learned
positions); the CUDA-graph machinery is ``jax.jit`` — one compiled program
per branch (text/vision), replayed on every call, which is exactly what the
reference's dual ``_cuda_graphs[iter]`` emulates by hand.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models import transformer as T
from deepspeed_tpu.utils.init_on_device import honors_on_device


@dataclasses.dataclass(frozen=True)
class CLIPTextConfig:
    vocab_size: int = 49408
    max_seq: int = 77
    n_layer: int = 12
    n_head: int = 8
    d_model: int = 512
    d_ff: int = 2048
    norm_eps: float = 1e-5
    projection_dim: Optional[int] = None  # None => no text projection
    # original CLIP uses quick_gelu; SD2-era OpenCLIP text towers use exact
    # gelu (HF hidden_act="gelu")
    activation: str = "quick_gelu"
    # pooled-token selection follows HF CLIPTextModel exactly: with
    # eos_token_id == 2 (or None) the LEGACY rule applies — pool at
    # argmax(token_id), which works because 49407 (eot) is the max id in the
    # real CLIP vocab; any other eos_token_id pools at its first occurrence
    eos_token_id: Optional[int] = 2

    def zoo(self) -> T.TransformerConfig:
        return T.TransformerConfig(
            vocab_size=self.vocab_size, max_seq=self.max_seq,
            n_layer=self.n_layer, n_head=self.n_head, d_model=self.d_model,
            d_ff=self.d_ff, pos_embedding="learned", norm="layernorm",
            activation=self.activation, causal=True, attn_bias=True,
            # tie_embeddings just suppresses the (unused) lm_head alloc —
            # the encoder never projects to vocab
            norm_eps=self.norm_eps, tie_embeddings=True)


@dataclasses.dataclass(frozen=True)
class CLIPVisionConfig:
    image_size: int = 224
    patch_size: int = 32
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: int = 3072
    norm_eps: float = 1e-5
    projection_dim: Optional[int] = None
    activation: str = "quick_gelu"

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    def zoo(self) -> T.TransformerConfig:
        return T.TransformerConfig(
            vocab_size=1, max_seq=self.n_patches + 1, n_layer=self.n_layer,
            n_head=self.n_head, d_model=self.d_model, d_ff=self.d_ff,
            pos_embedding="none", norm="layernorm", activation=self.activation,
            causal=False, attn_bias=True, norm_eps=self.norm_eps,
            tie_embeddings=True)


# ------------------------------------------------------------------ #
# text encoder

class CLIPTextEncoder:
    """HF ``CLIPTextModel`` semantics: causal pre-LN transformer; pooled
    output is the hidden state at each sequence's EOT (argmax token id)."""

    def __init__(self, config: CLIPTextConfig):
        self.config = config
        self.zoo_cfg = config.zoo()

    @honors_on_device
    def init_params(self, rng) -> Dict[str, Any]:
        p = T.init_params(self.zoo_cfg, rng)
        out = {"embed": p["embed"], "layers": p["layers"], "ln_f": p["ln_f"]}
        if self.config.projection_dim:
            k = jax.random.fold_in(rng, 7)
            out["text_projection"] = jax.random.normal(
                k, (self.config.d_model, self.config.projection_dim),
                jnp.float32) * self.config.d_model**-0.5
        return out

    def forward(self, params, tokens, attn_mask=None):
        """InferenceEngine-compatible surface (``fwd(params, tokens, mask)``):
        last hidden states. CLIP's serving flow (SD text conditioning) pads
        with EOT tokens instead of masking; a mask is rejected loudly rather
        than silently ignored."""
        if attn_mask is not None:
            raise ValueError(
                "CLIPTextEncoder takes no padding mask (CLIP pads with EOT "
                "tokens); pass attention_mask=None")
        hidden, _ = self(params, tokens)
        return hidden

    def __call__(self, params, tokens):
        """tokens [B, S] → (last_hidden [B, S, D], pooled [B, D or proj])."""
        cfg = self.zoo_cfg
        x = T.hidden_states(cfg, params, tokens)
        eos = self.config.eos_token_id
        if eos is None or eos == 2:   # HF legacy path (see config comment)
            eot = jnp.argmax(tokens, axis=-1)
        else:
            eot = jnp.argmax((tokens == eos).astype(jnp.int32), axis=-1)
        pooled = jnp.take_along_axis(x, eot[:, None, None], axis=1)[:, 0]
        if "text_projection" in params:
            pooled = pooled @ params["text_projection"]
        return x, pooled


# ------------------------------------------------------------------ #
# vision encoder

class CLIPVisionEncoder:
    """HF ``CLIPVisionModel`` semantics: conv patch embed (expressed as
    patchify + matmul — the TPU-native lowering of a stride=kernel conv),
    class token, learned positions, non-causal pre-LN transformer; pooled
    output is the post-LN class token."""

    def __init__(self, config: CLIPVisionConfig):
        self.config = config
        self.zoo_cfg = config.zoo()

    @honors_on_device
    def init_params(self, rng) -> Dict[str, Any]:
        c = self.config
        p = T.init_params(self.zoo_cfg, rng)
        k1, k2, k3, k4 = jax.random.split(jax.random.fold_in(rng, 11), 4)
        patch_dim = 3 * c.patch_size * c.patch_size
        out = {
            "patch_embed": jax.random.normal(k1, (patch_dim, c.d_model),
                                             jnp.float32) * patch_dim**-0.5,
            "class_token": jax.random.normal(k2, (c.d_model,), jnp.float32) * 0.02,
            "positions": jax.random.normal(k3, (c.n_patches + 1, c.d_model),
                                           jnp.float32) * 0.02,
            "ln_pre": {"scale": jnp.ones(c.d_model), "bias": jnp.zeros(c.d_model)},
            "layers": p["layers"],
            "ln_f": p["ln_f"],
        }
        if c.projection_dim:
            out["visual_projection"] = jax.random.normal(
                k4, (c.d_model, c.projection_dim), jnp.float32) * c.d_model**-0.5
        return out

    def forward(self, params, tokens, attn_mask=None):
        """Reject the generic InferenceEngine forward path loudly: the
        engine's surface is token ids, a vision tower consumes images."""
        raise ValueError(
            "CLIPVisionEncoder serves via __call__(params, images[B,H,W,3]), "
            "not the generic init_inference forward path")

    def _patchify(self, images):
        """[B, H, W, 3] → [B, n_patches, 3*ps*ps] (NHWC, TPU-preferred)."""
        c = self.config
        B, H, W, C = images.shape
        gh, gw = H // c.patch_size, W // c.patch_size
        x = images.reshape(B, gh, c.patch_size, gw, c.patch_size, C)
        x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
        return x.reshape(B, gh * gw, c.patch_size * c.patch_size * C)

    def __call__(self, params, images):
        """images [B, H, W, 3] → (last_hidden [B, P+1, D], pooled)."""
        cfg = self.zoo_cfg
        c = self.config
        x = self._patchify(images) @ params["patch_embed"]
        cls = jnp.broadcast_to(params["class_token"], (x.shape[0], 1, c.d_model))
        x = jnp.concatenate([cls, x], axis=1) + params["positions"][None]
        x = T._norm(cfg, x, params["ln_pre"])
        B, S, D = x.shape
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
        # shared layer-stack runner: remat policy + scan/unroll follow cfg
        x = T.run_layers(cfg, x, params["layers"], positions, None)
        pooled = T._norm(cfg, x[:, 0], params["ln_f"])
        if "visual_projection" in params:
            pooled = pooled @ params["visual_projection"]
        return x, pooled


# ------------------------------------------------------------------ #
# wrapper (reference DSClipEncoder)

class DSClipEncoder:
    """Holds both branches behind jitted entry points — the TPU analogue of
    the reference's two captured CUDA graphs (``clip_encoder.py:20-23``:
    ``static_inputs/[None, None]`` per branch)."""

    def __init__(self, text: CLIPTextEncoder, vision: Optional[CLIPVisionEncoder] = None):
        self.text = text
        self.vision = vision
        self._text_fn = jax.jit(lambda p, t: text(p, t))
        self._vision_fn = jax.jit(lambda p, im: vision(p, im)) if vision else None

    def forward(self, params, tokens, attn_mask=None):
        """Reject the generic InferenceEngine forward path loudly: a
        two-tower CLIP has no single forward surface."""
        raise ValueError(
            "DSClipEncoder serves via encode_text(params['text'], tokens) / "
            "encode_image(params['vision'], images), not the generic "
            "init_inference forward path")

    def encode_text(self, params, tokens):
        return self._text_fn(params, tokens)

    def encode_image(self, params, images):
        if self._vision_fn is None:
            raise ValueError("no vision encoder configured")
        return self._vision_fn(params, images)
