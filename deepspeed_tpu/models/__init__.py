"""Model zoo: TPU-first implementations of the architectures the reference's
inference policies cover (``deepspeed/module_inject/containers/``: GPT-2,
GPT-J/Neo/NeoX, OPT, BLOOM, Megatron-GPT, BERT/DistilBERT) plus Llama.

Every model is a thin preset over ``deepspeed_tpu.models.transformer``:
``CausalLM(config)`` exposes ``init_params(rng)``, ``loss(params, batch)``,
``forward(params, tokens)``, and ``tp_specs()`` so it plugs directly into
``deepspeed_tpu.initialize`` and the inference engine.
"""

from deepspeed_tpu.models.bert import BertConfig, BertModel
from deepspeed_tpu.models.causal_lm import CausalLM
from deepspeed_tpu.models.clip import (CLIPTextConfig, CLIPTextEncoder,
                                       CLIPVisionConfig, CLIPVisionEncoder,
                                       DSClipEncoder)
from deepspeed_tpu.models.diffusers_wrappers import DSUNet, DSVAE
from deepspeed_tpu.models.pipeline import PipelinedCausalLM
from deepspeed_tpu.models.presets import (MODEL_PRESETS, bloom, get_model, gpt2, gpt2_large,
                                          gpt2_medium, gpt2_xl, gpt_neox, llama, llama_7b, opt)

__all__ = [
    "CausalLM", "PipelinedCausalLM", "MODEL_PRESETS", "get_model", "gpt2", "gpt2_medium", "gpt2_large",
    "gpt2_xl", "llama", "llama_7b", "bloom", "opt", "gpt_neox",
    "CLIPTextEncoder", "CLIPVisionEncoder", "CLIPTextConfig", "CLIPVisionConfig",
    "DSClipEncoder", "DSUNet", "DSVAE", "BertModel", "BertConfig",
]
