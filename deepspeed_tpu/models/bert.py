"""BERT family: post-LN bidirectional encoder + MLM head.

Reference parity: the BERT/DistilBERT inference policies
(``deepspeed/module_inject/containers/bert.py``, ``distil_bert.py``) and the
fused BERT training layer (``csrc/transformer/ds_transformer_cuda.cpp`` —
the reference's headline "fastest BERT training" kernels support both
pre- and post-layernorm; this is the post-LN configuration of the same zoo
block, ``models/transformer.py block()``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models import transformer as T
from deepspeed_tpu.utils.init_on_device import honors_on_device


def _check_gather_budget(n_masked, k, budget):
    """Host-side (async debug.callback) overflow check for the MLM gather:
    masked positions beyond the budget are dropped from the loss, which
    silently biases training — warn once with the sizing fix. The message is
    built from the STATIC config values only (warn_once dedupes by exact
    string; a per-batch count would fire every step and grow its cache)."""
    if int(n_masked) > int(k):
        from deepspeed_tpu.utils.logging import warn_once
        warn_once(
            f"mlm_gather_budget={float(budget):g} gathers {int(k)} positions "
            "but batches are realising MORE masked labels than that; the "
            "overflow is DROPPED from the MLM loss (biased gradient). Raise "
            "the budget — recommended headroom is >= 1.5x the masking rate "
            "(e.g. 0.25 for 15% masking).")


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_seq: int = 512
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: int = 3072
    type_vocab_size: int = 2
    norm_eps: float = 1e-12
    activation: str = "gelu_exact"   # HF 'gelu' (erf); distilbert may use relu
    # training memory/speed knobs (models/transformer.py semantics);
    # loss_chunk streams the MLM vocab head over token chunks so the
    # [B, S, vocab] fp32 logits are never materialised (0 = unchunked);
    # fused_cross_entropy ("auto"|"on"|"off") instead routes the head through
    # the fused logits-free Pallas CE kernel (supersedes loss_chunk wherever
    # it engages — see models/transformer.py vocab_head_ce)
    remat: Any = True
    attention_backend: str = "auto"
    loss_chunk: int = 0
    fused_cross_entropy: str = "auto"
    # HF hidden_dropout_prob equivalent (embedding sum + residual-branch
    # outputs via the shared backbone); applied only on the rng-threaded
    # training loss — inference/eval stay deterministic
    dropout: float = 0.0
    # unrolled layers trade compile time for runtime (chip-measured faster
    # on every bench config; the scan keeps compiles fast for tests)
    scan_layers: bool = True
    # MLM masked-position gather: > 0 routes only the masked positions
    # through the prediction head (dense+LN transform + tied vocab decoder)
    # — a static budget of this fraction of B*S tokens is gathered, so the
    # head costs budget x instead of 1.0 x of its FLOPs (the head is ~9% of
    # BERT-large training FLOPs at 15% masking). Loss is numerically the
    # same CE over the same masked set as long as the actual masked count
    # stays within the budget; masked positions beyond it are SILENTLY
    # dropped from the loss (the loss path warns once at runtime when that
    # happens). Binomial masking fluctuates around its rate, so leave
    # headroom: budget >= 1.5x the masking rate (0.25 for the standard 15%)
    # keeps the overflow probability negligible at bench batch sizes.
    # 0 = off (every position goes through the head, reference semantics).
    mlm_gather_budget: float = 0.0

    def zoo(self) -> T.TransformerConfig:
        return T.TransformerConfig(
            vocab_size=self.vocab_size, max_seq=self.max_seq,
            n_layer=self.n_layer, n_head=self.n_head, d_model=self.d_model,
            d_ff=self.d_ff, pos_embedding="learned", norm="layernorm",
            norm_position="post", activation=self.activation, causal=False,
            attn_bias=True, norm_eps=self.norm_eps, tie_embeddings=True,
            remat=self.remat, attention_backend=self.attention_backend,
            scan_layers=self.scan_layers, dropout=self.dropout,
            loss_chunk=self.loss_chunk,
            fused_cross_entropy=self.fused_cross_entropy)


class BertModel:
    """HF ``BertModel`` semantics: word+position+token_type embeddings with
    LN, post-LN encoder stack, tanh pooler on [CLS]; optional MLM head
    (dense + exact-gelu + LN + tied decoder with bias)."""

    def __init__(self, config: BertConfig, with_mlm_head: bool = False):
        self.config = config
        self.zoo_cfg = config.zoo()
        self.with_mlm_head = with_mlm_head

    @honors_on_device
    def init_params(self, rng) -> Dict[str, Any]:
        c = self.config
        p = T.init_params(self.zoo_cfg, rng)
        k = jax.random.fold_in(rng, 13)
        k1, k2, k3 = jax.random.split(k, 3)
        out = {
            "embed": {
                "tokens": p["embed"]["tokens"],
                "positions": p["embed"]["positions"],
                "token_type": jax.random.normal(k1, (c.type_vocab_size, c.d_model),
                                                jnp.float32) * 0.02,
                "ln": {"scale": jnp.ones(c.d_model), "bias": jnp.zeros(c.d_model)},
            },
            "layers": p["layers"],
            "pooler": {"w": jax.random.normal(k2, (c.d_model, c.d_model),
                                              jnp.float32) * 0.02,
                       "b": jnp.zeros(c.d_model)},
        }
        if self.with_mlm_head:
            out["mlm"] = {
                "w": jax.random.normal(k3, (c.d_model, c.d_model),
                                       jnp.float32) * 0.02,
                "b": jnp.zeros(c.d_model),
                "ln": {"scale": jnp.ones(c.d_model), "bias": jnp.zeros(c.d_model)},
                "decoder_bias": jnp.zeros(c.vocab_size),
            }
        return out

    def __call__(self, params, input_ids, token_type_ids=None, attention_mask=None,
                 rng=None):
        """→ (last_hidden [B, S, D], pooled [B, D]). ``rng`` (training loss
        only) enables cfg.dropout; the default None is deterministic."""
        cfg = self.zoo_cfg
        B, S = input_ids.shape
        x = params["embed"]["tokens"][input_ids]
        x = x + params["embed"]["positions"][:S][None]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = x + params["embed"]["token_type"][token_type_ids]
        x = T._norm(cfg, x, params["embed"]["ln"])
        k_embed = k_layers = None
        if rng is not None and cfg.dropout:
            k_embed, k_layers = jax.random.split(rng)
        x = T._dropout(cfg, x, k_embed)

        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
        x = T.run_layers(cfg, x, params["layers"], positions,
                         T.key_mask_bias(attention_mask), rng=k_layers)
        # post-LN stacks end inside the last block: no final norm here
        pooled = jnp.tanh(x[:, 0] @ params["pooler"]["w"] + params["pooler"]["b"])
        return x, pooled

    def forward(self, params, tokens, attn_mask=None):
        """InferenceEngine-compatible surface (``fwd(params, tokens, mask)``):
        MLM logits when the head exists, else the last hidden states."""
        if self.with_mlm_head:
            return self.mlm_logits(params, tokens, attention_mask=attn_mask)
        hidden, _ = self(params, tokens, attention_mask=attn_mask)
        return hidden

    @property
    def num_parameters(self) -> int:
        c = self.config
        # per block: qkv/out + 2 FFN mats; biases bq/bk/bv/bo + b_down +
        # two LNs = 9*d_model, b_up = d_ff
        block = (4 * c.d_model * c.d_model + 2 * c.d_model * c.d_ff
                 + 9 * c.d_model + c.d_ff)
        n = (c.vocab_size + c.max_seq + c.type_vocab_size) * c.d_model
        n += 2 * c.d_model                               # embedding LN
        n += c.n_layer * block
        n += c.d_model * c.d_model + c.d_model           # pooler w + b
        if self.with_mlm_head:
            n += c.d_model * c.d_model + 3 * c.d_model + c.vocab_size
        return n

    def flops_per_token(self, seq_len=None) -> float:
        """Approximate training FLOPs/token (6N + attention term), the
        CausalLM accounting on the encoder dims. With an MLM gather budget
        the prediction-head matmuls (transform + tied decoder) run on only
        ``budget x B*S`` tokens — the accounting subtracts the skipped
        share so throughput-derived MFU stays honest."""
        c = self.config
        s = seq_len or c.max_seq
        f = 6.0 * self.num_parameters + 12.0 * c.n_layer * c.d_model * s
        if self.with_mlm_head and c.mlm_gather_budget:
            head = c.d_model * c.d_model + c.d_model * c.vocab_size
            f -= 6.0 * head * (1.0 - min(c.mlm_gather_budget, 1.0))
        return f

    def loss(self, params, batch, rng=None):
        """Masked-LM training loss — makes BertModel a first-class
        ``deepspeed_tpu.initialize`` model (the reference's headline
        fastest-BERT-training workload, docs/_posts/2020-05-28). batch:
        dict(input_ids [B,S], labels [B,S] with -100 on unmasked positions,
        optional token_type_ids / attention_mask). NSP is omitted by
        design (RoBERTa-style MLM-only pretraining)."""
        if not self.with_mlm_head:
            raise ValueError("training needs the MLM head: "
                             "BertModel(cfg, with_mlm_head=True)")
        x, _ = self(params, batch["input_ids"],
                    batch.get("token_type_ids"), batch.get("attention_mask"),
                    rng=rng)

        labels = batch["labels"]
        valid = (labels != -100)
        safe = jnp.where(valid, labels, 0)

        budget = self.config.mlm_gather_budget
        if budget:
            # masked-position gather: only ~15% of positions carry labels,
            # so the head (transform + 30k-vocab decoder) runs on a static
            # budget x B*S gather of them instead of every position. The
            # sort is stable, so within-budget the CE sums the exact same
            # masked set as the ungathered form.
            B, S, D = x.shape
            k = max(1, int(round(min(budget, 1.0) * B * S)))
            k = -(-k // 128) * 128 if k >= 128 else k  # lane-aligned gather
            flat_v = valid.reshape(-1)
            # masked positions beyond the budget silently bias the loss —
            # surface it (once) instead; recommended headroom: budget >=
            # 1.5x the masking rate (see BertConfig.mlm_gather_budget)
            jax.debug.callback(_check_gather_budget, jnp.sum(flat_v),
                               np.int64(k), np.float64(budget))
            idx = jnp.argsort(~flat_v, stable=True)[:k]
            h = self._mlm_transform(params, x.reshape(B * S, D)[idx][None])
            # the dispatch (fused Pallas CE / chunked XLA) handles the
            # gathered length's ragged tile shapes itself
            return T.vocab_head_ce(
                self.config, h, params["embed"]["tokens"].T,
                params["mlm"]["decoder_bias"], safe.reshape(-1)[idx][None],
                flat_v[idx][None])

        h = self._mlm_transform(params, x)
        # the CausalLM vocab-head machinery on the MLM head: the fused
        # Pallas CE (or cfg.loss_chunk streaming) never materialises the
        # [B, S, vocab] fp32 logits
        return T.vocab_head_ce(self.config, h, params["embed"]["tokens"].T,
                               params["mlm"]["decoder_bias"], safe, valid)

    def _mlm_transform(self, params, x):
        """HF BertPredictionHeadTransform: dense + config.hidden_act + LN
        (NOT a fixed gelu — relu/gelu_new checkpoints diverge otherwise)."""
        m = params["mlm"]
        act = {"gelu_exact": lambda h: jax.nn.gelu(h, approximate=False),
               "gelu": lambda h: jax.nn.gelu(h, approximate=True),
               "relu": jax.nn.relu}[self.config.activation]
        return T._norm(self.zoo_cfg, act(x @ m["w"] + m["b"]), m["ln"])

    def mlm_logits(self, params, input_ids, token_type_ids=None, attention_mask=None):
        """Masked-LM logits [B, S, vocab] (HF BertForMaskedLM head)."""
        if "mlm" not in params:
            raise ValueError("model has no MLM head (with_mlm_head=False)")
        x, _ = self(params, input_ids, token_type_ids, attention_mask)
        h = self._mlm_transform(params, x)
        return h @ params["embed"]["tokens"].T + params["mlm"]["decoder_bias"]
