"""BERT family: post-LN bidirectional encoder + MLM head.

Reference parity: the BERT/DistilBERT inference policies
(``deepspeed/module_inject/containers/bert.py``, ``distil_bert.py``) and the
fused BERT training layer (``csrc/transformer/ds_transformer_cuda.cpp`` —
the reference's headline "fastest BERT training" kernels support both
pre- and post-layernorm; this is the post-LN configuration of the same zoo
block, ``models/transformer.py block()``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models import transformer as T
from deepspeed_tpu.utils.init_on_device import honors_on_device


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_seq: int = 512
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: int = 3072
    type_vocab_size: int = 2
    norm_eps: float = 1e-12
    activation: str = "gelu_exact"   # HF 'gelu' (erf); distilbert may use relu

    def zoo(self) -> T.TransformerConfig:
        return T.TransformerConfig(
            vocab_size=self.vocab_size, max_seq=self.max_seq,
            n_layer=self.n_layer, n_head=self.n_head, d_model=self.d_model,
            d_ff=self.d_ff, pos_embedding="learned", norm="layernorm",
            norm_position="post", activation=self.activation, causal=False,
            attn_bias=True, norm_eps=self.norm_eps, tie_embeddings=True)


class BertModel:
    """HF ``BertModel`` semantics: word+position+token_type embeddings with
    LN, post-LN encoder stack, tanh pooler on [CLS]; optional MLM head
    (dense + exact-gelu + LN + tied decoder with bias)."""

    def __init__(self, config: BertConfig, with_mlm_head: bool = False):
        self.config = config
        self.zoo_cfg = config.zoo()
        self.with_mlm_head = with_mlm_head

    @honors_on_device
    def init_params(self, rng) -> Dict[str, Any]:
        c = self.config
        p = T.init_params(self.zoo_cfg, rng)
        k = jax.random.fold_in(rng, 13)
        k1, k2, k3 = jax.random.split(k, 3)
        out = {
            "embed": {
                "tokens": p["embed"]["tokens"],
                "positions": p["embed"]["positions"],
                "token_type": jax.random.normal(k1, (c.type_vocab_size, c.d_model),
                                                jnp.float32) * 0.02,
                "ln": {"scale": jnp.ones(c.d_model), "bias": jnp.zeros(c.d_model)},
            },
            "layers": p["layers"],
            "pooler": {"w": jax.random.normal(k2, (c.d_model, c.d_model),
                                              jnp.float32) * 0.02,
                       "b": jnp.zeros(c.d_model)},
        }
        if self.with_mlm_head:
            out["mlm"] = {
                "w": jax.random.normal(k3, (c.d_model, c.d_model),
                                       jnp.float32) * 0.02,
                "b": jnp.zeros(c.d_model),
                "ln": {"scale": jnp.ones(c.d_model), "bias": jnp.zeros(c.d_model)},
                "decoder_bias": jnp.zeros(c.vocab_size),
            }
        return out

    def __call__(self, params, input_ids, token_type_ids=None, attention_mask=None):
        """→ (last_hidden [B, S, D], pooled [B, D])."""
        cfg = self.zoo_cfg
        B, S = input_ids.shape
        x = params["embed"]["tokens"][input_ids]
        x = x + params["embed"]["positions"][:S][None]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = x + params["embed"]["token_type"][token_type_ids]
        x = T._norm(cfg, x, params["embed"]["ln"])

        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
        x = T.run_layers(cfg, x, params["layers"], positions,
                         T.key_mask_bias(attention_mask))
        # post-LN stacks end inside the last block: no final norm here
        pooled = jnp.tanh(x[:, 0] @ params["pooler"]["w"] + params["pooler"]["b"])
        return x, pooled

    def forward(self, params, tokens, attn_mask=None):
        """InferenceEngine-compatible surface (``fwd(params, tokens, mask)``):
        MLM logits when the head exists, else the last hidden states."""
        if self.with_mlm_head:
            return self.mlm_logits(params, tokens, attention_mask=attn_mask)
        hidden, _ = self(params, tokens, attention_mask=attn_mask)
        return hidden

    def mlm_logits(self, params, input_ids, token_type_ids=None, attention_mask=None):
        """Masked-LM logits [B, S, vocab] (HF BertForMaskedLM head)."""
        if "mlm" not in params:
            raise ValueError("model has no MLM head (with_mlm_head=False)")
        x, _ = self(params, input_ids, token_type_ids, attention_mask)
        m = params["mlm"]
        # HF BertPredictionHeadTransform applies config.hidden_act, not a
        # fixed gelu — relu/gelu_new checkpoints diverge otherwise
        act = {"gelu_exact": lambda h: jax.nn.gelu(h, approximate=False),
               "gelu": lambda h: jax.nn.gelu(h, approximate=True),
               "relu": jax.nn.relu}[self.config.activation]
        h = act(x @ m["w"] + m["b"])
        h = T._norm(self.zoo_cfg, h, m["ln"])
        return h @ params["embed"]["tokens"].T + m["decoder_bias"]
