"""``ds_report`` equivalent (reference ``env_report.py``): op compatibility
matrix + framework/platform versions."""

from __future__ import annotations

import importlib
import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
NO = f"{RED}[NO]{END}"


def op_report(verbose: bool = True):
    from deepspeed_tpu.ops import registry

    max_dots = 23
    print("-" * 64)
    print("deepspeed_tpu op availability report")
    print("-" * 64)
    print("op name" + "." * (max_dots - len("op name")) + " compatible")
    print("-" * 64)
    rows = []
    # registry.op_report is the single source of truth for availability
    for name, compatible in sorted(registry.op_report().items()):
        status = OKAY if compatible else NO
        print(name + "." * (max_dots - len(name)) + f" {status}")
        rows.append((name, compatible))
    return rows


def version_report():
    print("-" * 64)
    print("framework / platform versions")
    print("-" * 64)
    import deepspeed_tpu
    print(f"deepspeed_tpu ........ {deepspeed_tpu.__version__}")
    print(f"python ............... {sys.version.split()[0]}")
    for mod in ("jax", "jaxlib", "flax", "optax", "numpy"):
        try:
            m = importlib.import_module(mod)
            print(f"{mod} {'.' * (18 - len(mod))} {getattr(m, '__version__', '?')}")
        except ImportError:
            print(f"{mod} {'.' * (18 - len(mod))} {YELLOW}not installed{END}")


def device_report():
    print("-" * 64)
    print("devices")
    print("-" * 64)
    try:
        import jax
        devs = jax.devices()
        print(f"backend .............. {jax.default_backend()}")
        print(f"device count ......... {len(devs)}")
        for d in devs[:8]:
            print(f"  {d}")
        if len(devs) > 8:
            print(f"  ... and {len(devs) - 8} more")
    except Exception as e:  # backend may be unavailable in some environments
        print(f"{YELLOW}device query failed: {e}{END}")


def memory_report():
    print("-" * 64)
    print("device memory")
    print("-" * 64)
    # host RSS first: it stays printable even when the accelerator
    # backend is the very thing that is broken
    from deepspeed_tpu.monitor.health import host_rss_bytes
    rss = host_rss_bytes()
    if rss:
        print(f"  host RSS: {rss / 2.0 ** 30:.2f}GB")
    try:
        from deepspeed_tpu.accelerator import get_accelerator
        rep = get_accelerator().memory_report()
    except Exception as e:
        print(f"{YELLOW}device memory query failed: {e}{END}")
        return
    for name, st in rep.items():
        if st:
            gb = 2.0 ** 30
            print(f"  {name}: in_use {st['bytes_in_use'] / gb:.2f}GB  "
                  f"peak {st['peak_bytes_in_use'] / gb:.2f}GB  "
                  f"limit {st['bytes_limit'] / gb:.2f}GB  "
                  f"headroom {st['headroom_bytes'] / gb:.2f}GB")
        else:
            print(f"  {name}: {YELLOW}no memory stats exposed{END}")


def telemetry_report(path: str):
    """Latest snapshot summary from a JSONL telemetry sink (the same
    renderer the ``dscli health`` screen uses)."""
    print("-" * 64)
    print(f"latest telemetry snapshot ({path})")
    print("-" * 64)
    from deepspeed_tpu.monitor.health import (read_last_snapshots,
                                              render_health_table)
    recs = read_last_snapshots(path, 2)
    if not recs:
        print(f"{YELLOW}no parseable records{END}")
        return
    print(render_health_table(recs[-1], recs[-2] if len(recs) > 1 else None))


def main(hide_operator_status: bool = False, hide_errors_and_warnings: bool = False,
         telemetry_path=None):
    if not hide_operator_status:
        op_report(verbose=not hide_errors_and_warnings)
    version_report()
    device_report()
    memory_report()
    if telemetry_path:
        telemetry_report(telemetry_path)


def cli_main():
    main()


if __name__ == "__main__":
    main()
