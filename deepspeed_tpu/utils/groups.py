"""Parallel-group bookkeeping over mesh axes.

Reference parity: ``deepspeed/utils/groups.py`` — creation of expert-parallel
and expert-data-parallel process groups (``_create_expert_and_data_parallel``
:107, ``_create_expert_data_and_model_parallel`` :201) plus the accessor
surface (``_get_expert_parallel_group`` etc.).

TPU-native: a "group" IS a mesh axis (or tuple of axes). This module keeps
the reference's accessor names, returning axis names that
``deepspeed_tpu.comm`` collectives accept as ``group=``, and validates
EP×DP / EP×DP×TP decompositions against the live mesh.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import deepspeed_tpu.comm as dist

# axis-name conventions (see comm/mesh.py CANONICAL_AXIS_ORDER)
EXPERT_AXIS = "ep"
DATA_AXES = ("dp", "fsdp")
MODEL_AXIS = "tp"
PIPE_AXIS = "pp"
SEQUENCE_AXIS = "sp"

_expert_group_registry: Dict[str, str] = {}


def _mesh():
    return dist.get_mesh()


def initialize(ep_size: int = 1, mpu=None) -> None:
    """Validate that the live mesh supports ``ep_size`` expert parallelism
    (reference groups.initialize). The mesh's ``ep`` axis must equal ep_size
    (or be absent for ep_size=1)."""
    mesh = _mesh()
    actual = mesh.shape.get(EXPERT_AXIS, 1)
    if actual != ep_size:
        raise ValueError(f"mesh ep axis size {actual} != requested ep_size {ep_size}; "
                         f"build the mesh with axes={{'ep': {ep_size}, ...}}")
    _expert_group_registry[f"ep_size_{ep_size}"] = EXPERT_AXIS


def _create_expert_and_data_parallel(ep_size: int) -> None:
    initialize(ep_size)


def _create_expert_data_and_model_parallel(ep_size: int, mpu=None) -> None:
    initialize(ep_size)
    mesh = _mesh()
    if MODEL_AXIS not in mesh.shape:
        raise ValueError("expert+model parallel needs a tp axis in the mesh")


def _get_expert_parallel_group(group_name: str = ""):
    return EXPERT_AXIS


def _get_expert_parallel_group_dict() -> Dict[str, str]:
    return dict(_expert_group_registry) or {"default": EXPERT_AXIS}


def _get_expert_data_parallel_group(group_name: str = ""):
    """Axes over which NON-expert state of expert params replicates — the dp
    axes excluding ep (reference: expert-data-parallel group)."""
    mesh = _mesh()
    return tuple(a for a in DATA_AXES if a in mesh.shape)


def _get_data_parallel_group():
    mesh = _mesh()
    return tuple(a for a in DATA_AXES if a in mesh.shape)


def _get_model_parallel_group():
    return MODEL_AXIS


def _get_expert_parallel_world_size(group_name: str = "") -> int:
    return dist.get_world_size(EXPERT_AXIS)


def _get_expert_data_parallel_world_size(group_name: str = "") -> int:
    return dist.get_world_size(_get_expert_data_parallel_group())


def _get_expert_parallel_rank(group_name: str = "") -> int:
    return dist.get_rank(EXPERT_AXIS)


def _get_data_parallel_world_size() -> int:
    return dist.get_world_size(_get_data_parallel_group())


def _get_model_parallel_world_size() -> int:
    return dist.get_world_size(MODEL_AXIS)


def expert_sharding_axes(ep_size: int, num_experts: int) -> Tuple[Optional[str], int]:
    """(axis to shard the expert dim over, local experts per device)."""
    if ep_size <= 1:
        return None, num_experts
    return EXPERT_AXIS, num_experts // ep_size
