"""Per-collective profiling (reference: deepspeed/utils/comms_logging.py).

Every facade collective is wrapped by ``timed_op``-style accounting in
``deepspeed_tpu.comm``; this module aggregates latency and algorithmic/bus
bandwidth per (op, message size) and prints the reference-shaped summary.

Note on semantics under XLA: collectives issued inside a jitted program are
scheduled by the compiler, so per-op host timing is only meaningful for the
eager facade (benchmarks, ds_bench). That is exactly how the reference uses
its CommsLogger too — per-op wall clock around explicit calls.
"""

from __future__ import annotations

import math
from typing import Dict, List

from deepspeed_tpu.utils.logging import log_dist, logger


def get_caller_func(frame: int = 3) -> str:
    import sys
    return sys._getframe(frame).f_code.co_name


def convert_size(size_bytes: int) -> str:
    if size_bytes == 0:
        return "0B"
    size_name = ("B", "KB", "MB", "GB", "TB", "PB", "EB", "ZB", "YB")
    i = int(math.floor(math.log(size_bytes, 1024)))
    p = math.pow(1024, i)
    s = round(size_bytes / p, 2)
    return f"{s} {size_name[i]}"


def calc_bw_log(comm_op: str, size: int, duration: float, n: int) -> tuple:
    """Algorithmic and bus bandwidth for a collective of ``size`` bytes over
    ``n`` participants taking ``duration`` seconds (ring-algorithm factors)."""
    duration = max(duration, 1e-9)
    if comm_op in ("all_to_all_single", "all_to_all"):
        tput = size / duration
        busbw = (size / duration) * ((n - 1) / n) if n > 0 else 0
    elif comm_op in ("all_gather", "all_gather_into_tensor", "reduce_scatter", "reduce_scatter_tensor"):
        size *= n
        tput = size / duration
        busbw = (size / duration) * ((n - 1) / n) if n > 0 else 0
    elif comm_op in ("all_reduce",):
        tput = size * 2 / duration
        busbw = (size / duration) * (2 * (n - 1) / n) if n > 0 else 0
    elif comm_op in ("send", "recv", "isend", "irecv", "broadcast", "reduce", "gather", "scatter", "barrier",
                     "ppermute"):
        tput = size / duration
        busbw = tput
    else:
        logger.warning(f"Cannot derive bandwidth for unknown comm op {comm_op}")
        return 0, 0
    # GB/s
    tput /= 1e9
    busbw /= 1e9
    return tput, busbw


class CommsLogger:
    """Aggregates per-op/per-size latency and bandwidth records."""

    def __init__(self):
        from deepspeed_tpu.comm.config import CommsLoggerConfig
        defaults = CommsLoggerConfig()
        self.comms_dict: Dict[str, Dict[int, List]] = {}
        self.verbose = defaults.verbose
        self.debug = defaults.debug
        self.prof_ops = defaults.prof_ops
        self.prof_all = defaults.prof_all
        self.enabled = defaults.enabled

    @staticmethod
    def _tel_handles():
        """Registry families for the telemetry fan-in. Resolved per call
        (get-or-create under the registry lock — this is the eager
        collective path, not a jit hot loop) so a registry reset between
        bench metrics can't orphan cached handles."""
        from deepspeed_tpu.monitor.metrics import get_registry
        reg = get_registry()
        return (
            reg.counter("comm/ops", "collective calls", labelnames=("op",)),
            reg.counter("comm/bytes", "collective payload bytes",
                        labelnames=("op",)),
            reg.histogram("comm/latency_ms", "per-collective wall time",
                          labelnames=("op",)),
            reg.histogram("comm/busbw_gbps", "per-collective bus bandwidth",
                          labelnames=("op",)),
        )

    def configure(self, comms_config) -> None:
        self.enabled = comms_config.comms_logger_enabled
        if self.enabled:
            cl = comms_config.comms_logger
            self.verbose = cl.verbose
            self.debug = cl.debug
            self.prof_ops = cl.prof_ops
            self.prof_all = cl.prof_all

    def start_profiling_comms(self):
        self.prof_all = True

    def stop_profiling_comms(self):
        self.prof_all = False

    def start_profiling_op(self, op_name_list):
        self.prof_ops = list(set(self.prof_ops) | set(op_name_list))

    def stop_profiling_op(self, op_name_list):
        self.prof_ops = [op for op in self.prof_ops if op not in op_name_list]

    def append(self, raw_name: str, record_name: str, latency: float, msg_size: int, n_ranks: int) -> None:
        """Add a record. ``latency`` in ms, ``msg_size`` in bytes."""
        algbw, busbw = calc_bw_log(raw_name, msg_size, latency / 1e3, n_ranks)
        # fan the same record into the telemetry registry so comm costs
        # land in the unified snapshot next to step/serving series
        ops, nbytes, lat, bw = self._tel_handles()
        ops.labels(op=record_name).inc()
        nbytes.labels(op=record_name).inc(msg_size)
        lat.labels(op=record_name).observe(latency)
        bw.labels(op=record_name).observe(busbw * 8)
        if record_name in self.comms_dict:
            if msg_size in self.comms_dict[record_name]:
                self.comms_dict[record_name][msg_size][0] += 1
                self.comms_dict[record_name][msg_size][1].append(latency)
                self.comms_dict[record_name][msg_size][2].append(algbw)
                self.comms_dict[record_name][msg_size][3].append(busbw)
            else:
                self.comms_dict[record_name][msg_size] = [1, [latency], [algbw], [busbw]]
        else:
            self.comms_dict[record_name] = {msg_size: [1, [latency], [algbw], [busbw]]}
        if self.verbose:
            log_dist(f"rank=N | comm op: {record_name} | time (ms): {latency:.2f} | "
                     f"msg size: {convert_size(msg_size)} | algbw (Gbps): {algbw * 8:.2f} | "
                     f"busbw (Gbps): {busbw * 8:.2f}", ranks=[0])

    def log_all(self, print_log: bool = True, show_straggler: bool = False):
        from deepspeed_tpu.utils.timer import trim_mean
        if print_log:
            print("Comm. Op            Message Size        Count       Total Latency(ms)   "
                  "Avg Latency(ms)     tput_avg (Gbps)     busbw_avg (Gbps)")
        results = {}
        for record_name in self.comms_dict:
            if print_log:
                print(record_name)
            results[record_name] = {}
            for msg_size, vals in sorted(self.comms_dict[record_name].items()):
                count = vals[0]
                total_lat = sum(vals[1])
                avg_lat = trim_mean(vals[1], 0.1)
                avg_algbw = trim_mean(vals[2], 0.1)
                avg_busbw = trim_mean(vals[3], 0.1)
                results[record_name][msg_size] = {
                    "count": count, "total_latency_ms": total_lat, "avg_latency_ms": avg_lat,
                    "algbw_gbps": avg_algbw * 8, "busbw_gbps": avg_busbw * 8,
                }
                if print_log:
                    print(f"{' ':20}{convert_size(msg_size):<20}{count:<12}{total_lat:<20.2f}"
                          f"{avg_lat:<20.2f}{avg_algbw * 8:<20.2f}{avg_busbw * 8:<20.2f}")
        return results
