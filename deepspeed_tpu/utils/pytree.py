"""Pytree path/key helpers shared by offload, checkpoint tools, and the
universal-checkpoint loader — ONE naming scheme for dotted leaf keys so
checkpoint files, swap files, and lookups always line up."""

from __future__ import annotations

from typing import Any, Dict


def leaf_key(path) -> str:
    """jax tree path → dotted key. "." separator: keys double as NVMe swap
    file names, so no os.sep."""
    return ".".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def leaf_paths(tree: Any, prefix: str = "", *,
               descend_sequences: bool = False) -> Dict[str, Any]:
    """Flatten a nested dict tree into {'a.b.c': leaf} (same naming as
    :func:`leaf_key` for dict-only trees). With ``descend_sequences``,
    list/tuple nodes flatten too, their indices as key segments
    ({'a.0.c': leaf}) — the checkpoint on-disk key scheme; the default
    keeps sequences as leaves (an array-valued state_dict entry is one
    leaf, not a container)."""
    out: Dict[str, Any] = {}
    if isinstance(tree, dict):
        items = list(tree.items())
    elif descend_sequences and isinstance(tree, (list, tuple)):
        items = list(enumerate(tree))
    else:
        out[prefix[:-1]] = tree
        return out
    for k, v in items:
        out.update(leaf_paths(v, prefix + str(k) + ".",
                              descend_sequences=descend_sequences))
    return out
