"""Pytree path/key helpers shared by offload, checkpoint tools, and the
universal-checkpoint loader — ONE naming scheme for dotted leaf keys so
checkpoint files, swap files, and lookups always line up."""

from __future__ import annotations

from typing import Any, Dict


def leaf_key(path) -> str:
    """jax tree path → dotted key. "." separator: keys double as NVMe swap
    file names, so no os.sep."""
    return ".".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def leaf_paths(tree: Any, prefix: str = "") -> Dict[str, Any]:
    """Flatten a nested dict tree into {'a.b.c': leaf} (same naming as
    :func:`leaf_key` for dict-only trees)."""
    out: Dict[str, Any] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(leaf_paths(v, prefix + str(k) + "."))
    else:
        out[prefix[:-1]] = tree
    return out
