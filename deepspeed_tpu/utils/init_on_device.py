"""Meta-device / dtype-override model initialization.

Reference parity: ``deepspeed/utils/init_on_device.py`` ``OnDevice`` — a
context manager under which model construction materialises parameters on a
chosen device, as a chosen dtype, or not at all (``device="meta"``: shapes
and dtypes only, no memory). The reference monkey-patches
``Tensor.__new__``; the TPU redesign wraps the zoo's pure ``init_params``
functions instead: under ``device="meta"`` the init is traced with
``jax.eval_shape`` (zero FLOPs, zero bytes), otherwise it runs normally and
floating-point leaves are cast to the requested dtype.

Usage (reference ``OnDevice(dtype=torch.half, device="meta")``)::

    with deepspeed_tpu.OnDevice(dtype=jnp.bfloat16, device="meta"):
        params = model.init_params(jax.random.key(0))   # ShapeDtypeStructs

    engine = deepspeed_tpu.init_inference(model, params=real_params)

Every zoo model's ``init_params`` honors the context. Meta trees feed
memory estimation (autotuner AOT analysis, flops profiler) and huge-model
flows where the real weights arrive from a checkpoint loader instead of an
RNG.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

_local = threading.local()


class OnDevice:
    """Context manager selecting where/how ``init_params`` materialises.

    ``device``: ``"device"`` (default backend, normal init) or ``"meta"``
    (no allocation — returns a ``jax.ShapeDtypeStruct`` pytree).
    ``dtype``: optional override applied to floating-point leaves.
    """

    def __init__(self, dtype=None, device: str = "device", enabled: bool = True):
        if device not in ("device", "meta"):
            raise ValueError(f"device must be 'device' or 'meta', got {device!r}")
        self.dtype = dtype
        self.device = device
        self.enabled = enabled
        self._prev: Optional[OnDevice] = None

    @staticmethod
    def current() -> Optional["OnDevice"]:
        ctx = getattr(_local, "ctx", None)
        return ctx if ctx is not None and ctx.enabled else None

    def __enter__(self):
        # enabled=False is a no-op wrapper: an active outer context stays in
        # force (reference semantics — the patch simply isn't applied)
        if self.enabled:
            self._prev = getattr(_local, "ctx", None)
            _local.ctx = self
        return self

    def __exit__(self, *exc):
        if self.enabled:
            _local.ctx = self._prev
        return False


def _cast_floats(tree, dtype):
    import jax
    import jax.numpy as jnp

    def leaf(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            if isinstance(x, jax.ShapeDtypeStruct):
                return jax.ShapeDtypeStruct(x.shape, dtype)
            return x.astype(dtype)
        return x

    return jax.tree.map(leaf, tree)


def materialize_params(init_fn, *args) -> Any:
    """Run a pure params-init function under the active :class:`OnDevice`
    context (no-op passthrough when none is active). Called by every zoo
    model's ``init_params``."""
    import jax

    ctx = OnDevice.current()
    if ctx is None:
        return init_fn(*args)
    if ctx.device == "meta":
        tree = jax.eval_shape(init_fn, *args)
    else:
        tree = init_fn(*args)
    if ctx.dtype is not None:
        tree = _cast_floats(tree, ctx.dtype)
    return tree


def honors_on_device(init_method):
    """Decorator for ``init_params(self, rng, ...)``-shaped methods: the
    single place that expresses the OnDevice contract (apply to every
    params-producing entry so new model families can't silently bypass the
    context). Only the rng is traced; trailing args (e.g. a dtype) ride the
    closure."""
    import functools

    @functools.wraps(init_method)
    def wrapped(self, rng, *args, **kwargs):
        return materialize_params(
            lambda r: init_method(self, r, *args, **kwargs), rng)

    return wrapped
