"""Wall-clock and throughput timers.

Capability parity with the reference's ``deepspeed/utils/timer.py``
(``SynchronizedWallClockTimer`` with accelerator-event sync, ``ThroughputTimer``
samples/sec accounting). On TPU there are no user-visible streams, so
"synchronized" means draining outstanding async dispatch with
``jax.block_until_ready`` on live arrays (or ``jax.effects_barrier``) before
reading the host clock.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist

try:
    import psutil

    PSUTIL_AVAILABLE = True
except ImportError:  # pragma: no cover
    PSUTIL_AVAILABLE = False


def _device_synchronize() -> None:
    """Drain async dispatch so host wall-clock brackets device work."""
    try:
        import jax
        jax.effects_barrier()
    except Exception:
        pass


class Timer_:
    """A single named timer with start/stop/elapsed/mean."""

    def __init__(self, name: str, synchronize: bool = True):
        self.name_ = name
        self.synchronize = synchronize
        self.started_ = False
        self.start_time = 0.0
        self.elapsed_records: List[float] = []

    def start(self) -> None:
        assert not self.started_, f"{self.name_} timer has already been started"
        if self.synchronize:
            _device_synchronize()
        self.start_time = time.perf_counter()
        self.started_ = True

    def stop(self, reset: bool = False, record: bool = True) -> None:
        assert self.started_, f"{self.name_} timer is not started"
        if self.synchronize:
            _device_synchronize()
        elapsed = time.perf_counter() - self.start_time
        if record:
            self.elapsed_records.append(elapsed)
        self.started_ = False

    def _get_elapsed_msec(self) -> float:
        return sum(self.elapsed_records) * 1000.0

    def reset(self) -> None:
        self.started_ = False
        self.elapsed_records = []

    def elapsed(self, reset: bool = True) -> float:
        """Total elapsed time in milliseconds."""
        if self.started_:
            self.stop()
            self.start()
        total = self._get_elapsed_msec()
        if reset:
            self.elapsed_records = []
        return total

    def mean(self) -> float:
        """Mean of recorded intervals in milliseconds."""
        if not self.elapsed_records:
            return 0.0
        return self._get_elapsed_msec() / len(self.elapsed_records)


class SynchronizedWallClockTimer:
    """Group of named timers; mirrors the reference timer-group API."""

    FORWARD_MICRO_TIMER = "fwd_microstep"
    FORWARD_GLOBAL_TIMER = "fwd"
    BACKWARD_MICRO_TIMER = "bwd_microstep"
    BACKWARD_GLOBAL_TIMER = "bwd"
    BACKWARD_INNER_MICRO_TIMER = "bwd_inner_microstep"
    BACKWARD_INNER_GLOBAL_TIMER = "bwd_inner"
    BACKWARD_REDUCE_MICRO_TIMER = "bwd_allreduce_microstep"
    BACKWARD_REDUCE_GLOBAL_TIMER = "bwd_allreduce"
    STEP_MICRO_TIMER = "step_microstep"
    STEP_GLOBAL_TIMER = "step"

    def __init__(self, synchronize: bool = True):
        self.timers: Dict[str, Timer_] = {}
        self.synchronize = synchronize

    def __call__(self, name: str) -> Timer_:
        if name not in self.timers:
            self.timers[name] = Timer_(name, synchronize=self.synchronize)
        return self.timers[name]

    def has_timer(self, name: str) -> bool:
        return name in self.timers

    @staticmethod
    def memory_usage() -> str:
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats() or {}
            alloc = stats.get("bytes_in_use", 0) / (1024**3)
            peak = stats.get("peak_bytes_in_use", 0) / (1024**3)
            return f"Mem in use {round(alloc, 2)} GB | Peak {round(peak, 2)} GB"
        except Exception:
            return "Mem stats unavailable"

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True, memory_breakdown=None, ranks=None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) / normalizer
                string += f" | {name}: {elapsed_time:.2f}"
        log_dist(string, ranks=ranks or [0])

    def get_mean(self, names: List[str], normalizer: float = 1.0, reset: bool = True) -> Dict[str, float]:
        assert normalizer > 0.0
        means = {}
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].mean() / normalizer
                means[name] = elapsed_time
                if reset:
                    self.timers[name].reset()
        return means


class NoopTimer:
    """Timer stand-in used when wall-clock breakdown is disabled."""

    class Timer:

        def start(self):
            ...

        def reset(self):
            ...

        def stop(self, **kwargs):
            ...

        def elapsed(self, **kwargs):
            return 0

        def mean(self):
            return 0

    def __init__(self):
        self.timer = self.Timer()

    def __call__(self, name):
        return self.timer

    def has_timer(self, name):
        return True

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=None, ranks=None):
        ...

    def get_mean(self, names, normalizer=1.0, reset=True):
        return {}


class ThroughputTimer:
    """Samples/sec + TFLOPs accounting across steps (reference timer.py:136)."""

    def __init__(self,
                 batch_size: int,
                 start_step: int = 2,
                 steps_per_output: Optional[int] = None,
                 monitor_memory: bool = False,
                 logging_fn=None):
        self.start_time = 0.0
        self.end_time = 0.0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        # cumulative samples over the TIMED steps: batch_size can be
        # reassigned mid-run (elastic/curriculum ramp-up via
        # set_train_batch_size), so the average must sum what each step
        # actually carried, not multiply the current size by step count
        self.total_samples = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or log_dist
        self.initialized = False

    def update_epoch_count(self) -> None:
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self) -> None:
        self.initialized = True

    def start(self) -> None:
        self._init_timer()
        self.started = True
        if self.steps_per_output and self.global_step_count >= self.start_step:
            # only pay the device sync when the measurement is consumed —
            # with reporting off (steps_per_print=0) a per-step synchronize
            # would serialize host dispatch against the device (very costly
            # over remote-device transports) for a number nobody reads
            _device_synchronize()
            self.start_time = time.perf_counter()

    def stop(self, global_step: bool = False, report_speed: bool = True) -> None:
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        if self.start_time > 0:
            _device_synchronize()
            self.end_time = time.perf_counter()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration
            if global_step:
                self.total_samples += self.batch_size
                if report_speed and self.steps_per_output and (self.global_step_count % self.steps_per_output == 0):
                    self.logging(f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                                 f"global_step={self.global_step_count}, RunningAvgSamplesPerSec="
                                 f"{self.avg_samples_per_sec():.2f}, CurrSamplesPerSec="
                                 f"{self.batch_size / self.step_elapsed_time:.2f}")
                self.step_elapsed_time = 0.0

    def avg_samples_per_sec(self) -> float:
        """Running average over the timed window. Uses the CUMULATIVE
        sample count (one ``batch_size`` summed per timed step), so a
        ``set_train_batch_size`` ramp mid-run doesn't retroactively skew
        every earlier step's contribution."""
        if self.global_step_count > self.start_step and self.total_elapsed_time > 0:
            return self.total_samples / self.total_elapsed_time
        return -1.0


def trim_mean(data: List[float], trim_percent: float) -> float:
    """Compute the mean of the data, ignoring the tails (reference timer.py)."""
    assert 0.0 <= trim_percent <= 1.0
    n = len(data)
    if n == 0:
        return 0.0
    data_sorted = sorted(data)
    trim_off = int(n * trim_percent)
    trimmed = data_sorted[trim_off:max(n - trim_off, trim_off + 1)]
    if not trimmed:
        trimmed = data_sorted
    return sum(trimmed) / len(trimmed)
