"""Rank-aware logging utilities.

Capability parity with the reference's ``deepspeed/utils/logging.py`` (logger
factory, ``log_dist`` rank-filtered logging, ``should_log_le``), rebuilt for a
JAX multi-process world: rank discovery goes through ``jax.process_index()``
instead of ``torch.distributed``.
"""

from __future__ import annotations

import functools
import logging
import os
import sys

log_levels = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


class LoggerFactory:

    @staticmethod
    def create_logger(name: str = "deepspeed_tpu", level: int = logging.INFO) -> logging.Logger:
        if name is None:
            raise ValueError("name for logger cannot be None")
        formatter = logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(filename)s:%(lineno)d:%(funcName)s] %(message)s")
        logger_ = logging.getLogger(name)
        logger_.setLevel(level)
        logger_.propagate = False
        if not logger_.handlers:
            ch = logging.StreamHandler(stream=sys.stdout)
            ch.setLevel(level)
            ch.setFormatter(formatter)
            logger_.addHandler(ch)
        return logger_


logger = LoggerFactory.create_logger(
    name="deepspeed_tpu", level=log_levels.get(os.environ.get("DS_TPU_LOG_LEVEL", "info"), logging.INFO))


def _get_rank() -> int:
    """Process index of this host, without forcing distributed init."""
    # Environment first: works before jax.distributed.initialize and in launchers.
    for var in ("RANK", "PROCESS_ID", "JAX_PROCESS_ID"):
        if var in os.environ:
            try:
                return int(os.environ[var])
            except ValueError:
                pass
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message: str, ranks=None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the given process ranks (``[-1]`` or None = all)."""
    my_rank = _get_rank()
    if ranks is None or -1 in ranks or my_rank in ranks:
        final_message = f"[Rank {my_rank}] {message}"
        logger.log(level, final_message)


def print_rank_0(message: str) -> None:
    if _get_rank() == 0:
        print(message, flush=True)


@functools.lru_cache(None)
def warn_once(message: str) -> None:
    logger.warning(message)


def should_log_le(max_log_level_str: str) -> bool:
    """True if the logger's current level is <= the named level."""
    if not isinstance(max_log_level_str, str):
        raise ValueError("max_log_level_str must be a string")
    max_log_level_str = max_log_level_str.lower()
    if max_log_level_str not in log_levels:
        raise ValueError(f"{max_log_level_str} is not one of the `log_levels` keys: {list(log_levels)}")
    return logger.getEffectiveLevel() <= log_levels[max_log_level_str]
