"""Version-compat shims for the jax API surface this codebase targets.

The TPU toolchain ships a newer jax than some dev/CI containers; the
symbols that moved between those versions are normalised here so call
sites stay version-agnostic:

- ``shard_map``: top-level ``jax.shard_map`` (with its ``check_vma``
  kwarg) on newer jax; on 0.4.x the ``jax.experimental.shard_map``
  function, whose equivalent kwarg is spelled ``check_rep`` — the shim
  translates.
- axis-size-in-trace lives in :func:`deepspeed_tpu.comm.bound_axis_size`
  (``jax.lax.axis_size`` vs the classic psum-of-1 idiom).
"""

try:
    from jax import shard_map  # noqa: F401  (jax >= 0.5)
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, *, check_vma=None, axis_names=None, mesh=None, **kwargs):
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        if axis_names is not None and frozenset(axis_names) != frozenset(
                getattr(mesh, "axis_names", ())):
            # new API: axis_names = the MANUAL axes, the rest stay auto.
            # The experimental API spells that auto=complement, but this
            # jax generation lowers partial-auto bodies that take
            # axis_index to a PartitionId op its SPMD partitioner rejects
            # (and some such programs hard-abort the process) — refuse
            # cleanly instead of letting XLA crash the interpreter.
            raise NotImplementedError(
                "partial-auto shard_map (axis_names subset of the mesh) "
                "needs the newer jax this codebase targets; the installed "
                f"jax predates it (mesh axes {tuple(mesh.axis_names)}, "
                f"manual {tuple(axis_names)})")
        return _experimental_shard_map(f, mesh=mesh, **kwargs)

__all__ = ["shard_map"]
