"""Deterministic storage fault injection for the checkpoint subsystem.

The crash-safe checkpoint writer (``runtime/checkpoint_engine/safe_engine``)
routes every byte it persists through :func:`guarded_write`. When no injector
is installed that is a single ``None`` check; under an installed
:class:`FaultInjector` the harness can deterministically reproduce the
failure modes TPU fleets actually deliver:

- **kill mid-write** (``kill_at_byte=N``): the process "dies" after exactly
  ``N`` bytes have reached storage across the injected writes — the file is
  truncated at the offset and :class:`SimulatedCrash` propagates. Nothing
  after the kill point runs (no manifest, no rename, no ``latest`` update),
  exactly like a SIGKILL/power-loss at that byte.
- **transient/persistent I/O errors** (:meth:`FaultInjector.fail_writes`):
  raise ``OSError(ENOSPC)`` / ``OSError(EIO)`` (or any errno) for the first
  ``count`` matching writes — exercises the writer's retry-with-backoff and,
  when the fault outlives the retry budget, the failure-metrics + health
  path.
- **delayed writes** (``delay_per_write_s``): slows persistence so bounded
  async-queue behavior (backpressure, queue-depth telemetry) is observable.
- **bit-flip corruption** (:func:`bit_flip`): post-hoc, flips one bit of an
  already-committed file — the on-disk rot the manifest verification must
  catch.
- **serving step faults** (:meth:`FaultInjector.fail_step` +
  ``delay_per_step_s``): the serving-plane mirror of ``guarded_write`` —
  the paged engine's action executor (``_ServeSession._exec``) consults
  :func:`step_fault` at every dispatch site (``prefill`` / ``prefill_chunk``
  / ``decode`` / ``verify`` / ``cow`` / ``spill`` / ``fetch``), one ``None``
  check when no injector is installed. A scheduled fault raises at a pinned
  logical step: ``phase="pre"`` fires BEFORE the jit dispatch (the donated
  pools are intact — the fault is contained per-request), ``phase="post"``
  fires after the pools were donated but before the step's outputs were
  adopted (engine-fatal: recovery must rebuild the pool workspace). The
  step counter advances once per engine action, so a schedule is
  deterministic given a request trace.

``SimulatedCrash`` subclasses ``BaseException`` on purpose: retry loops
catching ``Exception``/``OSError`` must never "survive" a crash — only the
test harness (or the async writer's crash bookkeeping) may catch it.

Usage::

    from deepspeed_tpu.utils import fault_injection as fi

    with fi.inject(fi.FaultInjector(kill_at_byte=4096)):
        engine.save_checkpoint(d)        # raises fi.SimulatedCrash

    fi.bit_flip(os.path.join(tag_dir, "state.npz"))
"""

from __future__ import annotations

import contextlib
import errno as _errno
import os
import threading
import time
from typing import List, Optional

__all__ = [
    "SimulatedCrash", "FaultInjector", "install", "clear", "active",
    "inject", "guarded_write", "guarded_io", "step_fault", "bit_flip",
]


class SimulatedCrash(BaseException):
    """The injected process death. BaseException so ``except Exception``
    retry/cleanup paths cannot accidentally swallow it."""


class _WriteFault:
    """One scheduled OSError: fires for up to ``count`` writes whose path
    contains ``path_substr`` (empty matches everything)."""

    def __init__(self, errno: int, path_substr: str = "", count: int = 1):
        self.errno = errno
        self.path_substr = path_substr
        self.count = count


class _StepFault:
    """One scheduled serving-step fault: fires for up to ``count`` engine
    actions of ``kind`` (empty matches every kind) in ``phase`` once the
    injector's step counter reaches ``at_step`` (None = immediately)."""

    def __init__(self, kind: str = "", at_step: Optional[int] = None,
                 count: int = 1, exc=None, phase: str = "pre"):
        if phase not in ("pre", "post"):
            raise ValueError(f"phase must be 'pre' or 'post', got {phase!r}")
        self.kind = kind
        self.at_step = at_step
        self.count = count
        self.exc = exc
        self.phase = phase


class FaultInjector:
    """Deterministic write-path fault plan. Thread-safe: the async
    checkpoint writer hits it from its own thread."""

    def __init__(self, kill_at_byte: Optional[int] = None,
                 delay_per_write_s: float = 0.0,
                 delay_per_step_s: float = 0.0):
        self.kill_at_byte = kill_at_byte
        self.delay_per_write_s = delay_per_write_s
        self.delay_per_step_s = delay_per_step_s
        self._faults: List[_WriteFault] = []
        self._step_faults: List[_StepFault] = []
        self._lock = threading.Lock()
        self.bytes_seen = 0          # cumulative bytes offered to storage
        self.writes_seen = 0
        self.steps_seen = 0          # engine actions observed (pre-phase)
        self.crashed = False

    # ---- plan construction ---- #

    def fail_writes(self, errno_code: int = _errno.ENOSPC,
                    path_substr: str = "", count: int = 1) -> "FaultInjector":
        """Schedule ``count`` matching writes to raise ``OSError(errno)``.
        ``count < 0`` means every matching write fails forever (a persistent
        fault that outlives any retry budget). Returns self for chaining."""
        self._faults.append(_WriteFault(errno_code, path_substr, count))
        return self

    def fail_step(self, kind: str = "", at_step: Optional[int] = None,
                  count: int = 1, exc=None,
                  phase: str = "pre") -> "FaultInjector":
        """Schedule ``count`` serving engine steps to raise. ``kind``
        matches the dispatch site (``prefill`` / ``prefill_chunk`` /
        ``decode`` / ``verify`` / ``cow`` / ``spill`` / ``fetch``; empty =
        any), ``at_step`` pins the firing to the injector's engine-action
        counter (None = the first matching step), ``count < 0`` fails every
        matching step forever (a persistent fault that outlives any retry
        budget). ``exc`` is the exception instance (or zero-arg factory) to
        raise; default ``RuntimeError``. ``phase="pre"`` fires before the
        jit dispatch (per-request containable); ``phase="post"`` fires with
        the donated pools already consumed (engine-fatal). Returns self for
        chaining."""
        self._step_faults.append(_StepFault(kind, at_step, count, exc, phase))
        return self

    # ---- the serving step hook ---- #

    def on_step(self, kind: str, phase: str, tick: bool) -> None:
        """Called by :func:`step_fault` at a serving dispatch site.
        ``tick`` advances the engine-action counter (True exactly once per
        scheduler action — the top-of-executor pre consult); sub-action
        sites (cow/spill/fetch, post consults) observe without ticking so
        ``at_step`` schedules stay aligned with the scheduler's action
        sequence — and so does ``delay_per_step_s``, which sleeps once
        per ACTION (an action consults several times: pre, post, cow/
        fetch sub-sites). Raises the scheduled exception when a fault
        matches."""
        if self.delay_per_step_s > 0.0 and tick:
            time.sleep(self.delay_per_step_s)
        with self._lock:
            if tick:
                self.steps_seen += 1
            for f in self._step_faults:
                if f.count == 0 or f.phase != phase:
                    continue
                if f.kind and f.kind != kind:
                    continue
                if f.at_step is not None and self.steps_seen < f.at_step:
                    continue
                if f.count > 0:
                    f.count -= 1
                exc = f.exc
                if exc is None:
                    exc = RuntimeError(
                        f"injected {phase}-dispatch step fault "
                        f"({kind}, step {self.steps_seen})")
                elif not isinstance(exc, BaseException):
                    exc = exc()
                raise exc

    # ---- the write hook ---- #

    def on_write(self, path: str, size: int) -> int:
        """Called by :func:`guarded_write` before ``size`` bytes go to
        ``path``. Returns how many bytes may be written; raising ``OSError``
        models an I/O fault. A return < size means the crash point lies
        inside this write: the caller persists exactly that prefix, then
        :func:`guarded_write` raises :class:`SimulatedCrash`."""
        if self.delay_per_write_s > 0.0:
            time.sleep(self.delay_per_write_s)
        with self._lock:
            self.writes_seen += 1
            for f in self._faults:
                if f.count != 0 and f.path_substr in path:
                    if f.count > 0:
                        f.count -= 1
                    raise OSError(f.errno, os.strerror(f.errno), path)
            if self.kill_at_byte is not None:
                remaining = self.kill_at_byte - self.bytes_seen
                if remaining < size:
                    self.bytes_seen = self.kill_at_byte
                    self.crashed = True
                    return max(remaining, 0)
            self.bytes_seen += size
        return size


_active: Optional[FaultInjector] = None


def install(injector: FaultInjector) -> FaultInjector:
    global _active
    _active = injector
    return injector


def clear() -> None:
    global _active
    _active = None


def active() -> Optional[FaultInjector]:
    return _active


@contextlib.contextmanager
def inject(injector: FaultInjector):
    """``with fi.inject(FaultInjector(...)):`` — installed for the block."""
    install(injector)
    try:
        yield injector
    finally:
        clear()


def guarded_write(fileobj, data, path: str) -> None:
    """The checkpoint writer's single byte sink. No injector: one ``None``
    check and a plain ``write``. Injector: faults may fire; on a kill point
    the allowed prefix is flushed to disk (so the truncated file is really
    there, like after power loss) before :class:`SimulatedCrash` raises."""
    inj = _active
    if inj is None:
        fileobj.write(data)
        return
    view = memoryview(data) if not isinstance(data, memoryview) else data
    allowed = inj.on_write(path, len(view))
    if allowed < len(view):
        if allowed:
            fileobj.write(view[:allowed])
        try:
            fileobj.flush()
            os.fsync(fileobj.fileno())
        except (OSError, ValueError):
            pass
        raise SimulatedCrash(
            f"simulated crash after {inj.kill_at_byte} bytes (in {path})")
    fileobj.write(view)


def guarded_io(path: str, nbytes: int) -> None:
    """Fault gate for non-file byte movement (the tiered KV cache's
    D2H/H2D copies route through here under virtual paths like
    ``kv_host_pool/spill``). No injector: one ``None`` check. Installed:
    scheduled :meth:`FaultInjector.fail_writes` faults fire by path match
    (``OSError`` — the caller degrades gracefully), and the kill-at-byte
    crash plan advances too (a byte offered to storage is a byte,
    whichever channel carries it) — a kill point inside this transfer
    raises :class:`SimulatedCrash`, which callers must NOT catch."""
    inj = _active
    if inj is None:
        return
    allowed = inj.on_write(path, int(nbytes))
    if allowed < int(nbytes):
        raise SimulatedCrash(
            f"simulated crash after {inj.kill_at_byte} bytes (in {path})")


def step_fault(kind: str, phase: str = "pre", tick: bool = False) -> None:
    """Fault gate for the serving engine's action executor. No injector:
    one ``None`` check. Installed: scheduled :meth:`FaultInjector.fail_step`
    faults fire by (kind, phase, step) match — the serving loop contains
    them per-request (``phase="pre"``) or through engine restart
    (``phase="post"``) — and ``delay_per_step_s`` slows the loop so
    deadline / backpressure behavior is observable."""
    inj = _active
    if inj is None:
        return
    inj.on_step(kind, phase, tick)


def bit_flip(path: str, byte_index: Optional[int] = None, bit: int = 0) -> int:
    """Flip one bit of an existing file in place (default: the middle
    byte). Returns the byte index flipped. Deterministic corruption for
    manifest-verification tests."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot bit-flip empty file {path}")
    idx = size // 2 if byte_index is None else byte_index
    if not 0 <= idx < size:
        raise ValueError(f"byte_index {idx} out of range for {path} ({size}B)")
    with open(path, "r+b") as f:
        f.seek(idx)
        b = f.read(1)
        f.seek(idx)
        f.write(bytes([b[0] ^ (1 << bit)]))
        f.flush()
        os.fsync(f.fileno())
    return idx
