"""Multi-node launch command builders (reference ``launcher/multinode_runner.py``).

Each runner turns (user script, world layout, env exports) into the shell
command that starts one :mod:`deepspeed_tpu.launcher.launch` per node. On
TPU pods the common path is actually GKE/`gcloud compute tpus tpus-vm ssh`,
but the reference's PDSH/OpenMPI/SLURM/MPICH surface is preserved so
existing workflows translate; all builders are pure (command construction
only) and unit-testable without ssh (reference
``tests/unit/launcher/test_multinode_runner.py``).
"""

from __future__ import annotations

import os
import shlex
import sys
from abc import ABC, abstractmethod
from typing import Dict, List

PDSH_MAX_FAN_OUT = 1024


class MultiNodeRunner(ABC):
    def __init__(self, args, world_info_base64: str):
        self.args = args
        self.user_arguments = list(getattr(args, "user_args", []) or [])
        self.user_script = getattr(args, "user_script", "")
        self.world_info_base64 = world_info_base64
        self.exports: Dict[str, str] = {}

    @abstractmethod
    def get_cmd(self, environment: Dict[str, str], active_resources: Dict[str, List[int]]) -> List[str]:
        """The full launch command for this backend."""

    @abstractmethod
    def backend_exists(self) -> bool:
        """Whether the backend binary is available on this host."""

    def add_export(self, key: str, var: str) -> None:
        self.exports[key.strip()] = var.strip()

    @property
    def name(self) -> str:
        return type(self).__name__.lower().replace("runner", "")


class PDSHRunner(MultiNodeRunner):
    """pdsh fan-out: one launch.py per host over ssh (reference ``:48``)."""

    def backend_exists(self) -> bool:
        return _which("pdsh")

    def get_cmd(self, environment, active_resources):
        environment["PDSH_RCMD_TYPE"] = "ssh"
        active_workers = ",".join(active_resources.keys())

        exports = ""
        for key, val in self.exports.items():
            exports += f"export {key}={shlex.quote(val)}; "

        deepspeed_launch = [
            exports + f"cd {os.path.abspath('.')};",
            sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
            f"--world_info={self.world_info_base64}",
            "--node_rank=%n",
            f"--master_addr={self.args.master_addr}",
            f"--master_port={self.args.master_port}",
        ]
        if getattr(self.args, "save_pid", False):
            deepspeed_launch.append("--save_pid")
        if getattr(self.args, "enable_each_rank_log", None):
            deepspeed_launch.append(f"--enable_each_rank_log={self.args.enable_each_rank_log}")
        return (["pdsh", "-S", "-f", str(PDSH_MAX_FAN_OUT), "-w", active_workers] + deepspeed_launch
                + [self.user_script] + self.user_arguments)


class OpenMPIRunner(MultiNodeRunner):
    """mpirun -np <world> with one rank per chip (reference ``:115``)."""

    def backend_exists(self) -> bool:
        return _which("mpirun")

    def get_cmd(self, environment, active_resources):
        total_process_count = sum(len(v) for v in active_resources.values())
        mpirun_cmd = [
            "mpirun", "-n", f"{total_process_count}",
            "-hostfile", self.args.hostfile,
            "--mca", "btl", "^openib",
        ] + shlex.split(getattr(self.args, "launcher_args", "") or "")
        export_cmd = []
        # workers discover rank/size from OMPI_* env (comm.init_distributed);
        # the coordinator address must ride along explicitly
        self.add_export("MASTER_ADDR", str(self.args.master_addr))
        self.add_export("MASTER_PORT", str(self.args.master_port))
        for k, v in self.exports.items():
            export_cmd += ["-x", f"{k}={v}"]
        python_exec = [sys.executable, "-u"]
        return mpirun_cmd + export_cmd + python_exec + [self.user_script] + self.user_arguments


class MPICHRunner(MultiNodeRunner):
    def backend_exists(self) -> bool:
        return _which("mpirun")

    def get_cmd(self, environment, active_resources):
        total_process_count = sum(len(v) for v in active_resources.values())
        mpirun_cmd = ["mpirun", "-n", f"{total_process_count}", "-ppn",
                      f"{len(next(iter(active_resources.values())))}"] + \
            shlex.split(getattr(self.args, "launcher_args", "") or "")
        export_cmd = []
        self.add_export("MASTER_ADDR", str(self.args.master_addr))
        self.add_export("MASTER_PORT", str(self.args.master_port))
        for k, v in self.exports.items():
            export_cmd += ["-genv", k, str(v)]
        return mpirun_cmd + export_cmd + [sys.executable, "-u", self.user_script] + self.user_arguments


class MVAPICHRunner(MultiNodeRunner):
    """MVAPICH2 mpirun (reference ``:253``): one rank per chip, hostfile
    written from the world layout, MV2 tuning exports. The reference's
    CUDA-specific flags (MV2_USE_CUDA, GDR detection) have no TPU
    equivalent and are dropped; the generic MV2 exports are kept."""

    HOSTFILE = "/tmp/deepspeed_tpu_mvapich_hostfile"

    def __init__(self, args, world_info_base64: str):
        super().__init__(args, world_info_base64)
        self.add_export("MV2_SMP_USE_CMA", "0")        # CMA absent on Ubuntu
        self.add_export("MV2_DEBUG_SHOW_BACKTRACE", "1")
        self.add_export("MV2_SUPPORT_DL", "1")
        self.add_export("MV2_ENABLE_AFFINITY", "0")    # MPI_THREAD_MULTIPLE

    def backend_exists(self) -> bool:
        # mpiname ships with mvapich; plain `mpirun` alone could be openmpi
        return _which("mpiname")

    def get_cmd(self, environment, active_resources):
        per_node = [len(v) for v in active_resources.values()]
        if len(set(per_node)) > 1:
            raise ValueError("mvapich requires the same number of chips per node")
        total_process_count = sum(per_node)
        with open(self.HOSTFILE, "w") as fd:
            for host in active_resources:
                fd.write(f"{host}\n")
        mpirun_cmd = [
            "mpirun", "-np", f"{total_process_count}",
            "-ppn", f"{per_node[0]}",
            "--hostfile", self.HOSTFILE,
        ] + shlex.split(getattr(self.args, "launcher_args", "") or "")
        self.add_export("MASTER_ADDR", str(self.args.master_addr))
        self.add_export("MASTER_PORT", str(self.args.master_port))
        export_cmd = []
        for k, v in self.exports.items():
            export_cmd += ["-env", f"{k}={v}"]
        return mpirun_cmd + export_cmd + [sys.executable, "-u", self.user_script] \
            + self.user_arguments


class IMPIRunner(MultiNodeRunner):
    """Intel MPI mpirun (reference ``:184``): rank/size via PMI env, per-host
    -hosts list, -genv exports."""

    def backend_exists(self) -> bool:
        return _which("mpirun")

    def get_cmd(self, environment, active_resources):
        per_node = [len(v) for v in active_resources.values()]
        if len(set(per_node)) > 1:
            raise ValueError("impi requires the same number of chips per node")
        total_process_count = sum(per_node)
        mpirun_cmd = [
            "mpirun", "-ppn", f"{per_node[0]}",
            "-n", f"{total_process_count}",
            "-hosts", ",".join(active_resources.keys()),
        ] + shlex.split(getattr(self.args, "launcher_args", "") or "")
        self.add_export("MASTER_ADDR", str(self.args.master_addr))
        self.add_export("MASTER_PORT", str(self.args.master_port))
        export_cmd = []
        for k, v in self.exports.items():
            export_cmd += ["-genv", k, str(v)]
        return mpirun_cmd + export_cmd + [sys.executable, "-u", self.user_script] \
            + self.user_arguments


class SlurmRunner(MultiNodeRunner):
    def backend_exists(self) -> bool:
        return _which("sinfo")

    def get_cmd(self, environment, active_resources):
        total_process_count = sum(len(v) for v in active_resources.values())
        srun_cmd = ["srun", "-n", f"{total_process_count}"] + \
            shlex.split(getattr(self.args, "launcher_args", "") or "")
        self.add_export("MASTER_ADDR", str(self.args.master_addr))
        self.add_export("MASTER_PORT", str(self.args.master_port))
        if getattr(self.args, "include", ""):
            srun_cmd += ["--include", f"{self.args.include}"]
        if getattr(self.args, "exclude", ""):
            srun_cmd += ["--exclude", f"{self.args.exclude}"]
        if getattr(self.args, "num_nodes", -1) > 0:
            srun_cmd += ["--nodes", f"{self.args.num_nodes}"]
        exports = ""
        for key, val in self.exports.items():
            exports += f",{key}={val}"
        if exports:
            srun_cmd += ["--export", f"ALL{exports}"]
        return srun_cmd + [sys.executable, "-u", self.user_script] + self.user_arguments


def _which(binary: str) -> bool:
    import shutil
    return shutil.which(binary) is not None
