"""Per-node process launcher (reference ``launcher/launch.py:117-300``).

Spawns one worker process per local chip slot with the full distributed
environment (``RANK``/``LOCAL_RANK``/``WORLD_SIZE``/``MASTER_ADDR``/
``MASTER_PORT`` plus the JAX-native ``COORDINATOR_ADDRESS``/``NUM_PROCESSES``
/``PROCESS_ID`` that :func:`deepspeed_tpu.comm.init_distributed` consumes),
writes a pidfile, forwards SIGINT/SIGTERM to the children, and kills the
whole tree if any rank fails — the reference's failure-detection semantics.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from collections import defaultdict
from typing import Any, Dict, List

from deepspeed_tpu.launcher.runner import decode_world_info
from deepspeed_tpu.utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser(description="per-node deepspeed_tpu launcher")
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--master_addr", type=str, default="127.0.0.1")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--world_info", type=str, required=True, help="base64 world info")
    parser.add_argument("--save_pid", action="store_true")
    parser.add_argument("--enable_each_rank_log", default=None, type=str,
                        help="redirect each rank's stdout/err into this directory")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def build_rank_env(world_info: Dict[str, List[int]], node_rank: int, local_rank_idx: int,
                   master_addr: str, master_port: int) -> Dict[str, str]:
    """The distributed env block for one worker (pure; unit-testable)."""
    hosts = list(world_info.keys())
    node_host = hosts[node_rank]
    local_slots = world_info[node_host]
    global_rank = sum(len(world_info[h]) for h in hosts[:node_rank]) + local_rank_idx
    world_size = sum(len(slots) for slots in world_info.values())
    return {
        "RANK": str(global_rank),
        "LOCAL_RANK": str(local_rank_idx),
        "LOCAL_SIZE": str(len(local_slots)),
        "WORLD_SIZE": str(world_size),
        "MASTER_ADDR": master_addr,
        "MASTER_PORT": str(master_port),
        "COORDINATOR_ADDRESS": f"{master_addr}:{master_port}",
        "NUM_PROCESSES": str(world_size),
        "PROCESS_ID": str(global_rank),
        "TPU_VISIBLE_CHIPS": str(local_slots[local_rank_idx]),
    }


def main(args=None):
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    hosts = list(world_info.keys())
    node_host = hosts[args.node_rank]
    local_slots = world_info[node_host]

    processes: List[subprocess.Popen] = []

    # install forwarding handlers BEFORE spawning so an interrupt mid-spawn
    # cannot orphan already-started ranks (reference launch.py:292)
    def sig_handler(signum, frame):
        for p in processes:
            try:
                p.send_signal(signum)
            except ProcessLookupError:
                pass
        sys.exit(128 + signum)

    signal.signal(signal.SIGINT, sig_handler)
    signal.signal(signal.SIGTERM, sig_handler)

    log_dir = args.enable_each_rank_log
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)

    for local_rank in range(len(local_slots)):
        env = os.environ.copy()
        env.update(build_rank_env(world_info, args.node_rank, local_rank,
                                  args.master_addr, args.master_port))
        cmd = [sys.executable, "-u", args.user_script] + args.user_args
        if log_dir:
            rank = env["RANK"]
            out = open(os.path.join(log_dir, f"rank_{rank}.log"), "w")
            p = subprocess.Popen(cmd, env=env, stdout=out, stderr=subprocess.STDOUT)
        else:
            p = subprocess.Popen(cmd, env=env)
        processes.append(p)

    if args.save_pid:
        pidfile = os.path.join("/tmp", f"ds_launch_{os.getpid()}.pids")
        with open(pidfile, "w") as fd:
            json.dump([p.pid for p in processes], fd)
        logger.info(f"pids saved to {pidfile}")

    # monitor: any failure kills the tree (reference launch.py:103-117)
    alive = {p.pid: p for p in processes}
    exit_code = 0
    while alive:
        time.sleep(0.2)
        for pid, p in list(alive.items()):
            ret = p.poll()
            if ret is None:
                continue
            del alive[pid]
            if ret != 0:
                logger.error(f"rank process {pid} exited with code {ret}; terminating job")
                exit_code = ret
                for q in alive.values():
                    try:
                        q.terminate()
                    except ProcessLookupError:
                        pass
                alive = {}
                break
    sys.exit(exit_code)


if __name__ == "__main__":
    main()
