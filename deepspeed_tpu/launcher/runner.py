"""``dscli`` launcher front-end (reference ``launcher/runner.py``).

Parses the hostfile / include-exclude filters, encodes the world layout,
and either spawns the per-node :mod:`deepspeed_tpu.launcher.launch` locally
or builds the multi-node command (PDSH/OpenMPI/MPICH/SLURM). Hostfile
syntax, filter grammar (``host1@host2:0,2``), world-info base64 encoding and
``.deepspeed_env`` propagation all follow the reference
(``launcher/runner.py:176-335``) so existing workflows port unchanged; the
spawned workers talk to each other through ``jax.distributed`` (coordinator
= first host) instead of a NCCL store.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import re
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

from deepspeed_tpu.launcher.multinode_runner import (IMPIRunner, MPICHRunner, MVAPICHRunner,
                                                     OpenMPIRunner, PDSHRunner, SlurmRunner)
from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ["MLFLOW", "DS_", "JAX_", "LIBTPU", "TPU_", "PYTHON", "XLA_"]
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"
DEEPSPEED_ENVIRONMENT_PATHS = [".", os.path.expanduser("~")]


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="dscli launcher: run a deepspeed_tpu training script over one "
                    "or many hosts / TPU slices")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="Hostfile path: lines of '<host> slots=<n>'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="Resource filter, e.g. 'host1@host2:0,2'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="Resource exclusion filter, same grammar as --include")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_gpus", "--num_chips", dest="num_gpus", type=int, default=-1,
                        help="Processes (chips) per node")
    parser.add_argument("--master_port", type=int,
                        default=int(os.environ.get("DLTS_MASTER_PORT", 29500)))
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--launcher", type=str, default="pdsh",
                        choices=["pdsh", "openmpi", "mpich", "slurm", "mvapich", "impi"])
    parser.add_argument("--launcher_args", type=str, default="")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--save_pid", action="store_true")
    parser.add_argument("--enable_each_rank_log", default=None, type=str)
    parser.add_argument("user_script", type=str, help="User script to launch")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


# ------------------------------------------------------------------ #
# hostfile handling (reference runner.py:176-230)

def fetch_hostfile(hostfile_path: str) -> Optional[Dict[str, int]]:
    if not os.path.isfile(hostfile_path):
        return None
    resource_pool: "OrderedDict[str, int]" = OrderedDict()
    with open(hostfile_path, "r") as fd:
        for line in fd:
            line = line.strip()
            if line == "" or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                key, slot_count = slots.split("=")
                if key != "slots":
                    raise ValueError(f"expected 'slots=<n>', got {slots!r}")
                slot_count = int(slot_count)
            except ValueError:
                logger.error(f"Hostfile is not formatted correctly: {line}")
                raise ValueError(f"Hostfile is not formatted correctly: {line}")
            if hostname in resource_pool:
                raise ValueError(f"Hostfile contains duplicate hosts: {hostname}")
            resource_pool[hostname] = slot_count
    return resource_pool


def _parse_hostfile_filter(filter_str: str) -> Dict[str, Optional[List[int]]]:
    """'host1@host2:0,2' → {host1: None, host2: [0, 2]}; None = all slots."""
    mapping: "OrderedDict[str, Optional[List[int]]]" = OrderedDict()
    if not filter_str:
        return mapping
    for part in filter_str.split("@"):
        if ":" in part:
            host, slots = part.split(":")
            mapping[host] = [int(s) for s in slots.split(",")]
        else:
            mapping[part] = None
    return mapping


def parse_resource_filter(host_info: Dict[str, int], include_str: str = "",
                          exclude_str: str = "") -> Dict[str, List[int]]:
    """Apply include/exclude filters (reference runner.py:231-300)."""
    if include_str and exclude_str:
        raise ValueError("--include and --exclude are mutually exclusive")

    pool: "OrderedDict[str, List[int]]" = OrderedDict(
        (host, list(range(slots))) for host, slots in host_info.items())

    if include_str:
        include = _parse_hostfile_filter(include_str)
        filtered: "OrderedDict[str, List[int]]" = OrderedDict()
        for host, slots in include.items():
            if host not in pool:
                raise ValueError(f"Include host {host} not in hostfile")
            use = slots if slots is not None else pool[host]
            bad = [s for s in use if s not in pool[host]]
            if bad:
                raise ValueError(f"Include slots {bad} not available on {host}")
            filtered[host] = sorted(use)
        return filtered

    if exclude_str:
        exclude = _parse_hostfile_filter(exclude_str)
        for host, slots in exclude.items():
            if host not in pool:
                raise ValueError(f"Exclude host {host} not in hostfile")
            if slots is None:
                del pool[host]
            else:
                pool[host] = [s for s in pool[host] if s not in slots]
                if not pool[host]:
                    del pool[host]
    return pool


def encode_world_info(world_info: Dict[str, List[int]]) -> str:
    json_str = json.dumps(world_info)
    return base64.urlsafe_b64encode(json_str.encode()).decode()


def decode_world_info(encoded: str) -> Dict[str, List[int]]:
    return json.loads(base64.urlsafe_b64decode(encoded.encode()).decode())


# ------------------------------------------------------------------ #

def _local_chip_count() -> int:
    """Best-effort local device count WITHOUT initializing a backend."""
    for var in ("DS_NUM_CHIPS", "TPU_NUM_DEVICES"):
        if var in os.environ:
            return int(os.environ[var])
    return 1


def main(args=None):
    args = parse_args(args)

    resource_pool = fetch_hostfile(args.hostfile)

    if resource_pool is None:
        n = args.num_gpus if args.num_gpus > 0 else _local_chip_count()
        resource_pool = {"localhost": n}

    active_resources = parse_resource_filter(resource_pool, args.include, args.exclude)
    if args.num_nodes > 0:
        active_resources = OrderedDict(list(active_resources.items())[:args.num_nodes])
    if args.num_gpus > 0:
        active_resources = OrderedDict(
            (host, list(range(args.num_gpus))) for host in active_resources)

    # multi-node-ness is a property of the POST-filter layout (reference
    # computes it from active_resources): --include narrowing to one host
    # must take the local path
    multi_node = len(active_resources) > 1

    if args.launcher != "pdsh" and multi_node and (
            args.include or args.exclude or args.num_nodes > 0 or args.num_gpus > 0):
        raise ValueError(f"launcher {args.launcher} does not support worker "
                         "include/exclusion or node/chip count overrides "
                         "(mpirun/srun schedule from the full hostfile)")

    if not args.master_addr:
        args.master_addr = next(iter(active_resources))
        if args.master_addr == "localhost":
            args.master_addr = "127.0.0.1"

    world_info = encode_world_info(
        {h: (s if isinstance(s, list) else list(range(s))) for h, s in active_resources.items()})

    if not multi_node and not args.force_multi:
        # single node: exec launch.py directly
        cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
               f"--world_info={world_info}", "--node_rank=0",
               f"--master_addr={args.master_addr}", f"--master_port={args.master_port}"]
        if args.save_pid:
            cmd.append("--save_pid")
        if args.enable_each_rank_log:
            cmd.append(f"--enable_each_rank_log={args.enable_each_rank_log}")
        cmd += [args.user_script] + args.user_args
        logger.info(f"cmd = {' '.join(cmd)}")
        result = subprocess.Popen(cmd, env=os.environ.copy())
        result.wait()
        if result.returncode != 0:
            sys.exit(result.returncode)
        return

    runner_cls = {"pdsh": PDSHRunner, "openmpi": OpenMPIRunner,
                  "mpich": MPICHRunner, "slurm": SlurmRunner,
                  "mvapich": MVAPICHRunner, "impi": IMPIRunner}[args.launcher]
    runner = runner_cls(args, world_info)
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend {args.launcher} not installed on this host")

    # propagate whitelisted env vars + .deepspeed_env entries (runner.py:30-35)
    env = os.environ.copy()
    for var, val in env.items():
        if any(var.startswith(prefix) for prefix in EXPORT_ENVS):
            runner.add_export(var, val)
    for path in DEEPSPEED_ENVIRONMENT_PATHS:
        env_file = os.path.join(path, DEEPSPEED_ENVIRONMENT_NAME)
        if os.path.isfile(env_file):
            with open(env_file) as fd:
                for line in fd:
                    line = line.strip()
                    if line and not line.startswith("#") and "=" in line:
                        key, val = line.split("=", 1)
                        runner.add_export(key, val)

    cmd = runner.get_cmd(env, active_resources)
    logger.info(f"cmd = {' '.join(cmd)}")
    result = subprocess.Popen(cmd, env=env)
    result.wait()
    if result.returncode != 0:
        sys.exit(result.returncode)


if __name__ == "__main__":
    main()
