"""FLOPS profiler (reference ``profiling/flops_profiler/profiler.py``).

The reference monkey-patches ``torch.nn.functional`` and Tensor methods to
count MACs as the model runs (``:753-958``). The TPU-native equivalent is
static analysis of the traced computation:

- primary source: XLA's own ``compiled.cost_analysis()`` (exact flops for
  the optimized HLO, fusion-aware)
- fallback + per-op breakdown: walking the jaxpr and counting matmul/conv
  flops analytically (``flops_from_jaxpr``), which also yields the per-op
  table the reference prints per-module

``get_model_profile`` mirrors the reference's standalone API; the engine
calls :class:`FlopsProfiler` at ``flops_profiler.profile_step``.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np


# ------------------------------------------------------------------ #
# pretty printing (reference number_to_string/macs_to_string family)

def number_to_string(num: float, units: Optional[str] = None, precision: int = 2) -> str:
    if units is None:
        for cutoff, u in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
            if abs(num) >= cutoff:
                return f"{num / cutoff:.{precision}f} {u}"
        return f"{num:.{precision}f}"
    scale = {"T": 1e12, "G": 1e9, "M": 1e6, "K": 1e3, "": 1.0}[units]
    return f"{num / scale:.{precision}f} {units}"


# ------------------------------------------------------------------ #
# jaxpr walking

_ELEMENTWISE_PRIMS = {
    "add", "sub", "mul", "div", "max", "min", "exp", "log", "tanh", "logistic",
    "rsqrt", "sqrt", "neg", "abs", "pow", "integer_pow", "erf", "sin", "cos",
}


def _dot_general_flops(eqn) -> float:
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = math.prod(lhs[d] for d in lb) if lb else 1
    contract = math.prod(lhs[d] for d in lc) if lc else 1
    m = math.prod(s for d, s in enumerate(lhs) if d not in set(lc) | set(lb))
    n = math.prod(s for d, s in enumerate(rhs) if d not in set(rc) | set(rb))
    return 2.0 * batch * m * n * contract


def _conv_flops(eqn) -> float:
    out_shape = eqn.outvars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    dn = eqn.params["dimension_numbers"]
    # rhs_spec = (out_ch_dim, in_ch_dim, *spatial_dims)
    out_ch_dim = dn.rhs_spec[0]
    per_output = math.prod(s for d, s in enumerate(rhs) if d != out_ch_dim)
    return 2.0 * math.prod(out_shape) * per_output  # 2 * out_elems * (k·in_ch)


def flops_from_jaxpr(jaxpr, breakdown: Optional[Dict[str, float]] = None) -> float:
    """Analytic flop count by walking a (closed) jaxpr recursively. The
    per-primitive ``breakdown`` attributes nested flops to the INNER
    primitives only (wrapper eqns like pjit/scan contribute their own
    direct compute, which is zero), so it sums to the returned total."""
    total = 0.0
    breakdown = breakdown if breakdown is not None else {}
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "dot_general":
            own = _dot_general_flops(eqn)
        elif prim in ("conv_general_dilated",):
            own = _conv_flops(eqn)
        elif prim in _ELEMENTWISE_PRIMS:
            own = float(math.prod(eqn.outvars[0].aval.shape)) if eqn.outvars[0].aval.shape else 1.0
        elif prim == "reduce_sum" or prim.startswith("reduce_"):
            own = float(math.prod(eqn.invars[0].aval.shape)) if eqn.invars[0].aval.shape else 1.0
        else:
            own = 0.0
        if own:
            breakdown[prim] = breakdown.get(prim, 0.0) + own
        total += own
        # recurse into sub-jaxprs (jit/remat/scan bodies); scan multiplies by
        # length — in the total AND the per-primitive breakdown
        for name, val in eqn.params.items():
            sub = getattr(val, "jaxpr", None)
            if sub is not None:
                sub_bd: Dict[str, float] = {}
                inner = flops_from_jaxpr(sub, sub_bd)
                mult = eqn.params.get("length", 1) if prim == "scan" else 1
                total += inner * mult
                for k, v in sub_bd.items():
                    breakdown[k] = breakdown.get(k, 0.0) + v * mult
    return total


# ------------------------------------------------------------------ #

def _count_params(tree) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree) if hasattr(x, "shape"))


def get_model_profile(model=None, fn: Optional[Callable] = None, args: Tuple = (),
                      kwargs: Optional[Dict] = None, print_profile: bool = True,
                      detailed: bool = True, as_string: bool = True):
    """Standalone profile (reference ``get_model_profile``): returns
    (flops, macs, params) for one forward call.

    Either ``model`` (an object with ``.forward(params, ...)``; args[0] must
    be the param tree) or a bare ``fn``.
    """
    kwargs = kwargs or {}
    target = fn if fn is not None else (lambda *a, **k: model.forward(*a, **k))

    closed = jax.make_jaxpr(target)(*args, **kwargs)
    breakdown: Dict[str, float] = {}
    flops = flops_from_jaxpr(closed.jaxpr, breakdown)

    # prefer XLA's exact count when available
    try:
        cost = jax.jit(target).lower(*args, **kwargs).compile().cost_analysis()
        if cost and cost.get("flops"):
            flops = float(cost["flops"])
    except Exception:
        pass

    macs = flops / 2.0
    # contract: args[0] is the parameter pytree (both model and bare-fn
    # paths); counting all args would inflate params with batch elements
    params = _count_params(args[0]) if args else 0

    if print_profile:
        print("-" * 60)
        print("deepspeed_tpu flops profile")
        print(f"params:           {number_to_string(params)}")
        print(f"fwd flops:        {number_to_string(flops)}")
        print(f"fwd MACs:         {number_to_string(macs)}MACs")
        if detailed and breakdown:
            print("per-primitive breakdown (traced):")
            for prim, f in sorted(breakdown.items(), key=lambda kv: -kv[1])[:10]:
                print(f"  {prim:<24} {number_to_string(f)}")
        print("-" * 60)

    if as_string:
        return number_to_string(flops), f"{number_to_string(macs)}MACs", number_to_string(params)
    return flops, macs, params


class FlopsProfiler:
    """Engine-integrated profiler (reference ``FlopsProfiler``): profiles the
    training step function at the configured step."""

    def __init__(self, model=None, ds_engine=None, recompute_fwd_factor: float = 0.0):
        self.model = model
        self.ds_engine = ds_engine
        self.recompute_fwd_factor = recompute_fwd_factor
        self.started = False
        self.flops = 0.0
        self.macs = 0.0
        self.params = 0

    def start_profile(self, ignore_list=None) -> None:
        self.started = True
        self.flops = 0.0

    def profile_fn(self, fn: Callable, *args, **kwargs) -> None:
        closed = jax.make_jaxpr(fn)(*args, **kwargs)
        self.flops = flops_from_jaxpr(closed.jaxpr)
        try:
            cost = jax.jit(fn).lower(*args, **kwargs).compile().cost_analysis()
            if cost and cost.get("flops"):
                self.flops = float(cost["flops"])
        except Exception:
            pass
        self.macs = self.flops / 2.0
        if args:
            self.params = _count_params(args[0])

    def get_total_flops(self, as_string: bool = False):
        total = self.flops * (1.0 + self.recompute_fwd_factor)
        return number_to_string(total) if as_string else total

    def get_total_macs(self, as_string: bool = False):
        return number_to_string(self.macs) if as_string else self.macs

    def get_total_params(self, as_string: bool = False):
        return number_to_string(self.params) if as_string else self.params

    def print_model_profile(self, profile_step: int = 1, module_depth: int = -1,
                            top_modules: int = 1, detailed: bool = True,
                            output_file: Optional[str] = None) -> None:
        lines = [
            "-" * 60,
            f"flops profile at step {profile_step}",
            f"params:       {self.get_total_params(as_string=True)}",
            f"fwd flops:    {self.get_total_flops(as_string=True)}",
            f"fwd MACs:     {self.get_total_macs(as_string=True)}MACs",
            "-" * 60,
        ]
        text = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(text + "\n")
        else:
            print(text)

    def stop_profile(self) -> None:
        self.started = False

    def end_profile(self) -> None:
        self.stop_profile()
