from deepspeed_tpu.profiling.flops_profiler.profiler import (FlopsProfiler, flops_from_jaxpr,
                                                             get_model_profile,
                                                             number_to_string)
