"""FLOPS profiler config (reference: deepspeed/profiling/config.py)."""

from __future__ import annotations

from typing import Optional

from deepspeed_tpu.config.config_utils import ConfigModel


class DeepSpeedFlopsProfilerConfig(ConfigModel):
    enabled: bool = False
    recompute_fwd_factor: float = 0.0
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None
