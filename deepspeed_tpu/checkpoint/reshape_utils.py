"""Checkpoint reshaping helpers (reference ``checkpoint/reshape_meg_2d.py`` /
``reshape_3d_utils.py`` / ``merge`` logic in ``state_dict_factory.py``).

The reference reshapes Megatron-DS checkpoints between TP/PP degrees by
concatenating or splitting each weight along its sharded dim. Here the live
engine reshards natively via the mesh, so these helpers exist for IMPORT/
EXPORT interop: merging externally TP-sharded checkpoints (one file per
rank) into full logical arrays, splitting full arrays back out to a target
TP degree, and the qkv-aware variants that keep per-head blocks contiguous
(reference ``module_inject/replace_module.py:42-119`` ``qkv_copy``).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


def merge_tp_shards(shards: Sequence[np.ndarray], dim: int) -> np.ndarray:
    """Concatenate per-rank shards along the sharded dim (column-parallel:
    dim=last; row-parallel: dim=0)."""
    if len(shards) == 1:
        return np.asarray(shards[0])
    return np.concatenate([np.asarray(s) for s in shards], axis=dim)


def split_tp_shards(full: np.ndarray, dim: int, tp_degree: int) -> List[np.ndarray]:
    """Split a full array into tp_degree equal shards along ``dim``."""
    if full.shape[dim] % tp_degree != 0:
        raise ValueError(f"dim {dim} of shape {full.shape} not divisible by tp={tp_degree}")
    return [np.ascontiguousarray(s) for s in np.split(full, tp_degree, axis=dim)]


def merge_qkv_shards(shards: Sequence[np.ndarray], dim: int, num_splits: int = 3) -> np.ndarray:
    """Merge TP shards of a FUSED qkv weight.

    Each rank's shard holds [q_i | k_i | v_i] stacked along ``dim``; the
    merged fused weight must be [q_0..q_n | k_0..k_n | v_0..v_n] — plain
    concatenation would interleave q/k/v (reference ``qkv_copy``,
    ``replace_module.py:42``)."""
    if len(shards) == 1:
        return np.asarray(shards[0])
    per_rank = [np.split(np.asarray(s), num_splits, axis=dim) for s in shards]
    merged_each = [np.concatenate([r[i] for r in per_rank], axis=dim) for i in range(num_splits)]
    return np.concatenate(merged_each, axis=dim)


def split_qkv_shards(full: np.ndarray, dim: int, tp_degree: int,
                     num_splits: int = 3) -> List[np.ndarray]:
    """Inverse of :func:`merge_qkv_shards`: shard a fused qkv weight so each
    rank gets its contiguous [q_i | k_i | v_i] block."""
    parts = np.split(full, num_splits, axis=dim)  # [q, k, v]
    rank_shards = []
    for rank in range(tp_degree):
        pieces = []
        for part in parts:
            if part.shape[dim] % tp_degree != 0:
                raise ValueError(f"qkv split dim {dim} of {part.shape} not divisible by tp={tp_degree}")
            pieces.append(np.split(part, tp_degree, axis=dim)[rank])
        rank_shards.append(np.ascontiguousarray(np.concatenate(pieces, axis=dim)))
    return rank_shards


def partition_data(data: List, num_partitions: int) -> List[List]:
    """Even partitioning of a list (reference ``checkpoint/reshape_utils.py:
    partition_data``)."""
    if len(data) % num_partitions != 0:
        raise ValueError(f"cannot partition {len(data)} items into {num_partitions}")
    size = len(data) // num_partitions
    return [data[i * size:(i + 1) * size] for i in range(num_partitions)]
