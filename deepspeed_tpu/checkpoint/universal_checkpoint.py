"""Universal checkpoint (reference ``checkpoint/universal_checkpoint.py`` +
``ds_to_universal.py``): a topology-independent per-parameter layout.

``ds_to_universal`` explodes an engine checkpoint into one directory per
parameter holding its fp32 weight plus optimizer moments — the reference's
"param fragment" files (``universal_checkpoint.py:10-93``). A universal
checkpoint can be loaded into an engine running at ANY dp/tp/pp/world size:
each process reads the full logical arrays and ``jax.device_put`` shards
them to its own layout (where the reference needs explicit fragment
remapping via ``tensor_fragment.py``, the mesh resharding is native here).

Layout::

    <out_dir>/
      meta.json                     # step counters, source config
      params/<dotted.path>.npz      # param (fp32), exp_avg, exp_avg_sq
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np

from deepspeed_tpu.checkpoint.zero_to_fp32 import (_leaf_paths, _resolve_tag,
                                                   get_fp32_state_dict_from_zero_checkpoint)

def ds_to_universal(checkpoint_dir: str, out_dir: str, tag: Optional[str] = None) -> None:
    """Convert an engine checkpoint tag into the universal layout."""
    from deepspeed_tpu.runtime.checkpoint_engine.safe_engine import read_state_tree

    checkpoint_dir = os.path.abspath(checkpoint_dir)
    tag = _resolve_tag(checkpoint_dir, tag)
    tree = read_state_tree(os.path.join(checkpoint_dir, tag))

    fp32 = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag, _tree=tree)

    moments: Dict[str, Dict[str, np.ndarray]] = {p: {} for p in fp32}
    opt_flat = tree.get("opt_state_flat")
    labels = None
    meta_path = os.path.join(checkpoint_dir, tag, "meta.json")
    if os.path.isfile(meta_path):
        with open(meta_path) as f:
            labels = json.load(f).get("opt_state_labels")

    if opt_flat and labels:
        # structured metadata written at save time (checkpoint_engine):
        # each flat leaf is labelled with its moment kind + param path —
        # no shape guessing, extra optimizer state is simply skipped
        kind_map = {"mu": "exp_avg", "nu": "exp_avg_sq"}
        for i, lab in enumerate(labels):
            kind = kind_map.get(lab.get("moment"))
            pname = lab.get("param")
            if kind and pname in moments:
                moments[pname][kind] = np.asarray(opt_flat[f"leaf_{i}"]).astype(np.float32)
    elif opt_flat:
        # legacy checkpoints without labels: infer by runs of param-shaped
        # leaves — [count, mu..., nu...] for adam-family chains; refuse to
        # guess if the structure is ambiguous
        param_items = list(_leaf_paths(tree["params"]).items())
        n = len(param_items)
        param_shapes = [np.asarray(p).shape for _, p in param_items]
        leaves = [np.asarray(opt_flat[k])
                  for k in sorted(opt_flat, key=lambda s: int(s.split("_")[1]))]
        arrays = [a for a in leaves if a.shape != ()]
        runs = []
        i = 0
        while i + n <= len(arrays) and len(runs) < 2:
            if [a.shape for a in arrays[i:i + n]] == param_shapes:
                runs.append(arrays[i:i + n])
                i += n
            else:
                i += 1
        leftovers = len(arrays) - 2 * n
        if len(runs) != 2 or leftovers != 0:
            import warnings
            warnings.warn(
                f"ds_to_universal: optimizer state is ambiguous without labels "
                f"({len(runs)} shape-matched runs, {leftovers} leftover "
                f"non-scalar leaves); omitting moments — re-save the checkpoint "
                f"with this version to get labelled optimizer state")
            runs = []
        for name, run in zip(["exp_avg", "exp_avg_sq"], runs):
            for (pname, _), arr in zip(param_items, run):
                moments[pname][name] = arr.astype(np.float32)

    # canonicalize pipeline topology out of the layout (reference
    # reshape_meg_2d.py / deepspeed_checkpoint.py:30 reshape across tp x pp
    # degrees): "stages.*" leaves [num_stages, layers_per_stage, ...] are
    # stored as "layers.*" [n_layer, ...], so one universal checkpoint loads
    # at ANY pp degree (tp degree never enters: arrays are full logical)
    def canon(pname, arr):
        if pname.startswith("stages."):
            S, Lps = arr.shape[0], arr.shape[1]
            return "layers." + pname[len("stages."):], \
                arr.reshape((S * Lps,) + arr.shape[2:])
        if pname.startswith("head."):
            # the pipeline model nests ln_f/lm_head under "head."; the plain
            # model keeps them top-level — canonical form is top-level
            return pname[len("head."):], arr
        return pname, arr

    params_dir = os.path.join(out_dir, "params")
    if os.path.isdir(params_dir) and os.listdir(params_dir):
        # canonicalization renames entries (stages.* -> layers.*, head.* ->
        # top-level): stale files from a previous export would silently
        # shadow fresh weights on load — start clean
        import shutil
        shutil.rmtree(params_dir)
    os.makedirs(params_dir, exist_ok=True)
    for pname, arr in fp32.items():
        cname, carr = canon(pname, arr)
        payload = {"param": carr}
        payload.update({k: canon(pname, v)[1] for k, v in moments.get(pname, {}).items()})
        np.savez(os.path.join(params_dir, f"{cname}.npz"), **payload)

    meta_src = os.path.join(checkpoint_dir, tag, "meta.json")
    meta: Dict[str, Any] = {"source_tag": tag, "format": "universal", "version": 1}
    if os.path.isfile(meta_src):
        with open(meta_src) as f:
            meta["source_meta"] = json.load(f)
    with open(os.path.join(out_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2, default=str)
    print(f"Universal checkpoint with {len(fp32)} params written to {out_dir}")


def load_universal_state_dict(universal_dir: str) -> Dict[str, Dict[str, np.ndarray]]:
    """{dotted.path: {param, exp_avg?, exp_avg_sq?}} from a universal dir."""
    params_dir = os.path.join(universal_dir, "params")
    out: Dict[str, Dict[str, np.ndarray]] = {}
    for fname in sorted(os.listdir(params_dir)):
        if not fname.endswith(".npz"):
            continue
        dotted = fname[:-4]
        with np.load(os.path.join(params_dir, fname)) as z:
            out[dotted] = {k: z[k] for k in z.files}
    return out


def load_universal_into_params(universal_dir: str, params: Any, dtype=None) -> Any:
    """Map a universal checkpoint onto an existing (possibly sharded) param
    pytree: each leaf is replaced by the stored fp32 weight cast to the
    leaf's dtype and placed with the leaf's sharding.

    Pipeline topology adapts on load: a target "stages.*" leaf
    [num_stages, layers_per_stage, ...] pulls the canonical "layers.*"
    entry and re-stacks it, so a checkpoint saved at tp=2 x pp=2 loads at
    pp=4, pp=1, or any tp (reference reshape_meg_2d capability). Universal
    dirs written before canonicalization (carrying "stages.*" entries)
    still load when the stage split matches or the target is "layers.*"."""
    import jax
    import jax.numpy as jnp

    sd = load_universal_state_dict(universal_dir)

    from deepspeed_tpu.utils.pytree import leaf_key

    def lookup(dotted, leaf_shape):
        """Resolve stages<->layers and head-nesting naming + leading-dim
        re-stacking against the target leaf shape."""
        ent = sd.get(dotted)
        if ent is None:
            # pipeline "head.X" <-> canonical top-level "X"
            alias = dotted[len("head."):] if dotted.startswith("head.") \
                else "head." + dotted
            ent = sd.get(alias)
        if ent is not None and ent["param"].shape == leaf_shape:
            return ent["param"]
        if dotted.startswith("stages."):
            # target is pipelined [S, Lps, ...]: pull the canonical flat
            # "layers." entry (or flatten an old-format "stages." entry)
            tail = dotted[len("stages."):]
            src = sd.get("layers." + tail)
            if src is not None:
                flat = src["param"]
            elif ent is not None:
                flat = ent["param"].reshape((-1,) + ent["param"].shape[2:])
            else:
                raise KeyError(f"universal checkpoint missing parameter {dotted}")
            S, Lps = leaf_shape[0], leaf_shape[1]
            if flat.shape != (S * Lps,) + tuple(leaf_shape[2:]):
                raise ValueError(f"cannot re-stack {dotted}: ckpt layers "
                                 f"{flat.shape} vs target {leaf_shape}")
            return flat.reshape((S, Lps) + flat.shape[1:])
        if dotted.startswith("layers.") and ent is None:
            # target is non-pipelined: flatten an old-format "stages." entry
            src = sd.get("stages." + dotted[len("layers."):])
            if src is None:
                raise KeyError(f"universal checkpoint missing parameter {dotted}")
            flat = src["param"].reshape((-1,) + src["param"].shape[2:])
            if flat.shape != leaf_shape:
                raise ValueError(f"cannot flatten stages for {dotted}: ckpt "
                                 f"{src['param'].shape} vs target {leaf_shape}")
            return flat
        if ent is not None:
            raise ValueError(f"shape mismatch for {dotted}: ckpt "
                             f"{ent['param'].shape} vs model {leaf_shape}")
        raise KeyError(f"universal checkpoint missing parameter {dotted}")

    def replace(path_tuple, leaf):
        dotted = leaf_key(path_tuple)
        arr = lookup(dotted, tuple(leaf.shape))
        out_dtype = dtype or leaf.dtype
        if hasattr(leaf, "sharding"):
            return jax.device_put(jnp.asarray(arr, dtype=out_dtype), leaf.sharding)
        return jnp.asarray(arr, dtype=out_dtype)

    return jax.tree_util.tree_map_with_path(replace, params)
