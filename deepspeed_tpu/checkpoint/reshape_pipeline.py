"""Offline pipeline-degree reshaping of engine checkpoints.

Reference parity: ``deepspeed/checkpoint/reshape_meg_2d.py:1-219`` +
``deepspeed_checkpoint.py:30`` — reshape a saved checkpoint across tp x pp
degrees without running the model. In the TPU engine the tp degree never
enters the saved layout (orbax stores full logical arrays; the mesh
reshards natively on load), so "2D reshape" reduces to re-stacking the
pipeline stage axis: every ``stages`` leaf ``[S, layers_per_stage, ...]``
re-stacks to ``[S', n_layer/S', ...]`` — applied consistently to params,
fp32 masters, accumulated grads, and the labelled optimizer moments.

For topology-independent interop prefer ``ds_to_universal`` (it
canonicalizes the stage axis away entirely); this tool is the direct
tag -> tag equivalent of the reference's offline reshaper.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np


def stages_to_layers(tree: Any):
    """Stage-stacked subtree [S, Lps, ...] -> flat layer-stacked [L, ...]."""
    import jax
    return jax.tree.map(lambda a: np.asarray(a).reshape((-1,) + a.shape[2:]), tree)


def layers_to_stages(tree: Any, num_stages: int):
    """Flat layer-stacked subtree [L, ...] -> [num_stages, L/num_stages, ...]."""
    import jax

    def one(a):
        a = np.asarray(a)
        if a.shape[0] % num_stages:
            raise ValueError(f"n_layer {a.shape[0]} not divisible by "
                             f"target pp degree {num_stages}")
        return a.reshape((num_stages, a.shape[0] // num_stages) + a.shape[1:])

    return jax.tree.map(one, tree)


def _reshape_leaf(a: np.ndarray, target_pp: int) -> np.ndarray:
    a = np.asarray(a)
    L = a.shape[0] * a.shape[1]
    if L % target_pp:
        raise ValueError(f"n_layer {L} not divisible by target pp {target_pp}")
    return a.reshape((target_pp, L // target_pp) + a.shape[2:])


def reshape_stages_tree(stages: Any, target_pp: int):
    """[S, Lps, ...] stage leaves re-stacked to the target pp degree."""
    import jax
    return jax.tree.map(lambda a: _reshape_leaf(a, target_pp), stages)


def reshape_pipeline_checkpoint(src_dir: str, dst_dir: str, target_pp: int,
                                tag: Optional[str] = None) -> str:
    """Rewrite the checkpoint at ``src_dir[/tag]`` with its pipeline stage
    axis re-stacked to ``target_pp``; returns the destination tag dir. The
    destination can be loaded by an engine running pp=target_pp (any tp/dp)."""
    import jax
    import orbax.checkpoint as ocp

    from deepspeed_tpu.checkpoint.zero_to_fp32 import _resolve_tag

    src_dir = os.path.abspath(src_dir)
    tag = _resolve_tag(src_dir, tag)

    # per-process offload sidecars (host optimizer state) are dp-sharded and
    # topology-bound: refuse BEFORE the (potentially multi-GB) restore
    side = [p for p in os.listdir(os.path.join(src_dir, tag))
            if p.startswith("offload_state_p")]
    if side:
        raise ValueError("checkpoint has ZeRO-Offload host-state sidecars; "
                         "offload state is dp-rank-sharded and cannot be "
                         "reshaped offline — resume at the original topology "
                         "or convert via ds_to_universal")

    from deepspeed_tpu.runtime.checkpoint_engine.safe_engine import read_state_tree
    tree = read_state_tree(os.path.join(src_dir, tag))

    if "stages" not in tree.get("params", {}):
        raise ValueError(f"checkpoint {src_dir}/{tag} has no pipeline 'stages' "
                         "subtree; nothing to reshape")

    # original stage-stacked leaf shapes, recorded BEFORE reshaping: used to
    # refuse unattributable per-param optimizer state below
    stage_shapes = {tuple(np.asarray(a).shape)
                    for a in jax.tree.leaves(tree["params"]["stages"])}

    for section in ("params", "master", "acc_grads"):
        sub = tree.get(section)
        if isinstance(sub, dict) and "stages" in sub:
            sub["stages"] = reshape_stages_tree(sub["stages"], target_pp)

    # labelled optimizer moments: reshape every flat leaf whose param path
    # points into the stages subtree
    meta_path = os.path.join(src_dir, tag, "meta.json")
    meta: Dict[str, Any] = {}
    if os.path.isfile(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    labels = meta.get("opt_state_labels")
    opt_flat = tree.get("opt_state_flat")
    if opt_flat is not None:
        if labels is None:
            raise ValueError(
                "checkpoint carries optimizer state without opt_state_labels; "
                "re-save with a current engine (or drop the optimizer state) "
                "before reshaping")
        for i, lab in enumerate(labels):
            pname = lab.get("param") or ""
            key = f"leaf_{i}"
            if pname.startswith("stages."):
                opt_flat[key] = _reshape_leaf(opt_flat[key], target_pp)
            elif not pname and \
                    tuple(np.asarray(opt_flat[key]).shape) in stage_shapes:
                # a per-param leaf the labeller could not attribute (e.g. an
                # SGD momentum 'trace' — only adam-family mu/nu carry param
                # paths) that is stage-shaped: reshaping params around it
                # would write an unloadable mixed-shape checkpoint
                raise ValueError(
                    f"optimizer leaf {lab.get('path', key)} is stage-shaped "
                    "but not attributed to a parameter (non-adam-family "
                    "state); cannot reshape this checkpoint's optimizer "
                    "state — pass load_optimizer_states=False semantics by "
                    "deleting opt_state_flat, or re-save with an adam-family "
                    "optimizer")

    dst_dir = os.path.abspath(dst_dir)
    os.makedirs(os.path.join(dst_dir, tag), exist_ok=True)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.join(dst_dir, tag, "state"), tree, force=True)
    meta["reshaped_to_pp"] = int(target_pp)
    with open(os.path.join(dst_dir, tag, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2, default=str)
    with open(os.path.join(dst_dir, "latest"), "w") as f:
        f.write(tag)
    return os.path.join(dst_dir, tag)
