"""Checkpoint conversion & interop (reference ``deepspeed/checkpoint/`` +
``utils/zero_to_fp32.py`` + ``runtime/state_dict_factory.py``)."""

from deepspeed_tpu.checkpoint.reshape_pipeline import (layers_to_stages,
                                                       reshape_pipeline_checkpoint,
                                                       reshape_stages_tree,
                                                       stages_to_layers)
from deepspeed_tpu.checkpoint.reshape_utils import (merge_qkv_shards, merge_tp_shards,
                                                    partition_data, split_qkv_shards,
                                                    split_tp_shards)
from deepspeed_tpu.checkpoint.state_dict_factory import (MegatronSDLoader, SDLoaderFactory,
                                                         load_state_dict_file)
from deepspeed_tpu.checkpoint.universal_checkpoint import (ds_to_universal,
                                                           load_universal_into_params,
                                                           load_universal_state_dict)
from deepspeed_tpu.checkpoint.zero_to_fp32 import (convert_zero_checkpoint_to_fp32_state_dict,
                                                   get_fp32_state_dict_from_zero_checkpoint)

__all__ = [
    "merge_tp_shards", "split_tp_shards", "merge_qkv_shards", "split_qkv_shards",
    "partition_data", "SDLoaderFactory", "MegatronSDLoader", "load_state_dict_file",
    "reshape_pipeline_checkpoint", "reshape_stages_tree", "stages_to_layers",
    "layers_to_stages",
    "ds_to_universal", "load_universal_state_dict", "load_universal_into_params",
    "convert_zero_checkpoint_to_fp32_state_dict", "get_fp32_state_dict_from_zero_checkpoint",
]
