"""Checkpoint loaders for external (HF / Megatron-style) state dicts
(reference ``runtime/state_dict_factory.py``: ``SDLoaderFactory`` +
TP-degree resharding at inference load).

Supports:
- single-file torch checkpoints (``pytorch_model.bin`` — torch CPU is
  available in this image) and safetensors files
- HF sharded-index checkpoints (``*.index.json`` mapping weight → shard)
- the reference's ``ds_inference`` checkpoint-meta json
  ({"type": ..., "checkpoints": [...], "version": ...},
  ``inference/engine.py:354-419``)

All loaders return ``{name: np.ndarray}``; TP merge/split is delegated to
:mod:`deepspeed_tpu.checkpoint.reshape_utils`.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Union

import numpy as np


def _load_torch_file(path: str) -> Dict[str, np.ndarray]:
    import torch
    sd = torch.load(path, map_location="cpu", weights_only=True)
    if "module" in sd and isinstance(sd["module"], dict):
        sd = sd["module"]  # DS-style wrapper
    out = {}
    for k, v in sd.items():
        if hasattr(v, "numpy"):
            v = v.float().numpy() if v.dtype.is_floating_point else v.numpy()
        out[k] = np.asarray(v)
    return out


def _load_safetensors_file(path: str) -> Dict[str, np.ndarray]:
    from safetensors.numpy import load_file
    return load_file(path)


def load_state_dict_file(path: str) -> Dict[str, np.ndarray]:
    if path.endswith(".safetensors"):
        return _load_safetensors_file(path)
    return _load_torch_file(path)


class SDLoaderFactory:
    """Entry point mirroring the reference class (``state_dict_factory.py:24``)."""

    @staticmethod
    def get_sd_loader_json(json_file_or_dict: Union[str, dict]):
        """Parse a ds_inference checkpoint-meta json → (type, paths, version)."""
        if isinstance(json_file_or_dict, str):
            with open(json_file_or_dict) as f:
                data = json.load(f)
        else:
            data = json_file_or_dict
        sd_type = data.get("type", "Megatron")
        ckpt_list = data.get("checkpoints", [])
        if isinstance(ckpt_list, dict):  # BLOOM-style {"load": [...]}
            ckpt_list = ckpt_list.get("load", [])
        base = data.get("base_dir", "")
        paths = [os.path.join(base, c) if base else c for c in ckpt_list]
        version = data.get("version", 1.0)
        return sd_type, paths, version

    @staticmethod
    def get_sd_loader(ckpt_list: List[str], sd_type: str = "Megatron", version=None):
        return MegatronSDLoader(ckpt_list, version)


class MegatronSDLoader:
    """Loads a list of per-TP-rank checkpoint files and merges/splits to a
    target TP degree (reference ``state_dict_factory.py:60-426``)."""

    def __init__(self, ckpt_list: List[str], version=None):
        self.ckpt_list = ckpt_list
        self.version = version

    @staticmethod
    def _strategy_for(name: str, merge_strategies: Dict[str, object]):
        """(dim, kind) for the first matching pattern; kind is 'plain' or
        'qkv'. A strategy value may be an int dim, or a (dim, 'qkv') tuple
        for FUSED query_key_value weights, which must merge/split via the
        q/k/v-aware path (reference ``qkv_copy``, ``state_dict_factory.py``
        ``merge_query_key_value``) — plain concat would interleave the q/k/v
        blocks and silently produce wrong weights."""
        for pat, strat in merge_strategies.items():
            if pat in name:
                if isinstance(strat, (tuple, list)):
                    dim, kind = strat
                    return int(dim), str(kind)
                return int(strat), "plain"
        return None, None

    def load(self, mp_world_size: int = 1, mp_rank: int = 0,
             merge_strategies: Dict[str, object] = None) -> Dict[str, np.ndarray]:
        """Merge all ranks' files into full arrays, then (optionally) slice
        for (mp_world_size, mp_rank).

        ``merge_strategies``: {substring: strategy} — weights whose name
        contains the substring are sharded along the strategy's dim. A
        strategy is an int dim (e.g. {"dense_4h_to_h": 0}) or a
        ``(dim, "qkv")`` tuple for fused qkv weights (each rank's shard is
        [q_i|k_i|v_i]; merging must be q/k/v-aware). Unmatched weights must
        be identical replicas.
        """
        from deepspeed_tpu.checkpoint.reshape_utils import (
            merge_qkv_shards, merge_tp_shards, split_qkv_shards, split_tp_shards)

        shards = [load_state_dict_file(p) for p in self.ckpt_list]
        merge_strategies = merge_strategies or {}

        full: Dict[str, np.ndarray] = {}
        for name in shards[0]:
            parts = [s[name] for s in shards]
            dim, kind = self._strategy_for(name, merge_strategies)
            if dim is None or len(parts) == 1:
                full[name] = parts[0]
            elif kind == "qkv":
                full[name] = merge_qkv_shards(parts, dim)
            else:
                full[name] = merge_tp_shards(parts, dim)

        if mp_world_size <= 1:
            return full

        out: Dict[str, np.ndarray] = {}
        for name, arr in full.items():
            dim, kind = self._strategy_for(name, merge_strategies)
            if dim is None:
                out[name] = arr
            elif kind == "qkv":
                out[name] = split_qkv_shards(arr, dim, mp_world_size)[mp_rank]
            else:
                out[name] = split_tp_shards(arr, dim, mp_world_size)[mp_rank]
        return out
