"""Offline fp32 state-dict reconstruction (reference ``utils/zero_to_fp32.py``).

Reads a saved engine checkpoint tag (the orbax ``state`` tree plus any
per-process ZeRO-Offload host-state npz files) WITHOUT building an engine,
consolidates the highest-precision copy of every parameter (fp32 masters
when present, else the stored params upcast), and writes a single
``.npz`` file keyed by parameter path — loadable anywhere with plain numpy.

CLI::

    python -m deepspeed_tpu.checkpoint.zero_to_fp32 <checkpoint_dir> <output.npz> [--tag TAG]
"""

from __future__ import annotations

import argparse
import glob
import os
from typing import Any, Dict, Optional

import numpy as np


from deepspeed_tpu.utils.pytree import leaf_paths as _leaf_paths


def _resolve_tag(checkpoint_dir: str, tag: Optional[str]) -> str:
    if tag is not None:
        return str(tag)
    latest = os.path.join(checkpoint_dir, "latest")
    if not os.path.isfile(latest):
        raise FileNotFoundError(f"No 'latest' file in {checkpoint_dir}; pass --tag")
    with open(latest) as f:
        return f.read().strip()


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir: str,
                                             tag: Optional[str] = None,
                                             _tree: Any = None) -> Dict[str, np.ndarray]:
    """The reference's same-named API (``zero_to_fp32.py``): a dict of fp32
    numpy arrays keyed by dotted parameter path. ``_tree``: optionally pass
    an already-restored state tree to avoid a second disk read."""
    checkpoint_dir = os.path.abspath(checkpoint_dir)
    tag = _resolve_tag(checkpoint_dir, tag)

    tree = _tree
    if tree is None:
        # either checkpoint format: safe-engine state.npz or legacy orbax
        from deepspeed_tpu.runtime.checkpoint_engine.safe_engine import read_state_tree
        tree = read_state_tree(os.path.join(checkpoint_dir, tag))

    params = _leaf_paths(tree["params"])
    masters = _leaf_paths(tree["master"]) if tree.get("master") is not None else {}

    # ZeRO-Offload: host masters live in per-process npz files
    offload_masters: Dict[str, np.ndarray] = {}
    for npz_path in sorted(glob.glob(os.path.join(checkpoint_dir, tag, "offload_state_p*.npz"))):
        with np.load(npz_path) as z:
            for key in z.files:
                if key.startswith("masters|"):
                    offload_masters[key.split("|", 1)[1]] = z[key]

    out: Dict[str, np.ndarray] = {}
    for path, leaf in params.items():
        arr = np.asarray(leaf)
        if path in masters:
            arr = np.asarray(masters[path])
        elif path in offload_masters:
            arr = offload_masters[path].reshape(arr.shape)
        out[path] = np.ascontiguousarray(arr.astype(np.float32))
    return out


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir: str, output_file: str,
                                               tag: Optional[str] = None) -> None:
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    np.savez(output_file, **sd)
    total = sum(v.size for v in sd.values())
    print(f"Saved {len(sd)} fp32 tensors ({total:,} params) to {output_file}")


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("checkpoint_dir", type=str)
    parser.add_argument("output_file", type=str)
    parser.add_argument("--tag", type=str, default=None)
    args = parser.parse_args()
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir, args.output_file, args.tag)


if __name__ == "__main__":
    main()
