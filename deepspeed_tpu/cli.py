"""``dscli`` — the framework's command-line front door (reference ``bin/``).

Subcommands mirror the reference's script family:

- ``dscli run <script> [args...]``  — the ``deepspeed`` launcher CLI
- ``dscli serve [--model m] [--port p]`` — OpenAI-style completions endpoint
  (``/v1/completions``, SSE streaming) over the async paged serving loop
- ``dscli report [--telemetry f]``  — ``ds_report`` environment/op/memory report
- ``dscli health <jsonl> [--once|--json]`` — live health screen over a telemetry sink
- ``dscli top <url|jsonl>``         — refreshing serving/training dashboard (scrapes
  ``/metrics`` or tails a sampler JSONL; SLO burn rates, KV tiers, percentiles)
- ``dscli bench``                   — ``ds_bench`` collective micro-benchmarks
- ``dscli ckpt verify <dir>``       — checkpoint integrity audit (per-tag manifest check)
- ``dscli lint``                    — dslint trace-safety static analysis (rc=1 on new findings)
- ``dscli trace --validate <path>`` — chrome-trace / events.jsonl schema check
- ``dscli ctl replay|explain <events.jsonl>`` — adaptive-controller decision-
  ledger audit: re-run the pure decision core over the recorded observations
  (rc=1 on divergence) or print the human-readable decision story
- ``dscli profile <logdir|trace>``  — summarize a jax.profiler capture / chrome trace
- ``dscli elastic <config>``        — ``ds_elastic`` elastic-config inspector
- ``dscli autotune <config>``       — ``deepspeed --autotuning`` config search
- ``dscli ssh [-f hostfile] cmd``   — ``ds_ssh`` run a command on every host
"""

from __future__ import annotations

import sys


def _run(argv):
    from deepspeed_tpu.launcher import runner
    runner.main(argv)


def _serve(argv):
    """``dscli serve`` — stand up the always-on async serving loop behind
    an OpenAI-style HTTP endpoint (``POST /v1/completions``, with
    ``"stream": true`` server-sent events). Prompts are token-id lists;
    see ``docs/api.md`` "Async serving" for a curl example."""
    from deepspeed_tpu.inference.serve import serve_main
    return serve_main(argv)


def _report(argv):
    import argparse

    parser = argparse.ArgumentParser(
        description="environment / op / device-memory report")
    parser.add_argument("--telemetry", type=str, default=None,
                        help="JSONL telemetry sink path; also prints the "
                             "latest snapshot summary")
    args = parser.parse_args(argv)
    from deepspeed_tpu import env_report
    env_report.main(telemetry_path=args.telemetry)


def _health(argv):
    """Live one-screen training/serving health table tailing a JSONL
    telemetry sink (``telemetry.jsonl_path``); ``--once`` renders once."""
    from deepspeed_tpu.monitor.health import health_cli
    return health_cli(argv)


def _top(argv):
    """``dscli top`` — refreshing serving/training dashboard over a
    ``/metrics`` scrape URL (``dscli serve`` exposes one) or a sampler/
    telemetry JSONL: queue depth, TTFT/TPOT percentiles, KV pool + host
    tier, SLO burn rates, loss EWMA, tokens/s."""
    from deepspeed_tpu.monitor.top import top_cli
    return top_cli(argv)


def _bench(argv):
    from deepspeed_tpu.benchmarks.comm_bench import main as bench_main
    bench_main(argv)


def _ckpt(argv):
    """Checkpoint maintenance. ``verify <dir>`` full-checks every tag's
    blake2b manifest and prints INTACT/CORRUPT per tag; exit code 1 when
    any tag is corrupt (CI-friendly)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="dscli ckpt", description="checkpoint maintenance tools")
    sub = parser.add_subparsers(dest="action", required=True)
    vp = sub.add_parser("verify", help="verify every tag's manifest")
    vp.add_argument("dir", type=str, help="checkpoint save_dir (tag parent)")
    vp.add_argument("--tag", type=str, default=None,
                    help="verify only this tag")
    args = parser.parse_args(argv)

    import os

    from deepspeed_tpu.runtime.checkpoint_engine import safe_engine

    save_dir = os.path.abspath(args.dir)
    reports = ([safe_engine.verify_tag(os.path.join(save_dir, args.tag))]
               if args.tag else
               [safe_engine.verify_tag(r.path)
                for r in safe_engine.list_tags(save_dir)])
    if not reports:
        print(f"no checkpoint tags under {save_dir}")
        return 1
    latest = safe_engine._latest_target(save_dir)
    corrupt = 0
    for rep in reports:
        if rep.legacy:
            status = "LEGACY  (orbax tag: loadable, no manifest to verify)"
        elif rep.intact:
            status = "INTACT"
        else:
            corrupt += 1
            status = "CORRUPT (" + "; ".join(rep.errors) + ")"
        steps = "-" if rep.global_steps is None else str(rep.global_steps)
        mark = " <- latest" if rep.tag == latest else ""
        print(f"{rep.tag:<28} step {steps:<10} {status}{mark}")
    if latest and all(r.tag != latest for r in reports) and not args.tag:
        corrupt += 1
        print(f"latest -> {latest!r}: tag missing (CORRUPT pointer)")
    print(f"{len(reports)} tag(s), {corrupt} corrupt")
    return 1 if corrupt else 0


def _load_dslint():
    """Import ``tools/dslint`` (repo-level tool package, not a package
    module — the same analyzer CI runs standalone) off the checkout's
    tools/ directory."""
    import importlib
    import os

    import deepspeed_tpu
    tools_dir = os.path.abspath(os.path.join(
        os.path.dirname(deepspeed_tpu.__file__), "..", "tools"))
    if not os.path.isdir(os.path.join(tools_dir, "dslint")):
        raise RuntimeError(
            f"tools/dslint not found under {tools_dir} (run from a source "
            "checkout, or `python tools/dslint` directly)")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    return importlib.import_module("dslint")


def _lint(argv):
    """``dscli lint`` — trace-safety static analysis over the package.
    rc=0 clean / rc=1 on findings not in tools/dslint_baseline.json,
    matching ``dscli trace --validate`` semantics."""
    return _load_dslint().main(argv)


def _load_validator():
    """Load ``tools/validate_trace.py`` (repo-level tool, not a package
    module — the same file CI runs standalone) by path."""
    import importlib.util
    import os

    import deepspeed_tpu
    path = os.path.abspath(os.path.join(
        os.path.dirname(deepspeed_tpu.__file__), "..", "tools",
        "validate_trace.py"))
    if not os.path.isfile(path):
        raise RuntimeError(
            f"tools/validate_trace.py not found at {path} (run from a "
            "source checkout, or invoke the script directly)")
    spec = importlib.util.spec_from_file_location("validate_trace", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _trace(argv):
    """Trace tooling. ``dscli trace <request-id> --events <jsonl>``
    prints one request's latency anatomy (the phase ledger, recomputed
    from the flight-recorder export — ``<request-id>`` is an integer rid
    or a router trace id like ``t0``, which prints every leg of the
    causal chain plus the handoff hops). ``--validate <path>...``
    schema-checks chrome-trace JSON / events.jsonl exports (rc=1 on
    violations)."""
    import argparse
    import json as _json

    parser = argparse.ArgumentParser(
        prog="dscli trace",
        description="request latency anatomy + chrome-trace/events.jsonl "
                    "schema validation")
    parser.add_argument("request_id", nargs="?", default=None,
                        help="rid (integer) or trace id (t<seq>) to "
                             "decompose; needs --events")
    parser.add_argument("--events", metavar="PATH", default=None,
                        help="flight-recorder events.jsonl export "
                             "(FlightRecorder.write_jsonl) to read the "
                             "anatomy from")
    parser.add_argument("--json", action="store_true",
                        help="print the anatomy as JSON instead of the "
                             "phase table")
    parser.add_argument("--validate", nargs="+", metavar="PATH",
                        default=None, help="file(s) to schema-validate")
    parser.add_argument("--kind", choices=("auto", "chrome", "events"),
                        default="auto")
    args = parser.parse_args(argv)
    if args.validate is not None:
        return _load_validator().main(["--kind", args.kind] + args.validate)
    if args.request_id is None:
        parser.error("need a <request-id> (with --events) or --validate")
    if args.events is None:
        parser.error("anatomy needs --events <events.jsonl> (export one "
                     "with engine.export_events / the serve front-end)")
    from deepspeed_tpu.monitor.anatomy import (
        format_anatomy, format_trace_anatomy, request_anatomy,
        resolve_request_id, trace_anatomy)
    events = []
    with open(args.events) as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(_json.loads(line))
    trace, rid = resolve_request_id(args.request_id)
    if rid is not None:
        a = request_anatomy(events, rid)
        if a is None:
            print(f"rid {rid}: no events in {args.events}")
            return 1
        print(_json.dumps(a) if args.json else format_anatomy(a))
        return 0
    t = trace_anatomy(events, trace)
    if t is None:
        print(f"trace {trace}: no req.enqueue carries it in {args.events}")
        return 1
    print(_json.dumps(t) if args.json else format_trace_anatomy(t))
    return 0


def _ctl(argv):
    """``dscli ctl`` — audit an adaptive-controller decision ledger
    (a flight-recorder ``events.jsonl`` export holding ``ctl.*``
    events). ``replay`` re-runs the pure decision core over the recorded
    ``ctl.observe`` trace and diffs against the recorded ``ctl.decide``
    sequence — rc=0 on an exact reproduction, rc=1 on divergence (a
    divergence means the controller was NOT a pure function of its
    observations: nondeterminism worth paging on). ``explain`` prints
    the decision story: every knob movement with the burn/pressure
    observation that triggered it."""
    import argparse
    import json as _json

    parser = argparse.ArgumentParser(
        prog="dscli ctl",
        description="adaptive-controller decision-ledger audit "
                    "(monitor/controller.py)")
    sub = parser.add_subparsers(dest="action", required=True)
    rp = sub.add_parser("replay", help="re-run the decision core over the "
                                       "recorded observations and diff")
    rp.add_argument("events", help="events.jsonl ledger export")
    rp.add_argument("--json", action="store_true",
                    help="print the replayed action sequence as JSON")
    xp = sub.add_parser("explain", help="print the human-readable "
                                        "decision story")
    xp.add_argument("events", help="events.jsonl ledger export")
    args = parser.parse_args(argv)

    from deepspeed_tpu.monitor.controller import (
        explain_decisions, recorded_decisions, replay_decisions)
    if args.action == "explain":
        lines = explain_decisions(args.events)
        if not lines:
            print(f"{args.events}: no ctl.* events (run with --adaptive "
                  "/ telemetry.ctl enabled and export the recorder)")
            return 1
        for line in lines:
            print(line)
        return 0
    try:
        replayed = replay_decisions(args.events)
    except ValueError as e:
        print(f"replay failed: {e}")
        return 1
    recorded = recorded_decisions(args.events)
    if args.json:
        print(_json.dumps(replayed))
    if replayed == recorded:
        print(f"replay OK: {len(recorded)} action(s) reproduced exactly")
        return 0
    print(f"REPLAY DIVERGED: {len(recorded)} recorded vs "
          f"{len(replayed)} replayed action(s)")
    for i, (a, b) in enumerate(zip(recorded, replayed)):
        if a != b:
            print(f"  first divergence at action #{i}:")
            print(f"    recorded: {_json.dumps(a, sort_keys=True)}")
            print(f"    replayed: {_json.dumps(b, sort_keys=True)}")
            break
    return 1


def _profile(argv):
    """Summarize a profiling artifact: a ``jax.profiler`` capture dir
    (``telemetry.profile`` / ``engine.profile(steps=N)``) — run inventory
    plus how to open it — or a chrome-trace JSON (``export_trace`` /
    ``export_serving_trace``) — per-span statistics."""
    import argparse
    import json as _json
    import os

    parser = argparse.ArgumentParser(
        prog="dscli profile",
        description="summarize a jax.profiler logdir or chrome-trace JSON")
    parser.add_argument("path", help="profiler logdir or trace .json")
    parser.add_argument("--top", type=int, default=20,
                        help="spans to show for a chrome trace (default 20)")
    args = parser.parse_args(argv)
    path = os.path.abspath(args.path)

    if os.path.isfile(path):
        # chrome-trace JSON: per-name span statistics
        try:
            with open(path) as f:
                doc = _json.load(f)
            events = doc.get("traceEvents", [])
        except ValueError:
            print(f"{path}: not JSON (for xplane.pb captures pass the "
                  "logdir, then open it in TensorBoard/xprof)")
            return 1
        spans = {}
        for ev in events:
            if ev.get("ph") == "X" and isinstance(ev.get("dur"), (int, float)):
                s = spans.setdefault(ev.get("name", "?"),
                                     {"n": 0, "total_us": 0.0, "max_us": 0.0})
                s["n"] += 1
                s["total_us"] += ev["dur"]
                s["max_us"] = max(s["max_us"], ev["dur"])
        if not spans:
            print(f"{path}: no complete (ph=X) spans")
            return 1
        print(f"{path}: {sum(s['n'] for s in spans.values())} spans, "
              f"{len(spans)} names")
        print(f"{'name':<32} {'count':>7} {'total ms':>10} {'mean ms':>9} "
              f"{'max ms':>9}")
        ranked = sorted(spans.items(), key=lambda kv: -kv[1]["total_us"])
        for name, s in ranked[:args.top]:
            print(f"{name[:32]:<32} {s['n']:>7} {s['total_us'] / 1e3:>10.2f} "
                  f"{s['total_us'] / s['n'] / 1e3:>9.3f} "
                  f"{s['max_us'] / 1e3:>9.3f}")
        if len(ranked) > args.top:
            print(f"... {len(ranked) - args.top} more (raise --top)")
        return 0

    if not os.path.isdir(path):
        print(f"{path}: no such file or directory")
        return 1
    # jax.profiler logdir: TensorBoard layout <dir>/plugins/profile/<run>/
    runs_root = os.path.join(path, "plugins", "profile")
    runs = sorted(os.listdir(runs_root)) if os.path.isdir(runs_root) else []
    if not runs:
        print(f"{path}: no profiler runs under plugins/profile/ — capture "
              "one with engine.profile(steps=N) or telemetry.profile")
        return 1
    print(f"{path}: {len(runs)} profiler run(s)")
    for run in runs:
        rdir = os.path.join(runs_root, run)
        files = sorted(os.listdir(rdir))
        total = sum(os.path.getsize(os.path.join(rdir, f)) for f in files)
        hosts = sorted({f.split(".")[0] for f in files if ".xplane.pb" in f})
        print(f"  {run}: {len(files)} file(s), {total / 1e6:.1f} MB"
              + (f", hosts: {', '.join(hosts)}" if hosts else ""))
        for f in files:
            print(f"    {f}")
    print("open with: tensorboard --logdir", path,
          " (Profile tab), or xprof")
    return 0


def _elastic(argv):
    import argparse
    import json

    from deepspeed_tpu.elasticity import compute_elastic_config

    parser = argparse.ArgumentParser(description="elastic batch-size planner")
    parser.add_argument("config", type=str, help="ds_config json path")
    parser.add_argument("-w", "--world-size", type=int, default=0)
    args = parser.parse_args(argv)
    with open(args.config) as fd:
        ds_config = json.load(fd)
    if args.world_size:
        batch, micro, gas = compute_elastic_config(ds_config, world_size=args.world_size)
        print(f"world_size={args.world_size}: train_batch={batch}, "
              f"micro_batch={micro}, gradient_accumulation_steps={gas}")
    else:
        batch, valid_worlds = compute_elastic_config(ds_config)
        print(f"valid world sizes: {valid_worlds}")
        print(f"max train_batch:   {batch}")


def _autotune(argv):
    import argparse
    import json

    parser = argparse.ArgumentParser(
        description="search zero stage x micro-batch x remat x loss-chunk "
                    "(reference: deepspeed --autotuning)")
    parser.add_argument("config", type=str, help="ds_config json path")
    parser.add_argument("--model", type=str, default="gpt2:125m",
                        help="model zoo preset, e.g. gpt2:125m, llama:tiny")
    parser.add_argument("--seq-len", type=int, default=None)
    args = parser.parse_args(argv)

    from deepspeed_tpu.autotuning import Autotuner
    from deepspeed_tpu.models.presets import get_model

    with open(args.config) as fd:
        ds_config = json.load(fd)
    name, _, size = args.model.partition(":")
    model = get_model(name, *( [size] if size else [] ))
    best = Autotuner(model, base_config=ds_config, seq_len=args.seq_len).tune()
    print(json.dumps(best, indent=2))


def _ssh(argv):
    """Broadcast a shell command to every hostfile host over pdsh
    (reference ``bin/ds_ssh``)."""
    import argparse
    import os
    import shutil
    import subprocess

    parser = argparse.ArgumentParser(description="run a command on all hosts")
    parser.add_argument("-f", "--hostfile", type=str, default=None,
                        help=f"hostfile path (default {_dlts_hostfile()})")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="command to run on every host")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    if shutil.which("pdsh") is None:
        raise RuntimeError("cannot find pdsh; install it (apt-get install pdsh)")

    from deepspeed_tpu.launcher.runner import fetch_hostfile
    resources = fetch_hostfile(args.hostfile or _dlts_hostfile())
    if not resources:
        raise RuntimeError(f"missing or empty hostfile "
                           f"{args.hostfile or _dlts_hostfile()}")
    hosts = ",".join(resources)
    env = dict(os.environ, PDSH_RCMD_TYPE="ssh")
    return subprocess.call(["pdsh", "-w", hosts] + args.command, env=env)


def _dlts_hostfile():
    from deepspeed_tpu.launcher.runner import DLTS_HOSTFILE
    return DLTS_HOSTFILE


_COMMANDS = {"run": _run, "serve": _serve, "report": _report,
             "health": _health, "top": _top, "bench": _bench,
             "ckpt": _ckpt, "lint": _lint, "trace": _trace, "ctl": _ctl,
             "profile": _profile, "elastic": _elastic, "autotune": _autotune,
             "ssh": _ssh}


def main():
    if len(sys.argv) < 2 or sys.argv[1] in ("-h", "--help"):
        print(__doc__)
        print("usage: dscli {run|serve|report|health|top|bench|ckpt|lint|"
              "trace|ctl|profile|elastic|autotune|ssh} [args...]")
        return 0
    cmd = sys.argv[1]
    if cmd not in _COMMANDS:
        print(f"unknown command {cmd!r}; expected one of {sorted(_COMMANDS)}")
        return 2
    rc = _COMMANDS[cmd](sys.argv[2:])
    return 0 if rc is None else rc


if __name__ == "__main__":
    sys.exit(main())
