"""Process-local metrics registry (the telemetry substrate).

The serving/training stacks this reproduces are tuned almost entirely
through iteration-level stats (Orca/vLLM serving counters, PaLM-style MFU
accounting); this module is the common sink every layer writes into:

- :class:`Counter` / :class:`Gauge` / :class:`Histogram` families with
  optional label fan-out (``family.labels(op="all_reduce").inc()``),
  thread-safe behind one registry lock.
- Histograms keep **streaming quantiles** in constant memory: observations
  land in geometrically spaced buckets (ratio ``2**0.25`` ≈ ±9 % relative
  error per quantile) plus exact count/sum/min/max.
- ``snapshot()`` returns a plain JSON-able dict; ``to_prometheus()`` emits
  text exposition format; ``write_jsonl()`` appends snapshots to a file;
  ``publish()`` fans scalar series out through the existing
  ``MonitorMaster`` sinks (TensorBoard / W&B / CSV).
- Disabled mode is a per-op flag check and immediate return — **no device
  work, no ``effects_barrier``, no allocation** — so hot paths can keep
  their instrumentation calls unconditionally.

One process-global registry (:func:`get_registry`) is shared by the
training engine, the inference engine/scheduler, the comms logger, and the
compile watchdog, so one ``snapshot()`` sees the whole system.
"""

from __future__ import annotations

import bisect
import json
import math
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

# ------------------------------------------------------------------ #
# histogram bucketing: geometric ladder covering 1e-9 .. ~1e12 at ratio
# 2**0.25 (~19% bucket width => quantile relative error ~9%); shared by
# every histogram so snapshots merge trivially
_BUCKET_RATIO = 2.0 ** 0.25
_BUCKET_LO = 1e-9
_N_BUCKETS = int(math.ceil(math.log(1e12 / _BUCKET_LO, _BUCKET_RATIO))) + 1
_BOUNDS: List[float] = [_BUCKET_LO * _BUCKET_RATIO ** i for i in range(_N_BUCKETS)]


def _label_key(labelnames: Tuple[str, ...], labelvalues: Tuple[str, ...]) -> str:
    if not labelnames:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in zip(labelnames, labelvalues))
    return "{" + inner + "}"


class _Metric:
    """One concrete series (a family child). Not built directly — ask the
    registry for a family and (optionally) ``.labels(...)`` it."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str,
                 labelnames: Tuple[str, ...] = (),
                 labelvalues: Tuple[str, ...] = ()):
        self._reg = registry
        self.name = name
        self.labelnames = labelnames
        self.labelvalues = labelvalues

    @property
    def series_name(self) -> str:
        return self.name + _label_key(self.labelnames, self.labelvalues)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not self._reg._enabled:
            return
        if amount < 0:
            raise ValueError(f"counter {self.name}: inc by negative {amount}")
        with self._reg._lock:
            self.value += amount


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.value = 0.0

    def set(self, value: float) -> None:
        if not self._reg._enabled:
            return
        with self._reg._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if not self._reg._enabled:
            return
        with self._reg._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # sparse bucket map (most series touch a handful of buckets)
        self._buckets: Dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value: float) -> None:
        if not self._reg._enabled:
            return
        value = float(value)
        idx = bisect.bisect_left(_BOUNDS, value) if value > _BUCKET_LO else 0
        with self._reg._lock:
            self._buckets[idx] = self._buckets.get(idx, 0) + 1
            self.count += 1
            self.sum += value
            if value < self.min:
                self.min = value
            if value > self.max:
                self.max = value

    def quantile(self, q: float) -> float:
        """Streaming quantile estimate (geometric-midpoint of the bucket
        holding the q-th observation); exact at the recorded min/max ends."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._reg._lock:
            if self.count == 0:
                return 0.0
            target = q * self.count
            seen = 0
            for idx in sorted(self._buckets):
                seen += self._buckets[idx]
                if seen >= target:
                    lo = _BOUNDS[idx - 1] if idx > 0 else 0.0
                    hi = _BOUNDS[idx] if idx < len(_BOUNDS) else self.max
                    mid = math.sqrt(lo * hi) if lo > 0 else hi / 2.0
                    # clamp into the exactly-tracked envelope
                    return min(max(mid, self.min), self.max)
            return self.max

    def summary(self) -> Dict[str, float]:
        with self._reg._lock:
            count, total = self.count, self.sum
        if count == 0:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                    "mean": 0.0, "p50": 0.0, "p90": 0.0, "p99": 0.0}
        return {"count": count, "sum": total,
                "min": self.min, "max": self.max, "mean": total / count,
                "p50": self.quantile(0.50), "p90": self.quantile(0.90),
                "p99": self.quantile(0.99)}


class _Family:
    """A named metric family: either a single unlabeled series (all metric
    ops proxy to it) or a label fan-out via :meth:`labels`."""

    def __init__(self, registry: "MetricsRegistry", cls, name: str,
                 help: str, labelnames: Tuple[str, ...]):
        self._reg = registry
        self._cls = cls
        self.name = name
        self.help = help
        self.labelnames = labelnames
        self._children: Dict[Tuple[str, ...], _Metric] = {}
        if not labelnames:
            self._default = cls(registry, name)
            self._children[()] = self._default
        else:
            self._default = None

    @property
    def kind(self) -> str:
        return self._cls.kind

    def labels(self, **labelvalues) -> _Metric:
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(f"{self.name}: expected labels {self.labelnames}, "
                             f"got {tuple(labelvalues)}")
        key = tuple(str(labelvalues[k]) for k in self.labelnames)
        with self._reg._lock:
            child = self._children.get(key)
            if child is None:
                child = self._cls(self._reg, self.name, self.labelnames, key)
                self._children[key] = child
        return child

    def children(self) -> List[_Metric]:
        with self._reg._lock:
            return list(self._children.values())

    # unlabeled convenience proxies
    def _only(self) -> _Metric:
        if self._default is None:
            raise ValueError(f"{self.name} is labeled ({self.labelnames}); "
                             "use .labels(...)")
        return self._default

    def inc(self, amount: float = 1.0):
        self._only().inc(amount)

    def dec(self, amount: float = 1.0):
        self._only().dec(amount)

    def set(self, value: float):
        self._only().set(value)

    def observe(self, value: float):
        self._only().observe(value)

    # single-series reads (used pervasively by tests/tools)
    @property
    def value(self):
        return self._only().value

    def summary(self):
        return self._only().summary()

    def quantile(self, q: float):
        return self._only().quantile(q)


class MetricsRegistry:
    """Get-or-create metric families; snapshot/export them."""

    def __init__(self, enabled: bool = True):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}
        self._enabled = enabled

    # ---- lifecycle ---- #

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        """Disabled mode: every record op returns after one flag check —
        no locks taken, no allocation, and never any device/jax call."""
        self._enabled = bool(enabled)

    def reset(self) -> None:
        """Drop every family (fresh snapshot; used between bench metrics)."""
        with self._lock:
            self._families.clear()

    # ---- family constructors (get-or-create, type-checked) ---- #

    def _family(self, cls, name: str, help: str,
                labelnames: Iterable[str]) -> _Family:
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(self, cls, name, help, labelnames)
                self._families[name] = fam
            elif fam._cls is not cls or fam.labelnames != labelnames:
                raise ValueError(
                    f"metric {name!r} re-registered as {cls.kind} with labels "
                    f"{labelnames}; existing is {fam.kind} with {fam.labelnames}")
        return fam

    def counter(self, name: str, help: str = "",
                labelnames: Iterable[str] = ()) -> _Family:
        return self._family(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Iterable[str] = ()) -> _Family:
        return self._family(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Iterable[str] = ()) -> _Family:
        return self._family(Histogram, name, help, labelnames)

    # ---- export ---- #

    def snapshot(self) -> Dict[str, Dict]:
        """JSON-able view: ``{"counters": {series: value}, "gauges": {...},
        "histograms": {series: {count,sum,min,max,mean,p50,p90,p99}}}``."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            for child in fam.children():
                key = child.series_name
                if fam.kind == "counter":
                    out["counters"][key] = child.value
                elif fam.kind == "gauge":
                    out["gauges"][key] = child.value
                else:
                    out["histograms"][key] = child.summary()
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition (names sanitized: ``/`` → ``_``)."""
        lines: List[str] = []
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            pname = _prom_name(fam.name)
            if fam.help:
                lines.append(f"# HELP {pname} {fam.help}")
            lines.append(f"# TYPE {pname} {fam.kind}")
            for child in fam.children():
                labels = _label_key(child.labelnames, child.labelvalues)
                if fam.kind in ("counter", "gauge"):
                    lines.append(f"{pname}{labels} {_fmt(child.value)}")
                else:
                    cum = 0
                    base = labels[1:-1] if labels else ""
                    sep = "," if base else ""
                    with self._lock:
                        buckets = sorted(child._buckets.items())
                        count, total = child.count, child.sum
                    for idx, n in buckets:
                        cum += n
                        le = _BOUNDS[idx] if idx < len(_BOUNDS) else math.inf
                        lines.append(f'{pname}_bucket{{{base}{sep}le="{_fmt(le)}"}} {cum}')
                    lines.append(f'{pname}_bucket{{{base}{sep}le="+Inf"}} {count}')
                    lines.append(f"{pname}_sum{labels} {_fmt(total)}")
                    lines.append(f"{pname}_count{labels} {count}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write_jsonl(self, path: str, step: Optional[int] = None,
                    extra: Optional[Dict] = None) -> None:
        """Append one snapshot line to ``path`` (creating parent dirs)."""
        rec = {"ts": time.time()}
        if step is not None:
            rec["step"] = int(step)
        if extra:
            rec.update(extra)
        rec.update(self.snapshot())
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    def publish(self, monitor, step: int) -> None:
        """Fan scalar series out through a ``MonitorMaster`` (counters and
        gauges as-is; histograms as mean/p50/p99/count sub-series)."""
        if monitor is None or not getattr(monitor, "enabled", False):
            return
        snap = self.snapshot()
        events = []
        for key, v in snap["counters"].items():
            events.append((f"Telemetry/{key}", float(v), step))
        for key, v in snap["gauges"].items():
            events.append((f"Telemetry/{key}", float(v), step))
        for key, s in snap["histograms"].items():
            for stat in ("mean", "p50", "p99", "count"):
                events.append((f"Telemetry/{key}/{stat}", float(s[stat]), step))
        if events:
            monitor.write_events(events)


def _prom_name(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        ok = ch.isalnum() or ch in "_:"
        if i == 0 and ch.isdigit():
            ok = False
        out.append(ch if ok else "_")
    return "".join(out)


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


# ------------------------------------------------------------------ #
# snapshot schema validation (the CI smoke test's contract)

SNAPSHOT_SECTIONS = ("counters", "gauges", "histograms")
_HIST_KEYS = {"count", "sum", "min", "max", "mean", "p50", "p90", "p99"}


def validate_snapshot(snap: Dict) -> None:
    """Raise ``ValueError`` unless ``snap`` is a structurally valid
    registry snapshot (the three sections, numeric scalars, complete
    histogram summaries)."""
    if not isinstance(snap, dict):
        raise ValueError(f"snapshot must be a dict, got {type(snap).__name__}")
    for section in SNAPSHOT_SECTIONS:
        if section not in snap:
            raise ValueError(f"snapshot missing section {section!r}")
        if not isinstance(snap[section], dict):
            raise ValueError(f"snapshot[{section!r}] must be a dict")
    for sec in ("counters", "gauges"):
        for k, v in snap[sec].items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                raise ValueError(f"{sec}[{k!r}] is not numeric: {v!r}")
    for k, s in snap["histograms"].items():
        if not isinstance(s, dict) or not _HIST_KEYS.issubset(s):
            raise ValueError(f"histograms[{k!r}] missing keys "
                             f"{_HIST_KEYS - set(s or ())}")


# ------------------------------------------------------------------ #
# process-global registry

_global_registry: Optional[MetricsRegistry] = None
_global_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    global _global_registry
    if _global_registry is None:
        with _global_lock:
            if _global_registry is None:
                _global_registry = MetricsRegistry()
    return _global_registry
