"""Process-local metrics registry (the telemetry substrate).

The serving/training stacks this reproduces are tuned almost entirely
through iteration-level stats (Orca/vLLM serving counters, PaLM-style MFU
accounting); this module is the common sink every layer writes into:

- :class:`Counter` / :class:`Gauge` / :class:`Histogram` families with
  optional label fan-out (``family.labels(op="all_reduce").inc()``),
  thread-safe behind one registry lock.
- Histograms keep **streaming quantiles** in constant memory: observations
  land in geometrically spaced buckets (ratio ``2**0.25`` ≈ ±9 % relative
  error per quantile) plus exact count/sum/min/max.
- ``snapshot()`` returns a plain JSON-able dict; ``to_prometheus()`` emits
  text exposition format; ``write_jsonl()`` appends snapshots to a file;
  ``publish()`` fans scalar series out through the existing
  ``MonitorMaster`` sinks (TensorBoard / W&B / CSV).
- Disabled mode is a per-op flag check and immediate return — **no device
  work, no ``effects_barrier``, no allocation** — so hot paths can keep
  their instrumentation calls unconditionally.

One process-global registry (:func:`get_registry`) is shared by the
training engine, the inference engine/scheduler, the comms logger, and the
compile watchdog, so one ``snapshot()`` sees the whole system.
"""

from __future__ import annotations

import bisect
import json
import math
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

# ------------------------------------------------------------------ #
# histogram bucketing: geometric ladder covering 1e-9 .. ~1e12 at ratio
# 2**0.25 (~19% bucket width => quantile relative error ~9%); shared by
# every histogram so snapshots merge trivially
_BUCKET_RATIO = 2.0 ** 0.25
_BUCKET_LO = 1e-9
_N_BUCKETS = int(math.ceil(math.log(1e12 / _BUCKET_LO, _BUCKET_RATIO))) + 1
_BOUNDS: List[float] = [_BUCKET_LO * _BUCKET_RATIO ** i for i in range(_N_BUCKETS)]


def _label_key(labelnames: Tuple[str, ...], labelvalues: Tuple[str, ...]) -> str:
    if not labelnames:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in zip(labelnames, labelvalues))
    return "{" + inner + "}"


class _Metric:
    """One concrete series (a family child). Not built directly — ask the
    registry for a family and (optionally) ``.labels(...)`` it."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str,
                 labelnames: Tuple[str, ...] = (),
                 labelvalues: Tuple[str, ...] = ()):
        self._reg = registry
        self.name = name
        self.labelnames = labelnames
        self.labelvalues = labelvalues

    @property
    def series_name(self) -> str:
        return self.name + _label_key(self.labelnames, self.labelvalues)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not self._reg._enabled:
            return
        if amount < 0:
            raise ValueError(f"counter {self.name}: inc by negative {amount}")
        with self._reg._lock:
            self.value += amount


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.value = 0.0

    def set(self, value: float) -> None:
        if not self._reg._enabled:
            return
        with self._reg._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if not self._reg._enabled:
            return
        with self._reg._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # sparse bucket map (most series touch a handful of buckets)
        self._buckets: Dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        # last exemplar: (bucket idx, label dict, observed value) — the
        # OpenMetrics-style breadcrumb linking a percentile back to the
        # request that produced it (e.g. {"rid": "17"} on serving/ttft_ms)
        self._exemplar: Optional[Tuple[int, Dict[str, str], float]] = None

    def observe(self, value: float,
                exemplar: Optional[Dict[str, str]] = None) -> None:
        if not self._reg._enabled:
            return
        value = float(value)
        idx = bisect.bisect_left(_BOUNDS, value) if value > _BUCKET_LO else 0
        with self._reg._lock:
            self._buckets[idx] = self._buckets.get(idx, 0) + 1
            self.count += 1
            self.sum += value
            if value < self.min:
                self.min = value
            if value > self.max:
                self.max = value
            if exemplar is not None:
                self._exemplar = (idx, {str(k): str(v)
                                        for k, v in exemplar.items()}, value)

    def _quantile_locked(self, q: float) -> float:
        if self.count == 0:
            return 0.0
        target = q * self.count
        seen = 0
        for idx in sorted(self._buckets):
            seen += self._buckets[idx]
            if seen >= target:
                lo = _BOUNDS[idx - 1] if idx > 0 else 0.0
                hi = _BOUNDS[idx] if idx < len(_BOUNDS) else self.max
                mid = math.sqrt(lo * hi) if lo > 0 else hi / 2.0
                # clamp into the exactly-tracked envelope
                return min(max(mid, self.min), self.max)
        return self.max

    def quantile(self, q: float) -> float:
        """Streaming quantile estimate (geometric-midpoint of the bucket
        holding the q-th observation); exact at the recorded min/max ends."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._reg._lock:
            return self._quantile_locked(q)

    def count_le(self, value: float) -> int:
        """Observations ≤ ``value``, up to bucket quantization: the cut
        rounds up to the bucket boundary ``value`` itself would land in,
        so the answer is exact whenever ``value`` is compared against the
        same ladder observations use (the SLO engine's good-event count —
        deterministic given the observation trace)."""
        cut = (bisect.bisect_left(_BOUNDS, float(value))
               if value > _BUCKET_LO else 0)
        with self._reg._lock:
            return sum(n for idx, n in self._buckets.items() if idx <= cut)

    def summary(self) -> Dict[str, float]:
        # ONE lock over the whole read: count/sum/min/max and the three
        # quantiles must come from the same instant — a concurrent observe
        # between two reads could otherwise yield a torn p50 > max snapshot
        # (the registry lock is re-entrant, so _quantile_locked nests fine)
        with self._reg._lock:
            if self.count == 0:
                return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                        "mean": 0.0, "p50": 0.0, "p90": 0.0, "p99": 0.0}
            return {"count": self.count, "sum": self.sum,
                    "min": self.min, "max": self.max,
                    "mean": self.sum / self.count,
                    "p50": self._quantile_locked(0.50),
                    "p90": self._quantile_locked(0.90),
                    "p99": self._quantile_locked(0.99)}


class _Family:
    """A named metric family: either a single unlabeled series (all metric
    ops proxy to it) or a label fan-out via :meth:`labels`."""

    def __init__(self, registry: "MetricsRegistry", cls, name: str,
                 help: str, labelnames: Tuple[str, ...]):
        self._reg = registry
        self._cls = cls
        self.name = name
        self.help = help
        self.labelnames = labelnames
        self._children: Dict[Tuple[str, ...], _Metric] = {}
        if not labelnames:
            self._default = cls(registry, name)
            self._children[()] = self._default
        else:
            self._default = None

    @property
    def kind(self) -> str:
        return self._cls.kind

    def labels(self, **labelvalues) -> _Metric:
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(f"{self.name}: expected labels {self.labelnames}, "
                             f"got {tuple(labelvalues)}")
        key = tuple(str(labelvalues[k]) for k in self.labelnames)
        with self._reg._lock:
            child = self._children.get(key)
            if child is None:
                child = self._cls(self._reg, self.name, self.labelnames, key)
                self._children[key] = child
        return child

    def children(self) -> List[_Metric]:
        with self._reg._lock:
            return list(self._children.values())

    # unlabeled convenience proxies
    def _only(self) -> _Metric:
        if self._default is None:
            raise ValueError(f"{self.name} is labeled ({self.labelnames}); "
                             "use .labels(...)")
        return self._default

    def inc(self, amount: float = 1.0):
        self._only().inc(amount)

    def dec(self, amount: float = 1.0):
        self._only().dec(amount)

    def set(self, value: float):
        self._only().set(value)

    def observe(self, value: float,
                exemplar: Optional[Dict[str, str]] = None):
        self._only().observe(value, exemplar=exemplar)

    # single-series reads (used pervasively by tests/tools)
    @property
    def value(self):
        return self._only().value

    def summary(self):
        return self._only().summary()

    def quantile(self, q: float):
        return self._only().quantile(q)

    def count_le(self, value: float):
        return self._only().count_le(value)


class MetricsRegistry:
    """Get-or-create metric families; snapshot/export them."""

    def __init__(self, enabled: bool = True):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}
        self._enabled = enabled

    # ---- lifecycle ---- #

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        """Disabled mode: every record op returns after one flag check —
        no locks taken, no allocation, and never any device/jax call."""
        self._enabled = bool(enabled)

    def reset(self) -> None:
        """Drop every family (fresh snapshot; used between bench metrics)."""
        with self._lock:
            self._families.clear()

    # ---- family constructors (get-or-create, type-checked) ---- #

    def _family(self, cls, name: str, help: str,
                labelnames: Iterable[str]) -> _Family:
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(self, cls, name, help, labelnames)
                self._families[name] = fam
            elif fam._cls is not cls or fam.labelnames != labelnames:
                raise ValueError(
                    f"metric {name!r} re-registered as {cls.kind} with labels "
                    f"{labelnames}; existing is {fam.kind} with {fam.labelnames}")
        return fam

    def counter(self, name: str, help: str = "",
                labelnames: Iterable[str] = ()) -> _Family:
        return self._family(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Iterable[str] = ()) -> _Family:
        return self._family(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Iterable[str] = ()) -> _Family:
        return self._family(Histogram, name, help, labelnames)

    # ---- export ---- #

    def snapshot(self) -> Dict[str, Dict]:
        """JSON-able view: ``{"counters": {series: value}, "gauges": {...},
        "histograms": {series: {count,sum,min,max,mean,p50,p90,p99}}}``."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            for child in fam.children():
                key = child.series_name
                if fam.kind == "counter":
                    out["counters"][key] = child.value
                elif fam.kind == "gauge":
                    out["gauges"][key] = child.value
                else:
                    out["histograms"][key] = child.summary()
        return out

    def to_prometheus(self, exemplars: bool = False) -> str:
        """Prometheus text exposition: names sanitized (``/`` → ``_``),
        label values escaped per the text format (``\\``, ``\"``,
        newline), histograms as cumulative ``_bucket{le=}``/``_sum``/
        ``_count`` series. With ``exemplars=True`` a histogram's last
        exemplar (observe's ``exemplar=`` breadcrumb, e.g. the request
        id behind the newest TTFT sample) rides its bucket line
        OpenMetrics-style (``... # {rid="17"} 123.4``) — exemplars are
        ILLEGAL in the classic 0.0.4 text format (a strict scraper
        rejects the whole body), so callers must only request them when
        the scraper negotiated OpenMetrics (see
        ``monitor.exporter.render_exposition``)."""
        lines: List[str] = []
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            pname = _prom_name(fam.name)
            if fam.help:
                help_text = fam.help.replace("\\", "\\\\").replace("\n",
                                                                   "\\n")
                lines.append(f"# HELP {pname} {help_text}")
            lines.append(f"# TYPE {pname} {fam.kind}")
            for child in fam.children():
                labels = _prom_labels(child.labelnames, child.labelvalues)
                if fam.kind in ("counter", "gauge"):
                    lines.append(f"{pname}{labels} {_fmt(child.value)}")
                else:
                    cum = 0
                    base = labels[1:-1] if labels else ""
                    sep = "," if base else ""
                    with self._lock:
                        buckets = sorted(child._buckets.items())
                        count, total = child.count, child.sum
                        exemplar = child._exemplar
                    for idx, n in buckets:
                        cum += n
                        le = _BOUNDS[idx] if idx < len(_BOUNDS) else math.inf
                        line = (f'{pname}_bucket{{{base}{sep}'
                                f'le="{_fmt(le)}"}} {cum}')
                        if exemplars and exemplar is not None \
                                and exemplar[0] == idx:
                            ex = ",".join(
                                f'{k}="{_escape_label(v)}"'
                                for k, v in exemplar[1].items())
                            line += f" # {{{ex}}} {_fmt(exemplar[2])}"
                        lines.append(line)
                    lines.append(f'{pname}_bucket{{{base}{sep}le="+Inf"}} '
                                 f'{count}')
                    lines.append(f"{pname}_sum{labels} {_fmt(total)}")
                    lines.append(f"{pname}_count{labels} {count}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write_jsonl(self, path: str, step: Optional[int] = None,
                    extra: Optional[Dict] = None) -> None:
        """Append one snapshot line to ``path`` (creating parent dirs)."""
        rec = {"ts": time.time()}
        if step is not None:
            rec["step"] = int(step)
        if extra:
            rec.update(extra)
        rec.update(self.snapshot())
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    def publish(self, monitor, step: int) -> None:
        """Fan scalar series out through a ``MonitorMaster`` (counters and
        gauges as-is; histograms as mean/p50/p99/count sub-series)."""
        if monitor is None or not getattr(monitor, "enabled", False):
            return
        snap = self.snapshot()
        events = []
        for key, v in snap["counters"].items():
            events.append((f"Telemetry/{key}", float(v), step))
        for key, v in snap["gauges"].items():
            events.append((f"Telemetry/{key}", float(v), step))
        for key, s in snap["histograms"].items():
            for stat in ("mean", "p50", "p99", "count"):
                events.append((f"Telemetry/{key}/{stat}", float(s[stat]), step))
        if events:
            monitor.write_events(events)


def _prom_name(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        ok = ch.isalnum() or ch in "_:"
        if i == 0 and ch.isdigit():
            ok = False
        out.append(ch if ok else "_")
    return "".join(out)


def _escape_label(v: str) -> str:
    """Prometheus text-format label-value escaping (backslash first)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(labelnames: Tuple[str, ...],
                 labelvalues: Tuple[str, ...]) -> str:
    """Exposition-format label block (escaped — unlike the snapshot's
    ``_label_key``, which keeps raw values as stable dict keys)."""
    if not labelnames:
        return ""
    inner = ",".join(f'{_prom_name(k)}="{_escape_label(v)}"'
                     for k, v in zip(labelnames, labelvalues))
    return "{" + inner + "}"


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


# ------------------------------------------------------------------ #
# text-format parsing: the scrape half of the plane (`dscli top` over a
# /metrics URL) and the exposition tests' round-trip oracle


def _parse_series(line: str):
    """``name{labels} value [# exemplar]`` → (name, {label: value},
    float). Honors text-format escapes in label values; exemplar suffixes
    are tolerated and dropped. Raises ValueError on a malformed line."""
    name_end = len(line)
    labels: Dict[str, str] = {}
    rest = line
    brace = line.find("{")
    if brace != -1:
        name_end = brace
        i = brace + 1
        while True:
            while i < len(line) and line[i] in ", ":
                i += 1
            if i < len(line) and line[i] == "}":
                i += 1
                break
            eq = line.index("=", i)
            key = line[i:eq].strip()
            if line[eq + 1] != '"':
                raise ValueError(f"unquoted label value in {line!r}")
            j = eq + 2
            val: List[str] = []
            while line[j] != '"':
                ch = line[j]
                if ch == "\\":
                    nxt = line[j + 1]
                    val.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt,
                                                                    nxt))
                    j += 2
                else:
                    val.append(ch)
                    j += 1
            labels[key] = "".join(val)
            i = j + 1
        rest = line[i:]
    else:
        sp = line.index(" ")
        name_end = sp
        rest = line[sp:]
    # value, with any " # {exemplar} v" suffix dropped
    rest = rest.strip()
    if " # " in rest:
        rest = rest.split(" # ", 1)[0].strip()
    else:
        rest = rest.split()[0]
    v = math.inf if rest == "+Inf" else (-math.inf if rest == "-Inf"
                                         else float(rest))
    return line[:name_end], labels, v


def parse_prometheus_text(text: str) -> Dict[str, Dict]:
    """Parse Prometheus text exposition back into the snapshot schema:
    ``{"counters": {...}, "gauges": {...}, "histograms": {series:
    summary}}`` (the shape :meth:`MetricsRegistry.snapshot` produces and
    :func:`~deepspeed_tpu.monitor.health.health_summary` consumes).

    Histogram summaries are rebuilt from the cumulative ``_bucket``
    series with the registry's own geometric-midpoint quantile rule;
    min/max — lost by the format — degrade to the occupied bucket
    envelope's bounds. Series names keep their sanitized form
    (``serving_ttft_ms``); ``dscli top`` maps them back. Untyped or
    malformed lines are skipped, not fatal (a scrape must survive a
    foreign exporter's extensions)."""
    types: Dict[str, str] = {}
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    raw_hist: Dict[str, Dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3].strip()
            continue
        try:
            name, labels, value = _parse_series(line)
        except (ValueError, IndexError):
            continue
        base, suffix = name, ""
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf) and types.get(name[:-len(suf)]) \
                    == "histogram":
                base, suffix = name[:-len(suf)], suf
                break
        if suffix:
            le = labels.pop("le", None)
            # label order preserved as exposed (the registry exposes its
            # declared order, so round-trips reproduce snapshot keys)
            series = base + _label_key(tuple(labels),
                                       tuple(labels.values()))
            h = raw_hist.setdefault(series,
                                    {"buckets": [], "sum": 0.0, "count": 0})
            if suffix == "_bucket" and le is not None:
                h["buckets"].append((math.inf if le == "+Inf"
                                     else float(le), value))
            elif suffix == "_sum":
                h["sum"] = value
            elif suffix == "_count":
                h["count"] = int(value)
            continue
        series = name + _label_key(tuple(labels), tuple(labels.values()))
        if types.get(name) == "counter":
            counters[series] = value
        else:
            gauges[series] = value
    return {"counters": counters, "gauges": gauges,
            "histograms": {k: _hist_from_buckets(h)
                           for k, h in raw_hist.items()}}


def _hist_from_buckets(h: Dict) -> Dict[str, float]:
    """Histogram summary from parsed cumulative buckets (same
    geometric-midpoint quantile rule the live registry uses, with the
    bucket envelope standing in for the lost exact min/max)."""
    count, total = int(h["count"]), float(h["sum"])
    if count == 0:
        return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                "mean": 0.0, "p50": 0.0, "p90": 0.0, "p99": 0.0}
    buckets = sorted((le, cum) for le, cum in h["buckets"]
                     if le != math.inf)
    # per-bucket (lo, hi, n) deltas from the cumulative series. The
    # exposition is SPARSE (only occupied buckets appear), so a bucket's
    # true lower bound may sit between the previous exposed ``le`` and
    # this one: when the bound matches the registry's shared geometric
    # ladder, snap ``lo`` to the ladder's adjacent bound (a foreign
    # exporter's arbitrary bounds fall back to the exposed neighbor)
    deltas: List[Tuple[float, float, int]] = []
    prev_le, prev_cum = 0.0, 0
    for le, cum in buckets:
        n = int(cum) - prev_cum
        if n > 0:
            lo = prev_le
            i = bisect.bisect_left(_BOUNDS, le * (1 - 1e-9))
            if i < len(_BOUNDS) and abs(_BOUNDS[i] - le) <= 1e-9 * le:
                lo = max(lo, _BOUNDS[i - 1] if i > 0 else 0.0)
            deltas.append((lo, le, n))
        prev_le, prev_cum = le, int(cum)
    if prev_cum < count:                      # the +Inf overflow bucket
        deltas.append((prev_le, prev_le if prev_le > 0 else 1.0,
                       count - prev_cum))
    lo_env = deltas[0][0] if deltas else 0.0
    hi_env = deltas[-1][1] if deltas else 0.0

    def q(frac: float) -> float:
        target = frac * count
        seen = 0
        for lo, hi, n in deltas:
            seen += n
            if seen >= target:
                mid = math.sqrt(lo * hi) if lo > 0 else hi / 2.0
                return min(max(mid, lo_env), hi_env)
        return hi_env

    return {"count": count, "sum": total, "min": lo_env, "max": hi_env,
            "mean": total / count, "p50": q(0.50), "p90": q(0.90),
            "p99": q(0.99)}


# ------------------------------------------------------------------ #
# snapshot schema validation (the CI smoke test's contract)

SNAPSHOT_SECTIONS = ("counters", "gauges", "histograms")
_HIST_KEYS = {"count", "sum", "min", "max", "mean", "p50", "p90", "p99"}


def validate_snapshot(snap: Dict) -> None:
    """Raise ``ValueError`` unless ``snap`` is a structurally valid
    registry snapshot (the three sections, numeric scalars, complete
    histogram summaries)."""
    if not isinstance(snap, dict):
        raise ValueError(f"snapshot must be a dict, got {type(snap).__name__}")
    for section in SNAPSHOT_SECTIONS:
        if section not in snap:
            raise ValueError(f"snapshot missing section {section!r}")
        if not isinstance(snap[section], dict):
            raise ValueError(f"snapshot[{section!r}] must be a dict")
    for sec in ("counters", "gauges"):
        for k, v in snap[sec].items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                raise ValueError(f"{sec}[{k!r}] is not numeric: {v!r}")
    for k, s in snap["histograms"].items():
        if not isinstance(s, dict) or not _HIST_KEYS.issubset(s):
            raise ValueError(f"histograms[{k!r}] missing keys "
                             f"{_HIST_KEYS - set(s or ())}")


# ------------------------------------------------------------------ #
# process-global registry

_global_registry: Optional[MetricsRegistry] = None
_global_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    global _global_registry
    if _global_registry is None:
        with _global_lock:
            if _global_registry is None:
                _global_registry = MetricsRegistry()
    return _global_registry
