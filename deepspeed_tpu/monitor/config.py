"""Monitor (TensorBoard / W&B / CSV) config.

Reference parity: ``deepspeed/monitor/config.py``.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from pydantic import Field

from deepspeed_tpu.config.config_utils import ConfigModel


def get_monitor_config(param_dict: dict) -> "DeepSpeedMonitorConfig":
    monitor_dict = {key: param_dict.get(key, {}) for key in ("tensorboard", "wandb", "csv_monitor")}
    return DeepSpeedMonitorConfig(**monitor_dict)


class HealthConfig(ConfigModel):
    """"telemetry.health" sub-block: the training health observatory
    (``monitor/health.py``). Off by default; enabling it turns on the
    on-device numerics sentinels inside the compiled train step plus the
    host-side anomaly detectors over the per-step ring buffer."""
    enabled: bool = False
    # device-side sentinel collection (non-finite counts, param/update
    # norms, per-layer-group buckets) inside the compiled step. Off keeps
    # only the host-side detectors (loss, grad norm, skips, wall times) —
    # zero in-step overhead beyond the grad-norm reuse telemetry records
    sentinels: bool = True
    # what firing detectors do: "record" = counters only, "warn" = + a
    # rate-limited log line, "dump" = + a debug bundle on disk
    action: str = "warn"
    # ring-buffer length AND the per-detector warning/dump rate limit
    window: int = 50
    # loss spike: robust z-score against an EWMA mean/variance
    loss_spike_zscore: float = 6.0
    loss_ewma_alpha: float = 0.02
    # spike/explosion detectors hold fire for this many steps
    warmup_steps: int = 10
    # grad-norm explosion: fire when norm > factor x its EWMA
    grad_norm_factor: float = 10.0
    # plateau: no relative loss improvement for this many steps (0 = off)
    plateau_steps: int = 0
    plateau_rel_improvement: float = 1e-3
    # sustained fp16 overflow: consecutive skipped steps before the alarm
    # (also the rate limit of the engine's health-off skip warning)
    overflow_window: int = 25
    # repeated checkpoint failure: consecutive failed saves (sync or async)
    # before the ckpt_failure detector fires (0 = off)
    ckpt_failure_consecutive: int = 2
    # data stall: wait/(wait+step) above the fraction for this many
    # consecutive steps means the input pipeline is the bottleneck
    data_stall_fraction: float = 0.5
    data_stall_steps: int = 10
    # debug bundles (action: dump)
    dump_dir: str = "ds_health_dumps"
    dump_limit: int = 3
    keep_last_steps: int = 200
    # per-layer-group grad-norm buckets in the sentinel vector
    max_norm_buckets: int = 8


class EventsConfig(ConfigModel):
    """"telemetry.events" sub-block: the flight recorder
    (``monitor/events.py``) — a bounded ring of structured lifecycle
    events (train step/phase/skip, checkpoint phases, serving request
    lifecycle) with monotonic-ns timestamps. Off by default; when off
    every emit site costs one flag/None check and allocates nothing."""
    enabled: bool = False
    # ring size (events). The recorder keeps the NEWEST `capacity` events
    # and counts evictions — post-mortems want the tail, not the head.
    capacity: int = 16384


class ProfileConfig(ConfigModel):
    """"telemetry.profile" sub-block: an on-demand ``jax.profiler``
    capture window. ``num_steps > 0`` arms it: the capture starts at the
    ``start_step``-th train_batch call of this process and stops
    ``num_steps`` later, writing a TensorBoard/xprof profile under
    ``dir`` (summarize with ``dscli profile <dir>``). The host-side
    ``TraceAnnotation`` names pushed while capturing match the
    StepTracer span names, so host spans line up with the device
    timeline. ``engine.profile(steps=N)`` arms the same window
    programmatically."""
    start_step: int = 0
    num_steps: int = 0      # 0 = no config-armed capture window
    dir: str = "ds_profile"


class SamplerConfig(ConfigModel):
    """"telemetry.sampler" sub-block: the background snapshot daemon
    (``monitor/sampler.py``) — periodic registry snapshots appended to a
    rotated JSONL time series plus an in-memory ring (the SLO engine's
    input and ``dscli top``'s offline source). The sampler thread does
    host-side dict work ONLY: zero device work, zero added compiles
    (pinned by the ``serving_metrics_steady`` contract and dslint
    DS009)."""
    enabled: bool = False
    # seconds between snapshots (the background thread's cadence; tests
    # and trace replay drive tick() directly instead)
    interval_s: float = 1.0
    # JSONL sink (None = ring only). Rotated at max_bytes: path -> path.1
    # -> ... -> path.<keep>, oldest dropped
    path: Optional[str] = None
    max_bytes: int = 16 << 20
    keep: int = 2
    # in-memory snapshot ring length (newest retained)
    ring: int = 512


class SloConfig(ConfigModel):
    """"telemetry.slo" sub-block: service-level objectives evaluated by
    ``monitor/slo.py`` as multi-window burn rates over the sampler's
    ring. Each objective dict declares either a latency target
    (``{"name": "ttft_p99", "metric": "serving/ttft_ms", "kind":
    "latency", "threshold_ms": 500, "objective": 0.99}``: at most 1 % of
    observations above 500 ms) or a ratio (``{"kind": "ratio", "metric":
    "serving/rejected_requests", "total_metric": "serving/requests",
    "objective": 0.999}``). Breaches emit ``slo.breach`` flight-recorder
    events, increment ``slo/breaches{objective=}``, and surface in
    ``health_summary`` / ``dscli top``. Enabling SLOs implies the
    sampler (something must tick the evaluation)."""
    enabled: bool = False
    objectives: List[Dict] = Field(default_factory=list)
    # default evaluation windows in sampler ticks (long, short): a breach
    # needs EVERY window burning — the long window proves sustained
    # budget loss, the short one proves it is still happening now
    windows: List[int] = Field(default_factory=lambda: [60, 5])
    # burn-rate alarm level: 1.0 = budget exhausted exactly at the SLO
    # period's end; paging setups usually alarm well above 1
    burn_rate_threshold: float = 1.0


class CtlConfig(ConfigModel):
    """"telemetry.ctl" sub-block: the deterministic SLO-burn-rate
    autopilot (``monitor/controller.py``). When enabled (and the serving
    front-end runs with ``--adaptive`` / a controller attached), each
    sampler tick folds the burn-rate gauges plus serving pressure
    signals into one observation and may move serving knobs one ladder
    rung (tighten under burn, relax back toward config after sustained
    headroom). Every decision is a typed flight-recorder event — the
    auditable ledger ``replay_decisions`` reproduces exactly. Enabling
    ctl implies the sampler (something must tick the loop); pin a single
    knob static with ``knobs: {"<name>": "off"}``."""
    enabled: bool = False
    # burn rate at/above which a pressure class tightens its knobs
    tighten_threshold: float = 1.0
    # burn rate at/below which a tick counts toward the headroom streak;
    # the (relax_threshold, tighten_threshold) gap is the hysteresis
    # dead band where posture holds
    relax_threshold: float = 0.25
    # minimum ticks between movements of the SAME knob (flap guard)
    cooldown_ticks: int = 5
    # consecutive headroom ticks before knobs start stepping back
    relax_after: int = 10
    # tpot pressure only drops spec k while acceptance sits below this
    spec_accept_floor: float = 0.5
    # KV-block utilization at/above which spill aggressiveness rises
    # (only when the host tier is present and error-free)
    kv_util_high: float = 0.9
    # per-knob overrides: {"prefill_chunk": "off"} pins that knob at its
    # config value — the controller never builds a ladder for it
    knobs: Dict[str, str] = Field(default_factory=dict)


class TelemetryConfig(ConfigModel):
    """"telemetry" section: the cross-layer metrics registry + tracing.

    Accepted as a dict, a bool, or the strings ``"on"``/``"off"`` (see
    :func:`get_telemetry_config`). When enabled, the training engine
    records per-step time/tokens-per-sec/MFU, the inference engine records
    serving stats (TTFT/TPOT, queue depth, KV-block utilization,
    preemptions), and every ``jax.jit`` entry point the engines own runs
    under the compile watchdog. When disabled nothing is instrumented —
    the hot paths gate at one flag check, with no host/device syncs.
    """
    enabled: bool = False
    # append a registry snapshot to this JSONL file every
    # ``steps_per_snapshot`` steps (0 = only on demand / engine exit)
    jsonl_path: Optional[str] = None
    steps_per_snapshot: int = 0
    # also fan snapshots out through the MonitorMaster sinks at the same
    # cadence (TensorBoard / W&B / CSV, "Telemetry/*" series)
    publish_to_monitor: bool = True
    # chrome-trace span export path (written by engine.export_trace())
    chrome_trace_path: Optional[str] = None
    # compile watchdog: warn when one entry point compiles this many times
    # inside its rolling window
    compile_storm_threshold: int = 8
    # hardware peak for the MFU gauge, per chip; 0 = auto (DS_PEAK_TFLOPS
    # env, else the accelerator's device-kind table, else MFU reads 0)
    peak_tflops_per_chip: float = 0.0
    # health observatory sub-block (sentinels + anomaly detectors +
    # memory gauges + the `dscli health` screen); accepts a dict or a bool
    health: HealthConfig = Field(default_factory=HealthConfig)
    # flight recorder sub-block (event ring + serving trace export);
    # accepts a dict or a bool like `health`
    events: EventsConfig = Field(default_factory=EventsConfig)
    # on-demand jax.profiler capture window
    profile: ProfileConfig = Field(default_factory=ProfileConfig)
    # standalone Prometheus exposition endpoint (monitor/exporter.py):
    # GET /metrics on this port (0 = ephemeral, logged once bound; None =
    # no exporter). `dscli serve` exposes /metrics on its own front-end
    # regardless — this knob is the training-side scrape target.
    metrics_port: Optional[int] = None
    # background snapshot daemon (rotated JSONL + ring); bool shorthand
    sampler: SamplerConfig = Field(default_factory=SamplerConfig)
    # burn-rate SLO engine over the sampler's ring; bool shorthand
    slo: SloConfig = Field(default_factory=SloConfig)
    # adaptive serving controller over the SLO plane; bool shorthand
    ctl: CtlConfig = Field(default_factory=CtlConfig)


def get_telemetry_config(param_dict: dict) -> TelemetryConfig:
    """Parse the ``telemetry`` section: dict, bool/0/1, "on"/"off", or
    null (= defaults). The ``health`` sub-key accepts a bool shorthand,
    and enabling health implies telemetry itself unless the user
    explicitly disabled it (health rides the telemetry substrate)."""
    t = param_dict.get("telemetry", {})
    if t is None:
        t = {}
    elif isinstance(t, str):
        if t not in ("on", "off"):
            raise ValueError(f"telemetry={t!r} (expected 'on', 'off', "
                             "a bool, or a config dict)")
        t = {"enabled": t == "on"}
    elif isinstance(t, (bool, int)):
        t = {"enabled": bool(t)}
    elif not isinstance(t, dict):
        raise ValueError(f"telemetry section must be a dict, bool, or "
                         f"'on'/'off'; got {type(t).__name__}")
    t = dict(t)

    def _sub_shorthand(key):
        """bool / "on"/"off" / null shorthand for a sub-block (shared by
        ``health`` and ``events``)."""
        sub = t.get(key, {})
        if sub is None:
            sub = {}         # null = defaults, like the parent section
        elif isinstance(sub, str):
            if sub not in ("on", "off"):
                raise ValueError(f"telemetry.{key}={sub!r} (expected 'on', "
                                 "'off', a bool, or a config dict)")
            sub = {"enabled": sub == "on"}
        elif isinstance(sub, (bool, int)):
            sub = {"enabled": bool(sub)}
        t[key] = sub
        return sub

    health = _sub_shorthand("health")
    events = _sub_shorthand("events")
    sampler = _sub_shorthand("sampler")
    slo = _sub_shorthand("slo")
    ctl = _sub_shorthand("ctl")
    if t.get("profile") is None and "profile" in t:
        t["profile"] = {}    # null = defaults
    # enabling a sub-block implies the telemetry substrate it rides on,
    # unless the user explicitly disabled telemetry itself
    for sub in (health, events, sampler, slo, ctl):
        if isinstance(sub, dict) and sub.get("enabled") \
                and "enabled" not in t:
            t["enabled"] = True
    # a scrape endpoint with nothing behind it would silently serve an
    # empty registry: asking for /metrics implies telemetry too
    if t.get("metrics_port") is not None and "enabled" not in t:
        t["enabled"] = True
    # SLOs need something ticking the evaluation: enabling slo implies
    # the sampler (ring-only when no path is configured); same for the
    # controller, which ticks on the sampler's cadence
    if isinstance(sampler, dict) and "enabled" not in sampler and (
            (isinstance(slo, dict) and slo.get("enabled"))
            or (isinstance(ctl, dict) and ctl.get("enabled"))):
        sampler["enabled"] = True
    return TelemetryConfig(**t)


class TensorBoardConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(ConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


class CSVConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class DeepSpeedMonitorConfig(ConfigModel):
    tensorboard: TensorBoardConfig = Field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)
