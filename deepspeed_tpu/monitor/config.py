"""Monitor (TensorBoard / W&B / CSV) config.

Reference parity: ``deepspeed/monitor/config.py``.
"""

from __future__ import annotations

from typing import Optional

from pydantic import Field

from deepspeed_tpu.config.config_utils import ConfigModel


def get_monitor_config(param_dict: dict) -> "DeepSpeedMonitorConfig":
    monitor_dict = {key: param_dict.get(key, {}) for key in ("tensorboard", "wandb", "csv_monitor")}
    return DeepSpeedMonitorConfig(**monitor_dict)


class TensorBoardConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(ConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


class CSVConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class DeepSpeedMonitorConfig(ConfigModel):
    tensorboard: TensorBoardConfig = Field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)
