"""Monitor (TensorBoard / W&B / CSV) config.

Reference parity: ``deepspeed/monitor/config.py``.
"""

from __future__ import annotations

from typing import Optional

from pydantic import Field

from deepspeed_tpu.config.config_utils import ConfigModel


def get_monitor_config(param_dict: dict) -> "DeepSpeedMonitorConfig":
    monitor_dict = {key: param_dict.get(key, {}) for key in ("tensorboard", "wandb", "csv_monitor")}
    return DeepSpeedMonitorConfig(**monitor_dict)


class TelemetryConfig(ConfigModel):
    """"telemetry" section: the cross-layer metrics registry + tracing.

    Accepted as a dict, a bool, or the strings ``"on"``/``"off"`` (see
    :func:`get_telemetry_config`). When enabled, the training engine
    records per-step time/tokens-per-sec/MFU, the inference engine records
    serving stats (TTFT/TPOT, queue depth, KV-block utilization,
    preemptions), and every ``jax.jit`` entry point the engines own runs
    under the compile watchdog. When disabled nothing is instrumented —
    the hot paths gate at one flag check, with no host/device syncs.
    """
    enabled: bool = False
    # append a registry snapshot to this JSONL file every
    # ``steps_per_snapshot`` steps (0 = only on demand / engine exit)
    jsonl_path: Optional[str] = None
    steps_per_snapshot: int = 0
    # also fan snapshots out through the MonitorMaster sinks at the same
    # cadence (TensorBoard / W&B / CSV, "Telemetry/*" series)
    publish_to_monitor: bool = True
    # chrome-trace span export path (written by engine.export_trace())
    chrome_trace_path: Optional[str] = None
    # compile watchdog: warn when one entry point compiles this many times
    # inside its rolling window
    compile_storm_threshold: int = 8
    # hardware peak for the MFU gauge, per chip; 0 = auto (DS_PEAK_TFLOPS
    # env, else the accelerator's device-kind table, else MFU reads 0)
    peak_tflops_per_chip: float = 0.0


def get_telemetry_config(param_dict: dict) -> TelemetryConfig:
    """Parse the ``telemetry`` section: dict, bool/0/1, "on"/"off", or
    null (= defaults)."""
    t = param_dict.get("telemetry", {})
    if t is None:
        t = {}
    elif isinstance(t, str):
        if t not in ("on", "off"):
            raise ValueError(f"telemetry={t!r} (expected 'on', 'off', "
                             "a bool, or a config dict)")
        t = {"enabled": t == "on"}
    elif isinstance(t, (bool, int)):
        t = {"enabled": bool(t)}
    elif not isinstance(t, dict):
        raise ValueError(f"telemetry section must be a dict, bool, or "
                         f"'on'/'off'; got {type(t).__name__}")
    return TelemetryConfig(**t)


class TensorBoardConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(ConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


class CSVConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class DeepSpeedMonitorConfig(ConfigModel):
    tensorboard: TensorBoardConfig = Field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)
