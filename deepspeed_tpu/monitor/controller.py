"""Deterministic SLO-burn-rate autopilot with an auditable decision ledger.

The observability plane ends at "exposes metrics"; this module closes the
loop. An :class:`AdaptiveController`, ticked by the existing
``MetricsSampler``, maps burn-rate state (the TTFT / TPOT / goodput
objectives of ``monitor/slo.py``, plus spec acceptance, queue depth, KV
pressure and wasted-token rates from the phase ledger) to typed knob
actions:

- TTFT burning            -> shrink the prefill chunk + tighten admission
- TPOT burning, spec cold -> drop speculative ``k``
- goodput burning         -> shed earlier, admit less, keep pool headroom
- KV pressure, host OK    -> raise host-spill aggressiveness
- sustained headroom      -> step every knob back toward config, one rung
                             per cooldown window

Each knob moves on a **ladder** (index 0 = the config baseline, higher =
tighter posture) whose rungs are chosen so every value stays inside the
compile buckets the engine already owns — chunk sizes move only between
128-multiples, spec ``k`` only within its fixed pow2 verify window — so
the controller adds ZERO steady-state programs (pinned by the
``serving_adaptive_steady`` dslint contract). Per-knob **cooldown ticks**
plus a tighten/relax **hysteresis band** (relax only below
``relax_threshold`` for ``relax_after`` consecutive ticks) keep an
oscillating burn rate from flapping a knob.

Every observation -> decision -> application is a typed flight-recorder
event (``ctl.observe`` / ``ctl.decide`` / ``ctl.apply`` / ``ctl.revert``
in ``EVENT_KINDS``), forming the decision ledger, and the registry grows
``ctl/knob{name=}`` gauges plus ``ctl/actions{knob=,direction=}``
counters so any ``/metrics`` scrape explains *why* the system holds its
current posture. :class:`DecisionCore` is a pure function of its
observation trace — no wall time, no RNG — so :func:`replay_decisions`
over a recorded ``events.jsonl`` reproduces the exact action sequence
(the scheduler/router determinism discipline, applied to control).

This module is part of the telemetry exposition plane: host-side dict
arithmetic only — importing jax (or touching any device API) here is a
dslint DS009 violation.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

TIGHTEN = "tighten"
RELAX = "relax"

# bounded reason vocabulary (safe as metric label values)
REASON_TTFT = "ttft_burn"
REASON_TPOT = "tpot_burn"
REASON_GOODPUT = "goodput_burn"
REASON_KV = "kv_pressure"
REASON_HEADROOM = "headroom"
REASON_RESTART = "restart"

#: every knob the controller may drive, in its deterministic scan order
KNOB_NAMES = ("prefill_chunk", "spec_k", "max_queue", "min_free_blocks",
              "shed_depth", "kv_spill")


@dataclasses.dataclass(frozen=True)
class KnobSpec:
    """One runtime-adjustable serving knob.

    ``ladder[0]`` is the config baseline; ascending index = tighter
    posture. Rungs are fixed at build time (:func:`knobs_from_serving`)
    so every reachable value is known up front — the dslint
    ``serving_adaptive_steady`` contract and the docs knob catalogue both
    read straight off the ladder.
    """
    name: str
    ladder: Tuple[int, ...]

    def __post_init__(self):
        if len(self.ladder) < 2:
            raise ValueError(f"knob {self.name!r}: ladder needs >= 2 rungs "
                             f"(got {self.ladder!r})")
        if len(set(self.ladder)) != len(self.ladder):
            raise ValueError(f"knob {self.name!r}: duplicate ladder rungs "
                             f"{self.ladder!r}")

    @property
    def baseline(self) -> int:
        return self.ladder[0]


@dataclasses.dataclass(frozen=True)
class KnobAction:
    """One decided knob movement (a ``ctl.decide`` ledger entry)."""
    tick: int
    knob: str
    direction: str          # TIGHTEN | RELAX
    value: int              # the new knob value
    prev: int               # the value it moved away from
    reason: str             # bounded REASON_* vocabulary
    at_baseline: bool       # True when this relax lands back on config

    def to_payload(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Observation:
    """One sampler tick's folded controller inputs.

    Burn rates are folded per objective class (min across windows — the
    breach semantics — then max across the class's objectives), so the
    ledger entry is self-contained: :class:`DecisionCore` never re-reads
    the registry, which is what makes replay exact.
    """
    tick: int
    ttft_burn: float = 0.0
    tpot_burn: float = 0.0
    goodput_burn: float = 0.0
    queue_depth: float = 0.0
    kv_util: float = 0.0
    kv_free: float = 0.0
    spec_acceptance: float = 1.0
    host_tier_ok: bool = False
    wasted_rate: float = 0.0        # wasted tokens this tick (all causes)

    def to_payload(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_payload(cls, d: Dict[str, Any]) -> "Observation":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})

    @property
    def max_burn(self) -> float:
        return max(self.ttft_burn, self.tpot_burn, self.goodput_burn)


# ---------------------------------------------------------------------- #
# ladder builders
# ---------------------------------------------------------------------- #

def _chunk_ladder(chunk: int) -> Optional[Tuple[int, ...]]:
    """Descending 128-multiples below the configured chunk size.

    Chunked prefill pads each step to a 128-bucket
    (``engine._bucket``), so any 128-multiple <= the baseline reuses a
    program the warm engine has already compiled. Chunking is never
    ENABLED mid-flight (baseline 0 stays 0): turning it on would route
    prefill through chunk-sized buckets the engine never built.
    """
    if chunk <= 0:
        return None
    rungs = [chunk]
    step = (chunk // 2 // 128) * 128
    while step >= 128 and len(rungs) < 4:
        if step < rungs[-1]:
            rungs.append(step)
        step //= 2
        step = (step // 128) * 128
    if len(rungs) < 2 and chunk > 128:
        rungs.append(128)
    return tuple(rungs) if len(rungs) >= 2 else None


def _spec_ladder(k: int) -> Optional[Tuple[int, ...]]:
    """Descending spec ``k`` rungs, ending at 0 (spec off).

    The verify program pads candidates to a FIXED pow2 window set from
    the configured ``k`` at session open, so any ``k' <= k`` — including
    0, which degenerates to the already-compiled pure-decode step — is
    compile-free. Rungs: baseline, then the next pow2 window edges down
    (``2^i - 1``), then 0.
    """
    if k <= 0:
        return None
    rungs = [k]
    edge = (1 << max(k.bit_length() - 1, 0)) - 1    # e.g. k=4 -> 3
    while edge > 0:
        if edge < rungs[-1]:
            rungs.append(edge)
        edge = (1 << max(edge.bit_length() - 1, 0)) - 1
    rungs.append(0)
    return tuple(rungs)


def knobs_from_serving(serving, policy=None,
                       pinned: Sequence[str] = ()) -> List[KnobSpec]:
    """Build the knob set a :class:`ServingConfig` admits.

    Knobs whose baseline makes movement meaningless (chunking off, spec
    off, spill already on) are omitted rather than built immovable, and
    any name in ``pinned`` (config ``telemetry.ctl.knobs.<name>: off``)
    is excluded — the controller simply never sees it.
    """
    pinned = set(pinned)
    out: List[KnobSpec] = []

    def add(name: str, ladder: Optional[Tuple[int, ...]]) -> None:
        if ladder is not None and name not in pinned:
            out.append(KnobSpec(name, ladder))

    add("prefill_chunk", _chunk_ladder(int(serving.prefill_chunk_tokens)))

    spec = serving.speculative
    k = int(spec.k) if getattr(spec, "mode", "off") != "off" else 0
    add("spec_k", _spec_ladder(k))

    q = int(getattr(policy, "admission_max_queue", 0) or 0)
    if q > 0:
        ladder = [q]
        for v in (max(q // 2, 1), max(q // 4, 1)):
            if v < ladder[-1]:
                ladder.append(v)
        add("max_queue", tuple(ladder) if len(ladder) >= 2 else None)
    else:
        # baseline "unbounded": tightening imposes a bound at all
        add("max_queue", (0, 16, 8, 4))

    m = int(getattr(policy, "admission_min_free_blocks", 0) or 0)
    add("min_free_blocks", (m, m + 2, m + 4))

    s = int(serving.fault.shed_queue_depth)
    if s > 0:
        add("shed_depth", (s, max(s // 2, 1)) if s > 1 else None)
    else:
        add("shed_depth", (0, 16, 8))

    kv = serving.kv_host
    if kv.enabled and getattr(kv, "spill", "auto") == "off":
        # host tier present but demotion disabled: the one rung up turns
        # spill on (0 = config's fetch-only, 1 = demote cold blocks)
        add("kv_spill", (0, 1))

    return out


# ---------------------------------------------------------------------- #
# the pure decision core
# ---------------------------------------------------------------------- #

class _KnobState:
    __slots__ = ("idx", "last_tick")

    def __init__(self):
        self.idx = 0                # ladder index (0 = baseline)
        self.last_tick: Optional[int] = None


class DecisionCore:
    """Pure observation-trace -> action-sequence function.

    Holds only ladder indices, cooldown stamps and the headroom streak;
    :meth:`decide` consumes a folded :class:`Observation` and returns the
    actions for that tick. No clocks, no RNG, no registry reads — feeding
    the same observation sequence always yields the same actions, which
    is what :func:`replay_decisions` pins.
    """

    def __init__(self, knobs: Sequence[KnobSpec], *,
                 tighten_threshold: float = 1.0,
                 relax_threshold: float = 0.25,
                 cooldown_ticks: int = 5,
                 relax_after: int = 10,
                 spec_accept_floor: float = 0.5,
                 kv_util_high: float = 0.9):
        self.knobs: Dict[str, KnobSpec] = {}
        for k in knobs:
            if k.name in self.knobs:
                raise ValueError(f"duplicate knob {k.name!r}")
            self.knobs[k.name] = k
        self.tighten_threshold = float(tighten_threshold)
        self.relax_threshold = float(relax_threshold)
        self.cooldown_ticks = int(cooldown_ticks)
        self.relax_after = int(relax_after)
        self.spec_accept_floor = float(spec_accept_floor)
        self.kv_util_high = float(kv_util_high)
        self._state: Dict[str, _KnobState] = \
            {name: _KnobState() for name in self.knobs}
        self._headroom_streak = 0

    # current values, for gauges / panes / re-application
    def values(self) -> Dict[str, int]:
        return {name: spec.ladder[self._state[name].idx]
                for name, spec in self.knobs.items()}

    def params(self) -> Dict[str, float]:
        return {"tighten_threshold": self.tighten_threshold,
                "relax_threshold": self.relax_threshold,
                "cooldown_ticks": self.cooldown_ticks,
                "relax_after": self.relax_after,
                "spec_accept_floor": self.spec_accept_floor,
                "kv_util_high": self.kv_util_high}

    def manifest(self) -> Dict[str, Any]:
        """The replay seed: ladders + thresholds, stamped into the first
        ``ctl.observe`` ledger entry. Knob order is preserved (insertion
        order is the relax scan order, so it is part of the decision
        function)."""
        return {"knobs": {n: list(s.ladder)
                          for n, s in self.knobs.items()},
                "params": self.params()}

    @classmethod
    def from_manifest(cls, manifest: Dict[str, Any]) -> "DecisionCore":
        knobs = [KnobSpec(n, tuple(ladder))
                 for n, ladder in manifest.get("knobs", {}).items()]
        return cls(knobs, **manifest.get("params", {}))

    def _try(self, name: str, direction: str, reason: str,
             tick: int) -> Optional[KnobAction]:
        st = self._state.get(name)
        if st is None:
            return None                         # knob absent or pinned
        spec = self.knobs[name]
        if direction == TIGHTEN:
            if st.idx >= len(spec.ladder) - 1:
                return None                     # already at the floor
            new_idx = st.idx + 1
        else:
            if st.idx == 0:
                return None                     # already at baseline
            new_idx = st.idx - 1
        if st.last_tick is not None and \
                tick - st.last_tick < self.cooldown_ticks:
            return None                         # inside the cooldown
        prev = spec.ladder[st.idx]
        st.idx = new_idx
        st.last_tick = tick
        return KnobAction(tick=tick, knob=name, direction=direction,
                          value=spec.ladder[new_idx], prev=prev,
                          reason=reason, at_baseline=(new_idx == 0))

    def decide(self, obs: Observation) -> List[KnobAction]:
        """One tick: fold pressures into knob movements."""
        thr = self.tighten_threshold
        kv_hot = obs.kv_util >= self.kv_util_high and obs.host_tier_ok
        wants: List[Tuple[str, str]] = []       # (knob, reason) tighten list
        if obs.ttft_burn >= thr:
            wants += [("prefill_chunk", REASON_TTFT),
                      ("max_queue", REASON_TTFT)]
        if obs.tpot_burn >= thr and obs.spec_acceptance < \
                self.spec_accept_floor:
            wants.append(("spec_k", REASON_TPOT))
        if obs.goodput_burn >= thr:
            wants += [("shed_depth", REASON_GOODPUT),
                      ("max_queue", REASON_GOODPUT),
                      ("min_free_blocks", REASON_GOODPUT)]
        if kv_hot:
            wants.append(("kv_spill", REASON_KV))

        actions: List[KnobAction] = []
        under_pressure = obs.max_burn >= thr or kv_hot
        if under_pressure:
            self._headroom_streak = 0
            moved = set()
            for name, reason in wants:
                if name in moved:
                    continue                    # first pressure wins
                act = self._try(name, TIGHTEN, reason, obs.tick)
                if act is not None:
                    moved.add(name)
                    actions.append(act)
        elif obs.max_burn <= self.relax_threshold:
            self._headroom_streak += 1
            if self._headroom_streak >= self.relax_after:
                for name in self.knobs:         # insertion order: stable
                    act = self._try(name, RELAX, REASON_HEADROOM, obs.tick)
                    if act is not None:
                        actions.append(act)
        else:
            # the hysteresis dead band: burning, but not past the tighten
            # threshold — hold posture, reset the headroom streak
            self._headroom_streak = 0
        return actions


# ---------------------------------------------------------------------- #
# the live wrapper (registry + ledger + application)
# ---------------------------------------------------------------------- #

def _gauge_value(gauges: Dict[str, float], name: str,
                 default: float = 0.0) -> float:
    """Read a gauge that may be plain or labeled (single-replica serving
    emits plain; a tagged recorder adds ``replica=``). Labeled: max
    across series — the controller reacts to the hottest replica."""
    if name in gauges:
        return float(gauges[name])
    prefix = name + "{"
    vals = [v for k, v in gauges.items() if k.startswith(prefix)]
    return float(max(vals)) if vals else default


def _counter_sum(counters: Dict[str, float], name: str) -> float:
    """Sum a counter family across all its label series."""
    prefix = name + "{"
    total = float(counters.get(name, 0.0))
    total += sum(v for k, v in counters.items() if k.startswith(prefix))
    return total


def _burn_by_class(gauges: Dict[str, float]) -> Dict[str, float]:
    """Fold ``slo/burn_rate{objective=,window=}`` gauges into the three
    controller pressure classes. Per objective: min across windows (a
    breach needs EVERY window burning); per class: max across
    objectives. Objectives classify by name substring — ``ttft`` /
    ``tpot`` / everything else is goodput."""
    from deepspeed_tpu.monitor.health import multilabel_series
    per_obj: Dict[str, float] = {}
    for labels, v in multilabel_series(gauges, "slo/burn_rate"):
        obj = labels.get("objective")
        if obj is None:
            continue
        per_obj[obj] = v if obj not in per_obj else min(per_obj[obj], v)
    out = {"ttft": 0.0, "tpot": 0.0, "goodput": 0.0}
    for obj, burn in per_obj.items():
        cls = ("ttft" if "ttft" in obj else
               "tpot" if "tpot" in obj else "goodput")
        out[cls] = max(out[cls], burn)
    return out


class AdaptiveController:
    """The live loop: observe the registry, decide, ledger, apply.

    ``tick()`` is the sampler's hook (called after ``SloEngine.sample``
    refreshes the burn gauges); tests drive it directly for a fully
    deterministic tick sequence. ``apply_fn`` receives the tick's action
    list — the serving front-end queues them onto its intake so knob
    mutation happens between engine steps on the serving thread (donated
    pools stay single-threaded).
    """

    def __init__(self, knobs: Sequence[KnobSpec], *, registry=None,
                 events=None,
                 apply_fn: Optional[Callable[[List[KnobAction]], None]] = None,
                 **core_params):
        if registry is None:
            from deepspeed_tpu.monitor.metrics import get_registry
            registry = get_registry()
        self.registry = registry
        self.events = events
        self.apply_fn = apply_fn
        self.core = DecisionCore(knobs, **core_params)
        self._tick = 0
        self._sent_manifest = False
        self._last_host_errors: Optional[float] = None
        self._last_wasted: Optional[float] = None
        self._ensure_series()

    # ---- registry families ---- #

    @property
    def _knob_gauge(self):
        return self.registry.gauge(
            "ctl/knob", "current adaptive-controller knob value",
            labelnames=("name",))

    @property
    def _baseline_gauge(self):
        return self.registry.gauge(
            "ctl/knob_baseline", "config-baseline knob value (ladder[0])",
            labelnames=("name",))

    @property
    def _actions(self):
        return self.registry.counter(
            "ctl/actions", "adaptive-controller knob movements",
            labelnames=("knob", "direction"))

    @property
    def _last_action(self):
        return self.registry.gauge(
            "ctl/last_action",
            "tick of the most recent movement per (knob, direction, "
            "reason) — the scrape-side 'why is it in this posture'",
            labelnames=("knob", "direction", "reason"))

    def _ensure_series(self) -> None:
        for name, value in self.core.values().items():
            self._knob_gauge.labels(name=name).set(value)
            self._baseline_gauge.labels(name=name).set(
                self.core.knobs[name].baseline)
            for d in (TIGHTEN, RELAX):
                self._actions.labels(knob=name, direction=d)

    # ---- one tick ---- #

    def observe(self) -> Observation:
        """Fold the live registry into one self-contained observation."""
        self._tick += 1
        snap = self.registry.snapshot()
        g = snap.get("gauges", {}) or {}
        c = snap.get("counters", {}) or {}
        burns = _burn_by_class(g)

        host_blocks = _gauge_value(g, "serving/kv_host_blocks", -1.0)
        host_errors = _counter_sum(c, "serving/kv_host_errors")
        host_ok = host_blocks >= 0.0 and (
            self._last_host_errors is None
            or host_errors <= self._last_host_errors)
        self._last_host_errors = host_errors

        wasted = _counter_sum(c, "serving/wasted_tokens")
        wasted_rate = (wasted - self._last_wasted
                       if self._last_wasted is not None else 0.0)
        self._last_wasted = wasted

        return Observation(
            tick=self._tick,
            ttft_burn=burns["ttft"],
            tpot_burn=burns["tpot"],
            goodput_burn=burns["goodput"],
            queue_depth=_gauge_value(g, "serving/queue_depth"),
            kv_util=_gauge_value(g, "serving/kv_block_utilization"),
            kv_free=_gauge_value(g, "serving/kv_blocks_free"),
            spec_acceptance=_gauge_value(
                g, "serving/spec_acceptance_rate", 1.0),
            host_tier_ok=host_ok,
            wasted_rate=wasted_rate)

    def tick(self) -> List[KnobAction]:
        """One controller tick: observe -> ledger -> decide -> apply."""
        obs = self.observe()
        if self.events is not None:
            payload = obs.to_payload()
            if not self._sent_manifest:
                payload["config"] = self.core.manifest()
                self._sent_manifest = True
            self.events.emit("ctl.observe", **payload)
        actions = self.core.decide(obs)
        for a in actions:
            if self.events is not None:
                self.events.emit("ctl.decide", **a.to_payload())
            self._actions.labels(knob=a.knob, direction=a.direction).inc()
            self._knob_gauge.labels(name=a.knob).set(a.value)
            self._last_action.labels(knob=a.knob, direction=a.direction,
                                     reason=a.reason).set(a.tick)
        if actions and self.apply_fn is not None:
            self.apply_fn(actions)
        return actions

    def values(self) -> Dict[str, int]:
        return self.core.values()


def controller_from_config(ctl_cfg, serving, policy=None, *, registry=None,
                           events=None, apply_fn=None
                           ) -> Optional[AdaptiveController]:
    """Build the controller a ``telemetry.ctl`` config block asks for
    (None when disabled or no knob is movable)."""
    if ctl_cfg is None or not ctl_cfg.enabled:
        return None
    pinned = [name for name, mode in (ctl_cfg.knobs or {}).items()
              if str(mode).lower() in ("off", "static", "pin")]
    knobs = knobs_from_serving(serving, policy=policy, pinned=pinned)
    if not knobs:
        return None
    return AdaptiveController(
        knobs, registry=registry, events=events, apply_fn=apply_fn,
        tighten_threshold=ctl_cfg.tighten_threshold,
        relax_threshold=ctl_cfg.relax_threshold,
        cooldown_ticks=ctl_cfg.cooldown_ticks,
        relax_after=ctl_cfg.relax_after,
        spec_accept_floor=ctl_cfg.spec_accept_floor,
        kv_util_high=ctl_cfg.kv_util_high)


# ---------------------------------------------------------------------- #
# replay / explain (the audit path)
# ---------------------------------------------------------------------- #

def _iter_events(events_or_path) -> Iterable[Dict[str, Any]]:
    if isinstance(events_or_path, (str, bytes)):
        with open(events_or_path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield json.loads(line)
    else:
        for e in events_or_path:
            yield e if isinstance(e, dict) else dict(e)


def _event_fields(e: Dict[str, Any]) -> Dict[str, Any]:
    """Ledger events may be flat dicts (events.jsonl rows carry data
    keys at top level) or ``{"kind": ..., "data": {...}}`` shaped."""
    d = e.get("data")
    if isinstance(d, dict):
        merged = dict(e)
        merged.pop("data", None)
        merged.update(d)
        return merged
    return e


def replay_decisions(events_or_path,
                     manifest: Optional[Dict[str, Any]] = None
                     ) -> List[Dict[str, Any]]:
    """Re-run the pure decision core over a recorded observation trace.

    Reads the ``ctl.observe`` entries of a decision ledger (an
    ``events.jsonl`` path, or an iterable of event dicts), seeds a fresh
    :class:`DecisionCore` from the manifest stamped into the first entry
    (or an explicit ``manifest=``), and returns the reproduced action
    payloads — byte-identical to the recorded ``ctl.decide`` sequence
    when the controller is healthy (the replay-identity test pins this).
    """
    core: Optional[DecisionCore] = None
    if manifest is not None:
        core = DecisionCore.from_manifest(manifest)
    out: List[Dict[str, Any]] = []
    for e in _iter_events(events_or_path):
        if e.get("kind") != "ctl.observe":
            continue
        f = _event_fields(e)
        if core is None:
            cfg = f.get("config")
            if not isinstance(cfg, dict):
                raise ValueError(
                    "replay_decisions: first ctl.observe entry carries no "
                    "config manifest; pass manifest= explicitly")
            core = DecisionCore.from_manifest(cfg)
        for a in core.decide(Observation.from_payload(f)):
            out.append(a.to_payload())
    return out


def recorded_decisions(events_or_path) -> List[Dict[str, Any]]:
    """The ``ctl.decide`` payloads actually recorded in a ledger, in
    order — the reference side of the replay-identity comparison."""
    keys = {f.name for f in dataclasses.fields(KnobAction)}
    out: List[Dict[str, Any]] = []
    for e in _iter_events(events_or_path):
        if e.get("kind") != "ctl.decide":
            continue
        f = _event_fields(e)
        out.append({k: f[k] for k in keys if k in f})
    return out


def explain_decisions(events_or_path) -> List[str]:
    """Human-readable audit: one line per decision, annotated with the
    observation that triggered it (``dscli ctl explain``)."""
    last_obs: Dict[str, Any] = {}
    lines: List[str] = []
    for e in _iter_events(events_or_path):
        kind = e.get("kind")
        f = _event_fields(e)
        if kind == "ctl.observe":
            last_obs = f
        elif kind == "ctl.decide":
            burns = (f"ttft={last_obs.get('ttft_burn', 0):.2f} "
                     f"tpot={last_obs.get('tpot_burn', 0):.2f} "
                     f"goodput={last_obs.get('goodput_burn', 0):.2f} "
                     f"kv={last_obs.get('kv_util', 0):.2f}")
            lines.append(
                f"tick {f.get('tick')}: {f.get('direction')} "
                f"{f.get('knob')} {f.get('prev')} -> {f.get('value')} "
                f"[{f.get('reason')}] ({burns})")
        elif kind in ("ctl.apply", "ctl.revert"):
            extra = " after restart" if f.get("restart") else ""
            lines.append(
                f"tick {f.get('tick')}: {kind.split('.')[1]} "
                f"{f.get('knob')} = {f.get('value')}{extra}")
    return lines
