"""Monitoring fan-out (reference: deepspeed/monitor/monitor.py).

``MonitorMaster`` forwards scalar events to every enabled sink
(TensorBoard / W&B / CSV). Only process 0 writes.
"""

from __future__ import annotations

import os
from typing import List, Tuple

from deepspeed_tpu.monitor.config import DeepSpeedMonitorConfig
from deepspeed_tpu.utils.logging import logger


class Monitor:

    def __init__(self, monitor_config):
        self.monitor_config = monitor_config

    def write_events(self, event_list: List[Tuple[str, float, int]]) -> None:
        raise NotImplementedError


class TensorBoardMonitor(Monitor):

    def __init__(self, tensorboard_config):
        super().__init__(tensorboard_config)
        self.enabled = tensorboard_config.enabled
        self.summary_writer = None
        if self.enabled:
            try:
                from torch.utils.tensorboard import SummaryWriter
                log_dir = os.path.join(tensorboard_config.output_path or "./runs",
                                       tensorboard_config.job_name)
                self.summary_writer = SummaryWriter(log_dir=log_dir)
            except ImportError:
                logger.warning("tensorboard not available; TensorBoardMonitor disabled")
                self.enabled = False

    def write_events(self, event_list, flush=True):
        if self.summary_writer is None:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, value, step)
        if flush:
            self.summary_writer.flush()


class WandbMonitor(Monitor):

    def __init__(self, wandb_config):
        super().__init__(wandb_config)
        self.enabled = wandb_config.enabled
        if self.enabled:
            try:
                import wandb
                self.wandb = wandb
                wandb.init(project=wandb_config.project, group=wandb_config.group, entity=wandb_config.team)
            except ImportError:
                logger.warning("wandb not available; WandbMonitor disabled")
                self.enabled = False

    def write_events(self, event_list):
        if not self.enabled:
            return
        for name, value, step in event_list:
            self.wandb.log({name: value}, step=step)


class csvMonitor(Monitor):

    def __init__(self, csv_config):
        super().__init__(csv_config)
        self.enabled = csv_config.enabled
        self.output_path = csv_config.output_path or "./csv_monitor"
        self.job_name = csv_config.job_name
        self.filenames: dict = {}
        if self.enabled:
            os.makedirs(os.path.join(self.output_path, self.job_name), exist_ok=True)

    def write_events(self, event_list):
        if not self.enabled:
            return
        # group by metric name: each per-metric file is opened/closed ONCE
        # per call, not once per event (a telemetry snapshot fans out
        # hundreds of events; per-event open() made this O(events) syscalls)
        by_name: dict = {}
        for name, value, step in event_list:
            by_name.setdefault(name, []).append((step, value))
        for name, rows in by_name.items():
            safe = name.replace("/", "_")
            path = os.path.join(self.output_path, self.job_name, f"{safe}.csv")
            new = not os.path.exists(path)
            with open(path, "a") as f:
                if new:
                    f.write("step,value\n")
                f.writelines(f"{step},{value}\n" for step, value in rows)


class MonitorMaster(Monitor):

    def __init__(self, monitor_config: DeepSpeedMonitorConfig):
        super().__init__(monitor_config)
        rank = int(os.environ.get("RANK", 0))
        try:
            import jax
            rank = jax.process_index()
        except Exception:
            pass
        self.rank = rank
        self.tb_monitor = None
        self.wandb_monitor = None
        self.csv_monitor = None
        if rank == 0:
            if monitor_config.tensorboard.enabled:
                self.tb_monitor = TensorBoardMonitor(monitor_config.tensorboard)
            if monitor_config.wandb.enabled:
                self.wandb_monitor = WandbMonitor(monitor_config.wandb)
            if monitor_config.csv_monitor.enabled:
                self.csv_monitor = csvMonitor(monitor_config.csv_monitor)
        self.enabled = any(m is not None and m.enabled
                           for m in (self.tb_monitor, self.wandb_monitor, self.csv_monitor))

    def write_events(self, event_list):
        if self.rank != 0:
            return
        for m in (self.tb_monitor, self.wandb_monitor, self.csv_monitor):
            if m is not None and m.enabled:
                m.write_events(event_list)
