"""Training health observatory: sentinels, anomaly detectors, memory
telemetry, and the ``dscli health`` screen.

PR 3 built the recording substrate (metrics registry, step tracing,
compile watchdog); this module *interprets* the numbers, the way
production-scale training systems treat in-flight diagnostics as a
first-class subsystem (MegaScale's numerics/straggler sentinels, PaLM's
loss-spike skip-batch practice):

- **On-device numerics sentinels** (:func:`compute_sentinels`) — a compact
  per-step summary (non-finite grad/param element counts, pre-clip global
  grad norm, param/update norms, update/param ratio, per-layer-group norm
  buckets) computed *inside* the already-compiled train step and returned
  as ONE small fp32 vector. No extra host round-trips, no extra compiles:
  the reductions ride the same XLA program as the optimizer update (the
  same ``lax.cond`` discipline as the fp16 overflow skip).

- **Host-side anomaly detectors** (:class:`HealthMonitor`) over a ring
  buffer of :class:`StepHealth` records — loss spike (EWMA robust
  z-score), grad-norm explosion, plateau, sustained fp16 overflow skips,
  non-finite numerics, and a data-stall detector comparing host wait time
  against the bracketed device step time. Each firing increments a
  ``health/anomalies{type=}`` counter and, per the configured action,
  emits a rate-limited warning and/or a **debug bundle** (telemetry
  snapshot + chrome trace + last-K step records) to disk.

- **Memory telemetry** (:func:`sample_memory_gauges`) — per-device HBM
  live/peak/limit/headroom gauges from the accelerator's ``memory_stats``
  plus host RSS, sampled on the telemetry flush cadence.

- **The ``health`` CLI** (:func:`health_cli`) — tails the JSONL telemetry
  sink and renders a live one-screen status table (step rate, MFU, loss
  trend, grad norm, overflow/skip counts, HBM headroom, serving stats).

Everything here is host-side python except :func:`compute_sentinels`,
which is traced into the engines' compiled step when
``telemetry.health.enabled`` (and ``sentinels``) are on.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.utils.logging import logger

# ------------------------------------------------------------------ #
# on-device sentinels

#: fixed head of the sentinel vector; per-layer-group grad-norm buckets
#: follow (one slot per bucket name).
SENTINEL_FIELDS = ("nonfinite_grads", "nonfinite_params", "grad_norm",
                   "param_norm", "update_norm", "update_ratio")


def _path_head(path) -> str:
    """Top-level pytree key of a leaf path (the "layer group" name)."""
    if not path:
        return "params"
    k = path[0]
    for attr in ("key", "name", "idx"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def make_bucket_assignment(tree, max_buckets: int) -> Tuple[Tuple[int, ...],
                                                            Tuple[str, ...]]:
    """Map each leaf (flatten order) to a layer-group bucket.

    Groups are the top-level pytree keys in first-appearance order; when
    there are more groups than ``max_buckets``, the tail collapses into an
    ``"other"`` bucket. Deterministic for a fixed tree structure, so the
    compiled step can close over the assignment."""
    import jax
    leaves_with_path = jax.tree_util.tree_flatten_with_path(tree)[0]
    heads = []
    for path, _ in leaves_with_path:
        h = _path_head(path)
        if h not in heads:
            heads.append(h)
    if max_buckets < 1:
        max_buckets = 1
    if len(heads) > max_buckets:
        names = tuple(heads[:max_buckets - 1]) + ("other",)
        index = {h: min(i, max_buckets - 1) for i, h in enumerate(heads)}
    else:
        names = tuple(heads)
        index = {h: i for i, h in enumerate(heads)}
    assignment = tuple(index[_path_head(path)] for path, _ in leaves_with_path)
    return assignment, names


def compute_sentinels(grads, new_params, update_norm, grad_norm,
                      assignment: Sequence[int], names: Sequence[str]):
    """The per-step numerics summary, as one fp32 vector of
    ``len(SENTINEL_FIELDS) + len(names)`` entries. Pure jax — called
    INSIDE the engines' compiled step (zero extra compiles / host syncs):

    - non-finite element counts over the (unscaled, pre-clip) grads and
      the post-update params;
    - the pre-clip global grad norm (reused from ``clip_grad_norm_``'s
      computation — passed in, never recomputed);
    - param norm, the applied-update norm (computed by the caller from
      the optimizer's update vector — NOT ``new - old``, which would pin
      the pre-update tree past the update and defeat donation aliasing;
      zero on an fp16 skip step), and the update/param ratio (the
      classic LR-sanity signal);
    - per-layer-group grad-norm buckets (:func:`make_bucket_assignment`).
    """
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.loss_scaler import count_nonfinite
    from deepspeed_tpu.runtime.utils import global_norm

    grad_leaves = jax.tree.leaves(grads)
    if grad_norm is None:
        grad_norm = global_norm(grads)
    nf_g = count_nonfinite(grads)
    nf_p = count_nonfinite(new_params)
    pn = global_norm(new_params)
    un = jnp.asarray(update_norm, jnp.float32)
    ratio = un / (pn + 1e-12)

    sq = [jnp.asarray(0.0, jnp.float32) for _ in names]
    for leaf, b in zip(grad_leaves, assignment):
        sq[b] = sq[b] + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    buckets = jnp.sqrt(jnp.stack(sq)) if names else jnp.zeros((0,), jnp.float32)

    base = jnp.stack([jnp.asarray(v, jnp.float32) for v in
                      (nf_g, nf_p, grad_norm, pn, un, ratio)])
    return jnp.concatenate([base, buckets.astype(jnp.float32)])


def sentinel_to_dict(vec, names: Sequence[str]) -> Dict[str, Any]:
    """Host-side view of a sentinel vector: named scalars + a
    ``bucket_norms`` sub-dict."""
    import numpy as np
    v = np.asarray(vec, np.float32)
    out: Dict[str, Any] = {f: float(v[i]) for i, f in enumerate(SENTINEL_FIELDS)}
    off = len(SENTINEL_FIELDS)
    out["bucket_norms"] = {n: float(v[off + i]) for i, n in enumerate(names)
                           if off + i < v.size}
    return out


# ------------------------------------------------------------------ #
# memory telemetry


def host_rss_bytes() -> int:
    """Resident set size of this process (0 when unavailable)."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        pass
    try:
        import resource
        # ru_maxrss is the PEAK rss — a usable fallback; linux reports
        # kilobytes, macOS reports bytes
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return rss if sys.platform == "darwin" else rss * 1024
    except Exception:
        return 0


def sample_memory_gauges(registry=None) -> Dict[str, Any]:
    """Refresh the ``mem/*`` gauges from the accelerator memory APIs
    (``memory_stats`` per local device → HBM live/peak/limit/headroom)
    plus host RSS; returns the sampled report. Devices whose backend
    exposes no memory stats (e.g. the CPU test mesh) contribute empty
    entries and no gauges."""
    if registry is None:
        from deepspeed_tpu.monitor.metrics import get_registry
        registry = get_registry()
    report: Dict[str, Any] = {"devices": {}, "host_rss_bytes": host_rss_bytes()}
    try:
        from deepspeed_tpu.accelerator import get_accelerator
        devmap = get_accelerator().memory_report()
    except Exception:
        devmap = {}
    report["devices"] = devmap
    in_use = registry.gauge("mem/hbm_bytes_in_use",
                            "live HBM bytes per device", labelnames=("device",))
    peak = registry.gauge("mem/hbm_peak_bytes",
                          "peak HBM bytes per device", labelnames=("device",))
    limit = registry.gauge("mem/hbm_bytes_limit",
                           "allocator byte limit per device",
                           labelnames=("device",))
    headroom = registry.gauge("mem/hbm_headroom_bytes",
                              "limit - live bytes per device",
                              labelnames=("device",))
    for name, st in devmap.items():
        if not st:
            continue
        in_use.labels(device=name).set(st.get("bytes_in_use", 0))
        peak.labels(device=name).set(st.get("peak_bytes_in_use", 0))
        limit.labels(device=name).set(st.get("bytes_limit", 0))
        headroom.labels(device=name).set(st.get("headroom_bytes", 0))
    registry.gauge("mem/host_rss_bytes",
                   "host resident set size").set(report["host_rss_bytes"])
    return report


# ------------------------------------------------------------------ #
# host-side records + detectors


@dataclasses.dataclass
class StepHealth:
    """One step's host-side health record (everything a detector reads).
    ``grad_norm=None`` means "not measured" (skips the norm-based
    detectors) — a non-finite FLOAT means the grads really blew up."""
    step: int
    loss: float
    grad_norm: Optional[float] = None
    nonfinite_grads: float = 0.0
    nonfinite_params: float = 0.0
    update_ratio: float = 0.0
    skipped: bool = False               # fp16 overflow skip-update step
    loss_scale: float = 1.0
    step_time_s: float = 0.0            # bracketed compiled-step wall time
    wait_time_s: float = 0.0            # host time since the previous step
    bucket_norms: Tuple[float, ...] = ()


class HealthMonitor:
    """Ring buffer of :class:`StepHealth` + the anomaly detectors.

    Detector catalogue (all thresholds on :class:`HealthConfig`):

    - ``nonfinite`` — any non-finite grad/param element, loss, or grad
      norm on a step that was NOT an fp16 skip (skipped steps are the
      loss scaler doing its job; persistence is ``overflow``'s domain).
    - ``loss_spike`` — robust z-score of the loss against an EWMA
      mean/variance exceeds ``loss_spike_zscore`` (after warmup).
    - ``grad_explosion`` — grad norm > ``grad_norm_factor`` × its EWMA.
    - ``plateau`` — no relative loss improvement for ``plateau_steps``.
    - ``overflow`` — ``overflow_window`` CONSECUTIVE fp16 skip steps
      (re-fires every further window while the run stays stuck).
    - ``data_stall`` — wait/(wait+step) above ``data_stall_fraction`` for
      ``data_stall_steps`` consecutive steps: the input pipeline, not the
      device, is the bottleneck.
    - ``ckpt_failure`` — ``ckpt_failure_consecutive`` checkpoint saves in a
      row failed after exhausting their retry budget (flaky/full storage):
      the run is training fine but silently losing its recovery points.
      Fed by :meth:`observe_checkpoint`, not :meth:`observe_step`.

    Every firing increments ``health/anomalies{type=}``; ``action``
    escalates: ``record`` (counters only) → ``warn`` (+ rate-limited log,
    at most one per detector per ``window`` steps) → ``dump`` (+ a debug
    bundle via :meth:`dump_bundle`, at most ``dump_limit`` per run)."""

    DETECTORS = ("nonfinite", "loss_spike", "grad_explosion", "plateau",
                 "overflow", "data_stall", "ckpt_failure")
    ACTIONS = ("record", "warn", "dump")

    def __init__(self, config, registry=None, bucket_names: Sequence[str] = (),
                 snapshot_fn: Optional[Callable[[], Dict]] = None,
                 trace_export_fn: Optional[Callable[[str], str]] = None):
        if config.action not in self.ACTIONS:
            raise ValueError(f"telemetry.health.action={config.action!r} "
                             f"(expected one of {self.ACTIONS})")
        if registry is None:
            from deepspeed_tpu.monitor.metrics import get_registry
            registry = get_registry()
        self.cfg = config
        self.registry = registry
        self.bucket_names = tuple(bucket_names)
        self._snapshot_fn = snapshot_fn
        self._trace_export_fn = trace_export_fn
        self.ring: deque = deque(maxlen=max(config.window,
                                            config.keep_last_steps))
        self._n = 0
        self._ewma_loss: Optional[float] = None
        self._ewvar_loss = 0.0
        self._ewma_gnorm: Optional[float] = None
        self._best_loss = math.inf
        self._since_best = 0
        self._consec_skips = 0
        self._consec_stall = 0
        self._consec_ckpt_failures = 0
        self._ckpt_lock = threading.Lock()
        self._wait_total = 0.0
        self._busy_total = 0.0
        self._fired_counts: Dict[str, int] = {}
        self._last_warn: Dict[str, int] = {}
        self._last_dump_step: Optional[int] = None
        self._dumps = 0
        self.ensure()

    # families resolved per access (same pattern as ServingTelemetry) so a
    # registry reset between bench metrics can't orphan them

    @property
    def anomalies(self):
        return self.registry.counter(
            "health/anomalies", "detector firings by type",
            labelnames=("type",))

    @property
    def loss_ewma_gauge(self):
        return self.registry.gauge("health/loss_ewma",
                                   "EWMA of the training loss")

    @property
    def grad_norm_gauge(self):
        return self.registry.gauge("health/grad_norm",
                                   "last step's pre-clip global grad norm")

    @property
    def consec_skips_gauge(self):
        return self.registry.gauge("health/consecutive_skips",
                                   "consecutive fp16 overflow-skipped steps")

    def ensure(self) -> None:
        """Pre-create every series (incl. a zero child per detector type)
        so a clean run's snapshot shows explicit zeros, not absences."""
        for t in self.DETECTORS:
            self.anomalies.labels(type=t)
        self.loss_ewma_gauge, self.grad_norm_gauge
        self.consec_skips_gauge

    def set_bucket_names(self, names: Sequence[str]) -> None:
        """Called by the engine once the sentinel bucket layout is known
        (at trace time of the first compiled step)."""
        self.bucket_names = tuple(names)

    # ---- the per-step entry point ---- #

    def observe_step(self, rec: StepHealth) -> List[str]:
        """Feed one step record through every detector; returns the list
        of detectors that fired (and applies the configured action)."""
        cfg = self.cfg
        self._n += 1
        self.ring.append(rec)
        fired: List[str] = []
        loss_ok = math.isfinite(rec.loss)
        # grad_norm None = "not measured" (norm detectors skip); only a
        # non-finite MEASURED norm is an anomaly
        gn_known = rec.grad_norm is not None
        gn_ok = gn_known and math.isfinite(rec.grad_norm)

        # nonfinite: immediate, but NOT on fp16 skip steps (the scaler
        # already handled those; sustained skips are `overflow`)
        if not rec.skipped and (rec.nonfinite_grads > 0
                                or rec.nonfinite_params > 0
                                or not loss_ok or (gn_known and not gn_ok)):
            fired.append("nonfinite")

        # loss spike: robust z-score against EWMA mean/var
        if loss_ok:
            if self._ewma_loss is None:
                self._ewma_loss = rec.loss
            else:
                sd = math.sqrt(max(self._ewvar_loss, 0.0))
                denom = sd + 1e-8 + 1e-3 * abs(self._ewma_loss)
                if (self._n > cfg.warmup_steps
                        and (rec.loss - self._ewma_loss) / denom
                        > cfg.loss_spike_zscore):
                    fired.append("loss_spike")
                a = cfg.loss_ewma_alpha
                delta = rec.loss - self._ewma_loss
                self._ewma_loss += a * delta
                self._ewvar_loss = (1 - a) * (self._ewvar_loss + a * delta * delta)
            self.loss_ewma_gauge.set(self._ewma_loss)

        # grad-norm explosion
        if gn_ok:
            if (self._ewma_gnorm is not None and self._n > cfg.warmup_steps
                    and rec.grad_norm > cfg.grad_norm_factor
                    * max(self._ewma_gnorm, 1e-12)):
                fired.append("grad_explosion")
            a = cfg.loss_ewma_alpha
            self._ewma_gnorm = (rec.grad_norm if self._ewma_gnorm is None
                                else self._ewma_gnorm
                                + a * (rec.grad_norm - self._ewma_gnorm))
            self.grad_norm_gauge.set(rec.grad_norm)

        # plateau
        if cfg.plateau_steps and loss_ok:
            tol = cfg.plateau_rel_improvement * max(abs(self._best_loss), 1e-8)
            if not math.isfinite(self._best_loss) \
                    or rec.loss < self._best_loss - tol:
                self._best_loss = rec.loss
                self._since_best = 0
            else:
                self._since_best += 1
                if self._since_best >= cfg.plateau_steps:
                    fired.append("plateau")
                    self._since_best = 0

        # sustained fp16 overflow
        self._consec_skips = self._consec_skips + 1 if rec.skipped else 0
        self.consec_skips_gauge.set(self._consec_skips)
        if (cfg.overflow_window and self._consec_skips
                and self._consec_skips % cfg.overflow_window == 0):
            fired.append("overflow")

        # data stall (the published cumulative gauge is the engine's
        # train/data_stall_fraction — ONE series; these totals only feed
        # report() so a standalone monitor still summarizes)
        self._wait_total += max(rec.wait_time_s, 0.0)
        self._busy_total += max(rec.step_time_s, 0.0)
        per_step = rec.wait_time_s / max(rec.wait_time_s + rec.step_time_s,
                                         1e-9)
        self._consec_stall = (self._consec_stall + 1
                              if per_step > cfg.data_stall_fraction else 0)
        if (cfg.data_stall_steps and self._consec_stall
                and self._consec_stall % cfg.data_stall_steps == 0):
            fired.append("data_stall")

        if fired:
            self._act(fired, rec)
        return fired

    def observe_checkpoint(self, success: bool, step: Optional[int] = None
                           ) -> List[str]:
        """Checkpoint-writer result feed (sync saves and the async writer's
        completion callback both land here). Fires ``ckpt_failure`` after
        ``ckpt_failure_consecutive`` failures in a row, then resets so a
        persistently-broken store re-fires once per further run of K.

        Serialized under a lock: sync saves land here on the training thread
        while async results arrive on the writer thread, and the consecutive
        counter must not lose an increment or a reset between them."""
        with self._ckpt_lock:
            if success:
                self._consec_ckpt_failures = 0
                return []
            self._consec_ckpt_failures += 1
            k = self.cfg.ckpt_failure_consecutive
            if not k or self._consec_ckpt_failures < k:
                return []
            self._consec_ckpt_failures = 0
            self._fired_counts["ckpt_failure"] = \
                self._fired_counts.get("ckpt_failure", 0) + 1
        self.anomalies.labels(type="ckpt_failure").inc()
        if self.cfg.action != "record":
            at = self._n if step is None else int(step)
            if at - self._last_warn.get("ckpt_failure", -10**12) >= self.cfg.window:
                self._last_warn["ckpt_failure"] = at
                logger.warning(
                    f"health: ckpt_failure — {k} consecutive checkpoint "
                    f"saves failed (storage flaky or full); the run keeps "
                    f"training but is NOT gaining recovery points. Next "
                    f"warning in {self.cfg.window} steps.")
        return ["ckpt_failure"]

    # ---- actions ---- #

    def _act(self, fired: List[str], rec: StepHealth) -> None:
        cfg = self.cfg
        for t in fired:
            self._fired_counts[t] = self._fired_counts.get(t, 0) + 1
            self.anomalies.labels(type=t).inc()
        if cfg.action == "record":
            return
        to_warn = [t for t in fired
                   if rec.step - self._last_warn.get(t, -10**12) >= cfg.window]
        if to_warn:
            for t in to_warn:
                self._last_warn[t] = rec.step
            gn_s = "n/a" if rec.grad_norm is None else f"{rec.grad_norm:.4g}"
            logger.warning(
                f"health: {'+'.join(to_warn)} at step {rec.step} "
                f"(loss={rec.loss:.4g}, grad_norm={gn_s}, "
                f"nonfinite_grads={rec.nonfinite_grads:.0f}, "
                f"skipped={rec.skipped}, loss_scale={rec.loss_scale:.4g}, "
                f"wait/step={rec.wait_time_s * 1e3:.1f}/"
                f"{rec.step_time_s * 1e3:.1f}ms). "
                f"Next warning for these detectors in {cfg.window} steps.")
        if cfg.action == "dump" and self._dumps < cfg.dump_limit and \
                (self._last_dump_step is None
                 or rec.step - self._last_dump_step >= cfg.window):
            try:
                self.dump_bundle(fired, rec)
            except Exception as e:  # diagnostics must never kill the step
                logger.warning(f"health: debug-bundle dump failed: {e}")

    def dump_bundle(self, fired: Sequence[str], rec: StepHealth) -> str:
        """Write a debug bundle directory: ``report.json`` (what fired and
        the triggering record), ``steps.jsonl`` (last-K ring records),
        ``telemetry.json`` (full registry snapshot) and ``trace.json``
        (chrome trace) when the engine provided exporters. Returns the
        bundle path."""
        path = os.path.join(self.cfg.dump_dir,
                            f"step{rec.step:08d}_{'+'.join(fired)}")
        os.makedirs(path, exist_ok=True)
        report = {"ts": time.time(), "step": rec.step, "fired": list(fired),
                  "record": dataclasses.asdict(rec),
                  "anomaly_counts": dict(self._fired_counts),
                  "bucket_names": list(self.bucket_names),
                  "config": _config_dict(self.cfg)}
        with open(os.path.join(path, "report.json"), "w") as f:
            json.dump(report, f, indent=2)
        with open(os.path.join(path, "steps.jsonl"), "w") as f:
            for r in list(self.ring)[-self.cfg.keep_last_steps:]:
                f.write(json.dumps(dataclasses.asdict(r)) + "\n")
        if self._snapshot_fn is not None:
            try:
                with open(os.path.join(path, "telemetry.json"), "w") as f:
                    json.dump(self._snapshot_fn(), f, indent=2)
            except Exception as e:
                logger.warning(f"health: telemetry snapshot in bundle failed: {e}")
        if self._trace_export_fn is not None:
            try:
                self._trace_export_fn(os.path.join(path, "trace.json"))
            except Exception as e:
                logger.warning(f"health: trace export in bundle failed: {e}")
        # the flight-recorder tail (events.jsonl): the causal timeline —
        # checkpoint phases, fp16 skips, serving lifecycle — leading into
        # the anomaly (present when telemetry.events is on)
        from deepspeed_tpu.monitor.events import dump_events_jsonl
        dump_events_jsonl(path)
        self._dumps += 1
        self._last_dump_step = rec.step
        logger.warning(f"health: debug bundle written to {path} "
                       f"({self._dumps}/{self.cfg.dump_limit})")
        return path

    # ---- reporting ---- #

    def report(self) -> Dict[str, Any]:
        """One-call health summary: detector counts, EWMAs, stall
        fraction, the last step record, and a fresh memory sample."""
        tot = self._wait_total + self._busy_total
        return {
            "enabled": True,
            "steps": self._n,
            "anomalies": {t: self._fired_counts.get(t, 0)
                          for t in self.DETECTORS},
            "ewma_loss": self._ewma_loss,
            "ewma_grad_norm": self._ewma_gnorm,
            "consecutive_skips": self._consec_skips,
            "data_stall_fraction": (self._wait_total / tot) if tot > 0 else 0.0,
            "last": dataclasses.asdict(self.ring[-1]) if self.ring else None,
            "bucket_names": list(self.bucket_names),
            "dumps": self._dumps,
            "memory": sample_memory_gauges(self.registry),
        }


def _config_dict(cfg) -> Dict:
    for attr in ("model_dump", "dict"):
        fn = getattr(cfg, attr, None)
        if callable(fn):
            try:
                return {k: v for k, v in fn().items()
                        if isinstance(v, (int, float, str, bool, type(None)))}
            except Exception:
                pass
    return {}


# ------------------------------------------------------------------ #
# the `health` CLI: tail the JSONL sink, render one screen


def read_last_snapshots(path: str, n: int = 2,
                        tail_bytes: int = 1 << 19) -> List[Dict]:
    """Last ``n`` parseable JSONL records of ``path`` (bounded tail read,
    so multi-GB sinks tail in O(tail_bytes)). Empty list when the file is
    missing or holds no valid records."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - tail_bytes))
            chunk = f.read()
    except OSError:
        return []
    if size > tail_bytes:
        # drop the (possibly mid-record) first line of the tail window
        chunk = chunk.split(b"\n", 1)[-1]
    recs: List[Dict] = []
    for line in chunk.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            recs.append(rec)
    return recs[-n:]


def labeled_series(section: Dict, name: str) -> Dict[str, float]:
    """``{label_value: value}`` for every ``name{k="v"}`` series in a
    snapshot section (shared by the CLI renderer and bench.py's blob)."""
    out = {}
    prefix = name + "{"
    for k, v in section.items():
        if k.startswith(prefix) and k.endswith("}"):
            inner = k[len(prefix):-1]
            label = inner.split("=", 1)[-1].strip('"') if "=" in inner else inner
            out[label] = v
    return out


def multilabel_series(section: Dict, name: str):
    """``[({label: value}, metric_value)]`` for every ``name{k="v",...}``
    series — the multi-label sibling of :func:`labeled_series` (e.g.
    ``slo/burn_rate{objective=,window=}``). Values containing commas or
    quotes are beyond this tail parser and are skipped, matching the
    snapshot keys the registry actually writes."""
    out = []
    prefix = name + "{"
    for k, v in section.items():
        if not (k.startswith(prefix) and k.endswith("}")):
            continue
        labels = {}
        ok = True
        for part in k[len(prefix):-1].split(","):
            kk, eq, vv = part.partition("=")
            if not eq:
                ok = False
                break
            labels[kk.strip()] = vv.strip().strip('"')
        if ok:
            out.append((labels, v))
    return out


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TB"


def _fmt(v: Optional[float], spec: str = ".3g", missing: str = "-") -> str:
    if v is None:
        return missing
    try:
        return format(float(v), spec)
    except (TypeError, ValueError):
        return missing


def render_health_table(rec: Dict, prev: Optional[Dict] = None) -> str:
    """One-screen status table from a telemetry JSONL record (a registry
    snapshot line). ``prev`` (the previous record) sharpens the step-rate
    and loss-trend readouts. Thin wrapper: the metric-key extraction lives
    ONCE in :func:`health_summary`; this renders its dict (so the table
    and ``dscli health --json`` can never drift apart)."""
    return render_summary_table(health_summary(rec, prev))


def render_summary_table(s: Dict[str, Any]) -> str:
    """Render a :func:`health_summary` dict as the one-screen table.
    Sections absent from the summary are omitted."""
    lines: List[str] = []
    step = s.get("step")
    ts = s.get("ts")
    when = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts)) if ts else ""
    lines.append(f"deepspeed_tpu health — step {step if step is not None else '?'}"
                 f"  {when}".rstrip())
    lines.append("-" * 64)

    # ---- train throughput ---- #
    train = s.get("train")
    if train is not None:
        st = train.get("step_time_ms")
        rate = train.get("steps_per_sec")
        if rate is None and st and st.get("mean"):
            rate = 1000.0 / st["mean"]
        parts = [f"steps {train['steps']}"]
        if st:
            parts.append(f"step {st['mean']:.1f}ms (p50 {st['p50']:.1f}, "
                         f"p99 {st['p99']:.1f})")
        if rate:
            parts.append(f"rate {rate:.2f}/s")
        if "tokens_per_sec" in train:
            parts.append(f"tok/s {train['tokens_per_sec']:,.0f}")
        if "mfu" in train:
            parts.append(f"MFU {train['mfu']:.3f}")
        lines.append("train    " + "   ".join(parts))

    # ---- loss / grad ---- #
    loss = s.get("loss")
    if loss is not None:
        parts = []
        if "loss" in loss:
            trend = ""
            if "delta" in loss:
                d = loss["delta"]
                trend = " ↓" if d < 0 else (" ↑" if d > 0 else " →")
            parts.append(f"loss {_fmt(loss['loss'], '.4g')}{trend}")
        if "ewma" in loss:
            parts.append(f"ewma {_fmt(loss['ewma'], '.4g')}")
        gn = loss.get("grad_norm_hist")
        if gn:
            cur = loss.get("grad_norm")
            cur_s = f"{_fmt(cur)} " if cur is not None else ""
            parts.append(f"grad_norm {cur_s}(p50 {_fmt(gn['p50'])}, "
                         f"p99 {_fmt(gn['p99'])})")
        if parts:
            lines.append("loss     " + "   ".join(parts))

    # ---- fp16 / skips ---- #
    fp16 = s.get("fp16")
    if fp16 is not None:
        parts = []
        if "loss_scale" in fp16:
            parts.append(f"loss_scale {_fmt(fp16['loss_scale'], '.6g')}")
        if "skipped_steps" in fp16:
            # denominator: the snapshot's step stamp (advances on both the
            # train_batch and trio paths; the train/steps counter is
            # train_batch-only and would render "N/0" for trio runs)
            total = s.get("step") or (s.get("train") or {}).get("steps", 0)
            parts.append(f"skipped {int(fp16['skipped_steps'])}"
                         f"/{int(total)} steps")
        if "consecutive_skips" in fp16:
            parts.append(f"consecutive {int(fp16['consecutive_skips'])}")
        lines.append("fp16     " + "   ".join(parts))

    # ---- anomalies / stall ---- #
    anoms = s.get("anomalies")
    stall = s.get("data_stall_fraction")
    if anoms is not None or stall is not None:
        nonzero = {k: v for k, v in sorted((anoms or {}).items()) if v}
        a_s = ", ".join(f"{k}:{v}" for k, v in nonzero.items()) \
            if nonzero else ("none" if anoms else "-")
        parts = [f"anomalies {a_s}"]
        if stall is not None:
            parts.append(f"data-stall {stall:.1%}")
        lines.append("health   " + "   ".join(parts))

    # ---- memory ---- #
    mem = s.get("memory")
    if mem is not None:
        used = mem.get("hbm_bytes_in_use") or {}
        lim = mem.get("hbm_bytes_limit") or {}
        peak = mem.get("hbm_peak_bytes") or {}
        head = mem.get("hbm_headroom_bytes") or {}
        rss = mem.get("host_rss_bytes")
        parts = []
        if used:
            mx = max(used, key=used.get)
            u, l2, p = used[mx], lim.get(mx, 0), peak.get(mx, 0)
            line = f"HBM {_fmt_bytes(u)}"
            if l2:
                line += f"/{_fmt_bytes(l2)}"
            if p:
                line += f" (peak {_fmt_bytes(p)}"
                if head.get(mx) is not None:
                    line += f", headroom {_fmt_bytes(head[mx])}"
                line += ")"
            parts.append(line + f" [{mx}]")
        if rss:
            parts.append(f"host RSS {_fmt_bytes(rss)}")
        if parts:
            lines.append("memory   " + "   ".join(parts))

    # ---- serving ---- #
    serving = s.get("serving")
    if serving is not None and ("ttft_ms" in serving
                                or "queue_depth" in serving):
        parts = []
        ttft = serving.get("ttft_ms")
        if ttft:
            parts.append(f"TTFT p50 {ttft['p50']:.1f}ms p99 {ttft['p99']:.1f}ms")
        tpot = serving.get("tpot_ms")
        if tpot:
            parts.append(f"TPOT p50 {tpot['p50']:.2f}ms")
        qw = serving.get("queue_wait_ms")
        if qw:
            # submit->admit wait: the async loop's queueing-delay readout
            parts.append(f"wait p50 {qw['p50']:.1f}ms")
        if "queue_depth" in serving:
            parts.append(f"queue {int(serving['queue_depth'])}")
        if "running" in serving:
            parts.append(f"running {int(serving['running'])}")
        if "kv_block_utilization" in serving:
            line = f"KV util {serving['kv_block_utilization']:.2f}"
            if "kv_blocks_free" in serving:
                line += f" free {int(serving['kv_blocks_free'])}"
            if "kv_fragmentation" in serving:
                line += f" frag {serving['kv_fragmentation']:.2f}"
            if serving.get("tp", 1) > 1:
                # head-sharded pools: the block counts above are GLOBAL
                # per slice, not per shard — annotate so a tp pool is not
                # misread as 1/tp of the memory
                line += f" [tp={int(serving['tp'])}]"
            parts.append(line)
        lookups = serving.get("prefix_cache_lookups", 0)
        if lookups:
            hits = serving.get("prefix_cache_hits", 0)
            line = f"cache {int(hits)}/{int(lookups)} ({hits / lookups:.0%})"
            toks = serving.get("prefix_cache_hit_tokens", 0)
            if toks:
                line += f" +{int(toks)}tok"
            if "cold_blocks" in serving:
                line += f" cold {int(serving['cold_blocks'])}"
            parts.append(line)
        spills = serving.get("kv_spills", 0)
        fh = serving.get("kv_fetch_hits", 0)
        if spills or fh or serving.get("kv_host_blocks"):
            # tiered KV cache: host-tier hits / spills (the re-hit rate of
            # demoted content) + what the host pool currently holds
            line = f"host {int(fh)}H/{int(spills)}S"
            if spills:
                line += f" ({fh / spills:.0%})"
            ft = serving.get("kv_fetch_tokens", 0)
            if ft:
                line += f" +{int(ft)}tok"
            if "kv_host_blocks" in serving:
                line += f" {int(serving['kv_host_blocks'])}blk"
                if serving.get("kv_host_bytes"):
                    line += f"/{_fmt_bytes(serving['kv_host_bytes'])}"
            if serving.get("kv_host_errors"):
                line += f" err {int(serving['kv_host_errors'])}"
            parts.append(line)
        prop = serving.get("spec_proposed_tokens", 0)
        if prop:
            # speculation on: accepted/proposed candidates + rate
            acc = serving.get("spec_accepted_tokens", 0)
            line = f"spec {int(acc)}/{int(prop)} ({acc / prop:.0%})"
            rb = serving.get("spec_rollbacks", 0)
            if rb:
                line += f" rb {int(rb)}"
            parts.append(line)
        if "preemptions" in serving:
            parts.append(f"preempt {int(serving['preemptions'])}")
        if serving.get("rejected_requests"):
            # admission control is turning traffic away: pool pressure
            parts.append(f"rejected {int(serving['rejected_requests'])}")
        faults = serving.get("step_faults") or {}
        n_faults = sum(faults.values())
        restarts = serving.get("engine_restarts", 0)
        retries = serving.get("request_retries", 0)
        if n_faults or restarts or retries:
            # the fault-containment story: contained step faults, how many
            # retried per-request, how many cost an engine rebuild
            line = f"faults {int(n_faults)}"
            if retries:
                line += f" retry {int(retries)}"
            if restarts:
                line += f" restart {int(restarts)}"
            parts.append(line)
        if serving.get("timeouts"):
            parts.append(f"timeout {int(serving['timeouts'])}")
        if serving.get("shed_requests"):
            # load shedding is dropping queued work: sustained = capacity
            parts.append(f"shed {int(serving['shed_requests'])}")
        if parts:
            lines.append("serving  " + "   ".join(parts))

    # ---- request phase ledger (serving/phase_ms, anatomy order) ---- #
    ph = (serving or {}).get("phases") or {}
    if ph:
        order = ["intake", "queue", "prefill", "prefill_chunk", "cow",
                 "fetch", "spill", "handoff", "verify", "decode"]
        pparts = []
        for p in order + sorted(set(ph) - set(order)):
            reps = ph.get(p)
            if not reps:
                continue
            n = sum(int(v.get("count", 0)) for v in reps.values())
            tot = sum(float(v.get("sum", 0.0)) for v in reps.values())
            p99 = max(float(v.get("p99", 0.0)) for v in reps.values())
            # count-weighted fleet mean / worst-replica p99
            pparts.append(f"{p} {tot / max(n, 1):.1f}/{p99:.1f}ms")
        if pparts:
            lines.append("phases   " + "  ".join(pparts) + "  [mean/p99]")
    wt = (serving or {}).get("wasted_tokens") or {}
    if wt:
        wparts = [f"{cause} {int(sum(reps.values()))}"
                  for cause, reps in sorted(wt.items())
                  if sum(reps.values())]
        if wparts:
            lines.append("wasted   " + "   ".join(wparts) + " tok")

    # ---- replica router (dp serving axis) ---- #
    rep = s.get("replicas")
    if rep is not None:
        parts = []
        names = sorted(set(rep.get("requests", {}))
                       | set(rep.get("healthy", {}))
                       | set(rep.get("queue_depth", {}))
                       | set(rep.get("drained_requests", {})))
        for name in names:
            ok = rep.get("healthy", {}).get(name)
            # DOWN = breaker-tripped/stopped/draining: the router is
            # steering its traffic (and drained its in-flight) elsewhere
            line = (f"{name} {'up' if ok is None or ok else 'DOWN'}"
                    f" q{int(rep.get('queue_depth', {}).get(name, 0))}"
                    f" {int(rep.get('requests', {}).get(name, 0))}req")
            drained = rep.get("drained_requests", {}).get(name, 0)
            if drained:
                line += f" drained {int(drained)}"
            parts.append(line)
        if rep.get("handoffs"):
            # disaggregated prefill->decode transfers via the host tier
            parts.append(f"handoff {int(rep['handoffs'])}")
        if parts:
            lines.append("replicas " + "   ".join(parts))

    # ---- SLO burn rates ---- #
    slo = s.get("slo")
    if slo is not None:
        parts = []
        burn = slo.get("burn_rate") or {}
        fired = slo.get("breaches") or {}
        for obj in sorted(set(burn) | set(fired)):
            wins = burn.get(obj, {})
            # longest window first, matching the (long, short) config order
            ws = " ".join(
                f"{w}t {wins[w]:.2f}x"
                for w in sorted(wins, key=lambda x: -int(x)
                                if str(x).lstrip("-").isdigit() else 0))
            line = f"{obj} " + (f"burn {ws}" if ws else "burn -")
            n = int(fired.get(obj, 0))
            if n:
                line += f" BREACH x{n}"
            parts.append(line)
        if parts:
            lines.append("slo      " + "   ".join(parts))

    # ---- adaptive controller pane ---- #
    ctl = s.get("ctl")
    if ctl is not None:
        parts = []
        for name, kv in (ctl.get("knobs") or {}).items():
            v, b = kv.get("value"), kv.get("baseline")
            seg = f"{name} {int(v)}"
            if b is not None and v != b:
                # tightened away from config: show the baseline it left
                seg += f"<cfg {int(b)}>"
            parts.append(seg)
        if parts:
            lines.append("ctl      " + "   ".join(parts))
        info = []
        la = ctl.get("last_action")
        if la:
            info.append(f"last {la.get('direction')} {la.get('knob')} "
                        f"@t{la.get('tick')} [{la.get('reason')}]")
        n = ctl.get("actions_in_window")
        if n:
            info.append(f"{int(n)} action(s) this window")
        if info:
            lines.append("         " + "   ".join(info))

    # ---- flight-recorder ring loss ---- #
    ev = s.get("events")
    if ev and ev.get("dropped"):
        lines.append(f"events   dropped {int(ev['dropped'])} "
                     f"(ring {int(ev.get('capacity', 0))}) — trace tail "
                     "truncated")

    if len(lines) == 2:
        lines.append("(no recognized series in this snapshot)")
    return "\n".join(lines)


def health_summary(rec: Dict, prev: Optional[Dict] = None) -> Dict[str, Any]:
    """The machine-readable form of :func:`render_health_table`: the same
    snapshot-derived values the table shows, as a nested dict (consumed by
    ``dscli health --json`` so CI and scripts never screen-scrape the
    table). Sections with no data are omitted; the raw snapshot rides
    along under ``"snapshot"``."""
    g = rec.get("gauges", {}) or {}
    c = rec.get("counters", {}) or {}
    h = rec.get("histograms", {}) or {}
    out: Dict[str, Any] = {"step": rec.get("step"), "ts": rec.get("ts")}

    train: Dict[str, Any] = {}
    st = h.get("train/step_time_ms")
    if st or "train/steps" in c:
        train["steps"] = int(c.get("train/steps", 0))
        if st:
            train["step_time_ms"] = st
        ts = rec.get("ts")
        if prev and ts and prev.get("ts") and "train/steps" in c \
                and "train/steps" in (prev.get("counters") or {}):
            dt = ts - prev["ts"]
            dsteps = c["train/steps"] - prev["counters"]["train/steps"]
            if dt > 0 and dsteps > 0:
                train["steps_per_sec"] = dsteps / dt
        for key, name in (("train/tokens_per_sec", "tokens_per_sec"),
                          ("train/mfu", "mfu")):
            if key in g:
                train[name] = g[key]
    if train:
        out["train"] = train

    loss: Dict[str, Any] = {}
    for key, name in (("train/loss", "loss"), ("health/loss_ewma", "ewma"),
                      ("health/grad_norm", "grad_norm")):
        if key in g:
            loss[name] = g[key]
    pg = (prev or {}).get("gauges") or {}
    if "train/loss" in g and "train/loss" in pg:
        loss["delta"] = g["train/loss"] - pg["train/loss"]   # trend
    if h.get("train/grad_norm", {}).get("count"):
        loss["grad_norm_hist"] = h["train/grad_norm"]
    if loss:
        out["loss"] = loss

    fp16: Dict[str, Any] = {}
    for key, name in (("train/loss_scale", "loss_scale"),
                      ("train/skipped_steps", "skipped_steps"),
                      ("health/consecutive_skips", "consecutive_skips")):
        if key in g:
            fp16[name] = g[key]
    if fp16:
        out["fp16"] = fp16

    anoms = labeled_series(c, "health/anomalies")
    if anoms:
        out["anomalies"] = {k: int(v) for k, v in sorted(anoms.items())}
    if "train/data_stall_fraction" in g:
        out["data_stall_fraction"] = g["train/data_stall_fraction"]

    mem: Dict[str, Any] = {}
    for key, name in (("mem/hbm_bytes_in_use", "hbm_bytes_in_use"),
                      ("mem/hbm_peak_bytes", "hbm_peak_bytes"),
                      ("mem/hbm_bytes_limit", "hbm_bytes_limit"),
                      ("mem/hbm_headroom_bytes", "hbm_headroom_bytes")):
        series = labeled_series(g, key)
        if series:
            mem[name] = series
    if "mem/host_rss_bytes" in g:
        mem["host_rss_bytes"] = g["mem/host_rss_bytes"]
    if mem:
        out["memory"] = mem

    serving: Dict[str, Any] = {}
    for key, name in (("serving/ttft_ms", "ttft_ms"),
                      ("serving/tpot_ms", "tpot_ms"),
                      ("serving/queue_wait_ms", "queue_wait_ms")):
        if h.get(key, {}).get("count"):
            serving[name] = h[key]
    for key, name in (("serving/queue_depth", "queue_depth"),
                      ("serving/running", "running"),
                      ("serving/kv_block_utilization", "kv_block_utilization"),
                      ("serving/kv_blocks_free", "kv_blocks_free"),
                      ("serving/kv_fragmentation", "kv_fragmentation"),
                      ("serving/cold_blocks", "cold_blocks"),
                      ("serving/kv_host_blocks", "kv_host_blocks"),
                      ("serving/kv_host_bytes", "kv_host_bytes"),
                      ("serving/tp", "tp"),
                      ("serving/spec_acceptance_rate",
                       "spec_acceptance_rate")):
        if key in g:
            serving[name] = g[key]
    for key, name in (("serving/prefix_cache_lookups", "prefix_cache_lookups"),
                      ("serving/prefix_cache_hits", "prefix_cache_hits"),
                      ("serving/prefix_cache_hit_tokens",
                       "prefix_cache_hit_tokens"),
                      ("serving/spec_proposed_tokens", "spec_proposed_tokens"),
                      ("serving/spec_accepted_tokens", "spec_accepted_tokens"),
                      ("serving/spec_rollbacks", "spec_rollbacks"),
                      ("serving/kv_spills", "kv_spills"),
                      ("serving/kv_fetch_hits", "kv_fetch_hits"),
                      ("serving/kv_fetch_tokens", "kv_fetch_tokens"),
                      ("serving/kv_host_errors", "kv_host_errors"),
                      ("serving/preemptions", "preemptions"),
                      ("serving/rejected_requests", "rejected_requests"),
                      ("serving/engine_restarts", "engine_restarts"),
                      ("serving/request_retries", "request_retries"),
                      ("serving/timeouts", "timeouts"),
                      ("serving/shed_requests", "shed_requests")):
        if key in c:
            serving[name] = c[key]
    faults = labeled_series(c, "serving/step_faults")
    if faults:
        # contained engine-step exceptions by dispatch site (serving.fault)
        serving["step_faults"] = {k: int(v) for k, v in sorted(faults.items())}
    # request latency anatomy: {phase: {replica: histogram summary}} —
    # the phase ledger the trace/top/scrape surfaces all render from
    phases: Dict[str, Dict[str, Any]] = {}
    for labels, v in multilabel_series(h, "serving/phase_ms"):
        p, rep = labels.get("phase"), labels.get("replica")
        if p is not None and rep is not None and (v or {}).get("count"):
            phases.setdefault(p, {})[rep] = v
    if phases:
        serving["phases"] = phases
    # wasted-work accounting: {cause: {replica: tokens}}
    wasted: Dict[str, Dict[str, int]] = {}
    for labels, v in multilabel_series(c, "serving/wasted_tokens"):
        cause, rep = labels.get("cause"), labels.get("replica")
        if cause is not None and rep is not None:
            wasted.setdefault(cause, {})[rep] = int(v)
    if wasted:
        serving["wasted_tokens"] = wasted
    if serving:
        out["serving"] = serving

    # ---- replica router (dp serving axis, inference/router.py) ---- #
    replicas: Dict[str, Any] = {}
    for key, name in (("router/requests", "requests"),
                      ("router/drained_requests", "drained_requests")):
        series = labeled_series(c, key)
        if series:
            replicas[name] = {k: int(v) for k, v in sorted(series.items())}
    for key, name in (("router/healthy", "healthy"),
                      ("router/queue_depth", "queue_depth")):
        series = labeled_series(g, key)
        if series:
            replicas[name] = {k: v for k, v in sorted(series.items())}
    if "router/handoffs" in c:
        replicas["handoffs"] = int(c["router/handoffs"])
    if replicas:
        out["replicas"] = replicas

    # ---- SLO burn rates / breaches (monitor/slo.py) ---- #
    slo: Dict[str, Any] = {}
    breaches = labeled_series(c, "slo/breaches")
    if breaches:
        slo["breaches"] = {k: int(v) for k, v in sorted(breaches.items())}
    burn: Dict[str, Dict[str, float]] = {}
    for labels, v in multilabel_series(g, "slo/burn_rate"):
        obj = labels.get("objective")
        win = labels.get("window")
        if obj is not None and win is not None:
            burn.setdefault(obj, {})[win] = v
    if burn:
        slo["burn_rate"] = burn
    if slo:
        out["slo"] = slo

    # ---- adaptive controller posture (monitor/controller.py) ---- #
    ctl: Dict[str, Any] = {}
    knobs = labeled_series(g, "ctl/knob")
    if knobs:
        base = labeled_series(g, "ctl/knob_baseline")
        ctl["knobs"] = {k: {"value": v, "baseline": base.get(k)}
                        for k, v in sorted(knobs.items())}
    acts: Dict[str, Dict[str, int]] = {}
    for labels, v in multilabel_series(c, "ctl/actions"):
        kn, d = labels.get("knob"), labels.get("direction")
        if kn is not None and d is not None and v:
            acts.setdefault(kn, {})[d] = int(v)
    if acts:
        ctl["actions"] = acts
    pc = (prev or {}).get("counters") or {}
    if prev is not None and knobs:
        # movements since the previous snapshot: the pane's
        # actions-per-window readout (0 = posture held)
        now = sum(v for k, v in c.items() if k.startswith("ctl/actions{"))
        before = sum(v for k, v in pc.items()
                     if k.startswith("ctl/actions{"))
        ctl["actions_in_window"] = int(now - before)
    last = None
    for labels, v in multilabel_series(g, "ctl/last_action"):
        if last is None or v > last[0]:
            last = (v, labels)
    if last is not None:
        ctl["last_action"] = {"tick": int(last[0]),
                              "knob": last[1].get("knob"),
                              "direction": last[1].get("direction"),
                              "reason": last[1].get("reason")}
    if ctl:
        out["ctl"] = ctl

    # ---- flight-recorder ring loss (events/dropped gauges) ---- #
    if "events/dropped" in g:
        out["events"] = {"dropped": int(g["events/dropped"]),
                         "capacity": int(g.get("events/capacity", 0))}

    out["snapshot"] = rec
    return out


def health_cli(argv: Optional[List[str]] = None) -> int:
    """``dscli health <telemetry.jsonl>`` — live one-screen status table
    tailing the JSONL telemetry sink (``--once`` renders a single table
    and exits; ``--json`` prints the latest snapshot's summary as JSON
    and exits; default follows at ``--interval`` seconds)."""
    import argparse
    parser = argparse.ArgumentParser(
        prog="dscli health",
        description="live training/serving health screen over a JSONL "
                    "telemetry sink (telemetry.jsonl_path)")
    parser.add_argument("path", help="JSONL telemetry sink to tail")
    parser.add_argument("--once", action="store_true",
                        help="render one table and exit (no follow loop)")
    parser.add_argument("--json", action="store_true",
                        help="print the latest snapshot summary as JSON "
                             "and exit (machine-readable --once)")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh period in seconds (default 2)")
    args = parser.parse_args(argv)

    if args.once or args.json:
        recs = read_last_snapshots(args.path, 2)
        if not recs:
            if args.json:
                print(json.dumps({"error": "no telemetry records",
                                  "path": args.path}))
            else:
                print(f"health: no telemetry records in {args.path}")
            return 1
        prev = recs[-2] if len(recs) > 1 else None
        if args.json:
            print(json.dumps(health_summary(recs[-1], prev)))
        else:
            print(render_health_table(recs[-1], prev))
        return 0
    try:
        while True:
            recs = read_last_snapshots(args.path, 2)
            body = (render_health_table(recs[-1],
                                        recs[-2] if len(recs) > 1 else None)
                    if recs else f"health: waiting for records in {args.path} ...")
            sys.stdout.write("\033[2J\033[H" + body + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
