"""Structured per-step tracing + the compile watchdog.

Two instruments living next to the metrics registry:

- :class:`StepTracer` — host-side spans (``with tracer.span("fwd")``)
  that ALSO push/pop the accelerator's profiler ``TraceAnnotation`` (so
  the same names show up in an ``xprof``/TensorBoard device trace) and are
  exportable as chrome-trace JSON (``chrome://tracing`` / Perfetto).

- :class:`CompileWatchdog` — wraps the framework's ``jax.jit`` entry
  points. Every call through a watched function checks the jit cache size
  before/after: growth means XLA compiled a new program, and the watchdog
  records the compile wall-time (the triggering call's wall time — an
  upper bound including the first execution), the abstract input shapes
  that caused it, and bumps ``compile/count``. Crossing the storm
  threshold logs a loud warning: a recompilation storm (shape churn,
  weak_type flapping, python-scalar leakage) is the classic silent TPU
  perf killer — the program "works" while every step pays seconds of
  XLA compile time.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from deepspeed_tpu.monitor.metrics import MetricsRegistry, get_registry
from deepspeed_tpu.utils.logging import logger

# ------------------------------------------------------------------ #
# step tracer


class StepTracer:
    """Span recorder: chrome-trace "complete" (ph=X) events, bounded."""

    #: chrome-trace pid of the host-span track group (the serving trace
    #: renderer uses 1/2, so merged host+serving documents never collide)
    PID = 0

    def __init__(self, max_events: int = 100_000, use_accelerator: bool = True):
        self.max_events = max_events
        self.use_accelerator = use_accelerator
        self._events: List[Dict[str, Any]] = []
        self._thread_names: Dict[int, str] = {}   # tid -> thread name
        self._lock = threading.Lock()
        self._dropped = 0
        self._warned_drop = False
        self._t0 = time.perf_counter()

    @property
    def dropped(self) -> int:
        """Spans discarded at ``max_events`` — nonzero means every
        export from this tracer is TRUNCATED, not complete."""
        with self._lock:
            return self._dropped

    def _accelerator(self):
        if not self.use_accelerator:
            return None
        try:
            from deepspeed_tpu.accelerator import get_accelerator
            return get_accelerator()
        except Exception:
            return None

    @contextmanager
    def span(self, name: str, **args):
        """Host span around the with-block; mirrored onto the device
        profiler timeline via ``range_push``/``range_pop``."""
        acc = self._accelerator()
        if acc is not None:
            acc.range_push(name)
        start = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - start
            if acc is not None:
                acc.range_pop()
            self.add_event(name, start, dur, args or None)

    def add_event(self, name: str, start_s: float, dur_s: float,
                  args: Optional[Dict] = None) -> None:
        tid = threading.get_ident() % 2**31
        ev = {"name": name, "ph": "X", "pid": self.PID, "tid": tid,
              "ts": (start_s - self._t0) * 1e6, "dur": dur_s * 1e6}
        if args:
            ev["args"] = {k: str(v) for k, v in args.items()}
        with self._lock:
            if tid not in self._thread_names:
                # captured at record time: export may run from another
                # thread, by which point this one may be gone
                self._thread_names[tid] = threading.current_thread().name
            if len(self._events) >= self.max_events:
                # never silent: the registry counter makes truncation
                # scrapeable, the once-per-run warning makes it loud
                self._dropped += 1
                warn_now = not self._warned_drop
                self._warned_drop = True
                try:
                    get_registry().counter(
                        "trace/dropped_events",
                        "StepTracer spans discarded at max_events — a "
                        "nonzero value means exported chrome traces are "
                        "truncated, not complete").inc()
                except Exception:
                    pass     # a broken registry must never kill a span
                if warn_now:
                    logger.warning(
                        f"StepTracer hit max_events={self.max_events}; "
                        "further spans are dropped (trace/dropped_events "
                        "counts them) — exported traces are truncated")
                return
            self._events.append(ev)

    @property
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._thread_names.clear()
            self._dropped = 0
            self._warned_drop = False    # a fresh run warns afresh

    def export_chrome_trace(self, path: str) -> str:
        """Write the recorded spans as chrome-trace JSON; returns path.
        Process/thread metadata events name the tracks (Perfetto shows
        "deepspeed_tpu host / MainThread" instead of bare integers — and
        a merged host+serving document keeps its groups tellable)."""
        import json
        import os
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
            names = dict(self._thread_names)
        meta: List[Dict[str, Any]] = [
            {"ph": "M", "name": "process_name", "pid": self.PID,
             "args": {"name": "deepspeed_tpu host"}}]
        for tid in sorted(names):
            meta.append({"ph": "M", "name": "thread_name", "pid": self.PID,
                         "tid": tid, "args": {"name": names[tid]}})
        doc = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
        if dropped:
            doc["otherData"] = {"dropped_events": dropped}
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path


# ------------------------------------------------------------------ #
# on-demand device profiling: a jax.profiler capture window


class ProfileWindow:
    """Bounded ``jax.profiler`` capture armed by config
    (``telemetry.profile: {start_step, num_steps, dir}``) or
    programmatically (``engine.profile(steps=N)``): the engine calls
    :meth:`tick` once per ``train_batch`` dispatch (one None/flag check
    when nothing is armed) and the window starts/stops the device trace
    around the requested steps. Steps are counted as tick calls in THIS
    process (no device sync to read a global step). While capturing,
    :meth:`annotate` pushes the accelerator ``TraceAnnotation`` under the
    same names the :class:`StepTracer` spans use, so host spans line up
    with the device timeline in xprof/TensorBoard."""

    def __init__(self, log_dir: str = "ds_profile", start_step: int = 0,
                 num_steps: int = 0):
        self.log_dir = log_dir
        self._lock = threading.Lock()
        self._step = 0             # tick calls seen
        self._stop_at: Optional[int] = None
        self.active = False
        self.captures = 0
        self._armed: Optional[Dict[str, Any]] = None
        if num_steps > 0:
            self._armed = {"start": max(int(start_step), 0),
                           "steps": int(num_steps), "dir": log_dir}

    def arm(self, steps: int, log_dir: Optional[str] = None,
            start_step: Optional[int] = None) -> None:
        """Request a capture of ``steps`` train steps, starting at the
        next tick (or at absolute tick ``start_step``)."""
        if steps < 1:
            raise ValueError("profile steps must be >= 1")
        with self._lock:
            if self.active:
                raise RuntimeError("a profile capture is already running")
            self._armed = {"start": (self._step if start_step is None
                                     else int(start_step)),
                           "steps": int(steps),
                           "dir": log_dir or self.log_dir}

    def tick(self) -> None:
        """One train-step boundary: start the trace when the armed window
        begins, stop it when the window has covered its steps."""
        with self._lock:
            step = self._step
            self._step += 1
            if self.active:
                if step >= self._stop_at:
                    self._stop()
                return
            armed = self._armed
            if armed is None or step < armed["start"]:
                return
            self._armed = None
            self._stop_at = step + armed["steps"]
            try:
                import jax
                jax.profiler.start_trace(armed["dir"])
            except Exception as e:
                logger.warning(f"profile: start_trace failed ({e}); "
                               "capture window dropped")
                self._stop_at = None
                return
            self.active = True
            logger.info(f"profile: capturing {armed['steps']} step(s) to "
                        f"{armed['dir']} (summarize with "
                        f"`dscli profile {armed['dir']}`)")

    def _stop(self) -> None:
        try:
            import jax
            jax.profiler.stop_trace()
            self.captures += 1
            logger.info("profile: capture complete")
        except Exception as e:
            logger.warning(f"profile: stop_trace failed ({e})")
        self.active = False
        self._stop_at = None

    def stop(self) -> None:
        """Force-stop an active capture (engine teardown safety: a trace
        left open keeps the profiler session dangling)."""
        with self._lock:
            if self.active:
                self._stop()

    @contextmanager
    def annotate(self, name: str):
        """Accelerator ``TraceAnnotation`` around the with-block while a
        capture is active (no-op otherwise) — the host-side span marker
        on the device timeline."""
        if not self.active:
            yield
            return
        try:
            from deepspeed_tpu.accelerator import get_accelerator
            acc = get_accelerator()
        except Exception:
            acc = None
        if acc is not None:
            acc.range_push(name)
        try:
            yield
        finally:
            if acc is not None:
                acc.range_pop()


# ------------------------------------------------------------------ #
# compile watchdog

# Detection: jax emits a '/jax/core/compile/backend_compile_duration'
# monitoring event for every REAL XLA compile. A thread-local accumulator
# attributes those events to the watched call in flight — unlike the
# jit-cache-size heuristic this never miscounts C++ fastpath-cache
# signature misses (e.g. donated-output arrays re-entering a step) as
# compiles. When the listener can't register (older jax), the wrapper
# falls back to cache-size growth.

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_tls = threading.local()
_listener_state = {"registered": False, "ok": False}


def _compile_listener(name: str, dur: float, **kw) -> None:
    if name != _COMPILE_EVENT:
        return
    acc = getattr(_tls, "acc", None)
    if acc is not None:
        acc.append(dur)


def _ensure_compile_listener() -> bool:
    if not _listener_state["registered"]:
        _listener_state["registered"] = True
        try:
            import jax.monitoring
            jax.monitoring.register_event_duration_secs_listener(
                _compile_listener)
            _listener_state["ok"] = True
        except Exception:
            _listener_state["ok"] = False
    return _listener_state["ok"]


def _abstract_signature(args, kwargs, max_leaves: int = 24) -> str:
    """Compact dtype[shape] signature of a call's inputs — the shape set
    that *caused* a compilation, for the recompile post-mortem."""
    try:
        import jax
        leaves = jax.tree.leaves((args, kwargs))
    except Exception:
        leaves = list(args) + list(kwargs.values())
    sigs = []
    for leaf in leaves[:max_leaves]:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None:
            sigs.append(f"{getattr(dtype, 'name', dtype)}[{','.join(map(str, shape))}]")
        else:
            sigs.append(type(leaf).__name__)
    if len(leaves) > max_leaves:
        sigs.append(f"...+{len(leaves) - max_leaves}")
    return "(" + ", ".join(sigs) + ")"


class CompileWatchdog:
    """Counts compilations per watched entry point and flags storms."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 storm_threshold: int = 8, storm_window_s: float = 300.0):
        self.registry = registry if registry is not None else get_registry()
        self.storm_threshold = storm_threshold
        self.storm_window_s = storm_window_s
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._recent: Dict[str, List[float]] = {}   # compile timestamps
        self._warned_at: Dict[str, int] = {}
        self.events: List[Dict[str, Any]] = []      # compile records

    def _metrics(self):
        # resolved per compile (rare) rather than cached: a registry
        # reset between bench metrics must not orphan the families
        return (self.registry.counter(
                    "compile/count",
                    "XLA compilations per watched jit entry point",
                    labelnames=("fn",)),
                self.registry.histogram(
                    "compile/time_ms",
                    "compile wall time (incl. triggering run)",
                    labelnames=("fn",)))

    # ---- wrapping ---- #

    def watch(self, jitted, name: str):
        """Wrap an already-``jax.jit``-ed callable. The wrapper forwards
        the call unchanged (donation/sharding semantics are the inner
        function's) and records one compile — with the summed backend
        compile wall time and the triggering abstract input shapes —
        whenever XLA actually compiled during the call."""
        use_events = _ensure_compile_listener()
        cache_size = getattr(jitted, "_cache_size", None)

        def wrapped(*args, **kwargs):
            if use_events:
                prev = getattr(_tls, "acc", None)
                _tls.acc = acc = []
                try:
                    out = jitted(*args, **kwargs)
                finally:
                    _tls.acc = prev
                if acc:
                    self._record(name, sum(acc),
                                 _abstract_signature(args, kwargs))
                return out
            if cache_size is None:
                return jitted(*args, **kwargs)
            before = cache_size()
            t0 = time.perf_counter()
            out = jitted(*args, **kwargs)
            if cache_size() > before:
                self._record(name, time.perf_counter() - t0,
                             _abstract_signature(args, kwargs))
            return out

        wrapped.__name__ = f"watched[{name}]"
        wrapped.inner = jitted
        return wrapped

    def jit(self, fn, name: Optional[str] = None, **jit_kwargs):
        """``jax.jit`` + watch in one call — the framework-side entry
        point replacement."""
        import jax
        return self.watch(jax.jit(fn, **jit_kwargs),
                          name or getattr(fn, "__name__", "jit"))

    # ---- recording ---- #

    def _record(self, name: str, wall_s: float, signature: str) -> None:
        now = time.perf_counter()
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + 1
            count = self._counts[name]
            recent = self._recent.setdefault(name, [])
            recent.append(now)
            cutoff = now - self.storm_window_s
            while recent and recent[0] < cutoff:
                recent.pop(0)
            in_window = len(recent)
            self.events.append({"name": name, "wall_time_s": wall_s,
                                "shapes": signature, "count": count})
            should_warn = in_window >= self.storm_threshold and \
                self._warned_at.get(name, 0) < count
            if should_warn:
                # re-arm only after another full threshold of compiles, so
                # a sustained storm warns periodically, not every step
                self._warned_at[name] = count + self.storm_threshold - 1
        count_metric, time_metric = self._metrics()
        count_metric.labels(fn=name).inc()
        time_metric.labels(fn=name).observe(wall_s * 1e3)
        if should_warn:
            logger.warning(
                f"recompilation storm: {name!r} compiled {in_window} times in "
                f"the last {self.storm_window_s:.0f}s ({count} total; latest "
                f"inputs {signature}). Recompiles silently serialize every "
                "step behind XLA — check for shape churn (pad/bucket inputs), "
                "python scalars that should be jnp arrays, or weak_type flap.")

    # ---- queries ---- #

    def compile_count(self, name: Optional[str] = None) -> int:
        with self._lock:
            if name is not None:
                return self._counts.get(name, 0)
            return sum(self._counts.values())

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {"total": sum(self._counts.values()),
                    "by_fn": dict(self._counts),
                    "events": list(self.events[-50:])}

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._recent.clear()
            self._warned_at.clear()
            self.events.clear()


# ------------------------------------------------------------------ #
# process-global instances

_tracer: Optional[StepTracer] = None
_watchdog: Optional[CompileWatchdog] = None
_lock = threading.Lock()


def get_tracer() -> StepTracer:
    global _tracer
    if _tracer is None:
        with _lock:
            if _tracer is None:
                _tracer = StepTracer()
    return _tracer


def get_compile_watchdog() -> CompileWatchdog:
    global _watchdog
    if _watchdog is None:
        with _lock:
            if _watchdog is None:
                _watchdog = CompileWatchdog()
    return _watchdog


def watched_jit(fn, name: Optional[str] = None, **jit_kwargs):
    """Module-level convenience: ``jax.jit`` through the global watchdog."""
    return get_compile_watchdog().jit(fn, name=name, **jit_kwargs)
