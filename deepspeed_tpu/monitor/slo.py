"""Burn-rate SLO engine: config-declared objectives over sampled metrics.

One-shot threshold alerts ("p99 > 500 ms right now") page on noise and
sleep through slow leaks; the SRE-workbook answer — and this module — is
**multi-window burn rates**: an objective declares how much of the traffic
may be bad (``objective: 0.99`` = 1 % error budget), the engine samples
the cumulative good/bad event counts once per sampler tick, and an alert
fires only when EVERY configured window (a long one proving the budget
loss is sustained, a short one proving it is still happening) burns
budget at ``burn_rate_threshold`` or faster. A window reads zero burn
until the ring holds its full history — a fresh-from-startup engine
cannot page off a long window that has degenerated to a one-tick delta. A burn rate of 1.0 means the
error budget exactly runs out at the SLO period's end; 10 means ten times
that fast.

Two objective kinds:

- ``latency`` — a histogram plus a per-observation budget: ``bad`` =
  observations above ``threshold_ms``, counted through the registry's
  bucket ladder (:meth:`Histogram.count_le`), so "p99 TTFT ≤ 500 ms"
  becomes "≤ 1 % of TTFT observations above 500 ms".
- ``ratio`` — two counters: ``bad`` = ``metric``, total =
  ``total_metric`` (e.g. rejected / submitted requests).

Evaluation is **deterministic given the observation trace**: the engine
keeps a ring of per-tick cumulative counts, windows are measured in
ticks (never wall time), and a breach re-fires at most once per longest
window — replaying the same request trace through the same tick sequence
fires the same alerts at the same ticks (the scheduler-pin discipline,
applied to alerting). Breaches emit a typed ``slo.breach`` flight-
recorder event, increment ``slo/breaches{objective=}``, and the
per-window ``slo/burn_rate{objective=,window=}`` gauges refresh every
tick — all of which surface in ``health_summary``, ``dscli top``, and
the ``/metrics`` plane.

This module is part of the telemetry exposition plane: host-side dict
arithmetic only — importing jax (or touching any device API) here is a
dslint DS009 violation.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

DEFAULT_WINDOWS = (60, 5)


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One declared objective (see ``telemetry.slo.objectives``)."""
    name: str
    metric: str                     # histogram (latency) / bad counter (ratio)
    kind: str = "latency"           # "latency" | "ratio"
    threshold_ms: float = 0.0       # latency: per-observation budget
    objective: float = 0.99         # good-fraction target (p99 -> 0.99)
    total_metric: str = ""          # ratio: denominator counter
    windows: Tuple[int, ...] = DEFAULT_WINDOWS   # ticks, longest first
    burn_rate_threshold: float = 1.0

    def __post_init__(self):
        if self.kind not in ("latency", "ratio"):
            raise ValueError(f"slo objective {self.name!r}: kind "
                             f"{self.kind!r} (expected latency|ratio)")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"slo objective {self.name!r}: objective "
                             f"{self.objective} outside (0, 1)")
        if self.kind == "latency" and self.threshold_ms <= 0:
            raise ValueError(f"slo objective {self.name!r}: latency kind "
                             "needs threshold_ms > 0")
        if self.kind == "ratio" and not self.total_metric:
            raise ValueError(f"slo objective {self.name!r}: ratio kind "
                             "needs total_metric")
        if not self.windows or any(w < 1 for w in self.windows):
            raise ValueError(f"slo objective {self.name!r}: windows must "
                             "be >= 1 tick")

    @property
    def error_budget(self) -> float:
        return max(1.0 - self.objective, 1e-9)


def parse_objectives(raw: Sequence[Dict], *,
                     default_windows: Sequence[int] = DEFAULT_WINDOWS,
                     default_burn_rate_threshold: float = 1.0
                     ) -> List[SloObjective]:
    """Objective dicts (the ``telemetry.slo.objectives`` list) →
    :class:`SloObjective`, filling section-level defaults."""
    out: List[SloObjective] = []
    for i, d in enumerate(raw):
        if not isinstance(d, dict):
            raise ValueError(f"slo objective #{i} must be a dict, got "
                             f"{type(d).__name__}")
        d = dict(d)
        unknown = set(d) - {"name", "metric", "kind", "threshold_ms",
                            "objective", "total_metric", "windows",
                            "burn_rate_threshold"}
        if unknown:
            raise ValueError(f"slo objective #{i}: unknown keys "
                             f"{sorted(unknown)}")
        if "metric" not in d:
            raise ValueError(f"slo objective #{i}: missing 'metric'")
        d.setdefault("name", d["metric"])
        d.setdefault("windows", list(default_windows))
        d.setdefault("burn_rate_threshold", default_burn_rate_threshold)
        d["windows"] = tuple(int(w) for w in d["windows"])
        out.append(SloObjective(**d))
    names = [o.name for o in out]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate slo objective names in {names}")
    return out


def serving_objectives(ttft_p99_ms: Optional[float] = None,
                       tpot_p99_ms: Optional[float] = None,
                       error_rate: Optional[float] = None) -> List[Dict]:
    """The stock serving objective set (``dscli serve --slo-ttft-ms``
    etc.) as config dicts: p99 TTFT / p99 TPOT latency budgets plus an
    admission-rejection rate bound."""
    objs: List[Dict] = []
    if ttft_p99_ms:
        objs.append({"name": "ttft_p99", "metric": "serving/ttft_ms",
                     "kind": "latency", "threshold_ms": float(ttft_p99_ms),
                     "objective": 0.99})
    if tpot_p99_ms:
        objs.append({"name": "tpot_p99", "metric": "serving/tpot_ms",
                     "kind": "latency", "threshold_ms": float(tpot_p99_ms),
                     "objective": 0.99})
    if error_rate:
        objs.append({"name": "error_rate",
                     "metric": "serving/rejected_requests",
                     "kind": "ratio", "total_metric": "serving/requests",
                     "objective": 1.0 - float(error_rate)})
    return objs


class SloEngine:
    """Evaluate objectives against the live registry, once per sampler
    tick. The sampler owns the cadence (:meth:`sample` is its hook);
    tests and trace replay call :meth:`sample` directly for a fully
    deterministic tick sequence."""

    def __init__(self, objectives: Sequence[SloObjective], registry=None,
                 events=None):
        if registry is None:
            from deepspeed_tpu.monitor.metrics import get_registry
            registry = get_registry()
        self.registry = registry
        self.events = events            # flight recorder or None
        self.objectives = list(objectives)
        self.tick = 0
        # per-objective ring of cumulative (total, bad) samples; one more
        # entry than the longest window so a full window has its base
        self._rings: Dict[str, List[Tuple[float, float]]] = \
            {o.name: [] for o in self.objectives}
        self._last_fire: Dict[str, int] = {}
        self._ensure_series()

    def _ensure_series(self) -> None:
        """Pre-create the slo/* families (zero-valued breach counters
        must appear in snapshots before the first breach)."""
        for o in self.objectives:
            self._breaches.labels(objective=o.name)
            for w in o.windows:
                self._burn.labels(objective=o.name, window=str(w))

    @property
    def _breaches(self):
        return self.registry.counter(
            "slo/breaches",
            "burn-rate alerts fired (every configured window burning "
            "past burn_rate_threshold; at most one firing per longest "
            "window)", labelnames=("objective",))

    @property
    def _burn(self):
        return self.registry.gauge(
            "slo/burn_rate",
            "error-budget burn rate per evaluation window (1.0 = budget "
            "gone exactly at the SLO period's end)",
            labelnames=("objective", "window"))

    # ---- one tick ---- #

    def _read(self, o: SloObjective) -> Tuple[float, float]:
        """Cumulative (total, bad) event counts for one objective, read
        atomically (one registry lock hold — a concurrent observe cannot
        tear total away from bad)."""
        if o.kind == "latency":
            fam = self.registry.histogram(o.metric)
            child = fam._only()
            with self.registry._lock:
                total = float(child.count)
                bad = total - float(child.count_le(o.threshold_ms))
            return total, bad
        bad_fam = self.registry.counter(o.metric)
        total_fam = self.registry.counter(o.total_metric)
        with self.registry._lock:
            return float(total_fam.value), float(bad_fam.value)

    def sample(self) -> List[Dict]:
        """One evaluation tick: read cumulative counts, refresh the
        burn-rate gauges, fire breaches. Returns the breach dicts fired
        THIS tick (empty most ticks). Host-side arithmetic only."""
        self.tick += 1
        fired: List[Dict] = []
        for o in self.objectives:
            ring = self._rings[o.name]
            ring.append(self._read(o))
            horizon = max(o.windows) + 1
            if len(ring) > horizon:
                del ring[:len(ring) - horizon]
            burns: Dict[int, float] = {}
            for w in o.windows:
                if len(ring) <= w:
                    # a window with incomplete history reads ZERO burn:
                    # the long window's whole job is proving the loss is
                    # SUSTAINED, and a fresh-from-startup engine whose
                    # 60-tick window degenerated to a 2-tick delta would
                    # page on the first blip instead
                    burns[w] = 0.0
                else:
                    base = ring[len(ring) - 1 - w]
                    d_total = ring[-1][0] - base[0]
                    d_bad = ring[-1][1] - base[1]
                    frac = d_bad / d_total if d_total > 0 else 0.0
                    burns[w] = frac / o.error_budget
                self._burn.labels(objective=o.name, window=str(w)) \
                    .set(burns[w])
            breach = all(b >= o.burn_rate_threshold
                         for b in burns.values())
            if not breach:
                continue
            last = self._last_fire.get(o.name)
            if last is not None and self.tick - last < max(o.windows):
                continue            # one firing per longest window
            self._last_fire[o.name] = self.tick
            self._breaches.labels(objective=o.name).inc()
            info = {"objective": o.name, "tick": self.tick,
                    "burn_rate": round(min(burns.values()), 4),
                    "threshold": o.burn_rate_threshold,
                    "windows": list(o.windows)}
            if self.events is not None:
                self.events.emit("slo.breach", objective=o.name,
                                 tick=self.tick,
                                 burn_rate=info["burn_rate"],
                                 threshold=o.burn_rate_threshold,
                                 window=max(o.windows))
            fired.append(info)
        return fired


def slo_from_config(slo_cfg, registry=None, events=None
                    ) -> Optional[SloEngine]:
    """Build the engine a ``telemetry.slo`` config block asks for (None
    when disabled or no objectives are declared)."""
    if slo_cfg is None or not slo_cfg.enabled:
        return None
    objectives = parse_objectives(
        slo_cfg.objectives, default_windows=slo_cfg.windows,
        default_burn_rate_threshold=slo_cfg.burn_rate_threshold)
    if not objectives:
        return None
    return SloEngine(objectives, registry=registry, events=events)
