from deepspeed_tpu.monitor.config import (DeepSpeedMonitorConfig,
                                          EventsConfig, HealthConfig,
                                          ProfileConfig, SamplerConfig,
                                          SloConfig, TelemetryConfig,
                                          get_monitor_config,
                                          get_telemetry_config)
from deepspeed_tpu.monitor.events import (EVENT_KINDS, Event, FlightRecorder,
                                          export_recorder_metrics,
                                          export_serving_trace,
                                          get_flight_recorder,
                                          render_serving_trace)
from deepspeed_tpu.monitor.exporter import (MetricsExporter,
                                            render_exposition)
from deepspeed_tpu.monitor.sampler import MetricsSampler, sampler_from_config
from deepspeed_tpu.monitor.slo import (SloEngine, SloObjective,
                                       parse_objectives, serving_objectives,
                                       slo_from_config)
from deepspeed_tpu.monitor.health import (HealthMonitor, StepHealth,
                                          compute_sentinels,
                                          make_bucket_assignment,
                                          render_health_table,
                                          sample_memory_gauges,
                                          sentinel_to_dict)
from deepspeed_tpu.monitor.metrics import (MetricsRegistry, get_registry,
                                           parse_prometheus_text,
                                           validate_snapshot)
from deepspeed_tpu.monitor.monitor import MonitorMaster
from deepspeed_tpu.monitor.trace import (CompileWatchdog, ProfileWindow,
                                         StepTracer, get_compile_watchdog,
                                         get_tracer, watched_jit)

__all__ = [
    "DeepSpeedMonitorConfig", "EventsConfig", "HealthConfig",
    "ProfileConfig", "SamplerConfig", "SloConfig", "TelemetryConfig",
    "EVENT_KINDS", "Event", "FlightRecorder", "get_flight_recorder",
    "export_recorder_metrics", "export_serving_trace",
    "render_serving_trace",
    "MetricsExporter", "render_exposition",
    "MetricsSampler", "sampler_from_config",
    "SloEngine", "SloObjective", "parse_objectives", "serving_objectives",
    "slo_from_config", "parse_prometheus_text",
    "get_monitor_config", "get_telemetry_config", "MetricsRegistry",
    "get_registry", "validate_snapshot", "MonitorMaster", "CompileWatchdog",
    "ProfileWindow", "StepTracer", "get_compile_watchdog", "get_tracer",
    "watched_jit",
    "HealthMonitor", "StepHealth", "compute_sentinels",
    "make_bucket_assignment", "render_health_table", "sample_memory_gauges",
    "sentinel_to_dict",
]
