from deepspeed_tpu.monitor.config import (DeepSpeedMonitorConfig,
                                          EventsConfig, HealthConfig,
                                          ProfileConfig, TelemetryConfig,
                                          get_monitor_config,
                                          get_telemetry_config)
from deepspeed_tpu.monitor.events import (EVENT_KINDS, Event, FlightRecorder,
                                          export_serving_trace,
                                          get_flight_recorder,
                                          render_serving_trace)
from deepspeed_tpu.monitor.health import (HealthMonitor, StepHealth,
                                          compute_sentinels,
                                          make_bucket_assignment,
                                          render_health_table,
                                          sample_memory_gauges,
                                          sentinel_to_dict)
from deepspeed_tpu.monitor.metrics import (MetricsRegistry, get_registry,
                                           validate_snapshot)
from deepspeed_tpu.monitor.monitor import MonitorMaster
from deepspeed_tpu.monitor.trace import (CompileWatchdog, ProfileWindow,
                                         StepTracer, get_compile_watchdog,
                                         get_tracer, watched_jit)

__all__ = [
    "DeepSpeedMonitorConfig", "EventsConfig", "HealthConfig",
    "ProfileConfig", "TelemetryConfig",
    "EVENT_KINDS", "Event", "FlightRecorder", "get_flight_recorder",
    "export_serving_trace", "render_serving_trace",
    "get_monitor_config", "get_telemetry_config", "MetricsRegistry",
    "get_registry", "validate_snapshot", "MonitorMaster", "CompileWatchdog",
    "ProfileWindow", "StepTracer", "get_compile_watchdog", "get_tracer",
    "watched_jit",
    "HealthMonitor", "StepHealth", "compute_sentinels",
    "make_bucket_assignment", "render_health_table", "sample_memory_gauges",
    "sentinel_to_dict",
]
