from deepspeed_tpu.monitor.config import (DeepSpeedMonitorConfig,
                                          HealthConfig, TelemetryConfig,
                                          get_monitor_config,
                                          get_telemetry_config)
from deepspeed_tpu.monitor.health import (HealthMonitor, StepHealth,
                                          compute_sentinels,
                                          make_bucket_assignment,
                                          render_health_table,
                                          sample_memory_gauges,
                                          sentinel_to_dict)
from deepspeed_tpu.monitor.metrics import (MetricsRegistry, get_registry,
                                           validate_snapshot)
from deepspeed_tpu.monitor.monitor import MonitorMaster
from deepspeed_tpu.monitor.trace import (CompileWatchdog, StepTracer,
                                         get_compile_watchdog, get_tracer,
                                         watched_jit)

__all__ = [
    "DeepSpeedMonitorConfig", "HealthConfig", "TelemetryConfig",
    "get_monitor_config", "get_telemetry_config", "MetricsRegistry",
    "get_registry", "validate_snapshot", "MonitorMaster", "CompileWatchdog",
    "StepTracer", "get_compile_watchdog", "get_tracer", "watched_jit",
    "HealthMonitor", "StepHealth", "compute_sentinels",
    "make_bucket_assignment", "render_health_table", "sample_memory_gauges",
    "sentinel_to_dict",
]
