"""Request latency anatomy: the per-request phase ledger, recomputed
from a flight-recorder event trace.

``request_anatomy`` decomposes ONE request's end-to-end latency into the
same phases the live ledger books into ``serving/phase_ms`` — intake,
queue, prefill (incl. chunks + CoW), fetch (host-tier H2D), verify,
decode — plus a ``sched_wait`` remainder so the phases ALWAYS sum to the
end-to-end total exactly.  ``trace_anatomy`` groups every request
carrying one causal trace id (a disaggregated prefill→decode pair plus
any failover replays) and adds the cross-replica ``handoff`` phase from
the router's ``serve.handoff`` marker.

Everything here is a pure function of the event list: feed it a
recorder ``snapshot()`` or re-parsed ``write_jsonl`` lines and the
decomposition is replay-identical — no wall clock, no recorder access,
no jax.  ``dscli trace <request-id>`` and the tests render from these
functions so screen / JSON / scrape cannot drift.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

#: ledger phases, in anatomy order.  ``sched_wait`` is the remainder
#: (total minus everything attributable), clamped at zero — it absorbs
#: scheduler bookkeeping, host-side sampling, and inter-tick idle time.
PHASES = ("intake", "queue", "prefill", "fetch", "verify", "decode",
          "sched_wait")

_END_KINDS = ("req.retire", "req.cancel", "req.timeout", "req.shed")
_PREFILL_KINDS = ("req.prefill", "req.prefill_chunk", "req.cow_copy")


def _norm(e: Any) -> Tuple[int, str, Optional[int], int, Dict[str, Any]]:
    """Normalize one event — a frozen ``Event`` or a flattened JSONL
    dict — to ``(ts_ns, kind, rid, dur_ns, extras)``."""
    if isinstance(e, dict):
        extras = {k: v for k, v in e.items()
                  if k not in ("ts_ns", "kind", "rid", "step", "dur_ns")}
        return (int(e.get("ts_ns", 0)), str(e.get("kind", "")),
                e.get("rid"), int(e.get("dur_ns") or 0), extras)
    return (e.ts_ns, e.kind, e.rid, e.dur_ns or 0, dict(e.data or {}))


def request_anatomy(events: Iterable[Any], rid: int,
                    replica: Optional[str] = None
                    ) -> Optional[Dict[str, Any]]:
    """Decompose request ``rid``'s latency from its event trace.

    Returns ``None`` when the trace never mentions the rid.  The result
    dict carries ``phases_ms`` (every :data:`PHASES` key, summing to
    ``total_ms`` exactly), ``ttft_ms`` (intake + queue + prefill + fetch
    + first decode tick), ``total_ms``, per-phase event counts, the
    propagated ``trace``/``parent`` context and ``replica`` tag from
    ``req.enqueue``, and the terminal ``outcome`` (``retire`` | ``cancel``
    | ``timeout`` | ``shed`` | ``running`` for an unfinished trace).

    Rids are PER-ENGINE counters, so a fleet-merged trace usually holds
    the same rid on several replicas: pass ``replica`` to scope the
    decomposition to one replica's events (events carrying no replica
    tag match the default ``"r0"``)."""
    rid = int(rid)
    want_rep = None if replica is None else str(replica)
    submit_ts = enqueue_ts = admit_ts = end_ts = None
    last_ts = None
    outcome = "running"
    trace = parent = replica = None
    generated = None
    phase_ns = {p: 0 for p in PHASES}
    counts = {p: 0 for p in PHASES}
    explicit = set()           # phases covered by req.phase ledger events
    first_decode_ns = None
    seen = False
    for raw in events:
        ts, kind, erid, dur, d = _norm(raw)
        if want_rep is not None and d.get("replica", "r0") != want_rep:
            continue
        if kind == "decode.tick":
            if rid in (d.get("rids") or ()):
                seen = True
                phase_ns["decode"] += dur
                counts["decode"] += 1
                if first_decode_ns is None:
                    first_decode_ns = dur
                last_ts = max(last_ts or 0, ts + dur)
            continue
        if erid != rid:
            continue
        seen = True
        last_ts = max(last_ts or 0, ts + dur)
        if kind == "req.submit":
            submit_ts = ts
        elif kind == "req.enqueue":
            enqueue_ts = ts
            trace = d.get("trace", trace)
            parent = d.get("parent", parent)
            replica = d.get("replica", replica)
        elif kind == "req.admit":
            if admit_ts is None:
                admit_ts = ts
        elif kind == "req.phase":
            p = d.get("phase")
            if p in phase_ns:
                phase_ns[p] += dur
                counts[p] += 1
                explicit.add(p)
        elif kind in _PREFILL_KINDS:
            phase_ns["prefill"] += dur
            counts["prefill"] += 1
        elif kind == "kv.fetch":
            phase_ns["fetch"] += dur
            counts["fetch"] += 1
        elif kind == "req.spec_verify":
            phase_ns["verify"] += dur
            counts["verify"] += 1
        elif kind in _END_KINDS:
            end_ts = ts
            outcome = kind.split(".", 1)[1]
            if "generated" in d:
                generated = d["generated"]
    if not seen:
        return None
    # pre-admission phases: the req.phase ledger events are authoritative
    # (emitted from the scheduler's own clocks); reconstruct from the
    # submit/enqueue/admit timestamps only when they are absent
    if "intake" not in explicit and submit_ts is not None \
            and enqueue_ts is not None:
        phase_ns["intake"] = max(enqueue_ts - submit_ts, 0)
    if "queue" not in explicit and enqueue_ts is not None \
            and admit_ts is not None:
        phase_ns["queue"] = max(admit_ts - enqueue_ts, 0)
    start_ts = submit_ts if submit_ts is not None else enqueue_ts
    stop_ts = end_ts if end_ts is not None else last_ts
    total_ns = max((stop_ts or 0) - (start_ts or 0), 0) \
        if start_ts is not None else sum(phase_ns.values())
    attributed = sum(v for p, v in phase_ns.items() if p != "sched_wait")
    if total_ns < attributed:
        # clock-skew guard (phase durs come from monotonic_ns, the
        # boundaries from emit timestamps): never report negative wait
        total_ns = attributed
    phase_ns["sched_wait"] = total_ns - attributed
    ttft_ns = (phase_ns["intake"] + phase_ns["queue"]
               + phase_ns["prefill"] + phase_ns["fetch"]
               + (first_decode_ns or 0))
    return {
        "rid": rid, "trace": trace, "parent": parent, "replica": replica,
        "outcome": outcome, "generated": generated,
        "phases_ms": {p: phase_ns[p] / 1e6 for p in PHASES},
        "counts": counts,
        "total_ms": total_ns / 1e6,
        "ttft_ms": ttft_ns / 1e6,
    }


def trace_anatomy(events: Iterable[Any],
                  trace: str) -> Optional[Dict[str, Any]]:
    """Anatomy of one CAUSAL trace id across the fleet: every request
    enqueued with ``trace=`` (prefill warm-up, decode continuation,
    failover replays), ordered by enqueue time, plus the router's
    ``handoff_ms`` (``serve.handoff`` marks completion; the phase wall
    time lives on the prefill replica's ledger).  Returns ``None`` for
    an unknown trace id."""
    trace = str(trace)
    events = list(events)
    # (enqueue ts, rid, replica): rids are per-engine counters, so legs
    # are identified by the (replica, rid) PAIR, never the rid alone
    rids: List[Tuple[int, int, str]] = []
    handoffs: List[Dict[str, Any]] = []
    for raw in events:
        ts, kind, rid, _dur, d = _norm(raw)
        if kind == "req.enqueue" and d.get("trace") == trace \
                and rid is not None:
            rids.append((ts, int(rid), str(d.get("replica", "r0"))))
        elif kind == "serve.handoff" and d.get("trace") == trace:
            handoffs.append({"from": d.get("from_replica"),
                             "to": d.get("to_replica"), "rid": rid})
    if not rids:
        return None
    rids.sort()
    legs = [request_anatomy(events, r, replica=rep) for _, r, rep in rids]
    legs = [a for a in legs if a is not None]
    return {
        "trace": trace,
        "legs": legs,
        "handoffs": handoffs,
        "total_ms": sum(a["total_ms"] for a in legs),
    }


def resolve_request_id(request_id) -> Tuple[Optional[str], Optional[int]]:
    """CLI convenience: map a user-supplied request id — an integer rid
    or a ``t<seq>`` trace id — to ``(trace, rid)`` (exactly one set)."""
    s = str(request_id)
    try:
        return None, int(s)
    except ValueError:
        return s, None


def format_anatomy(a: Dict[str, Any]) -> str:
    """Render one request's anatomy for ``dscli trace`` — a fixed-width
    phase table plus the TTFT/outcome summary line."""
    lines = []
    head = f"request {a['rid']}"
    if a.get("replica"):
        head += f" @ {a['replica']}"
    if a.get("trace"):
        head += f"  trace={a['trace']}"
    if a.get("parent") is not None:
        head += f" parent={a['parent']}"
    lines.append(head)
    total = a["total_ms"] or 1e-9
    for p in PHASES:
        ms = a["phases_ms"][p]
        n = a["counts"].get(p, 0)
        bar = "#" * min(int(round(40 * ms / total)), 40)
        ev = f" ({n} ev)" if n else ""
        lines.append(f"  {p:<10} {ms:>10.3f} ms  {bar}{ev}")
    lines.append(f"  {'total':<10} {a['total_ms']:>10.3f} ms   "
                 f"ttft={a['ttft_ms']:.3f} ms  outcome={a['outcome']}"
                 + (f"  generated={a['generated']}"
                    if a.get("generated") is not None else ""))
    return "\n".join(lines)


def format_trace_anatomy(t: Dict[str, Any]) -> str:
    """Render a fleet trace id's anatomy: one block per leg, joined by
    the handoff hops."""
    lines = [f"trace {t['trace']}: {len(t['legs'])} leg(s), "
             f"{t['total_ms']:.3f} ms total"]
    for hop in t["handoffs"]:
        lines.append(f"  handoff: {hop['from']} -> {hop['to']} "
                     f"(prefill rid {hop['rid']})")
    for a in t["legs"]:
        lines.append("")
        lines.append(format_anatomy(a))
    return "\n".join(lines)
