"""Standalone Prometheus exposition endpoint (the scrape half).

``dscli serve`` exposes ``GET /metrics`` on its own HTTP front-end; this
module is the same plane for everything else — a training run, a bench,
an embedded engine — as a tiny threaded ``http.server`` publishing the
process-global registry:

- ``GET /metrics`` — Prometheus text exposition
  (:meth:`MetricsRegistry.to_prometheus`), with the flight recorder's
  ring-loss gauges (``events/dropped``/``events/capacity``) refreshed
  per scrape. The ``serving/phase_ms`` ledger renders with OpenMetrics
  exemplars (``# {rid="..."} v``) so a p99 bucket links straight to the
  request in a merged fleet trace;
- ``GET /healthz`` — 200 while serving, for scrape-target liveness.

Config: ``telemetry.metrics_port`` (the training engine starts/stops one
around its lifetime); or construct :class:`MetricsExporter` directly.

Cost discipline: a scrape renders host-side registry state — **zero
device work, zero compiles** (the ``serving_metrics_steady`` contract;
importing jax here is a dslint DS009 violation). Handler threads only
read under the registry lock, so a scrape can stall a hot-path
``observe`` for at most one text render.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

#: the classic text-format content type scrapers expect
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
#: the OpenMetrics content type — the only format exemplars are legal in
OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"


def wants_openmetrics(accept_header: Optional[str]) -> bool:
    """Did the scraper's ``Accept`` header negotiate OpenMetrics?"""
    return "application/openmetrics-text" in (accept_header or "")


def render_exposition(registry=None,
                      openmetrics: bool = False) -> Tuple[str, str]:
    """One exposition body as ``(text, content_type)`` — THE rendering
    path shared by the standalone exporter and the ``dscli serve``
    ``/metrics`` route: recorder-loss gauges refreshed, then the
    registry's text format. Exemplars are emitted only under
    ``openmetrics`` (they are illegal in the 0.0.4 format — a strict
    scraper would reject the entire body), which also appends the
    ``# EOF`` terminator the OpenMetrics grammar requires."""
    if registry is None:
        from deepspeed_tpu.monitor.metrics import get_registry
        registry = get_registry()
    from deepspeed_tpu.monitor.events import export_recorder_metrics
    export_recorder_metrics(registry)
    text = registry.to_prometheus(exemplars=openmetrics)
    if openmetrics:
        text += "# EOF\n"
        return text, OPENMETRICS_CONTENT_TYPE
    return text, PROM_CONTENT_TYPE


class MetricsExporter:
    """Serve ``/metrics`` for one registry on a background thread.

    ``port=0`` binds an ephemeral port (read it back from
    :attr:`address` / :attr:`url` after :meth:`start`)."""

    def __init__(self, registry=None, host: str = "127.0.0.1",
                 port: int = 0):
        if registry is None:
            from deepspeed_tpu.monitor.metrics import get_registry
            registry = get_registry()
        self.registry = registry
        self._host = host
        self._port = int(port)
        self._server = None
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle ---- #

    def start(self) -> Tuple[str, int]:
        """Bind and serve; returns ``(host, port)`` (idempotent)."""
        if self._server is not None:
            return self.address
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        exporter = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):    # scrapes are not console news
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    text, ctype = render_exposition(
                        exporter.registry,
                        openmetrics=wants_openmetrics(
                            self.headers.get("Accept")))
                    payload = text.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                elif self.path == "/healthz":
                    payload = b'{"status": "ok"}'
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                else:
                    payload = f'{{"error": "no route {self.path}"}}'.encode()
                    self.send_response(404)
                    self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        class Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((self._host, self._port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="ds-metrics-exporter",
                                        daemon=True)
        self._thread.start()
        return self.address

    def render(self, openmetrics: bool = False) -> str:
        """One exposition body (the scrape handler's work, callable
        directly); see :func:`render_exposition`."""
        return render_exposition(self.registry, openmetrics=openmetrics)[0]

    @property
    def address(self) -> Tuple[str, int]:
        if self._server is None:
            return (self._host, self._port)
        return self._server.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}/metrics"

    def stop(self) -> None:
        srv, self._server = self._server, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(5)

    def __enter__(self) -> "MetricsExporter":
        self.start()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()
