"""Flight recorder: a typed, bounded, thread-safe ring of lifecycle events.

PR 3/4 built *aggregate* observability (metrics registry, health
detectors); this module records *what happened when*: a ring buffer of
structured events with monotonic-ns timestamps and request/step identity,
emitted by the training engine (step / phase / checkpoint phases / fp16
skip), the continuous-batching scheduler (enqueue / admit / cache hit /
preempt / retire / cancel, speculative propose / rollback), the inference
engine (prefill, prefill chunk, COW copy, fused decode tick, speculative
verify, tiered-KV spill / fetch), the async serving front-end (submit /
drain, step-fault containment / engine restart / request requeue /
timeout / shed), the burn-rate SLO engine (breach), and the crash-safe
checkpoint writer (snapshot / serialize / commit / retry). The buffer keeps the newest
``capacity`` events (a flight recorder preserves the TAIL — the moments
before the incident), counting evictions in ``dropped``.

Cost discipline: when disabled, every emit site gates at ONE flag/None
check and allocates nothing (the engines hold ``None`` instead of the
recorder on their hot paths; :meth:`FlightRecorder.emit` itself returns
after one flag check for the module-level sites like the checkpoint
writer). Enabled, an emit is one :class:`Event` allocation and a locked
deque append — host-side work on paths that already do host-side
bookkeeping, never inside compiled code.

Two export shapes:

- :meth:`FlightRecorder.write_jsonl` — the raw timeline, one event per
  line. Anomaly debug bundles and the SIGTERM/emergency-save path ship
  this as ``events.jsonl`` so every post-mortem carries its timeline.
- :func:`export_serving_trace` — the serving events rendered as
  chrome-trace JSON (Perfetto / chrome://tracing): one track per request
  holding its admission→retire span with prefill/decode/preempt child
  events, plus queue-depth and KV-block counter tracks.
- :func:`export_fleet_trace` — the replica fleet's merged timeline: one
  track group per replica (events tagged by :class:`TaggedRecorder`),
  the router's decision track, and ``ph:"s"``/``ph:"f"`` flow arrows
  stitching the disaggregated prefill→decode handoff across replicas.

All are schema-checked by ``tools/validate_trace.py``
(``dscli trace --validate``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

#: the typed event catalogue — ``emit`` rejects anything else, so the
#: exporters and the schema validator can rely on the vocabulary.
EVENT_KINDS = frozenset({
    # training engine
    "train.step",           # one train_batch (step=, dur_ns=)
    "train.phase",          # trio phase (step=, dur_ns=, phase=fwd|bwd|step)
    "train.fp16_skip",      # overflow skipped the update (step=)
    # checkpoint (crash-safe two-phase path)
    "ckpt.snapshot",        # device->host snapshot (step=, dur_ns=, tag=)
    "ckpt.serialize",       # npz+manifest write+fsync (dur_ns=, tag=)
    "ckpt.commit",          # atomic rename + dir fsync (dur_ns=, tag=, bytes=)
    "ckpt.retry",           # transient I/O fault retried (what=, attempt=, error=)
    # serving: scheduler state machine (rid= identity)
    "req.submit",           # async front-end accepted a submission
    #                         (prompt_tokens=, priority=; ts = caller-side
    #                         submit time, may precede ring neighbors)
    "req.enqueue",          # add_request (prompt_tokens=, max_new=)
    "req.admit",            # admission (cached_tokens=, blocks=)
    "req.cancel",           # cancellation retired the request (generated=)
    "req.cache_hit",        # admission prefix-cache probe hit (tokens=)
    "req.cache_miss",       # admission prefix-cache probe miss
    "req.preempt",          # recompute-preemption (blocks=, recompute_tokens=)
    "req.retire",           # finished (generated=, error=)
    # serving: engine compute steps (dur_ns= brackets the jit dispatch)
    "req.prefill",          # whole-prompt prefill (tokens=)
    "req.prefill_chunk",    # one prefill chunk (start=, tokens=)
    "req.cow_copy",         # copy-on-write block split (src=, dst=)
    "decode.tick",          # one fused decode step (rids=, n=)
    # serving: speculative decoding (n-gram self-speculation)
    "req.spec_propose",     # host n-gram proposal (tokens=, found=)
    "req.spec_verify",      # fused verify step slice (window=, accepted=)
    "req.spec_rollback",    # rejection rewound pos (rejected=, unregistered=)
    # serving: tiered KV cache (host-RAM spill pool)
    "kv.spill",             # cold block demoted D2H (blocks=, bytes=,
    #                         block=; dur_ns brackets the gather dispatch +
    #                         async-copy kick-off)
    "kv.fetch",             # host hit re-materialized H2D into the rid's
    #                         fresh blocks (blocks=, bytes=; dur_ns
    #                         brackets the synced scatters)
    "serve.begin",          # generate_batch / async-loop entry (requests=)
    "serve.end",            # serve span (dur_ns=, requests=)
    "serve.drain",          # async loop stopped intake (waiting=,
    #                         running=, pending=; router-level drains add
    #                         replica= — the breaker-tripped source being
    #                         drained to siblings)
    "serve.route",          # replica router decision (seq=, replica=,
    #                         reason= affinity | least_loaded | failover |
    #                         handoff | prefill, session=)
    # serving fault tolerance (serving.fault)
    "serve.fault",          # an engine-step exception was contained
    #                         (action= dispatch site, error=)
    "serve.restart",        # crash-safe engine recovery: pools + jits
    #                         rebuilt, in-flight re-admitted (restart=,
    #                         error=)
    "req.requeue",          # per-request fault retry: re-queued through
    #                         recompute-preemption with logical-step
    #                         backoff (retry=, backoff_steps=, error=)
    "req.timeout",          # deadline expiry retired the request
    #                         (generated=, error=)
    "req.shed",             # load shedding dropped a queued request
    #                         (priority=)
    "serve.handoff",        # disaggregated prefill->decode transfer
    #                         completed: the prefill replica demoted the
    #                         chain to the host tier and the decode
    #                         sibling resubmitted (trace=, from_replica=,
    #                         to_replica=; rid = the prefill-side rid)
    # request latency anatomy (phase ledger)
    "req.phase",            # one phase of a request's latency anatomy
    #                         (phase= intake | queue | ..., dur_ns= the
    #                         phase duration — an already-elapsed
    #                         interval ENDING at ts, unlike the timed
    #                         compute spans above)
    # scheduler occupancy sample (the counter-track source)
    "sched.gauge",          # queued=, running=, kv_used=, kv_free=
    # SLO engine (monitor/slo.py): a burn-rate alert fired
    "slo.breach",           # objective=, tick=, burn_rate=, threshold=,
    #                         window= (the longest evaluation window —
    #                         also the refire period)
    # adaptive controller decision ledger (monitor/controller.py):
    # observation -> decision -> application, replayable end to end
    "ctl.observe",          # one folded sampler-tick observation (tick=,
    #                         ttft_burn=, tpot_burn=, goodput_burn=,
    #                         queue_depth=, kv_util=, spec_acceptance=,
    #                         ...; the FIRST entry also carries config=
    #                         the ladder/threshold manifest replay seeds
    #                         from)
    "ctl.decide",           # one knob movement decided (tick=, knob=,
    #                         direction= tighten | relax, value=, prev=,
    #                         reason=, at_baseline=)
    "ctl.apply",            # serving thread applied the movement between
    #                         engine steps (knob=, value=, prev=, tick=,
    #                         reason=; restart=True when re-applied from
    #                         the ledger after an engine restart)
    "ctl.revert",           # a relax landed the knob back on its config
    #                         baseline (same payload as ctl.apply)
})


@dataclasses.dataclass(frozen=True)
class Event:
    """One flight-recorder entry. ``ts_ns`` is ``time.monotonic_ns()`` at
    the event's START (timed events pass their start explicitly so the
    slice covers [ts_ns, ts_ns + dur_ns]); ``rid``/``step`` carry request
    or training-step identity; ``data`` the kind-specific payload."""
    ts_ns: int
    kind: str
    rid: Optional[int] = None
    step: Optional[int] = None
    dur_ns: Optional[int] = None
    data: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"ts_ns": self.ts_ns, "kind": self.kind}
        if self.rid is not None:
            d["rid"] = self.rid
        if self.step is not None:
            d["step"] = self.step
        if self.dur_ns is not None:
            d["dur_ns"] = self.dur_ns
        if self.data:
            d.update(self.data)
        return d


class FlightRecorder:
    """Bounded ring of :class:`Event`. Oldest events are evicted when the
    ring is full (``dropped`` counts them); ``snapshot()`` returns the
    retained tail oldest-first. Thread-safe: scheduler/engine emits land
    from the caller thread, checkpoint emits from the writer thread."""

    DEFAULT_CAPACITY = 16384

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 enabled: bool = False):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=capacity)
        self._dropped = 0
        self.enabled = enabled

    @property
    def capacity(self) -> int:
        return self._buf.maxlen

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def enable(self, capacity: Optional[int] = None) -> "FlightRecorder":
        """Turn recording on, optionally resizing the ring (a resize keeps
        the newest events that still fit)."""
        with self._lock:
            if capacity is not None and capacity != self._buf.maxlen:
                if capacity < 1:
                    raise ValueError("capacity must be >= 1")
                self._buf = deque(self._buf, maxlen=capacity)
            self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def emit(self, kind: str, rid: Optional[int] = None,
             step: Optional[int] = None, dur_ns: Optional[int] = None,
             t_ns: Optional[int] = None, **data) -> None:
        """Record one event. Disabled-mode cost is this method's first
        flag check (hot paths gate even earlier by holding ``None``).
        ``t_ns`` overrides the start timestamp for timed events whose
        duration was measured before emitting."""
        if not self.enabled:
            return
        if kind not in EVENT_KINDS:
            raise ValueError(f"unknown event kind {kind!r} "
                             "(see monitor.events.EVENT_KINDS)")
        ev = Event(ts_ns=t_ns if t_ns is not None else time.monotonic_ns(),
                   kind=kind, rid=rid, step=step, dur_ns=dur_ns,
                   data=data or None)
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self._dropped += 1
            self._buf.append(ev)

    def snapshot(self) -> List[Event]:
        """The retained events, oldest first."""
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def write_jsonl(self, path: str) -> str:
        """Dump the retained tail as JSONL (one event dict per line,
        oldest first); returns the path. The schema is what
        ``tools/validate_trace.py --kind events`` checks."""
        events = self.snapshot()
        dropped = self.dropped
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            if dropped:
                f.write(json.dumps({"ts_ns": events[0].ts_ns if events else 0,
                                    "kind": "recorder.dropped",
                                    "count": dropped}) + "\n")
            for ev in events:
                f.write(json.dumps(ev.to_dict()) + "\n")
        return path


# ------------------------------------------------------------------ #
# process-global recorder (the engines all share one timeline, so a merged
# post-mortem interleaves training, checkpoint, and serving events)

_recorder: Optional[FlightRecorder] = None
_lock = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


class TaggedRecorder:
    """Replica-tagging emit proxy over a (shared) :class:`FlightRecorder`.

    Every in-process replica records into the ONE global ring (so a merged
    post-mortem interleaves the whole fleet), which means the ring itself
    cannot say which replica an event came from. Each engine therefore
    wraps the shared recorder in its own ``TaggedRecorder``: ``emit``
    stamps ``replica=<name>`` into the payload (``setdefault`` — emit
    sites that name a replica explicitly, like router drains, win), and
    the fleet renderer groups tracks by that tag. ``replica`` is mutable:
    the router renames engines after construction and the schedulers
    holding this wrapper pick the new tag up on their next emit.

    Everything else (``snapshot``/``clear``/``enable``/``write_jsonl``/
    ``dropped``/...) proxies to the wrapped recorder, so existing callers
    cannot tell the difference."""

    def __init__(self, recorder: FlightRecorder, replica: str = "r0"):
        self._recorder = recorder
        self.replica = replica

    def emit(self, kind: str, rid: Optional[int] = None,
             step: Optional[int] = None, dur_ns: Optional[int] = None,
             t_ns: Optional[int] = None, **data) -> None:
        if not self._recorder.enabled:
            return
        data.setdefault("replica", self.replica)
        self._recorder.emit(kind, rid=rid, step=step, dur_ns=dur_ns,
                            t_ns=t_ns, **data)

    def __len__(self) -> int:
        return len(self._recorder)

    def __getattr__(self, name):
        return getattr(self._recorder, name)


def export_recorder_metrics(registry=None,
                            recorder: Optional[FlightRecorder] = None
                            ) -> None:
    """Publish the recorder's ring health as ``events/dropped`` /
    ``events/capacity`` gauges so silent trace loss is visible on the
    ``/metrics`` plane (a post-mortem that trusts a ring which quietly
    evicted its incident is worse than no ring). Called by the exporter
    on every scrape and by the sampler on every tick; a disabled
    recorder exports nothing (nothing is being lost — it records
    nothing by design)."""
    rec = recorder if recorder is not None else get_flight_recorder()
    if not rec.enabled:
        return
    if registry is None:
        from deepspeed_tpu.monitor.metrics import get_registry
        registry = get_registry()
    registry.gauge(
        "events/capacity",
        "flight-recorder ring size (events retained before eviction)"
    ).set(rec.capacity)
    registry.gauge(
        "events/dropped",
        "flight-recorder events evicted since enable/clear — nonzero "
        "means the trace tail no longer reaches back to the incident"
    ).set(rec.dropped)


# ------------------------------------------------------------------ #
# serving trace rendering: chrome-trace JSON, one track per request

_SERVING_PID = 1      # per-request tracks
_ENGINE_PID = 2       # engine spans + counter tracks
_ENGINE_TID = 0

#: request-track child slices: recorder kind -> slice name
_CHILD_SLICES = {"req.prefill": "prefill", "req.prefill_chunk": "prefill_chunk",
                 "req.cow_copy": "cow_copy",
                 "req.spec_propose": "spec_propose",
                 "req.spec_verify": "spec_verify",
                 "kv.fetch": "kv_fetch"}
#: request-track instants
_INSTANTS = {"req.enqueue": "enqueue", "req.submit": "submit",
             "req.cache_hit": "cache_hit",
             "req.cache_miss": "cache_miss", "req.preempt": "preempt",
             "req.cancel": "cancel",
             "req.spec_rollback": "spec_rollback",
             "req.requeue": "requeue", "req.timeout": "timeout",
             "req.shed": "shed"}
#: retirement-flavored kinds: each CLOSES its request's span (a timed-out
#: or shed request's lifetime ends there, exactly like cancel)
_SPAN_CLOSERS = ("req.retire", "req.cancel", "req.timeout", "req.shed")


def render_serving_trace(events: Iterable[Event], *,
                         t0_ns: Optional[int] = None,
                         serving_pid: int = _SERVING_PID,
                         engine_pid: int = _ENGINE_PID,
                         name_prefix: str = "") -> Dict[str, Any]:
    """Render serving events as a chrome-trace document: per-request
    tracks (pid 1, tid = rid) each holding exactly ONE admission→retire
    span (first admission to final retirement — a preempted-and-resumed
    request stays one span, with its preemption as an instant inside)
    with prefill / prefill-chunk / decode-tick / COW child slices, plus
    ``queue_depth`` and ``kv_blocks`` counter tracks and the
    ``generate_batch`` engine spans (pid 2).

    The keyword overrides exist for :func:`render_fleet_trace`, which
    renders each replica's slice of the shared ring as its own process
    pair on ONE timeline: a shared ``t0_ns`` epoch, per-replica pids,
    and a ``name_prefix`` distinguishing the track groups. Defaults
    reproduce the single-replica document exactly."""
    events = [e for e in events
              if e.kind.startswith(("req.", "serve.", "decode.", "sched.",
                                    "kv.", "slo.", "ctl."))]
    out: List[Dict[str, Any]] = []
    if not events:
        return {"traceEvents": out, "displayTimeUnit": "ms"}
    t0 = t0_ns if t0_ns is not None else min(e.ts_ns for e in events)

    def us(ts_ns: int) -> float:
        return (ts_ns - t0) / 1e3

    # ---- per-request lifecycle ---- #
    admits: Dict[int, int] = {}            # rid -> first admission ts
    last_seen: Dict[int, int] = {}         # rid -> newest event end ts
    retires: Dict[int, Event] = {}
    info: Dict[int, Dict[str, Any]] = {}
    for e in events:
        rid = e.rid
        if rid is None and e.kind == "decode.tick":
            end = e.ts_ns + (e.dur_ns or 0)
            for r in (e.data or {}).get("rids", ()):  # fused over many reqs
                last_seen[r] = max(last_seen.get(r, 0), end)
            continue
        if rid is None:
            continue
        # req.phase durations are already-elapsed intervals ENDING at ts
        # (a queue wait reported at admission), so they must not push the
        # request's observed end forward
        last_seen[rid] = max(last_seen.get(rid, 0),
                             e.ts_ns + (0 if e.kind == "req.phase"
                                        else (e.dur_ns or 0)))
        meta = info.setdefault(rid, {"preemptions": 0, "cached_tokens": 0})
        if e.kind == "req.admit":
            admits.setdefault(rid, e.ts_ns)
            meta["cached_tokens"] += (e.data or {}).get("cached_tokens", 0)
        elif e.kind == "req.enqueue":
            meta["prompt_tokens"] = (e.data or {}).get("prompt_tokens")
        elif e.kind == "req.preempt":
            meta["preemptions"] += 1
        elif e.kind in _SPAN_CLOSERS:
            # cancellation / timeout / shed end the request's lifetime
            # exactly like a retirement: the span closes at that instant
            retires[rid] = e
            if e.kind == "req.cancel":
                meta["cancelled"] = True
            elif e.kind == "req.timeout":
                meta["timed_out"] = True
            elif e.kind == "req.shed":
                meta["shed"] = True

    for rid in sorted(admits):
        out.append({"ph": "M", "name": "thread_name", "pid": serving_pid,
                    "tid": rid, "args": {"name": f"{name_prefix}req {rid}"}})
        start = admits[rid]
        ret = retires.get(rid)
        end = ret.ts_ns if ret is not None else last_seen[rid]
        args = {k: v for k, v in info[rid].items() if v is not None}
        if ret is not None:
            args.update({k: v for k, v in (ret.data or {}).items()
                         if v is not None})
        else:
            args["incomplete"] = True      # truncated ring / still running
        out.append({"name": f"request {rid}", "cat": "request", "ph": "X",
                    "pid": serving_pid, "tid": rid, "ts": us(start),
                    "dur": max((end - start) / 1e3, 0.001), "args": args})

    # ---- child slices, instants, counters, engine spans ---- #
    for e in events:
        if e.kind in _CHILD_SLICES:
            out.append({"name": _CHILD_SLICES[e.kind], "cat": "serving",
                        "ph": "X", "pid": serving_pid, "tid": e.rid,
                        "ts": us(e.ts_ns), "dur": (e.dur_ns or 0) / 1e3,
                        "args": dict(e.data or {})})
        elif e.kind in _INSTANTS:
            if e.rid is None:
                # no request track to pin it to (e.g. an intake-deadline
                # timeout that never reached the scheduler): engine track
                out.append({"name": _INSTANTS[e.kind], "cat": "serving",
                            "ph": "i", "s": "p", "pid": engine_pid,
                            "tid": _ENGINE_TID, "ts": us(e.ts_ns),
                            "args": dict(e.data or {})})
                continue
            out.append({"name": _INSTANTS[e.kind], "cat": "serving",
                        "ph": "i", "s": "t", "pid": serving_pid,
                        "tid": e.rid, "ts": us(e.ts_ns),
                        "args": dict(e.data or {})})
        elif e.kind == "req.phase":
            # phase-ledger entries: the interval already elapsed when the
            # phase was reported, so an X slice would spill outside the
            # request span — render as an instant carrying the duration
            d = dict(e.data or {})
            d["dur_ms"] = (e.dur_ns or 0) / 1e6
            out.append({"name": f"phase:{d.get('phase', '?')}",
                        "cat": "serving", "ph": "i", "s": "t",
                        "pid": serving_pid, "tid": e.rid,
                        "ts": us(e.ts_ns), "args": d})
        elif e.kind == "decode.tick":
            d = dict(e.data or {})
            for rid in d.get("rids", ()):
                out.append({"name": "decode", "cat": "serving", "ph": "X",
                            "pid": serving_pid, "tid": rid,
                            "ts": us(e.ts_ns), "dur": (e.dur_ns or 0) / 1e3,
                            "args": {"n": d.get("n")}})
        elif e.kind == "sched.gauge":
            d = dict(e.data or {})
            out.append({"name": "queue_depth", "ph": "C", "pid": engine_pid,
                        "tid": _ENGINE_TID, "ts": us(e.ts_ns),
                        "args": {"queued": d.get("queued", 0),
                                 "running": d.get("running", 0)}})
            out.append({"name": "kv_blocks", "ph": "C", "pid": engine_pid,
                        "tid": _ENGINE_TID, "ts": us(e.ts_ns),
                        "args": {"used": d.get("kv_used", 0),
                                 "free": d.get("kv_free", 0)}})
        elif e.kind == "kv.spill":
            # demotions have no single request: they happen inside another
            # request's allocation, so they render on the engine track
            out.append({"name": "kv_spill", "cat": "serving", "ph": "X",
                        "pid": engine_pid, "tid": _ENGINE_TID,
                        "ts": us(e.ts_ns), "dur": (e.dur_ns or 0) / 1e3,
                        "args": dict(e.data or {})})
        elif e.kind == "serve.end":
            out.append({"name": "generate_batch", "cat": "serving",
                        "ph": "X", "pid": engine_pid, "tid": _ENGINE_TID,
                        "ts": us(e.ts_ns), "dur": (e.dur_ns or 0) / 1e3,
                        "args": dict(e.data or {})})
        elif e.kind == "serve.drain":
            out.append({"name": "drain", "cat": "serving", "ph": "i",
                        "s": "p", "pid": engine_pid, "tid": _ENGINE_TID,
                        "ts": us(e.ts_ns), "args": dict(e.data or {})})
        elif e.kind == "serve.route":
            # replica-router decisions render on the engine track: the
            # trace shows WHICH replica each request landed on and WHY
            # (affinity re-hit, least-loaded, drain failover, handoff)
            out.append({"name": "route", "cat": "serving", "ph": "i",
                        "s": "t", "pid": engine_pid, "tid": _ENGINE_TID,
                        "ts": us(e.ts_ns), "args": dict(e.data or {})})
        elif e.kind == "serve.handoff":
            # prefill->decode transfer completion (the router's causal
            # stitch point; the fleet renderer also draws flow arrows)
            out.append({"name": "handoff", "cat": "serving", "ph": "i",
                        "s": "t", "pid": engine_pid, "tid": _ENGINE_TID,
                        "ts": us(e.ts_ns), "args": dict(e.data or {})})
        elif e.kind in ("serve.fault", "serve.restart"):
            # containment/recovery belongs to the engine timeline: the
            # trace shows WHEN the step died / the engine rebuilt relative
            # to the request spans it re-queued
            out.append({"name": e.kind.split(".", 1)[1], "cat": "serving",
                        "ph": "i", "s": "p", "pid": engine_pid,
                        "tid": _ENGINE_TID, "ts": us(e.ts_ns),
                        "args": dict(e.data or {})})
        elif e.kind == "slo.breach":
            # burn-rate alerts belong to the engine timeline: the trace
            # shows WHEN the budget blew relative to the request spans
            out.append({"name": "slo_breach", "cat": "serving", "ph": "i",
                        "s": "p", "pid": engine_pid, "tid": _ENGINE_TID,
                        "ts": us(e.ts_ns), "args": dict(e.data or {})})
        elif e.kind in ("ctl.apply", "ctl.revert"):
            # controller knob applications on the engine timeline (the
            # serving thread mutates between steps, so the instant sits
            # exactly where the posture changed relative to the request
            # spans), plus a per-knob counter track plotting the value
            d = dict(e.data or {})
            out.append({"name": e.kind.replace(".", "_"), "cat": "serving",
                        "ph": "i", "s": "p", "pid": engine_pid,
                        "tid": _ENGINE_TID, "ts": us(e.ts_ns), "args": d})
            if d.get("knob") is not None and d.get("value") is not None:
                out.append({"name": f"ctl/knob:{d['knob']}", "ph": "C",
                            "pid": engine_pid, "tid": _ENGINE_TID,
                            "ts": us(e.ts_ns),
                            "args": {"value": d["value"]}})

    out.append({"ph": "M", "name": "process_name", "pid": serving_pid,
                "args": {"name": f"{name_prefix}serving requests"}})
    out.append({"ph": "M", "name": "process_name", "pid": engine_pid,
                "args": {"name": f"{name_prefix}serving engine"}})
    out.append({"ph": "M", "name": "thread_name", "pid": engine_pid,
                "tid": _ENGINE_TID, "args": {"name": "engine steps"}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def export_serving_trace(events: Iterable[Event], path: str) -> str:
    """Write :func:`render_serving_trace` of ``events`` to ``path``."""
    doc = render_serving_trace(events)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


# ------------------------------------------------------------------ #
# fleet trace rendering: every replica's slice of the (shared) ring as
# its own track group on ONE timeline, router decisions on their own
# track, and chrome-trace flow arrows stitching the prefill->decode
# handoff across replicas

_ROUTER_PID = 99      # the replica router's decision track
_ROUTER_TID = 0


def render_fleet_trace(events: Iterable[Event]) -> Dict[str, Any]:
    """Merge a replica fleet's serving events onto ONE chrome-trace
    timeline: each replica (the ``replica=`` tag :class:`TaggedRecorder`
    stamps) renders as its own process pair via
    :func:`render_serving_trace` with a SHARED epoch, router decisions
    (``serve.route`` / ``serve.handoff``) land on a dedicated router
    track, and every causal handoff — requests sharing a ``trace=`` id
    across different replicas — gets a ``ph:"s"``/``ph:"f"`` flow arrow
    from the prefill-side span's close to the decode-side span's
    admission, so Perfetto draws the cross-replica hop that a
    per-replica export cannot show."""
    events = [e for e in events
              if e.kind.startswith(("req.", "serve.", "decode.", "sched.",
                                    "kv.", "slo.", "ctl."))]
    out: List[Dict[str, Any]] = []
    if not events:
        return {"traceEvents": out, "displayTimeUnit": "ms"}
    t0 = min(e.ts_ns for e in events)

    def us(ts_ns: int) -> float:
        return (ts_ns - t0) / 1e3

    # ---- split the router plane from the per-replica groups ---- #
    router_events: List[Event] = []
    groups: Dict[str, List[Event]] = {}
    for e in events:
        if e.kind in ("serve.route", "serve.handoff"):
            router_events.append(e)
        else:
            groups.setdefault((e.data or {}).get("replica", "r0"),
                              []).append(e)

    pids: Dict[str, int] = {}              # replica -> its request pid
    for i, name in enumerate(sorted(groups)):
        spid, epid = 2 * i + 1, 2 * i + 2
        pids[name] = spid
        doc = render_serving_trace(groups[name], t0_ns=t0,
                                   serving_pid=spid, engine_pid=epid,
                                   name_prefix=f"{name} ")
        out.extend(doc["traceEvents"])

    for e in router_events:
        out.append({"name": "route" if e.kind == "serve.route"
                    else "handoff", "cat": "serving", "ph": "i", "s": "t",
                    "pid": _ROUTER_PID, "tid": _ROUTER_TID,
                    "ts": us(e.ts_ns), "args": dict(e.data or {})})
    if router_events:
        out.append({"ph": "M", "name": "process_name", "pid": _ROUTER_PID,
                    "args": {"name": "replica router"}})
        out.append({"ph": "M", "name": "thread_name", "pid": _ROUTER_PID,
                    "tid": _ROUTER_TID, "args": {"name": "decisions"}})

    # ---- flow arrows: requests chained by a shared trace id ---- #
    # rids are per-engine counters, so cross-replica collisions are the
    # NORM (both sides of a handoff are often rid 0): every lookup keys
    # on (replica, rid)
    enq: Dict[Any, Any] = {}        # (replica, rid) -> (enqueue ts, trace)
    admits: Dict[Any, int] = {}
    ends: Dict[Any, int] = {}
    for e in events:
        if e.rid is None:
            continue
        key = ((e.data or {}).get("replica", "r0"), e.rid)
        if e.kind == "req.enqueue":
            tr = (e.data or {}).get("trace")
            if tr is not None:
                enq[key] = (e.ts_ns, tr)
        elif e.kind == "req.admit":
            admits.setdefault(key, e.ts_ns)
        elif e.kind in _SPAN_CLOSERS:
            ends[key] = e.ts_ns
    by_trace: Dict[Any, List] = {}
    for (rep, rid), (ts, tr) in enq.items():
        by_trace.setdefault(tr, []).append((ts, rid, rep))
    for tr in sorted(by_trace, key=str):
        hops = sorted(by_trace[tr])        # causal order = enqueue order
        for k, ((ts_a, rid_a, rep_a), (ts_b, rid_b, rep_b)) \
                in enumerate(zip(hops, hops[1:])):
            if rep_a == rep_b or rep_a not in pids or rep_b not in pids:
                continue
            fid = f"{tr}/{k}"
            out.append({"name": "handoff", "cat": "handoff", "ph": "s",
                        "id": fid, "pid": pids[rep_a], "tid": rid_a,
                        "ts": us(ends.get((rep_a, rid_a), ts_a))})
            out.append({"name": "handoff", "cat": "handoff", "ph": "f",
                        "bp": "e", "id": fid, "pid": pids[rep_b],
                        "tid": rid_b,
                        "ts": us(admits.get((rep_b, rid_b), ts_b))})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def export_fleet_trace(sources, path: str) -> str:
    """Write :func:`render_fleet_trace` to ``path``. ``sources`` is an
    iterable of :class:`Event` (e.g. one shared ring's ``snapshot()``),
    a single recorder, or a list of recorders — recorder snapshots are
    merged by timestamp with identity dedupe, so in-process replicas
    whose :class:`TaggedRecorder` wrappers share the ONE global ring
    merge without duplication."""
    items = [sources] if hasattr(sources, "snapshot") else list(sources)
    if items and hasattr(items[0], "snapshot"):
        seen: set = set()
        merged: List[Event] = []
        for rec in items:
            for e in rec.snapshot():
                if id(e) not in seen:
                    seen.add(id(e))
                    merged.append(e)
        merged.sort(key=lambda e: e.ts_ns)
        events = merged
    else:
        events = items
    doc = render_fleet_trace(events)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def dump_events_jsonl(dirpath: str,
                      filename: str = "events.jsonl") -> Optional[str]:
    """Post-mortem helper: write the global recorder's tail into
    ``dirpath/filename`` when recording is on and anything was captured.
    Never raises (debug artifacts must not break the failing path);
    returns the path or None."""
    try:
        rec = get_flight_recorder()
        if not rec.enabled or not len(rec):
            return None
        return rec.write_jsonl(os.path.join(dirpath, filename))
    except Exception:
        return None
