"""Background snapshot daemon: the telemetry plane's time axis.

The registry (``monitor/metrics.py``) is a point-in-time aggregate; the
sampler turns it into a series: every ``interval_s`` it appends one
registry snapshot — including the labeled ``serving/phase_ms`` /
``serving/wasted_tokens`` ledger families — to a size-rotated JSONL
sink (the ``dscli health`` / ``dscli top`` offline source) and to an
in-memory ring, refreshes the
flight-recorder loss gauges (``events/dropped``/``events/capacity``),
and — when an :class:`~deepspeed_tpu.monitor.slo.SloEngine` is attached
— runs one burn-rate evaluation tick.

Cost discipline (the ``serving_metrics_steady`` contract): a tick is
host-side dict work only — ``registry.snapshot()``, JSON serialization,
an append — with **zero device work and zero added compiles**, so the
daemon can run beside a hot serving loop without perturbing it. That is
why a tick deliberately does NOT call ``sample_memory_gauges`` (HBM
stats are a device query; the engines refresh those on their own step
cadence). Importing jax here is a dslint DS009 violation.

Determinism: :meth:`tick` is the whole step — the background thread
only supplies a wall-clock cadence. Tests and trace replay call
``tick()`` themselves, so SLO evaluation ticks line up reproducibly
with a replayed request trace.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional


class MetricsSampler:
    """Periodic registry snapshots → rotated JSONL + ring (+ SLO ticks).

    ``path=None`` keeps the series in the ring only. Rotation: when the
    sink would exceed ``max_bytes``, it shifts ``path -> path.1 -> ...
    -> path.<keep>`` (oldest dropped), so the live file always tails
    cleanly. ``start()`` runs ticks on a daemon thread; ``stop()`` joins
    it. Also a context manager."""

    def __init__(self, registry=None, *, interval_s: float = 1.0,
                 path: Optional[str] = None, max_bytes: int = 16 << 20,
                 keep: int = 2, ring: int = 512, slo=None, ctl=None):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        if keep < 1:
            raise ValueError("keep must be >= 1")
        if registry is None:
            from deepspeed_tpu.monitor.metrics import get_registry
            registry = get_registry()
        self.registry = registry
        self.interval_s = float(interval_s)
        self.path = path
        self.max_bytes = int(max_bytes)
        self.keep = int(keep)
        self.slo = slo
        self.ctl = ctl
        self.ring: deque = deque(maxlen=max(1, int(ring)))
        self.seq = 0
        self._lock = threading.Lock()     # manual tick() vs daemon thread
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- one sampling step (the deterministic unit) ---- #

    def tick(self) -> Dict:
        """Take one snapshot: refresh recorder-loss gauges, run one SLO
        evaluation tick, snapshot the registry, append to ring + sink.
        Returns the record. Host-side only — no device work, ever."""
        with self._lock:
            self.seq += 1
            from deepspeed_tpu.monitor.events import export_recorder_metrics
            export_recorder_metrics(self.registry)
            breaches: List[Dict] = []
            if self.slo is not None:
                breaches = self.slo.sample()
            actions = []
            if self.ctl is not None:
                # controller ticks AFTER the SLO evaluation (it reads the
                # burn gauges that sample() just refreshed) and BEFORE the
                # snapshot, so ctl/knob gauges in this record are current
                actions = self.ctl.tick()
            rec: Dict = {"ts": time.time(), "seq": self.seq}
            if breaches:
                # breach markers ride the snapshot line so an offline
                # tail (dscli top over the JSONL) sees the firing even
                # between counter reads
                rec["slo_breaches"] = breaches
            if actions:
                rec["ctl_actions"] = [a.to_payload() for a in actions]
            rec.update(self.registry.snapshot())
            self.ring.append(rec)
            if self.path:
                self._append(rec)
            return rec

    def _append(self, rec: Dict) -> None:
        line = json.dumps(rec) + "\n"
        path = os.path.abspath(self.path)
        d = os.path.dirname(path)
        os.makedirs(d, exist_ok=True)
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        if size and size + len(line) > self.max_bytes:
            self._rotate(path)
        with open(path, "a") as f:
            f.write(line)

    def _rotate(self, path: str) -> None:
        for i in range(self.keep, 0, -1):
            src = path if i == 1 else f"{path}.{i - 1}"
            dst = f"{path}.{i}"
            try:
                os.replace(src, dst)
            except OSError:
                pass        # a missing intermediate just shortens history

    # ---- background cadence ---- #

    def start(self) -> "MetricsSampler":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="ds-metrics-sampler",
                                        daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — telemetry must not crash
                # the host process; a failing sink degrades to ring-only
                pass

    def stop(self, final_tick: bool = True,
             timeout: Optional[float] = 5.0) -> None:
        """Stop the daemon (and by default take one last snapshot so
        shutdown state lands in the series)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None
        if final_tick:
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — shutdown must not raise
                pass

    def __enter__(self) -> "MetricsSampler":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()


def sampler_from_config(tcfg, registry=None, events=None, ctl=None
                        ) -> Optional[MetricsSampler]:
    """Build the sampler (with an attached SLO engine when
    ``telemetry.slo`` declares objectives, and an attached
    :class:`~deepspeed_tpu.monitor.controller.AdaptiveController` when
    the caller passes one) a :class:`TelemetryConfig` asks for. None
    when neither sampler nor slo is enabled. The caller owns
    ``start()``/``stop()``."""
    scfg = getattr(tcfg, "sampler", None)
    slo_cfg = getattr(tcfg, "slo", None)
    slo_on = slo_cfg is not None and slo_cfg.enabled
    if not ((scfg is not None and scfg.enabled) or slo_on):
        return None
    from deepspeed_tpu.monitor.slo import slo_from_config
    slo = slo_from_config(slo_cfg, registry=registry, events=events) \
        if slo_on else None
    return MetricsSampler(
        registry, interval_s=scfg.interval_s, path=scfg.path,
        max_bytes=scfg.max_bytes, keep=scfg.keep, ring=scfg.ring,
        slo=slo, ctl=ctl)
