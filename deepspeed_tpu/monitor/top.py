"""``dscli top`` — the live operator dashboard over the telemetry plane.

One refreshing terminal screen with serving panes (queue depth, running
rows, TTFT/TPOT/queue-wait percentiles, the request phase-ledger
breakdown from ``serving/phase_ms`` + wasted-token causes, KV pool +
host tier, prefix cache, SLO burn rates, the adaptive controller's knob
posture vs its config baseline with the last action + reason) and
training panes (loss EWMA, grad norm, tokens/s, MFU, fp16 skips), from
either of the plane's two surfaces:

- **scrape mode** — ``dscli top http://host:port/metrics``: fetch the
  Prometheus exposition (the ``dscli serve`` front-end's ``/metrics``
  route or a standalone :class:`MetricsExporter`), parse it back into a
  snapshot (``parse_prometheus_text``), render;
- **tail mode** — ``dscli top telemetry.jsonl``: tail the sampler's (or
  the engine flush cadence's) JSONL time series, exactly like
  ``dscli health`` but with the full pane set.

Rendering is :func:`~deepspeed_tpu.monitor.health.health_summary` →
``render_summary_table`` — the same extraction ``dscli health --json``
uses, so the screen, the JSON surface, and the scrape plane can never
drift apart. Part of the exposition plane: importing jax here is a
dslint DS009 violation.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Dict, Optional, Tuple


def _desanitize(series: str) -> str:
    """Prometheus-sanitized series name → registry name: every metric in
    this repo is ``<layer>/<rest>`` with a slash-free first segment
    (``serving``, ``train``, ``slo``, ...), so the first underscore of
    the sanitized form maps back to the slash. Label blocks pass
    through untouched."""
    name, brace, labels = series.partition("{")
    return name.replace("_", "/", 1) + brace + labels


def snapshot_from_prometheus(text: str) -> Dict:
    """Parsed ``/metrics`` exposition as a registry-snapshot record
    (the shape ``health_summary`` consumes), series names de-sanitized
    back to their ``layer/name`` form."""
    from deepspeed_tpu.monitor.metrics import parse_prometheus_text
    snap = parse_prometheus_text(text)
    return {"ts": time.time(),
            "counters": {_desanitize(k): v
                         for k, v in snap["counters"].items()},
            "gauges": {_desanitize(k): v
                       for k, v in snap["gauges"].items()},
            "histograms": {_desanitize(k): v
                           for k, v in snap["histograms"].items()}}


def fetch_snapshots(source: str, timeout: float = 5.0
                    ) -> Tuple[Optional[Dict], Optional[Dict]]:
    """(latest, previous) snapshot records from ``source`` — a
    ``/metrics`` URL (previous is None: the caller keeps scrape history)
    or a JSONL path. (None, None) when nothing is readable."""
    if source.startswith(("http://", "https://")):
        import urllib.request
        try:
            with urllib.request.urlopen(source, timeout=timeout) as resp:
                text = resp.read().decode("utf-8", "replace")
        except Exception:  # noqa: BLE001 — unreachable scrape = no data
            return None, None
        return snapshot_from_prometheus(text), None
    from deepspeed_tpu.monitor.health import read_last_snapshots
    recs = read_last_snapshots(source, 2)
    if not recs:
        return None, None
    return recs[-1], (recs[-2] if len(recs) > 1 else None)


def render_top(rec: Optional[Dict], prev: Optional[Dict],
               source: str) -> str:
    from deepspeed_tpu.monitor.health import (health_summary,
                                              render_summary_table)
    if rec is None:
        return (f"dscli top: no data from {source}\n"
                "(scrape a /metrics URL — dscli serve exposes one — or "
                "tail a sampler/telemetry JSONL)")
    head = f"source {source}"
    drop = (rec.get("gauges") or {}).get("events/dropped")
    if drop:
        head += f"   [flight recorder dropped {int(drop)}]"
    return head + "\n" + render_summary_table(health_summary(rec, prev))


def top_cli(argv=None) -> int:
    """``dscli top <url-or-jsonl>`` — refreshing dashboard (``--once``
    renders a single screen; ``--json`` prints the summary dict)."""
    import argparse
    parser = argparse.ArgumentParser(
        prog="dscli top",
        description="live serving/training dashboard over a /metrics "
                    "URL or a telemetry JSONL")
    parser.add_argument("source",
                        help="http(s)://.../metrics to scrape, or a "
                             "JSONL telemetry/sampler sink to tail")
    parser.add_argument("--once", action="store_true",
                        help="render one screen and exit")
    parser.add_argument("--json", action="store_true",
                        help="print the latest health_summary as JSON "
                             "and exit")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh period in seconds (default 2)")
    args = parser.parse_args(argv)

    if args.once or args.json:
        rec, prev = fetch_snapshots(args.source)
        if args.json:
            if rec is None:
                print(json.dumps({"error": "no data", "source": args.source}))
                return 1
            from deepspeed_tpu.monitor.health import health_summary
            print(json.dumps(health_summary(rec, prev)))
            return 0
        print(render_top(rec, prev, args.source))
        return 0 if rec is not None else 1
    prev: Optional[Dict] = None
    try:
        while True:
            rec, tail_prev = fetch_snapshots(args.source)
            body = render_top(rec, tail_prev if tail_prev is not None
                              else prev, args.source)
            sys.stdout.write("\033[2J\033[H" + body + "\n")
            sys.stdout.flush()
            if rec is not None:
                prev = rec          # scrape mode: this screen is next
                # screen's rate base (tail mode reads its own history)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
