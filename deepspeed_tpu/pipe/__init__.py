"""Pipeline-parallelism API re-exports (reference ``deepspeed/pipe/__init__.py``)."""

from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule, TiedLayerSpec
from deepspeed_tpu.runtime.pipe.engine import PipelineEngine, spmd_pipeline_loss
from deepspeed_tpu.runtime.pipe.topology import (PipeDataParallelTopology, PipelineParallelGrid,
                                                 PipeModelDataParallelTopology, ProcessTopology)

__all__ = ["LayerSpec", "TiedLayerSpec", "PipelineModule", "PipelineEngine", "spmd_pipeline_loss",
           "ProcessTopology", "PipeDataParallelTopology", "PipeModelDataParallelTopology",
           "PipelineParallelGrid"]
