"""Top-k gating and the expert-parallel MoE layer.

Reference parity: ``deepspeed/moe/sharded_moe.py`` — ``TopKGate`` (:176) with
top-1/top-2 gating, capacity factor, jittered gates, load-balancing auxiliary
loss, and random token selection; ``MOELayer`` (:417) dispatching tokens to
experts with all-to-all over the expert-parallel group.

TPU-native design: the gating math keeps the GShard einsum formulation (the
reference's own ancestry) in pure jnp with STATIC capacity (XLA requires
static shapes — ``drop_tokens=False`` therefore sets capacity = tokens
instead of growing it dynamically). Expert parallelism is declarative:
expert-stacked weights are sharded over the ``ep`` mesh axis and the
dispatched token tensor ``[E, C, D]`` is constrained to ``P("ep")`` on the
expert dim — the SPMD partitioner inserts the all-to-all pair the reference
issues by hand (``sharded_moe.py:467-499``).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float, min_capacity: int) -> int:
    cap = int(math.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


_warned_rts = False


def gumbel_noise(rng, shape):
    u = jax.random.uniform(rng, shape, minval=1e-9, maxval=1.0 - 1e-9)
    return -jnp.log(-jnp.log(u))


def top1gating(logits: jnp.ndarray,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               used_token: Optional[jnp.ndarray] = None,
               noisy_gate_policy: Optional[str] = None,
               drop_tokens: bool = True,
               use_rts: bool = True,
               rng=None) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-1 gating (reference sharded_moe.py:176-300).

    Returns (l_aux, combine_weights [T,E,C], dispatch_mask [T,E,C] bool,
    exp_counts [E]).

    - ``noisy_gate_policy``: None | 'RSample' (gumbel-perturbed routing) |
      'Jitter' (multiplicative input jitter is applied by the gate module).
    - ``use_rts``: random token selection — capacity slots go to a random
      subset of each expert's tokens rather than the lowest token indices,
      debiasing drops (reference :262). Needs ``rng``: gating is a pure
      function, so without a key there is no randomness to draw — RTS falls
      back to positional priority (with a one-time warning) rather than
      reusing a constant key that would re-drop the same positions every step.
    """
    T, E = logits.shape
    C = T if not drop_tokens else _capacity(T, E, capacity_factor, min_capacity)
    C = min(C, T)

    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    route_logits = logits
    if noisy_gate_policy == "RSample":
        if rng is None:
            raise ValueError("RSample gating needs an rng")
        route_logits = logits + gumbel_noise(rng, logits.shape)
    idx1 = jnp.argmax(route_logits, axis=-1)
    mask1 = _one_hot(idx1, E)
    if used_token is not None:
        mask1 = mask1 * used_token[:, None]

    # load-balancing loss: E * sum_e mean_gate_e * mean_count_e
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    # expert load BEFORE capacity truncation (reference :203) — the
    # monitoring signal must show overflow, not the clipped counts
    exp_counts = jnp.sum(mask1, axis=0).astype(jnp.int32)

    if use_rts and rng is None:
        global _warned_rts
        if not _warned_rts:
            from deepspeed_tpu.utils.logging import logger
            logger.warning("top1gating: use_rts=True but no rng was provided; "
                           "falling back to positional capacity priority")
            _warned_rts = True
        use_rts = False

    # capacity assignment priority: positional, or randomized (RTS)
    if use_rts:
        scores = jax.random.uniform(jax.random.fold_in(rng, 1), (T,))
        order = jnp.argsort(scores)  # random permutation of token priority
        mask1_prio = mask1[order]
        loc_sorted = jnp.cumsum(mask1_prio, axis=0) - mask1_prio
        inv = jnp.argsort(order)
        locations1 = jnp.sum(loc_sorted[inv] * mask1, axis=1)
    else:
        loc = jnp.cumsum(mask1, axis=0) - mask1
        locations1 = jnp.sum(loc * mask1, axis=1)

    keep = (locations1 < C).astype(jnp.float32) * jnp.sum(mask1, axis=1)
    mask1 = mask1 * keep[:, None]

    gates1 = jnp.sum(gates * mask1, axis=1)  # selected gate value (0 if dropped)
    combine = (gates1[:, None, None] * mask1[:, :, None] *
               _one_hot(locations1.astype(jnp.int32), C)[:, None, :])
    dispatch_mask = combine > 0
    return l_aux, combine, dispatch_mask, exp_counts


def top2gating(logits: jnp.ndarray,
               capacity_factor: float = 1.0,
               min_capacity: int = 4,
               drop_tokens: bool = True,
               rng=None) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-2 gating (reference sharded_moe.py:303-415): second expert chosen
    from gumbel-perturbed logits with the first masked out; gate values of the
    two experts renormalized; capacity doubled vs top-1."""
    T, E = logits.shape
    C = T if not drop_tokens else _capacity(T, E, 2 * capacity_factor, min_capacity)
    C = min(C, T)

    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(idx1, E)

    noise = gumbel_noise(rng, logits.shape) if rng is not None else 0.0
    logits2 = logits.astype(jnp.float32) + noise
    logits2 = jnp.where(mask1 > 0, -jnp.inf, logits2)
    idx2 = jnp.argmax(logits2, axis=-1)
    mask2 = _one_hot(idx2, E)

    loc1 = jnp.cumsum(mask1, axis=0) - mask1
    # expert-1 tokens take priority; expert-2 slots start after them
    loc2 = jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0, keepdims=True)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    # first-choice expert load before truncation (reference parity)
    exp_counts = jnp.sum(mask1, axis=0).astype(jnp.int32)

    locations1 = jnp.sum(loc1 * mask1, axis=1)
    locations2 = jnp.sum(loc2 * mask2, axis=1)
    mask1 = mask1 * (locations1 < C)[:, None]
    mask2 = mask2 * (locations2 < C)[:, None]

    g1 = jnp.sum(gates * mask1, axis=1)
    g2 = jnp.sum(gates * mask2, axis=1)
    denom = jnp.clip(g1 + g2, 1e-9, None)
    g1, g2 = g1 / denom, g2 / denom

    combine1 = g1[:, None, None] * mask1[:, :, None] * _one_hot(locations1.astype(jnp.int32), C)[:, None, :]
    combine2 = g2[:, None, None] * mask2[:, :, None] * _one_hot(locations2.astype(jnp.int32), C)[:, None, :]
    combine = combine1 + combine2
    dispatch_mask = combine > 0
    return l_aux, combine, dispatch_mask, exp_counts


class TopKGate:
    """Gate module (reference sharded_moe.py:176): a linear router + top-k
    gating. ``params`` = {"wg": [D, E]}."""

    def __init__(self, model_dim: int, num_experts: int, k: int = 1,
                 capacity_factor: float = 1.0, eval_capacity_factor: float = 1.0,
                 min_capacity: int = 4, noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True, use_rts: bool = True):
        if k not in (1, 2):
            raise ValueError("TopKGate supports k=1 or k=2")
        self.model_dim = model_dim
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens
        self.use_rts = use_rts

    def init(self, rng):
        scale = 1.0 / math.sqrt(self.model_dim)
        return {"wg": jax.random.normal(rng, (self.model_dim, self.num_experts)) * scale}

    def __call__(self, params, tokens, used_token=None, rng=None, train: bool = True):
        """tokens [T, D] → (l_aux, combine [T,E,C], dispatch [T,E,C], counts)."""
        x = tokens
        if train and self.noisy_gate_policy == "Jitter" and rng is not None:
            x = x * jax.random.uniform(rng, x.shape, minval=0.99, maxval=1.01)
        logits = x.astype(jnp.float32) @ params["wg"].astype(jnp.float32)
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            return top1gating(logits, cf, self.min_capacity, used_token,
                              self.noisy_gate_policy if train else None,
                              # RTS is a training regularizer: eval routes
                              # deterministically (reference inference kernels)
                              self.drop_tokens, self.use_rts and train, rng=rng)
        return top2gating(logits, cf, self.min_capacity, self.drop_tokens, rng=rng)


def dispatch_combine(tokens: jnp.ndarray,
                     combine: jnp.ndarray,
                     dispatch: jnp.ndarray,
                     expert_fn: Callable,
                     expert_params: Any,
                     mesh=None) -> jnp.ndarray:
    """Dispatch → expert compute → combine (shared by MOELayer and the MoE
    model zoo). ``tokens [T,D]``, ``combine/dispatch [T,E,C]`` →  ``[T,D]``.

    The dispatched tensor is constrained to ``P("ep")`` on its expert dim so
    the SPMD partitioner inserts the all-to-all pair over the ep axis.
    """
    dispatched = jnp.einsum("tec,td->ecd", dispatch.astype(tokens.dtype), tokens)
    if mesh is not None and "ep" in mesh.shape:
        dispatched = jax.lax.with_sharding_constraint(
            dispatched, NamedSharding(mesh, P("ep", None, None)))
    expert_out = jax.vmap(expert_fn)(expert_params, dispatched)
    if mesh is not None and "ep" in mesh.shape:
        expert_out = jax.lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P("ep", None, None)))
    return jnp.einsum("tec,ecd->td", combine.astype(tokens.dtype), expert_out)


class MOELayer:
    """Dispatch → expert compute → combine (reference sharded_moe.py:417).

    ``expert_fn(expert_params_slice, x[C, D]) -> [C, D]`` is vmapped over the
    leading expert dim; expert params and the dispatched tensor are sharded
    over ``ep`` so each device computes only its local experts and XLA
    inserts the all-to-all pair.
    """

    def __init__(self, gate: TopKGate, expert_fn: Callable, num_local_experts: int = 1,
                 mesh=None):
        self.gate = gate
        self.expert_fn = expert_fn
        self.num_local_experts = num_local_experts
        self.mesh = mesh

    def __call__(self, params, x, rng=None, train: bool = True):
        """x [B, S, D] (or [T, D]) → same shape; returns (out, l_aux, exp_counts)."""
        orig_shape = x.shape
        D = orig_shape[-1]
        tokens = x.reshape(-1, D)
        l_aux, combine, dispatch, exp_counts = self.gate(params["gate"], tokens, rng=rng, train=train)
        out = dispatch_combine(tokens, combine, dispatch, self.expert_fn, params["experts"],
                               mesh=self.mesh)
        return out.reshape(orig_shape), l_aux, exp_counts
