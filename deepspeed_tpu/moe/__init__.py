"""Mixture-of-experts with expert parallelism (reference ``deepspeed/moe/``)."""

from deepspeed_tpu.moe.experts import ExpertFFN
from deepspeed_tpu.moe.layer import MoE
from deepspeed_tpu.moe.sharded_moe import MOELayer, TopKGate, top1gating, top2gating
from deepspeed_tpu.moe.utils import has_moe_layers, is_moe_param_path, split_moe_params

__all__ = ["MoE", "ExpertFFN", "MOELayer", "TopKGate", "top1gating", "top2gating",
           "is_moe_param_path", "split_moe_params", "has_moe_layers"]
