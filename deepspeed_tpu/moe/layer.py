"""User-facing MoE layer.

Reference parity: ``deepspeed/moe/layer.py`` — ``MoE`` wrapping gate +
experts (+ optional residual MLP with a learned mixing coefficient,
"Residual MoE" from DeepSpeed-MoE), and the EP×DP process-group bookkeeping
(``layer.py:84`` → ``deepspeed/utils/groups.py``). On TPU the "groups" are
mesh axes: experts shard over ``ep``; ZeRO/data parallelism uses the
remaining axes (see ``deepspeed_tpu/utils/groups.py``).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.utils.init_on_device import honors_on_device

from deepspeed_tpu.moe.experts import ExpertFFN
from deepspeed_tpu.moe.sharded_moe import MOELayer, TopKGate
from deepspeed_tpu.utils.logging import log_dist


class MoE:
    """Mixture-of-experts block: ``out, l_aux, exp_counts = moe(params, x)``.

    Args mirror the reference ``MoE.__init__`` (layer.py:15): hidden_size,
    expert (an ExpertFFN or compatible bank), num_experts, ep_size (informational
    on TPU — the mesh's ``ep`` axis size governs the actual sharding), k,
    capacity factors, noisy gating, drop_tokens, use_rts, use_residual.
    """

    def __init__(self,
                 hidden_size: int,
                 expert: Optional[ExpertFFN] = None,
                 num_experts: int = 1,
                 ep_size: int = 1,
                 k: int = 1,
                 capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0,
                 min_capacity: int = 4,
                 use_residual: bool = False,
                 noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True,
                 use_rts: bool = True,
                 d_ff: Optional[int] = None,
                 mesh=None):
        if num_experts % max(ep_size, 1) != 0:
            raise ValueError(f"num_experts {num_experts} must be divisible by ep_size {ep_size}")
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        self.ep_size = ep_size
        self.num_local_experts = num_experts // max(ep_size, 1)
        self.use_residual = use_residual
        self.expert = expert or ExpertFFN(num_experts, hidden_size, d_ff or 4 * hidden_size)
        self.gate = TopKGate(hidden_size, num_experts, k, capacity_factor, eval_capacity_factor,
                             min_capacity, noisy_gate_policy, drop_tokens, use_rts)
        self.moe_layer = MOELayer(self.gate, self.expert.apply_one, self.num_local_experts, mesh=mesh)
        log_dist(f"MoE: {num_experts} experts, k={k}, capacity_factor={capacity_factor}, "
                 f"residual={use_residual}", ranks=[0])

    @honors_on_device
    def init_params(self, rng) -> Dict[str, Any]:
        kg, ke, kr, kc = jax.random.split(rng, 4)
        params: Dict[str, Any] = {"gate": self.gate.init(kg), "experts": self.expert.init(ke)}
        if self.use_residual:
            D = self.hidden_size
            F = self.expert.d_ff
            s_in, s_out = 1.0 / math.sqrt(D), 1.0 / math.sqrt(F)
            # one fresh key per draw: fold_in on the key w_up already
            # consumed would derive w_down from a spent key (DS002)
            k_up, k_down = jax.random.split(kr)
            params["residual_mlp"] = {
                "w_up": jax.random.normal(k_up, (D, F)) * s_in, "b_up": jnp.zeros((F,)),
                "w_down": jax.random.normal(k_down, (F, D)) * s_out,
                "b_down": jnp.zeros((D,))}
            params["coefficient"] = {"w": jax.random.normal(kc, (D, 2)) * 0.02, "b": jnp.zeros((2,))}
        return params

    def ep_specs(self) -> Dict[str, Any]:
        specs: Dict[str, Any] = {"gate": {"wg": P(None, None)}, "experts": self.expert.ep_specs()}
        if self.use_residual:
            specs["residual_mlp"] = {"w_up": P(None, "tp"), "b_up": P("tp"),
                                     "w_down": P("tp", None), "b_down": P(None)}
            specs["coefficient"] = {"w": P(None, None), "b": P(None)}
        return specs

    def __call__(self, params, x, rng=None, train: bool = True):
        out, l_aux, exp_counts = self.moe_layer(params, x, rng=rng, train=train)
        if self.use_residual:
            rp = params["residual_mlp"]
            h = jax.nn.gelu(x @ rp["w_up"] + rp["b_up"], approximate=True)
            res = h @ rp["w_down"] + rp["b_down"]
            coef = jax.nn.softmax(x @ params["coefficient"]["w"] + params["coefficient"]["b"], axis=-1)
            out = out * coef[..., 0:1] + res * coef[..., 1:2]
        return out, l_aux, exp_counts
