"""MoE parameter classification helpers.

Reference parity: ``deepspeed/moe/utils.py`` — ``is_moe_param`` (:14) and the
param-group splitting used by ZeRO to give expert params their own
(expert-data-parallel) partitioning group. On TPU the analogue is a path
predicate over the params pytree: expert leaves live under an "experts" key
and are sharded over ``ep``, so ZeRO's dp sharding must skip the ``ep`` dims
— which `ZeroShardingRules` does by treating the ep spec like a TP spec.
"""

from __future__ import annotations

from typing import Any, List, Tuple

import jax


def is_moe_param_path(path: Tuple) -> bool:
    """True if a pytree key-path belongs to an expert parameter."""
    for k in path:
        name = getattr(k, "key", getattr(k, "name", None))
        if name == "experts":
            return True
    return False


def split_moe_params(params: Any) -> Tuple[List, List]:
    """(expert_leaves, dense_leaves) by key path."""
    expert, dense = [], []
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        (expert if is_moe_param_path(path) else dense).append(leaf)
    return expert, dense


def has_moe_layers(model) -> Tuple[bool, int]:
    """(has_moe, num_experts) for an engine-visible model."""
    moe = getattr(model, "moe", None)
    if moe is not None:
        return True, getattr(moe, "num_experts", 0)
    if getattr(model, "num_experts", 0):
        return True, model.num_experts
    return False, 0
