"""Expert bank: an expert-stacked feed-forward network.

Reference parity: ``deepspeed/moe/experts.py`` — ``Experts`` holding
``num_local_experts`` copies of the expert module. TPU-native: ONE parameter
pytree with a leading ``num_experts`` dim (sharded over ``ep``), applied with
``jax.vmap`` — the stacked layout XLA partitions cleanly instead of a Python
list of modules.
"""

from __future__ import annotations

import math
from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class ExpertFFN:
    """num_experts × (Linear → activation → Linear)."""

    def __init__(self, num_experts: int, d_model: int, d_ff: int, activation: str = "gelu"):
        self.num_experts = num_experts
        self.d_model = d_model
        self.d_ff = d_ff
        self.activation = activation

    def init(self, rng) -> Dict[str, jnp.ndarray]:
        k1, k2 = jax.random.split(rng)
        E, D, F = self.num_experts, self.d_model, self.d_ff
        s_in, s_out = 1.0 / math.sqrt(D), 1.0 / math.sqrt(F)
        return {"w_up": jax.random.normal(k1, (E, D, F)) * s_in,
                "b_up": jnp.zeros((E, F)),
                "w_down": jax.random.normal(k2, (E, F, D)) * s_out,
                "b_down": jnp.zeros((E, D))}

    def apply_one(self, p, x):
        """One expert: p leaves without the leading E dim, x [C, D]."""
        h = x @ p["w_up"] + p["b_up"]
        h = jax.nn.gelu(h, approximate=True) if self.activation == "gelu" else jax.nn.relu(h)
        return h @ p["w_down"] + p["b_down"]

    def ep_specs(self) -> Dict[str, P]:
        """Expert-parallel shardings: experts over ``ep``, with the ff dim
        available for tp."""
        return {"w_up": P("ep", None, "tp"), "b_up": P("ep", "tp"),
                "w_down": P("ep", "tp", None), "b_down": P("ep", None)}
