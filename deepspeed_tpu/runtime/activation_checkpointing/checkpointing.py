"""Activation checkpointing (reference
``runtime/activation_checkpointing/checkpointing.py`` — the Megatron-derived
``CheckpointFunction`` with activation partitioning, CPU checkpointing, RNG
fork tracking and ``configure()``).

TPU mapping: manual save/recompute becomes ``jax.checkpoint`` (remat).

- default → full remat (save only inputs, like the reference's checkpoint)
- ``partition_activations`` → residuals carry a sharding constraint over the
  tp/sp axes instead of being gathered (the reference splits saved
  activations across TP ranks, ``:366``); under SPMD saved residuals are
  already sharded like the forward values, so this is the default behavior
  and the flag simply keeps the constraint explicit
- ``cpu_checkpointing`` → remat policy that offloads saved dots to pinned
  host memory (``save_and_offload_only_these_names`` family)
- ``CudaRNGStatesTracker`` → named JAX PRNG streams forked per checkpoint
  region (``get_rng_tracker``/``model_parallel_seed``)
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax

_config: Dict[str, Any] = {
    "partition_activations": False,
    "contiguous_memory_optimization": False,
    "cpu_checkpointing": False,
    "number_checkpoints": None,
    "synchronize_checkpoint_boundary": False,
    "profile": False,
    "configured": False,
}


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None, checkpoint_in_cpu=None,
              synchronize=None, profile=None) -> None:
    """Reference ``configure()`` (``checkpointing.py:789``)."""
    cfg = None
    if deepspeed_config is not None:
        if hasattr(deepspeed_config, "activation_checkpointing_config"):
            cfg = deepspeed_config.activation_checkpointing_config
        elif isinstance(deepspeed_config, dict):
            from deepspeed_tpu.runtime.activation_checkpointing.config import (
                DeepSpeedActivationCheckpointingConfig)
            cfg = DeepSpeedActivationCheckpointingConfig(
                **deepspeed_config.get("activation_checkpointing", {}))
    if cfg is not None:
        _config.update(
            partition_activations=cfg.partition_activations,
            contiguous_memory_optimization=cfg.contiguous_memory_optimization,
            cpu_checkpointing=cfg.cpu_checkpointing,
            number_checkpoints=cfg.number_checkpoints,
            synchronize_checkpoint_boundary=cfg.synchronize_checkpoint_boundary,
            profile=cfg.profile)
    for key, val in (("partition_activations", partition_activations),
                     ("contiguous_memory_optimization", contiguous_checkpointing),
                     ("number_checkpoints", num_checkpoints),
                     ("cpu_checkpointing", checkpoint_in_cpu),
                     ("synchronize_checkpoint_boundary", synchronize),
                     ("profile", profile)):
        if val is not None:
            _config[key] = val
    _config["configured"] = True


def is_configured() -> bool:
    return _config["configured"]


def reset() -> None:
    for key in _config:
        _config[key] = False if isinstance(_config[key], bool) else None
    _config["configured"] = False


def _policy():
    """Map config → jax.checkpoint policy."""
    if _config["cpu_checkpointing"]:
        pols = jax.checkpoint_policies
        # offload matmul results to pinned host memory instead of recompute
        if hasattr(pols, "offload_dot_with_no_batch_dims"):
            return pols.offload_dot_with_no_batch_dims("device", "pinned_host")
    return None  # full remat: save nothing but the inputs


def checkpoint(function: Callable, *args):
    """Reference ``checkpoint(function, *args)`` (``CheckpointFunction``,
    ``checkpointing.py:474``): run ``function`` saving only its inputs (or
    the configured policy's residuals); recompute in backward."""
    policy = _policy()
    wrapped = jax.checkpoint(function, policy=policy, prevent_cse=False)
    return wrapped(*args)


def checkpoint_wrapper(function: Callable) -> Callable:
    """Decorator form used by model code (``lax.scan`` bodies)."""
    policy = _policy()
    return jax.checkpoint(function, policy=policy, prevent_cse=False)


# ------------------------------------------------------------------ #
# RNG tracking (reference CudaRNGStatesTracker, checkpointing.py:121)

_MODEL_PARALLEL_RNG = "model-parallel-rng"


class RNGStatesTracker:
    """Named PRNG streams; ``fork`` yields a fresh key per call within a
    name, deterministically — the JAX analogue of forked CUDA RNG states."""

    def __init__(self):
        self.states: Dict[str, jax.Array] = {}

    def reset(self) -> None:
        self.states = {}

    def get_states(self) -> Dict[str, jax.Array]:
        return dict(self.states)

    def set_states(self, states: Dict[str, jax.Array]) -> None:
        self.states = dict(states)

    def add(self, name: str, seed: int) -> None:
        if name in self.states:
            raise ValueError(f"rng state {name} already exists")
        self.states[name] = jax.random.key(seed)

    def fork(self, name: str = _MODEL_PARALLEL_RNG) -> jax.Array:
        """Split the named stream and return a fresh key."""
        if name not in self.states:
            raise ValueError(f"rng state {name} was never seeded")
        self.states[name], out = jax.random.split(self.states[name])
        return out


_RNG_TRACKER = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    return _RNG_TRACKER


def model_parallel_seed(seed: int, tp_rank: int = 0) -> None:
    """Reference ``model_parallel_cuda_manual_seed`` (``:198``): the model-
    parallel stream is offset per TP rank so dropout differs across ranks
    while the default stream stays identical."""
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add(_MODEL_PARALLEL_RNG, seed + 2718 + tp_rank)
