"""Activation checkpointing config.

Reference parity: ``deepspeed/runtime/activation_checkpointing/config.py``.
On TPU these knobs drive ``jax.checkpoint`` (remat) policies rather than
manual save/recompute: ``partition_activations`` becomes sequence/TP-axis
sharding of saved activations; ``cpu_checkpointing`` becomes a host-offload
remat policy (``jax.ad_checkpoint.checkpoint_policies.offload_dot_with_no_batch_dims``-style).
"""

from __future__ import annotations

from deepspeed_tpu.config.config_utils import ConfigModel


class DeepSpeedActivationCheckpointingConfig(ConfigModel):
    partition_activations: bool = False
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    number_checkpoints: int | None = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
