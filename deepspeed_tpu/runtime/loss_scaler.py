"""Loss scaling for fp16 training.

Reference parity: ``deepspeed/runtime/fp16/loss_scaler.py`` —
``LossScaler`` (static) and ``DynamicLossScaler`` (grow/backoff with
hysteresis). Rebuilt as a pure state-transition so the overflow check and
scale update live *inside* the compiled train step (reference "hard part"
noted in SURVEY.md §7: skip-update semantics without a host round-trip).

State is a small pytree; ``update(state, overflow)`` returns the next state.
The train step uses ``jax.lax.cond`` on ``overflow`` to skip the optimizer
update for that step, exactly matching the reference's skip semantics.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class LossScaleState:
    loss_scale: jnp.ndarray        # f32 scalar
    good_steps: jnp.ndarray        # i32 scalar: consecutive non-overflow steps
    hysteresis: jnp.ndarray        # i32 scalar: remaining tolerated overflows
    # static config: aux data of the pytree, not traced leaves
    init_scale: float = dataclasses.field(default=2.0**16, metadata={"static": True})
    scale_window: int = dataclasses.field(default=1000, metadata={"static": True})
    min_scale: float = dataclasses.field(default=1.0, metadata={"static": True})
    delayed_shift: int = dataclasses.field(default=2, metadata={"static": True})
    scale_factor: float = dataclasses.field(default=2.0, metadata={"static": True})
    dynamic: bool = dataclasses.field(default=True, metadata={"static": True})

    def _replace(self, **kwargs) -> "LossScaleState":
        return dataclasses.replace(self, **kwargs)


def make_loss_scale_state(init_scale: float = 2.0**16,
                          scale_window: int = 1000,
                          min_scale: float = 1.0,
                          delayed_shift: int = 2,
                          scale_factor: float = 2.0,
                          dynamic: bool = True) -> LossScaleState:
    return LossScaleState(
        loss_scale=jnp.asarray(init_scale, jnp.float32),
        good_steps=jnp.asarray(0, jnp.int32),
        hysteresis=jnp.asarray(delayed_shift, jnp.int32),
        init_scale=init_scale,
        scale_window=scale_window,
        min_scale=min_scale,
        delayed_shift=delayed_shift,
        scale_factor=scale_factor,
        dynamic=dynamic,
    )


def has_overflow(grads) -> jnp.ndarray:
    """True if any grad element is NaN/Inf (reference CheckOverflow,
    runtime/utils.py:171 — here a single fused reduction instead of a
    per-tensor loop + collective)."""
    import jax

    leaves = jax.tree.leaves(grads)
    if not leaves:
        return jnp.asarray(False)
    flat = [jnp.sum(jnp.abs(leaf.astype(jnp.float32))) for leaf in leaves]
    total = sum(flat)
    return ~jnp.isfinite(total)


def count_nonfinite(tree) -> jnp.ndarray:
    """Total non-finite elements across the pytree (fp32 scalar) — the
    counting twin of :func:`has_overflow`, feeding the health sentinels:
    where ``has_overflow`` answers "skip this step?", this answers "how
    bad is it?" for the anomaly report."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    return sum(jnp.sum(~jnp.isfinite(l.astype(jnp.float32))).astype(jnp.float32)
               for l in leaves)


def update(state: LossScaleState, overflow) -> LossScaleState:
    """Next scaler state after a step that did/didn't overflow."""
    if not state.dynamic:
        return state
    overflow = jnp.asarray(overflow)

    # overflow: consume hysteresis; only back off once hysteresis exhausted
    new_hyst = jnp.where(overflow, jnp.maximum(state.hysteresis - 1, 0), state.hysteresis)
    backoff = overflow & (state.hysteresis <= 1)
    scale_after_backoff = jnp.maximum(state.loss_scale / state.scale_factor, state.min_scale)

    # growth: scale_window consecutive good steps
    good = jnp.where(overflow, 0, state.good_steps + 1)
    grow = (~overflow) & (good >= state.scale_window)
    new_scale = jnp.where(backoff, scale_after_backoff,
                          jnp.where(grow, state.loss_scale * state.scale_factor, state.loss_scale))
    good = jnp.where(grow, 0, good)
    new_hyst = jnp.where(~overflow & (state.good_steps > 0), jnp.asarray(state.delayed_shift, jnp.int32), new_hyst)

    return state._replace(loss_scale=new_scale, good_steps=good.astype(jnp.int32),
                          hysteresis=new_hyst.astype(jnp.int32))


# Reference-shaped class wrappers --------------------------------------- #

class LossScalerBase:

    def __init__(self, cur_scale: float):
        self.cur_scale = cur_scale
        self.dynamic = False

    @property
    def loss_scale(self):
        return self.cur_scale

    def scale_gradient(self, module, grad_in, grad_out):
        return tuple(self.loss_scale * g for g in grad_in)

    def update_scale(self, overflow):
        pass

    def backward(self, loss, retain_graph=False):
        return loss * self.loss_scale


class LossScaler(LossScalerBase):
    """Static loss scale."""

    def __init__(self, scale: float = 1.0):
        super().__init__(scale)


class DynamicLossScaler(LossScalerBase):
    """Host-side mirror of the in-step dynamic scaler (for reference-shaped
    access patterns and tests)."""

    def __init__(self, init_scale: float = 2.0**32, scale_factor: float = 2.0, scale_window: int = 1000,
                 min_scale: float = 1.0, delayed_shift: int = 1, consecutive_hysteresis: bool = False,
                 raise_error_at_min_scale: bool = True):
        super().__init__(init_scale)
        self.cur_iter = 0
        self.last_overflow_iter = -1
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.delayed_shift = delayed_shift
        self.cur_hysteresis = delayed_shift
        self.consecutive_hysteresis = consecutive_hysteresis
        self.raise_error_at_min_scale = raise_error_at_min_scale
        self.dynamic = True

    def update_scale(self, overflow: bool):
        if overflow:
            if self.delayed_shift == 1 or self.cur_hysteresis == 1:
                if self.cur_scale == self.min_scale and self.raise_error_at_min_scale:
                    raise Exception("Current loss scale already at minimum - cannot decrease scale anymore. "
                                    "Exiting run.")
                self.cur_scale = max(self.cur_scale / self.scale_factor, self.min_scale)
            else:
                self.cur_hysteresis -= 1
            self.last_overflow_iter = self.cur_iter
        else:
            if self.consecutive_hysteresis:
                self.cur_hysteresis = self.delayed_shift
            if (self.cur_iter - self.last_overflow_iter) % self.scale_window == 0:
                if not self.consecutive_hysteresis:
                    self.cur_hysteresis = self.delayed_shift
                self.cur_scale *= self.scale_factor
        self.cur_iter += 1


INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
MIN_LOSS_SCALE = "min_scale"


def CreateLossScaler(dtype, static_loss_scale, dynamic_scaling, dynamic_loss_args):
    """Factory mirroring the reference's loss_scaler.CreateLossScaler."""
    import jax.numpy as jnp_
    if dtype == jnp_.float16 and dynamic_scaling:
        kwargs = dynamic_loss_args or {}
        return DynamicLossScaler(**kwargs)
    loss_scale_value = static_loss_scale if dtype == jnp_.float16 else 1.0
    return LossScaler(scale=loss_scale_value)
