"""Progressive layer dropping (reference: deepspeed/runtime/progressive_layer_drop.py).

Keeps a theta value that decays toward ``theta`` over training; models that
support PLD read ``get_theta()`` and skip layers stochastically with
probability schedules derived from it.
"""

from __future__ import annotations

import numpy as np


class ProgressiveLayerDrop:

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> None:
        def _prob(x, gamma, p):
            return (1.0 - p) * np.exp(-gamma * x) + p

        self.current_theta = _prob(global_step, self.gamma, self.theta)
