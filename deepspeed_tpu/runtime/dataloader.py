"""Data loading helpers.

Reference parity: ``deepspeed/runtime/dataloader.py`` —
``DeepSpeedDataLoader`` (distributed sampling + batching) and
``RepeatingLoader``. Works with torch datasets/dataloaders, plain sequences,
or generators of numpy arrays; yields host numpy pytrees the engine shards
onto the mesh.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

import numpy as np


class RepeatingLoader:
    """Wrap an iterator to restart from the beginning when exhausted
    (reference dataloader.py:9)."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        return batch


def resume_loader_iterator(loader, consumed_batches: int):
    """Auto-resume support: a standing iterator over ``loader`` positioned
    ``consumed_batches`` batches in, continuing across epoch boundaries
    forever (so a resumed run sees exactly the batches an uninterrupted run
    would have seen next). For :class:`DeepSpeedDataLoader` the consumed
    epochs are replayed by COUNTER, not by iteration: ``loader.epoch`` is
    set so the shuffle seed of the current epoch matches, then only the
    in-epoch remainder is skipped."""
    per_epoch = None
    try:
        per_epoch = len(loader)
    except TypeError:
        pass
    skip = consumed_batches
    if per_epoch and hasattr(loader, "epoch"):
        loader.epoch = consumed_batches // per_epoch
        skip = consumed_batches % per_epoch

    def _stream():
        skipped = 0
        while True:
            empty = True
            for item in iter(loader):
                empty = False
                if skipped < skip:
                    skipped += 1
                    continue
                yield item
            if empty:
                # an empty pass would otherwise spin forever (empty dataset,
                # or a one-shot generator that iter() cannot restart)
                raise RuntimeError(
                    f"resume_loader_iterator: loader yielded no batches; "
                    f"cannot position the stream {skip} batch(es) in")

    return _stream()


def _default_collate(samples):
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([s[i] for s in samples]) for i in range(len(first)))
    return np.stack(samples)


class DeepSpeedDataLoader:
    """Batches a dataset for this process's data-parallel shard.

    In the single-controller JAX model every process loads its slice of the
    global batch; with one process (TPU slice per host), that is the whole
    per-host batch and the engine shards it over the mesh.
    """

    def __init__(self,
                 dataset,
                 batch_size: int,
                 collate_fn: Optional[Callable] = None,
                 drop_last: bool = False,
                 shuffle: bool = False,
                 seed: int = 0,
                 num_local_io_workers: int = 0,
                 data_sampler=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _default_collate
        self.drop_last = drop_last
        self.shuffle = shuffle
        self.seed = seed
        self.data_sampler = data_sampler
        self.epoch = 0

    def __len__(self):
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        n = len(self.dataset)
        if self.data_sampler is not None:
            order = list(iter(self.data_sampler))
        elif self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            order = rng.permutation(n).tolist()
        else:
            order = list(range(n))
        self.epoch += 1
        for start in range(0, n, self.batch_size):
            idx = order[start:start + self.batch_size]
            if len(idx) < self.batch_size and self.drop_last:
                break
            yield self.collate_fn([self.dataset[i] for i in idx])
