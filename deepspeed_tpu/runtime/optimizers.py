"""Optimizer factory.

Reference parity: ``deepspeed/runtime/engine.py:1225``
(``_configure_basic_optimizer`` choosing Adam/AdamW/Lamb/1-bit/cpu-offload
variants). Optimizers are optax ``GradientTransformation``s so ZeRO sharding
rules apply uniformly to their state trees; the "fused" device variants
(Pallas) and the C++ host ``cpu_adam`` slot in behind the same names
(see ``deepspeed_tpu.ops``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import optax

from deepspeed_tpu.config import core as config_core
from deepspeed_tpu.utils.logging import logger


def _adam_args(params: Dict[str, Any]) -> Dict[str, Any]:
    betas = params.get("betas", (0.9, 0.999))
    return dict(
        learning_rate=None,
        b1=betas[0],
        b2=betas[1],
        eps=params.get("eps", 1e-8),
    )


def build_optimizer(name: Optional[str],
                    params: Optional[Dict[str, Any]] = None,
                    offload: bool = False) -> optax.GradientTransformation:
    """Build the inner optimizer (LR is injected by the engine each step via
    ``optax.inject_hyperparams``-free scaling, so schedules stay inside jit).
    """
    params = dict(params or {})
    name = (name or config_core.ADAMW_OPTIMIZER).lower()
    wd = params.get("weight_decay", 0.0)

    if name in (config_core.ONEBIT_ADAM_OPTIMIZER, config_core.ZERO_ONE_ADAM_OPTIMIZER,
                config_core.ONEBIT_LAMB_OPTIMIZER):
        # the 1-bit family are not optax transformations: their compressed
        # collectives run INSIDE the engine's compiled step (engine
        # _build_onebit_batch_fn; reference runtime/fp16/onebit/adam.py:11)
        raise ValueError(
            f"{name} is engine-integrated (compressed collectives inside the step); "
            "configure it via deepspeed_tpu.initialize(config={'optimizer': ...}) — "
            "it cannot be built as a standalone optax transformation")

    if name == config_core.ADAM_OPTIMIZER:
        # reference Adam applies L2-style weight decay unless adam_w_mode
        adam_w_mode = params.get("adam_w_mode", False)
        args = _adam_args(params)
        if adam_w_mode or wd == 0.0:
            tx = optax.chain(optax.scale_by_adam(b1=args["b1"], b2=args["b2"], eps=args["eps"]),
                             optax.add_decayed_weights(wd) if wd else optax.identity())
        else:
            tx = optax.chain(optax.add_decayed_weights(wd),
                             optax.scale_by_adam(b1=args["b1"], b2=args["b2"], eps=args["eps"]))
        return tx

    if name == config_core.ADAMW_OPTIMIZER:
        args = _adam_args(params)
        return optax.chain(optax.scale_by_adam(b1=args["b1"], b2=args["b2"], eps=args["eps"]),
                           optax.add_decayed_weights(wd) if wd else optax.identity())

    if name == config_core.FUSED_ADAM_OPTIMIZER:
        # named Pallas fused op (reference csrc/adam/multi_tensor_adam.cu:163)
        from deepspeed_tpu.ops.adam.fused_adam_kernel import fused_adam
        args = _adam_args(params)
        return fused_adam(b1=args["b1"], b2=args["b2"], eps=args["eps"],
                          weight_decay=wd,
                          adam_w_mode=params.get("adam_w_mode", True))

    if name == config_core.FUSED_LAMB_OPTIMIZER:
        # named Pallas fused op (reference csrc/lamb/fused_lamb_cuda_kernel.cu)
        from deepspeed_tpu.ops.lamb.fused_lamb_kernel import fused_lamb
        betas = params.get("betas", (0.9, 0.999))
        return fused_lamb(b1=betas[0], b2=betas[1], eps=params.get("eps", 1e-6),
                          weight_decay=wd)

    if name == config_core.LAMB_OPTIMIZER:
        betas = params.get("betas", (0.9, 0.999))
        return optax.chain(
            optax.scale_by_adam(b1=betas[0], b2=betas[1], eps=params.get("eps", 1e-6)),
            optax.add_decayed_weights(wd) if wd else optax.identity(),
            optax.scale_by_trust_ratio(),
        )

    if name == config_core.SGD_OPTIMIZER:
        return optax.chain(
            optax.trace(decay=params.get("momentum", 0.0), nesterov=params.get("nesterov", False)),
            optax.add_decayed_weights(wd) if wd else optax.identity(),
        )

    if name == config_core.ADAGRAD_OPTIMIZER:
        return optax.chain(
            optax.scale_by_rss(initial_accumulator_value=params.get("initial_accumulator_value", 0.0),
                               eps=params.get("eps", 1e-10)),
            optax.add_decayed_weights(wd) if wd else optax.identity(),
        )

    if name == config_core.LION_OPTIMIZER:
        betas = params.get("betas", (0.9, 0.99))
        return optax.chain(
            optax.scale_by_lion(b1=betas[0], b2=betas[1]),
            optax.add_decayed_weights(wd) if wd else optax.identity(),
        )

    raise ValueError(f"Unknown optimizer: {name}")


def optimizer_momenta(name: Optional[str], params: Optional[Dict[str, Any]]):
    """The momenta ``build_optimizer`` actually applies for this config —
    shares the builder's key lookups and defaults so engine.get_mom() can
    never report values the optimizer ignored. Returns a ``momentum`` float
    for the SGD family, a ``(b1, b2)`` tuple for the Adam family, or None
    for a client-supplied optax chain (not introspectable)."""
    if name is None or name == "client":
        return None
    params = params or {}
    lname = name.lower()
    if lname in ("sgd", "rmsprop"):
        return params.get("momentum", 0.0)
    if lname == "lion":
        return tuple(params.get("betas", (0.9, 0.99)))
    # adam / adamw / fusedadam / lamb / fusedlamb / onebit* default alike
    return tuple(params.get("betas", (0.9, 0.999)))
