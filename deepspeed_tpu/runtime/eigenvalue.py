"""Per-block Hessian max-eigenvalue estimation by power iteration.

Reference parity: ``deepspeed/runtime/eigenvalue.py:13`` (``Eigenvalue`` —
power iteration with Hessian-vector products per transformer block, used by
the training-time quantizer to schedule per-layer precision: blocks with
larger curvature quantize later/finer, ``deepspeed/runtime/quantize.py``).

TPU redesign: the reference needs ``torch.autograd.grad(grads, params,
grad_outputs=v, retain_graph=True)`` on a live autograd graph, which forces
it to run between backward and step. In JAX the Hessian-vector product is a
closed-form transform — forward-over-reverse ``jvp(grad(loss))`` — so the
whole power iteration is a pure jittable function of ``(params, batch)``
that can run anywhere (engine hook, async eval job, ...). Block restriction
is a tangent mask: tangents are zero outside the block's leaves, and the
iteration stays inside that subspace because H is block-restricted by the
mask on both sides.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import log_dist


def _nan_to_num(x):
    return jnp.nan_to_num(x, nan=0.0, posinf=0.0, neginf=0.0)


def _inner(a, b):
    return sum(jnp.sum(x * y) for x, y in
               zip(jax.tree.leaves(a), jax.tree.leaves(b)))


class Eigenvalue:
    """Config surface mirrors the reference (max_iter/tol/stability/
    gas_boundary_resolution); ``layer_name``/``layer_num`` become an
    explicit block mask list (functional params have no module paths)."""

    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution

    # -------------------- core math -------------------- #

    def _hvp_fn(self, loss_fn: Callable):
        def hvp(params, v):
            return jax.jvp(jax.grad(loss_fn), (params,), (v,))[1]
        return hvp

    def _power_iterate(self, hvp, params, v, mask, scale):
        """Power iteration restricted to the masked subspace."""
        def project(t):
            return jax.tree.map(lambda x, m: _nan_to_num(x) * m, t, mask)

        def normalize(t):
            norm = jnp.sqrt(_inner(t, t)) + self.stability
            return jax.tree.map(lambda x: _nan_to_num(x / norm), t)

        v = normalize(project(v))
        eig_prev, eig = 0.0, 1.0
        it = 0
        while it < self.max_iter and abs(eig) > 0 and \
                abs((eig - eig_prev) / eig) >= self.tol:
            eig_prev = eig
            hv = project(hvp(params, v))
            eig = float(_inner(hv, v))
            v = jax.tree.map(lambda x: x / scale, normalize(hv))
            it += 1
        return eig * scale, it

    # -------------------- public API -------------------- #

    def compute_eigenvalue(self, loss_fn: Callable, params: Any,
                           blocks: Sequence[Any], rng=None,
                           scale: float = 1.0) -> List[float]:
        """Max |eigenvalue| of the loss Hessian restricted to each block.

        ``loss_fn(params) -> scalar`` (close over the batch); ``blocks`` is a
        list of 0/1 masks congruent with ``params`` selecting each block's
        leaves. Returns the reference's post-processed values: ``|λ|`` mapped
        to [0, 1] by the max across blocks, invalid blocks → 1.0.
        """
        rng = jax.random.key(0) if rng is None else rng
        hvp = self._hvp_fn(loss_fn)
        raw = []
        for i, mask in enumerate(blocks):
            k = jax.random.fold_in(rng, i)
            leaves, treedef = jax.tree.flatten(params)
            keys = jax.random.split(k, len(leaves))
            v = treedef.unflatten([
                jax.random.normal(kk, a.shape, jnp.float32)
                for kk, a in zip(keys, leaves)])
            eig, iters = self._power_iterate(hvp, params, v, mask, scale)
            raw.append(eig)
            if self.verbose:
                log_dist(f"block {i}: power iterations {iters}, "
                         f"eigenvalue {eig}", ranks=[0])
        return self.post_process(raw)

    def layer_masks(self, params: Any, stacked_path: str, n_layer: int) -> List[Any]:
        """Masks for the zoo's stacked-layer layout: block i selects index i
        of the leading layer dim of every leaf under ``params[stacked_path]``
        (the analogue of the reference's ``layer_name``/``layer_num``)."""
        def mask_for(i):
            def one(path_key, a):
                return (jnp.zeros(a.shape, jnp.float32).at[i].set(1.0)
                        if path_key else jnp.zeros(a.shape, jnp.float32))
            return {
                k: (jax.tree.map(lambda a: one(True, a), v) if k == stacked_path
                    else jax.tree.map(lambda a: one(False, a), v))
                for k, v in params.items()
            }
        return [mask_for(i) for i in range(n_layer)]

    def post_process(self, values: List[float]) -> List[float]:
        """Reference semantics: |λ| / max|λ|; zero (failed) blocks → 1.0."""
        if not values:
            return values
        mx = abs(max(values, key=abs))
        if mx == 0.0:
            return [1.0] * len(values)
        return [abs(v) / mx if v != 0.0 else 1.0 for v in values]
