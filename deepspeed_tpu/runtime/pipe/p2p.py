"""Point-to-point helpers for pipeline stages.

Reference parity: ``deepspeed/runtime/pipe/p2p.py`` — ``send``/``recv``
between adjacent stages with shape/meta exchange.

On TPU, inter-stage transfer inside the compiled pipeline is a
CollectivePermute emitted by XLA for the stage-axis rotation
(``engine.spmd_pipeline_loss``); shapes are static under jit so the
reference's runtime meta exchange (``pipe/engine.py:786-903``) has no
analogue. These eager helpers exist for the interpretive executor and tests.
"""

from __future__ import annotations

import deepspeed_tpu.comm as dist

_grid = None


def init_process_groups(grid) -> None:
    global _grid
    _grid = grid


def can_send_recv() -> bool:
    return _grid is not None and _grid.pipe_parallel_size > 1


def send_to_next(tensor, axis: str = "pp"):
    """Rotate ``tensor`` one step forward along the pipeline axis."""
    return dist.ring_send_recv(tensor, shift=1, group=axis)


def recv_from_prev(tensor, axis: str = "pp"):
    """Alias of :func:`send_to_next` — a ring shift delivers the previous
    stage's tensor to this stage."""
    return dist.ring_send_recv(tensor, shift=1, group=axis)


def send_grads_to_prev(tensor, axis: str = "pp"):
    """Rotate gradients one step backward along the pipeline axis."""
    return dist.ring_send_recv(tensor, shift=-1, group=axis)
