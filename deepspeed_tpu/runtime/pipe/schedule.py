"""Pipeline instruction schedules.

Reference parity: ``deepspeed/runtime/pipe/schedule.py`` — ``PipeSchedule``
ABC yielding per-step instruction lists, ``TrainSchedule`` (1F1B),
``InferenceSchedule``, ``DataParallelSchedule``, and the instruction
dataclasses.

Role in the TPU build: the compiled SPMD pipeline (``engine.py``) lowers the
whole schedule into one XLA program (a ``lax.scan`` over pipeline clock
ticks), so these instruction streams are not dispatched op-by-op on the hot
path. They remain the source of truth for (a) the interpretive executor used
by heterogeneous-stage models, (b) schedule analysis/tests (buffer counts,
send/recv pairing), and (c) parity with the reference API.
"""

from __future__ import annotations

from typing import Iterator, List


class PipeInstruction:
    """Base instruction. Carries arbitrary kwargs as attributes."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{self.name}({args})"

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    """Apply the optimizer update (all stages, at batch end)."""


class ReduceGrads(PipeInstruction):
    """Data-parallel gradient reduction."""


class ReduceTiedGrads(PipeInstruction):
    """All-reduce grads of tied layers over their replica group."""


class BufferOpInstruction(PipeInstruction):
    """Instruction operating on a pipeline activation buffer ``buffer_id``."""

    def __init__(self, buffer_id: int, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    """Load micro-batch ``micro_batch_id`` into ``buffer_id`` (first/last stage)."""


class ForwardPass(BufferOpInstruction):
    """Run the stage forward on buffer ``buffer_id``."""


class BackwardPass(BufferOpInstruction):
    """Run the stage backward for buffer ``buffer_id``."""


class SendActivation(BufferOpInstruction):
    """Send activations in ``buffer_id`` to the next stage."""


class RecvActivation(BufferOpInstruction):
    """Receive activations into ``buffer_id`` from the previous stage."""


class SendGrad(BufferOpInstruction):
    """Send input-activation grads for ``buffer_id`` to the previous stage."""


class RecvGrad(BufferOpInstruction):
    """Receive output grads into ``buffer_id`` from the next stage."""


class PipeSchedule:
    """Iterable of per-step instruction lists for one stage of one batch.

    Subclasses implement ``steps()``. ``micro_batches`` is the number of
    micro-batches in the batch; ``stages`` the pipeline depth; ``stage_id``
    this stage's index.
    """

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        if not 0 <= stage_id < stages:
            raise ValueError(f"stage_id {stage_id} out of range for {stages} stages")
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    def num_pipe_buffers(self) -> int:
        """Number of activation buffers this stage needs."""
        raise NotImplementedError

    @property
    def stage(self) -> int:
        return self.stage_id

    @property
    def num_stages(self) -> int:
        return self.stages

    @property
    def num_micro_batches(self) -> int:
        return self.micro_batches

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, micro_batch_id: int) -> int:
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only pipelined inference: stages stream micro-batches with a
    two-buffer rotation (reference schedule.py:132)."""

    def num_pipe_buffers(self) -> int:
        return 2

    def steps(self):
        total = self.micro_batches + self.stages - 1
        for tick in range(total):
            cmds: List[PipeInstruction] = []
            mb = tick - self.stage_id  # micro-batch this stage handles at this tick
            if 0 <= mb < self.micro_batches:
                buf = self._buffer_idx(mb)
                if self.is_first_stage or self.is_last_stage:
                    cmds.append(LoadMicroBatch(buffer_id=buf, micro_batch_id=mb))
                if not self.is_first_stage:
                    cmds.append(RecvActivation(buffer_id=buf))
                cmds.append(ForwardPass(buffer_id=buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buffer_id=buf))
            yield cmds


class TrainSchedule(PipeSchedule):
    """1F1B schedule (reference schedule.py:186): each stage runs
    ``stages - stage_id - 1`` warmup forwards, then alternates one-forward/
    one-backward in steady state, then drains remaining backwards. Peak live
    activations per stage = warmup + 1, which is what bounds pipeline memory.
    """

    def num_pipe_buffers(self) -> int:
        # in-flight forwards never exceed (stages - stage_id), capped by M
        return max(1, min(self.stages - self.stage_id, self.micro_batches))

    def _phase_sequence(self) -> List[tuple]:
        """[('F', mb) | ('B', mb)] in execution order for this stage."""
        M = self.micro_batches
        warmup = min(self.stages - self.stage_id - 1, M)
        seq: List[tuple] = [("F", i) for i in range(warmup)]
        next_f, next_b = warmup, 0
        # steady state: 1F1B
        while next_f < M:
            seq.append(("F", next_f))
            next_f += 1
            seq.append(("B", next_b))
            next_b += 1
        # drain
        while next_b < M:
            seq.append(("B", next_b))
            next_b += 1
        return seq

    def steps(self):
        for kind, mb in self._phase_sequence():
            buf = self._buffer_idx(mb)
            cmds: List[PipeInstruction] = []
            if kind == "F":
                if not self.is_first_stage:
                    cmds.append(RecvActivation(buffer_id=buf))
                if self.is_first_stage or self.is_last_stage:
                    # inputs on the first stage, labels on the last — one load each
                    cmds.append(LoadMicroBatch(buffer_id=buf, micro_batch_id=mb))
                cmds.append(ForwardPass(buffer_id=buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buffer_id=buf))
            else:
                if not self.is_last_stage:
                    cmds.append(RecvGrad(buffer_id=buf))
                cmds.append(BackwardPass(buffer_id=buf))
                if not self.is_first_stage:
                    cmds.append(SendGrad(buffer_id=buf))
            yield cmds
        # batch epilogue: reductions + optimizer step
        yield [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule: forward/backward every micro-batch,
    reduce + step at the end (reference schedule.py:298)."""

    def num_pipe_buffers(self) -> int:
        return 1

    def steps(self):
        for mb in range(self.micro_batches):
            yield [LoadMicroBatch(buffer_id=0, micro_batch_id=mb),
                   ForwardPass(buffer_id=0),
                   BackwardPass(buffer_id=0)]
        yield [ReduceGrads(), OptimizerStep()]
