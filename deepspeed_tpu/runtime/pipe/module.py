"""Pipeline module: layer specs, stage partitioning, tied layers.

Reference parity: ``deepspeed/runtime/pipe/module.py`` — ``LayerSpec`` (:26),
``TiedLayerSpec`` (:73), ``PipelineModule`` (:82) with layer partitioning by
``parameters | uniform | type:regex`` (:350) and per-layer checkpoint files
(:544-603).

TPU-native design: a "layer" is a pure function plus its parameter pytree —
``init(rng) -> params`` and ``apply(params, x) -> x`` — instead of an
``nn.Module``. The module supports two execution paths:

- **sequential** (always available): compose the stage's layers in order;
  with pp=1 this is the whole model. Used for heterogeneous stages and eval.
- **SPMD pipelined** (``engine.py``): when the model exposes homogeneous
  stages, the engine lowers the schedule into a single compiled program over
  the ``pp`` mesh axis. The partitioning below decides which layers form a
  stage in both paths.
"""

from __future__ import annotations

import math
import os
import pickle
import re
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.utils import partition_balanced, partition_uniform
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.init_on_device import honors_on_device


class LayerSpec:
    """Delayed layer construction (reference module.py:26): stores the builder
    and arguments; ``build()`` instantiates. The built object must be either a
    plain callable ``fn(x)`` (stateless) or expose ``init(rng) -> params`` and
    ``apply(params, x)`` / be callable as ``layer(params, x)``."""

    def __init__(self, typename: Callable, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        name = getattr(self.typename, "__name__", str(self.typename))
        return f"LayerSpec({name})"


class TiedLayerSpec(LayerSpec):
    """A layer whose parameters are shared with every other tied layer of the
    same ``key`` (reference module.py:73). The first tied occurrence owns the
    parameters; later ones reference them. ``forward_fn`` optionally overrides
    how the tied layer is applied (e.g. embedding reused as the LM head)."""

    def __init__(self, key: str, typename: Callable, *module_args,
                 forward_fn: Optional[Callable] = None, tied_weight_attr: str = "weight",
                 **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


class _FnLayer:
    """Adapter wrapping a parameterless callable into the layer protocol."""

    def __init__(self, fn: Callable):
        self.fn = fn

    def init(self, rng):
        return None

    def __call__(self, params, x):
        return self.fn(x)


def _as_layer(obj):
    if hasattr(obj, "init") and (hasattr(obj, "apply") or callable(obj)):
        return obj
    if callable(obj):
        return _FnLayer(obj)
    raise TypeError(f"layer {obj!r} is neither a layer object nor a callable")


def _apply_layer(layer, params, x):
    if hasattr(layer, "apply"):
        return layer.apply(params, x)
    return layer(params, x)


class PipelineModule:
    """Sequence of layers partitioned into pipeline stages.

    Args mirror the reference: ``layers`` (specs/callables), ``num_stages``
    or ``topology``, ``loss_fn`` applied to (output, labels),
    ``partition_method`` in {"parameters", "uniform", "type:REGEX"},
    ``activation_checkpoint_interval`` (remat every N layers).
    """

    def __init__(self,
                 layers: Sequence,
                 num_stages: Optional[int] = None,
                 topology=None,
                 loss_fn: Optional[Callable] = None,
                 partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 0,
                 seed_layers: bool = False,
                 base_seed: int = 1234):
        if num_stages is None and topology is None:
            num_stages = 1
        if topology is not None and num_stages is None:
            num_stages = topology.get_dim("pipe")
        self.num_stages = int(num_stages)
        self.topology = topology
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.seed_layers = seed_layers
        self.base_seed = base_seed

        self._specs = list(layers)
        self._layers = []
        self._tied_keys: List[Optional[str]] = []
        self._tied_fwd: Dict[int, Callable] = {}
        for i, spec in enumerate(self._specs):
            if isinstance(spec, TiedLayerSpec):
                self._layers.append(_as_layer(spec.build()))
                self._tied_keys.append(spec.key)
                if spec.forward_fn is not None:
                    self._tied_fwd[i] = spec.forward_fn
            elif isinstance(spec, LayerSpec):
                self._layers.append(_as_layer(spec.build()))
                self._tied_keys.append(None)
            else:
                self._layers.append(_as_layer(spec))
                self._tied_keys.append(None)

        self.parts = self._partition_layers()
        logger.info(f"PipelineModule: {len(self._layers)} layers -> {self.num_stages} stages, "
                    f"bounds {self.parts} (method={partition_method})")

    # ------------------------------------------------------------- #
    # partitioning

    def _layer_param_counts(self) -> List[int]:
        counts = []
        rng = jax.random.key(0)
        for layer in self._layers:
            try:
                shapes = jax.eval_shape(lambda: layer.init(rng))
            except Exception:
                shapes = None
            n = 0
            if shapes is not None:
                for leaf in jax.tree.leaves(shapes):
                    if hasattr(leaf, "shape"):
                        n += int(math.prod(leaf.shape))
            counts.append(n)
        return counts

    def _partition_layers(self) -> List[int]:
        method = self.partition_method.lower()
        n = len(self._layers)
        if self.num_stages > n:
            raise ValueError(f"num_stages {self.num_stages} > num layers {n}")
        if method == "uniform":
            return partition_uniform(n, self.num_stages)
        if method in ("parameters", "params"):
            weights = [max(c, 1) for c in self._layer_param_counts()]
            return partition_balanced(weights, self.num_stages)
        if method.startswith("type:"):
            pattern = method.split(":", 1)[1]
            weights = [1 if re.search(pattern, type(l).__name__, re.IGNORECASE) else 0
                       for l in self._layers]
            if sum(weights) == 0:
                raise ValueError(f"partition type:{pattern} matched no layers")
            return partition_balanced([max(w, 0) or 0 for w in weights], self.num_stages)
        raise NotImplementedError(f"partition_method {self.partition_method}")

    def stage_of_layer(self, layer_idx: int) -> int:
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise IndexError(layer_idx)

    def stage_layers(self, stage_id: int) -> List[int]:
        return list(range(self.parts[stage_id], self.parts[stage_id + 1]))

    # ------------------------------------------------------------- #
    # params

    @honors_on_device
    def init_params(self, rng) -> Dict[str, Any]:
        """Per-layer parameter list; tied layers share one entry under
        ``tied[key]`` (first occurrence initializes)."""
        layer_params: List[Any] = []
        tied: Dict[str, Any] = {}
        for i, layer in enumerate(self._layers):
            key = self._tied_keys[i]
            lrng = jax.random.fold_in(rng, (self.base_seed if self.seed_layers else 0) + i)
            if key is not None:
                if key not in tied:
                    tied[key] = layer.init(lrng)
                layer_params.append(None)
            else:
                layer_params.append(layer.init(lrng))
        return {"layers": layer_params, "tied": tied}

    def _layer_apply(self, i: int, params: Dict[str, Any], x):
        layer = self._layers[i]
        key = self._tied_keys[i]
        if key is not None:
            p = params["tied"][key]
            fwd = self._tied_fwd.get(i)
            if fwd is not None:
                return fwd(p, x)
            return _apply_layer(layer, p, x)
        return _apply_layer(layer, params["layers"][i], x)

    # ------------------------------------------------------------- #
    # execution (sequential; the SPMD path lives in engine.py)

    def forward(self, params, x, start: Optional[int] = None, stop: Optional[int] = None):
        start = 0 if start is None else start
        stop = len(self._layers) if stop is None else stop
        interval = self.activation_checkpoint_interval
        i = start
        while i < stop:
            j = min(i + interval, stop) if interval > 0 else i + 1

            def chunk(h, lo=i, hi=j):
                for k in range(lo, hi):
                    h = self._layer_apply(k, params, h)
                return h

            if interval > 0:
                x = jax.checkpoint(chunk, prevent_cse=False)(x)
            else:
                x = chunk(x)
            i = j
        return x

    def stage_forward(self, params, x, stage_id: int):
        return self.forward(params, x, self.parts[stage_id], self.parts[stage_id + 1])

    def __call__(self, params, x):
        return self.forward(params, x)

    def loss(self, params, batch):
        """Engine-compatible loss: batch is (inputs, labels) or a dict with
        'inputs'/'labels'."""
        if isinstance(batch, dict):
            inputs, labels = batch["inputs"], batch.get("labels")
        else:
            inputs, labels = batch
        out = self.forward(params, inputs)
        if self.loss_fn is None:
            return jnp.mean(out)
        return self.loss_fn(out, labels)

    # ------------------------------------------------------------- #
    # tied-grad bookkeeping (reference module.py:403-474): with a single
    # params dict the tied weight exists once, so gradient sharing is
    # automatic under jax.grad; this helper lists tied groups for parity.

    def tied_comms(self) -> Dict[str, List[int]]:
        groups: Dict[str, List[int]] = {}
        for i, key in enumerate(self._tied_keys):
            if key is not None:
                groups.setdefault(key, []).append(i)
        return groups

    # ------------------------------------------------------------- #
    # per-layer checkpoint files (reference module.py:544-603)

    def ckpt_layer_path(self, ckpt_dir: str, local_layer_idx: int) -> str:
        return os.path.join(ckpt_dir, f"layer_{local_layer_idx:02d}-model_states.pkl")

    def save_state_dict(self, params, save_dir: str, stage_id: Optional[int] = None) -> None:
        os.makedirs(save_dir, exist_ok=True)
        layers = (self.stage_layers(stage_id) if stage_id is not None
                  else range(len(self._layers)))
        for i in layers:
            entry = {"params": jax.tree.map(lambda a: jax.device_get(a), params["layers"][i]),
                     "tied_key": self._tied_keys[i]}
            with open(self.ckpt_layer_path(save_dir, i), "wb") as f:
                pickle.dump(entry, f)
        tied_path = os.path.join(save_dir, "tied-model_states.pkl")
        with open(tied_path, "wb") as f:
            pickle.dump(jax.tree.map(lambda a: jax.device_get(a), params["tied"]), f)

    def load_state_dir(self, load_dir: str, params=None) -> Dict[str, Any]:
        layer_params: List[Any] = []
        for i in range(len(self._layers)):
            path = self.ckpt_layer_path(load_dir, i)
            if os.path.exists(path):
                with open(path, "rb") as f:
                    entry = pickle.load(f)
                layer_params.append(jax.tree.map(jnp.asarray, entry["params"]))
            else:
                layer_params.append(None if params is None else params["layers"][i])
        tied_path = os.path.join(load_dir, "tied-model_states.pkl")
        tied = {}
        if os.path.exists(tied_path):
            with open(tied_path, "rb") as f:
                tied = jax.tree.map(jnp.asarray, pickle.load(f))
        return {"layers": layer_params, "tied": tied}

    @property
    def num_layers(self) -> int:
        return len(self._layers)
