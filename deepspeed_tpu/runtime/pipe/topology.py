"""Named-axis process topology.

Reference parity: ``deepspeed/runtime/pipe/topology.py`` — ``ProcessTopology``
(cartesian rank mapping over named axes), ``PipeDataParallelTopology``,
``PipeModelDataParallelTopology``, and ``PipelineParallelGrid``.

A named-axis cartesian grid IS a ``jax.sharding.Mesh`` — the TPU build keeps
this class as the pure-Python coordinate calculus (used by checkpoint
reshaping, the launcher, and schedule tests, all hardware-free) and provides
``to_mesh()`` / ``from_mesh()`` bridges. Ranks are laid out with the LAST axis
varying fastest, matching mesh device order so that rank i == mesh.devices.flat[i].
"""

from __future__ import annotations

import itertools
import math
from collections import namedtuple
from typing import Dict, List, Optional, Sequence


class ProcessTopology:
    """Maps an N-dimensional named-axis cartesian coordinate to a linear rank
    and back. Axes are ordered outermost → innermost."""

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        if len(axes) != len(dims):
            raise ValueError("axes and dims must have equal length")
        self.axes = list(axes)
        self.dims = list(int(d) for d in dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        self.mapping: Dict = {}
        for rank, coord in enumerate(itertools.product(*[range(d) for d in self.dims])):
            self.mapping[self.ProcessCoord(*coord)] = rank

    def world_size(self) -> int:
        return math.prod(self.dims)

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)] if axis in self.axes else 0

    def get_rank(self, **coord_kwargs) -> int:
        if sorted(coord_kwargs) != sorted(self.axes):
            raise ValueError(f"get_rank() needs all axes {self.axes}, got {list(coord_kwargs)}")
        return self.mapping[self.ProcessCoord(**coord_kwargs)]

    def get_coord(self, rank: int):
        for coord, r in self.mapping.items():
            if r == rank:
                return coord
        raise ValueError(f"rank {rank} not in topology")

    def get_rank_repr(self, rank: int, omit_axes=("data", "dp"), inner_sep="_", outer_sep="-") -> str:
        """String like ``pipe_0-model_1`` identifying the rank's coordinates on
        non-data axes (used in checkpoint file names)."""
        omit = set(omit_axes)
        coord = self.get_coord(rank)
        parts = [f"{ax}{inner_sep}{getattr(coord, ax)}" for ax in self.axes if ax not in omit]
        return outer_sep.join(parts)

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        """All ranks whose coordinate on ``axis`` equals ``idx``, sorted."""
        return sorted(rank for coord, rank in self.mapping.items() if getattr(coord, axis) == idx)

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Groups of ranks that differ only along ``axis`` — i.e. the process
        groups for collectives over that axis."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        for combo in itertools.product(*[range(self.get_dim(a)) for a in other_axes]):
            fixed = dict(zip(other_axes, combo))
            ranks = [self.get_rank(**fixed, **{axis: i}) for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        """Ranks whose coordinates match all given axis=value filters."""
        return sorted(rank for coord, rank in self.mapping.items()
                      if all(getattr(coord, ax) == v for ax, v in filter_kwargs.items()))

    def __str__(self):
        return f"ProcessTopology(axes={self.axes}, dims={self.dims})"

    # ---- mesh bridges ---- #

    def to_mesh(self, devices=None):
        """Build a ``jax.sharding.Mesh`` with these axes/dims."""
        import jax
        import numpy as np
        from jax.sharding import Mesh
        if devices is None:
            devices = jax.devices()
        arr = np.array(devices[:self.world_size()]).reshape(self.dims)
        return Mesh(arr, tuple(self.axes))

    # mesh axis names → topology axis names used by grids/modules
    _MESH_AXIS_ALIASES = {"pp": "pipe", "dp": "data", "tp": "model", "mp": "model"}

    @classmethod
    def from_mesh(cls, mesh) -> "ProcessTopology":
        """Translate mesh axis names (pp/dp/tp) to topology names (pipe/data/
        model) so grid consumers see the axes they expect."""
        axes = [cls._MESH_AXIS_ALIASES.get(a, a) for a in mesh.axis_names]
        return cls(axes=axes, dims=[mesh.shape[a] for a in mesh.axis_names])


class PipeDataParallelTopology(ProcessTopology):
    """pipe × data grid; data innermost so DP collectives ride the faster
    interconnect (reference topology.py:229, same choice on TPU: inner axes
    map to ICI)."""

    def __init__(self, num_pp: int, num_dp: int):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """pipe × data × model grid for 3D parallelism (reference topology.py:241);
    model (TP) innermost — highest-bandwidth axis."""

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Axis-group bookkeeping for a pipeline topology (reference
    topology.py:248): per-rank stage_id/data_parallel_id and the rank lists of
    each communication group. On TPU these map to mesh sub-axes rather than
    NCCL communicators; the grid remains the coordinate source of truth for
    checkpoint naming and the launcher."""

    def __init__(self, topology: Optional[ProcessTopology] = None, process_group=None,
                 global_rank: int = 0, world_size: Optional[int] = None):
        if topology is None:
            ws = world_size or 1
            topology = PipeDataParallelTopology(num_pp=1, num_dp=ws)
        self._topo = topology
        self.global_rank = global_rank
        self.world_size = topology.world_size()

        self.data_parallel_size = max(topology.get_dim("data"), 1)
        self.pipe_parallel_size = max(topology.get_dim("pipe"), 1)
        self.model_parallel_size = max(topology.get_dim("model"), 1)
        self.slice_parallel_size = self.model_parallel_size

        coord = topology.get_coord(global_rank)
        self.stage_id = getattr(coord, "pipe", 0) if "pipe" in topology.axes else 0
        self.data_parallel_id = getattr(coord, "data", 0) if "data" in topology.axes else 0
        self.model_parallel_id = getattr(coord, "model", 0) if "model" in topology.axes else 0

        # rank lists per group (the reference builds dist groups from these)
        self.dp_groups = topology.get_axis_comm_lists("data") if "data" in topology.axes else []
        self.pp_groups = topology.get_axis_comm_lists("pipe") if "pipe" in topology.axes else []
        self.mp_groups = topology.get_axis_comm_lists("model") if "model" in topology.axes else []

        # p2p groups: adjacent stages within the same (data, model) coordinate
        self.p2p_groups = self._build_p2p_groups()

    def _build_p2p_groups(self) -> List[List[int]]:
        if "pipe" not in self._topo.axes or self.pipe_parallel_size == 1:
            return []
        groups = []
        for ranks in self.pp_groups:
            for i in range(len(ranks)):
                groups.append(sorted([ranks[i], ranks[(i + 1) % len(ranks)]]))
        return groups

    def get_stage_id(self) -> int:
        return self.stage_id

    def get_data_parallel_id(self) -> int:
        return self.data_parallel_id

    def get_pipe_parallel_rank(self) -> int:
        return self.stage_id

    def get_data_parallel_rank(self) -> int:
        return self.data_parallel_id

    def get_model_parallel_rank(self) -> int:
        return self.model_parallel_id

    def get_pipe_parallel_world_size(self) -> int:
        return self.pipe_parallel_size

    def get_data_parallel_world_size(self) -> int:
        return self.data_parallel_size

    def get_model_parallel_world_size(self) -> int:
        return self.model_parallel_size

    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    def is_last_stage(self) -> bool:
        return self.stage_id == self.pipe_parallel_size - 1

    def stage_to_global(self, stage_id: int) -> int:
        """Global rank of ``stage_id`` at this rank's data/model coordinate."""
        coord = self._topo.get_coord(self.global_rank)
        kwargs = {ax: getattr(coord, ax) for ax in self._topo.axes}
        kwargs["pipe"] = stage_id
        return self._topo.get_rank(**kwargs)

    @property
    def topology(self) -> ProcessTopology:
        return self._topo
