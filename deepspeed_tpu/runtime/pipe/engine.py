"""Pipeline-parallel training engine.

Reference parity: ``deepspeed/runtime/pipe/engine.py`` — ``PipelineEngine``
(:37) with ``train_batch``/``eval_batch`` (:282,:359) executing the 1F1B
instruction schedule via p2p send/recv between stage processes.

TPU-native design (NOT a port of the instruction interpreter): the entire
schedule — every micro-batch forward, inter-stage transfer, backward, and the
optimizer step — is lowered into ONE compiled XLA program:

- Stage parameters are stacked on a leading ``num_stages`` axis sharded over
  the ``pp`` mesh axis.
- A ``lax.scan`` over pipeline clock ticks runs every stage in parallel
  (``vmap`` over the stage axis; XLA partitions it so each device computes
  only its own stage) and rotates activations one stage forward with
  ``jnp.roll`` on the stage axis, which XLA lowers to a CollectivePermute
  over the ``pp`` axis — the compiled equivalent of the reference's
  ``SendActivation``/``RecvActivation`` instruction pairs
  (``pipe/engine.py:904,996``).
- ``jax.grad`` through the scan yields the reverse rotation
  (``SendGrad``/``RecvGrad``) automatically; ``jax.checkpoint`` on the stage
  body bounds live activations the way 1F1B does.
- The (pp × dp × tp) composition is expressed as shardings, so DP grad
  reduction and TP collectives are inserted by the SPMD partitioner.

The instruction-stream schedules (``schedule.py``) remain available through
the interpretive executor for heterogeneous-stage models.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu.comm as dist
from deepspeed_tpu.runtime.engine import DeepSpeedEngine, TrainState


def _stage_map_builder(stage_fn, mesh, num_stages: int, batch_size: int,
                       tp_stage=None):
    """Build the per-tick stage executors: a shard_map over (pp × dp/fsdp
    [× tp]) when the mesh allows it, else plain vmaps over the stage axis.

    Under the shard_map each device's stage body runs on fully LOCAL arrays
    (stage extent 1, batch already split over dp), so attention inside the
    stage reaches the Pallas flash kernel — ``_use_flash`` recognises the
    fully-manual context (models/transformer.py). The vmap form instead
    relies on the SPMD partitioner, under which a ``pallas_call`` cannot be
    placed and attention pays the XLA streaming core. The reference's fused
    kernels are schedule-agnostic (csrc/transformer/inference/csrc/
    pt_binding.cpp:1668-1793 run unchanged under PP via
    runtime/pipe/engine.py forward passes); this is the TPU equivalent.

    Eligibility: pp partitions exactly one stage per device, every other
    partitioned axis is batch-like (dp/fsdp) — or ``tp`` when the model
    provides manual-tp hooks via ``tp_stage = (stage_fn_tp, stage_specs)``:
    ``stage_fn_tp(axis, size)`` returns a stage body that runs on tp-sliced
    weights with explicit Megatron f/g collectives (or None to refuse), and
    ``stage_specs`` is the per-leaf PartitionSpec tree for the stacked stage
    params (leading ``pp`` dim + the tp placement). ep/sp stage bodies have
    no manual form — those compositions keep the vmap path. The batch must
    divide the dp extent. Returns ``(fwd, bwd, manual)``:

    - ``fwd(stage_params, bufs, aux, keys) -> outs``
    - ``bwd(stage_params, x, aux, keys, cots, valid) -> (dstage_params, dx)``
      (vjp w.r.t. params and input, fp32 grads, zeroed where ``not valid``)
    - ``manual``: True when the shard_map path engaged — the pair must then
      NOT be differentiated through (shard_map's AD transpose would re-sum
      replicated-leaf cotangents); callers either call ``bwd`` explicitly
      (1F1B) or wrap the pair in a custom_vjp (GPipe's ``run_stages``).
    """
    tp_size = mesh.shape.get("tp", 1) if mesh is not None else 1

    eligible = (
        mesh is not None
        and mesh.shape.get("pp", 1) > 1
        and mesh.shape["pp"] == num_stages
        and all(size == 1 or name in ("pp", "dp", "fsdp", "tp")
                for name, size in mesh.shape.items())
    )
    param_specs = P("pp")                # uniform: params replicated off-pp
    if eligible:
        dp_axes = tuple(a for a in ("dp", "fsdp") if mesh.shape.get(a, 1) > 1)
        nb = 1
        for a in dp_axes:
            nb *= mesh.shape[a]
        eligible = batch_size % nb == 0
    if eligible and tp_size > 1:
        fn = tp_stage[0]("tp", tp_size) if tp_stage and tp_stage[0] else None
        if fn is None or tp_stage[1] is None:
            eligible = False
        else:
            stage_fn = fn
            param_specs = tp_stage[1]    # per-leaf P("pp", ..., "tp", ...)

    def stage_bwd_one(sp, x, aux, key, cot, valid):
        y, vjp = jax.vjp(lambda sp_, x_: stage_fn(sp_, x_, aux, key), sp, x)
        dsp, dx = vjp(cot)
        z = jnp.where(valid, 1.0, 0.0).astype(jnp.float32)
        dsp = jax.tree.map(lambda a: a.astype(jnp.float32) * z, dsp)
        return dsp, dx * z.astype(dx.dtype)

    if not eligible:
        return (jax.vmap(stage_fn, in_axes=(0, 0, 0, 0)),
                jax.vmap(stage_bwd_one, in_axes=(0, 0, 0, 0, 0, 0)), False)

    from deepspeed_tpu.utils.jax_compat import shard_map

    dp = dp_axes if len(dp_axes) != 1 else dp_axes[0]
    pspec = P("pp")                      # keys / valid flags
    aspec = P("pp", dp or None)          # activations & aux: [stage, batch, ...]

    def local(tree):
        return jax.tree.map(lambda a: a[0], tree)

    def shard_key(keys):
        # stage keys enter replicated over dp (P("pp") spec) while the
        # activations are dp-sharded: fold the dp coordinate in so dropout
        # masks differ per data shard (the vmap/SPMD path draws masks at
        # global shape and partitions them — this is the manual analogue)
        k = keys[0]
        for a in dp_axes:
            k = jax.random.fold_in(k, jax.lax.axis_index(a))
        return k

    def fwd_body(sp, x, aux, keys):
        y = stage_fn(local(sp), x[0], local(aux), shard_key(keys))
        return y[None]

    def bwd_body(sp, x, aux, keys, cots, valid):
        dsp, dx = stage_bwd_one(local(sp), x[0], local(aux), shard_key(keys),
                                cots[0], valid[0])
        if dp_axes:
            # the local vjp saw only this shard's batch rows; the param grad
            # is the sum over the dp extent (the SPMD partitioner inserted
            # this reduction automatically on the vmap path — a manual
            # context must say it, or each replica keeps a partial grad)
            dsp = jax.tree.map(lambda a: jax.lax.psum(a, dp_axes), dsp)
        return jax.tree.map(lambda a: a[None], dsp), dx[None]

    # param_specs: P("pp") uniformly, or the per-leaf tp spec tree — grads
    # mirror the placement (tp-sharded leaves return local shards; leaves
    # replicated over tp return identical copies, asserted by the spec)
    fwd = shard_map(fwd_body, mesh=mesh,
                    in_specs=(param_specs, aspec, aspec, pspec),
                    out_specs=aspec, check_vma=False)
    bwd = shard_map(bwd_body, mesh=mesh,
                    in_specs=(param_specs, aspec, aspec, pspec, aspec, pspec),
                    out_specs=(param_specs, aspec), check_vma=False)
    return fwd, bwd, True


def spmd_pipeline_loss(embed_fn: Callable,
                       stage_fn: Callable,
                       head_loss_fn: Callable,
                       params: Any,
                       microbatches: Any,
                       rng,
                       num_stages: int,
                       mesh=None,
                       carry_keys: tuple = (),
                       tp_stage=None) -> jnp.ndarray:
    """Run a GPipe-style pipelined forward over ``num_stages`` and return the
    mean loss over micro-batches.

    - ``params`` = {"embed": ..., "stages": <leading-dim num_stages>, "head": ...}
    - ``microbatches``: pytree with leading dim M (number of micro-batches)
    - ``embed_fn(params, mb, rng) -> x`` first-stage input (sees the full
      params so tied embeddings work — the reference's ``TiedLayerSpec``)
    - ``stage_fn(stage_params, x, aux, rng) -> x`` one stage (vmapped over stages)
    - ``head_loss_fn(params, x, mb, rng) -> scalar loss`` (last stage)
    - ``carry_keys``: micro-batch dict keys whose values must travel with the
      activations through the pipeline (e.g. attention_mask) — they are
      injected at stage 0 and rotated alongside ``x``.
    - ``tp_stage``: optional ``(stage_fn_tp, stage_tp_specs)`` manual-tp
      hooks (the model's ``pipeline_spec()["stage_fn_tp"/"stage_tp_specs"]``)
      enabling Megatron-manual stage bodies — and the flash kernel — under
      pp×tp meshes; see ``_stage_map_builder``.

    Total ticks T = M + num_stages - 1; the (S-1)/T bubble is the standard
    GPipe cost and shrinks with more micro-batches.
    """
    S = num_stages
    leaves = jax.tree.leaves(microbatches)
    M = leaves[0].shape[0]
    T = M + S - 1
    if isinstance(microbatches, dict):
        carry_keys = tuple(k for k in carry_keys if k in microbatches)

    stage_params = params["stages"]

    dp_axes = tuple(dist.data_parallel_axes(mesh)) if mesh is not None else ()
    dp = dp_axes if len(dp_axes) != 1 else dp_axes[0]

    def mb_at(t):
        """Micro-batch ``t`` (clamped) from the stacked batch."""
        idx = jnp.clip(t, 0, M - 1)
        return jax.tree.map(lambda a: jax.lax.dynamic_index_in_dim(a, idx, 0, keepdims=False),
                            microbatches)

    def constrain(x):
        if mesh is None or "pp" not in mesh.shape:
            return x
        def one(a):
            spec = [None] * a.ndim
            spec[0] = "pp"
            if a.ndim > 1 and dp_axes:
                spec[1] = dp
            return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, P(*spec)))
        return jax.tree.map(one, x)

    # initial buffers: embed of micro-batch 0 broadcast over the stage dim
    # (shape prefill only — every slot is overwritten or feeds discarded
    # warmup compute, so no rng: sampling with the ROOT key here would
    # reuse the key its fold_in children consume)
    mb0 = mb_at(jnp.asarray(0, jnp.int32))
    x0 = embed_fn(params, mb0, None)
    bufs = jnp.broadcast_to(x0[None], (S,) + x0.shape).astype(x0.dtype)
    carry0 = {k: jnp.broadcast_to(mb0[k][None], (S,) + mb0[k].shape) for k in carry_keys}
    bufs, carry0 = constrain(bufs), constrain(carry0)

    # This GPipe form is differentiated THROUGH (jax.grad over the whole
    # scan). shard_map's AD transpose would psum the cotangents of
    # tp-unmentioned inputs over tp — double-counting against the explicit
    # f/g collectives — so when the manual path engages, each tick wraps the
    # stage executor in a custom_vjp that routes the backward through the
    # builder's explicit manual bwd (the same placements 1F1B uses) instead
    # of letting AD transpose the shard_map.
    vstage, vbwd, vmanual = _stage_map_builder(stage_fn, mesh, S, x0.shape[0],
                                               tp_stage=tp_stage)

    def _zero_tan(x):
        # cotangent for a non-differentiable primal (int aux, PRNG keys)
        import numpy as _np
        aval = jax.typeof(x)
        if jnp.issubdtype(aval.dtype, jnp.inexact):
            return jnp.zeros(aval.shape, aval.dtype)
        return _np.zeros(aval.shape, jax.dtypes.float0)

    @jax.custom_vjp
    def _manual_stages(sp, bufs, aux, keys):
        return vstage(sp, bufs, aux, keys)

    def _manual_fwd(sp, bufs, aux, keys):
        return _manual_stages(sp, bufs, aux, keys), (sp, bufs, aux, keys)

    def _manual_bwd(res, cot):
        sp, bufs, aux, keys = res
        # built here, not closed over: an outer jit would otherwise bake a
        # tracer into the custom_vjp bwd closure (S is static, so this is a
        # compile-time constant either way)
        dsp, dx = vbwd(sp, bufs, aux, keys, cot, jnp.ones((S,), bool))
        dsp = jax.tree.map(lambda g, p: g.astype(p.dtype), dsp, sp)
        return (dsp, dx.astype(bufs.dtype),
                jax.tree.map(_zero_tan, aux), _zero_tan(keys))

    _manual_stages.defvjp(_manual_fwd, _manual_bwd)
    run_stages = _manual_stages if vmanual else vstage

    def tick(state, t):
        bufs, aux, loss_sum = state
        mb = mb_at(t)
        # T + t: disjoint from the tick_keys parents fold_in(rng, t) — the
        # embed dropout draw must not consume a key the stages split
        x_in = embed_fn(params, mb, jax.random.fold_in(rng, T + t))
        bufs = bufs.at[0].set(x_in.astype(bufs.dtype))
        for k in carry_keys:
            aux[k] = aux[k].at[0].set(mb[k])
        bufs, aux = constrain(bufs), constrain(aux)

        tick_keys = jax.vmap(lambda s: jax.random.fold_in(
            jax.random.fold_in(rng, t), s))(jnp.arange(S, dtype=jnp.int32))
        outs = run_stages(stage_params, bufs, aux, tick_keys)
        # last stage completes micro-batch t - (S-1); the head (a full vocab
        # matmul) only runs on ticks where one actually exits
        mb_done = mb_at(t - (S - 1))
        # 2*T + t: the head's dropout draw gets its own disjoint range —
        # stage parents use fold_in(rng, t) ∈ [0, T) and the embed draw
        # fold_in(rng, T + t) ∈ [T, 2T), so t + T here would REUSE the same
        # tick's embed key (mirrors 1F1B's stage-key separation, where the
        # head is stage index S and embed S + 1)
        loss_t = jax.lax.cond(
            t >= S - 1,
            lambda: head_loss_fn(params, outs[S - 1], mb_done,
                                 jax.random.fold_in(rng, 2 * T + t)).astype(jnp.float32),
            lambda: jnp.float32(0.0))
        loss_sum = loss_sum + loss_t

        bufs = constrain(jnp.roll(outs, 1, axis=0))
        aux = constrain({k: jnp.roll(v, 1, axis=0) for k, v in aux.items()})
        return (bufs, aux, loss_sum), None

    init = (bufs, carry0, jnp.zeros((), jnp.float32))
    (final_bufs, _, loss_sum), _ = jax.lax.scan(tick, init, jnp.arange(T, dtype=jnp.int32))
    return loss_sum / M


def spmd_pipeline_1f1b(embed_fn: Callable,
                       stage_fn: Callable,
                       head_loss_fn: Callable,
                       params: Any,
                       microbatches: Any,
                       rng,
                       num_stages: int,
                       mesh=None,
                       carry_keys: tuple = (),
                       cot_scale=1.0,
                       tp_stage=None):
    """1F1B pipelined loss AND grads in one forward-only ``lax.scan``.

    Reference parity: ``deepspeed/runtime/pipe/schedule.py:186-296``
    (``TrainSchedule`` — interleaved forward/backward so live activations
    stay bounded by the stage count, not the micro-batch count).

    TPU redesign: instead of interpreting Send/Recv instructions per rank —
    or differentiating through a GPipe scan, which makes AD save O(M) tick
    states — the backward wave is computed EXPLICITLY inside the same scan:

    - tick t forwards micro-batch ``t-s`` on stage s and backwards
      micro-batch ``t-2(S-1)+s`` via per-stage ``jax.vjp`` (activation
      recompute, the reference's checkpointing default);
    - each stage keeps its last ``2S-1`` inputs in a ring buffer — the 1F1B
      memory bound of O(S) activations per stage, independent of M;
    - activations roll forward and cotangents roll backward one stage per
      tick (CollectivePermute over ``pp`` in both directions);
    - parameter gradients accumulate in the scan carry, so AD never
      differentiates the schedule at all.

    Returns ``(mean_loss, grads)`` where grads covers the full params tree.
    ``cot_scale`` seeds the head cotangent (loss-scaling support).

    Contract: ``embed_fn``/``head_loss_fn`` may read only the non-``stages``
    subtree of params (embed/head/tied weights); their vjps run over that
    subtree alone, so any read of ``params["stages"]`` would be treated as a
    constant (stage grads flow exclusively through ``stage_fn``).
    """
    S = num_stages
    leaves = jax.tree.leaves(microbatches)
    M = leaves[0].shape[0]
    T = M + 2 * (S - 1)
    R = max(2 * S - 1, 1)  # ring depth: max write->read delay is 2(S-1)
    if isinstance(microbatches, dict):
        carry_keys = tuple(k for k in carry_keys if k in microbatches)

    stage_params = params["stages"]
    s_idx = jnp.arange(S, dtype=jnp.int32)

    def mb_at(t):
        idx = jnp.clip(t, 0, M - 1)
        return jax.tree.map(lambda a: jax.lax.dynamic_index_in_dim(a, idx, 0, keepdims=False),
                            microbatches)

    def stage_key(s, m):
        # one key per (stage, micro-batch), identical at fwd and recompute
        return jax.random.fold_in(rng, s * M + jnp.clip(m, 0, M - 1))

    def constrain(x, batch_dim=1):
        """Shard dim 0 over pp and the given batch dim over dp (ring
        buffers carry [stage, ring_slot, batch, ...] so their batch dim is
        2; rolling buffers are [stage, batch, ...])."""
        if mesh is None or "pp" not in mesh.shape:
            return x
        dp_axes = tuple(dist.data_parallel_axes(mesh))
        dp = dp_axes if len(dp_axes) != 1 else (dp_axes[0] if dp_axes else None)

        def one(a):
            spec = [None] * a.ndim
            spec[0] = "pp"
            if a.ndim > batch_dim and dp_axes:
                spec[batch_dim] = dp
            return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, P(*spec)))
        return jax.tree.map(one, x)

    # head/embed cotangents only touch the NON-stage subtree: vjp over the
    # full tree would carry (and add, every tick) an all-zero second copy of
    # every stage weight — double gradient memory and two wasted full-model
    # HBM passes per tick
    nonstage = {k: v for k, v in params.items() if k != "stages"}

    def with_stages(pns):
        return {**pns, "stages": stage_params}

    # shapes (no rng: value only prefills zero buffers)
    mb0 = mb_at(jnp.asarray(0, jnp.int32))
    x0 = embed_fn(params, mb0, None)

    ring0 = constrain(jnp.zeros((S, R) + x0.shape, x0.dtype), batch_dim=2)
    outs0 = constrain(jnp.zeros((S,) + x0.shape, x0.dtype))
    cots0 = constrain(jnp.zeros((S,) + x0.shape, x0.dtype))
    gstages0 = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32), stage_params)
    gns0 = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32), nonstage)

    stage_fwd, stage_bwd, _ = _stage_map_builder(stage_fn, mesh, S, x0.shape[0],
                                                 tp_stage=tp_stage)

    def tick(state, t):
        ring, prev_outs, cots, gstages, gns, loss_sum = state

        # ---- forward wave: stage s processes micro-batch t - s ----
        mb = mb_at(t)
        # stage index S+1: embed's dropout draw must be disjoint from stage
        # 0's key (which run_layers splits) and the head's (S)
        x_embed = embed_fn(params, mb, stage_key(S + 1, t)).astype(prev_outs.dtype)
        bufs_in = jnp.roll(prev_outs, 1, axis=0).at[0].set(x_embed)
        # aux travels with activations: stage s sees micro-batch t-s's aux
        aux_in = {k: jax.vmap(lambda s: mb_at(t - s)[k])(s_idx) for k in carry_keys}
        bufs_in = constrain(bufs_in)

        slot = jnp.mod(t, R)
        ring = jax.lax.dynamic_update_index_in_dim(
            jnp.swapaxes(ring, 0, 1), bufs_in, slot, 0)
        ring = jnp.swapaxes(ring, 0, 1)

        fwd_keys = jax.vmap(lambda s: stage_key(s, t - s))(s_idx)
        outs = stage_fwd(stage_params, bufs_in,
                         {k: aux_in[k] for k in carry_keys}, fwd_keys)

        # ---- head: micro-batch t - (S-1) exits; loss + cotangent seed ----
        mb_h = mb_at(t - (S - 1))

        def head_branch():
            def f(pns, x):
                return head_loss_fn(with_stages(pns), x, mb_h,
                                    stage_key(S, t - (S - 1)))
            loss_h, vjp = jax.vjp(f, nonstage, outs[S - 1])
            gp, gx = vjp(jnp.asarray(cot_scale, jnp.float32))
            return (loss_h.astype(jnp.float32),
                    jax.tree.map(lambda a: a.astype(jnp.float32), gp),
                    gx.astype(outs.dtype))

        def head_zeros():
            return (jnp.float32(0.0), gns0, jnp.zeros_like(outs[S - 1]))

        valid_h = (t >= S - 1) & (t - (S - 1) < M)
        loss_h, gp_h, cot_head = jax.lax.cond(valid_h, head_branch, head_zeros)
        loss_sum = loss_sum + loss_h
        gns = jax.tree.map(jnp.add, gns, gp_h)

        # ---- backward wave: stage s backwards micro-batch t - 2(S-1) + s ----
        m_b = t - 2 * (S - 1) + s_idx                  # per stage
        valid_b = (m_b >= 0) & (m_b < M)
        read_slot = jnp.mod(t - (2 * (S - 1) - 2 * s_idx), R)
        x_saved = jax.vmap(lambda s, i: jax.lax.dynamic_index_in_dim(ring[s], i, 0, keepdims=False),
                           in_axes=(0, 0))(s_idx, read_slot)
        # aux values are pure functions of the micro-batch index (they ride
        # along unchanged through stages), so the backward wave re-gathers
        # them exactly like the forward wave — no aux ring buffers needed
        aux_saved = {k: jax.vmap(lambda m: mb_at(m)[k])(m_b) for k in carry_keys}
        bwd_keys = jax.vmap(lambda s, m: stage_key(s, m))(s_idx, m_b)

        cot_in = cots.at[S - 1].set(cot_head)
        dsp, dx = stage_bwd(stage_params, x_saved, aux_saved, bwd_keys,
                            cot_in, valid_b)
        gstages = jax.tree.map(jnp.add, gstages, dsp)

        # ---- embed backward: cotangent exiting stage 0 ----
        m_b0 = t - 2 * (S - 1)
        mb_b0 = mb_at(m_b0)

        def embed_branch():
            _, vjp = jax.vjp(
                lambda pns: embed_fn(with_stages(pns), mb_b0, stage_key(S + 1, m_b0)),
                nonstage)
            (gp,) = vjp(dx[0])
            return jax.tree.map(lambda a: a.astype(jnp.float32), gp)

        gp_e = jax.lax.cond((m_b0 >= 0) & (m_b0 < M), embed_branch, lambda: gns0)
        gns = jax.tree.map(jnp.add, gns, gp_e)

        # cotangents roll backward one stage; slot S-1 is re-seeded next tick
        cots = constrain(jnp.roll(dx, -1, axis=0))
        prev_outs = constrain(outs)
        return (ring, prev_outs, cots, gstages, gns, loss_sum), None

    init = (ring0, outs0, cots0, gstages0, gns0, jnp.zeros((), jnp.float32))
    (ring, _, _, gstages, gns, loss_sum), _ = jax.lax.scan(
        tick, init, jnp.arange(T, dtype=jnp.int32))

    grads = dict(gns)
    grads["stages"] = gstages
    return loss_sum / M, grads


class PipelineEngine(DeepSpeedEngine):
    """Engine for models exposing a homogeneous-stage pipeline:

    The model must provide ``pipeline_spec()`` returning a dict with keys
    ``embed_fn, stage_fn, head_loss_fn, num_stages`` and optional
    ``carry_keys``; its params pytree must be ``{"embed", "stages", "head"}``
    with ``stages`` leaves stacked on a leading ``num_stages`` dim.

    ``gradient_accumulation_steps`` plays the reference's ``micro_batches``
    role (pipe/engine.py: micro_batches == gas): each ``train_batch`` feeds
    gas micro-batches through the pipeline and applies one update.

    CONTRACT: ``embed_fn`` and ``head_loss_fn`` may read only the NON-stage
    parameter subtree (everything except ``params["stages"]``). The 1F1B
    schedule takes their vjps over that subtree alone — a read of
    ``params["stages"]`` inside embed/head would silently receive ZERO
    gradient (e.g. do not store the final norm under stages). Stage weights
    get gradients exclusively through ``stage_fn``.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        spec = self.client_model.pipeline_spec()
        self._pipe_spec = spec
        self.num_stages = spec["num_stages"]
        pp = self.mesh.shape.get("pp", 1)
        if pp > 1 and pp != self.num_stages:
            raise ValueError(f"mesh pp={pp} != model num_stages={self.num_stages}")

    @property
    def micro_batches(self) -> int:
        # reference-shaped surface (pipe/engine.py micro_batches == gas); a
        # property so set_train_batch_size's gas changes are never stale here
        return self.gradient_accumulation_steps()

    def _uses_acc_grad_buffers(self) -> bool:
        # the 1F1B schedule accumulates grads inside its own scan carry
        if str(self._config.pipeline.get("schedule", "1f1b")).lower() == "1f1b":
            return False
        return super()._uses_acc_grad_buffers()

    def is_pipe_parallel(self) -> bool:
        return True

    # ---- reference surface (pipe/engine.py) under SPMD semantics ---- #

    def is_first_stage(self) -> bool:
        """Reference gates data loading on stage membership; under the
        single-controller SPMD schedule every process drives every stage,
        so membership is always true (ported code keeps working: it loads
        data everywhere, which is exactly what SPMD needs)."""
        return True

    def is_last_stage(self) -> bool:
        """See is_first_stage — loss is computed by this process too."""
        return True

    def set_has_attention_mask(self, value: bool) -> None:
        """Reference toggles mask transmission between stages; masks ride
        the carry automatically here (pipeline_spec carry_keys). No-op."""

    def reset_activation_shape(self) -> None:
        """Reference re-exchanges activation shape metadata; XLA shapes are
        static per compiled program and recompile on change. No-op."""

    def mem_status(self, msg: str = "", print_rank: int = -1,
                   reset_max: bool = False) -> None:
        """Log the device-memory breakdown (reference mem_status)."""
        from deepspeed_tpu.utils.logging import log_dist
        log_dist(f"mem_status {msg}: {self.memory_breakdown()}", ranks=[0])

    def _build_train_batch_fn(self, gas: int) -> Callable:
        spec = self._pipe_spec
        schedule = str(self._config.pipeline.get("schedule", "1f1b")).lower()

        if schedule == "1f1b":
            def train_batch_fn(state: TrainState, batch, rng):
                scale = state.scaler.loss_scale
                # manual-backprop 1F1B: loss AND grads from one forward-only
                # scan; per-micro-batch cotangents seeded with the loss scale
                # (the sum is divided by scale*gas in _apply_update)
                loss, grads = spmd_pipeline_1f1b(
                    spec["embed_fn"], spec["stage_fn"], spec["head_loss_fn"],
                    state.params, batch, rng, spec["num_stages"], mesh=self.mesh,
                    carry_keys=tuple(spec.get("carry_keys", ())), cot_scale=scale,
                    tp_stage=(spec.get("stage_fn_tp"), spec.get("stage_tp_specs")))
                grads = jax.lax.with_sharding_constraint(
                    jax.tree.map(lambda g: g.astype(self.grad_acc_dtype), grads),
                    self._grad_shardings)
                state = state._replace(micro_steps=state.micro_steps + gas)
                state, aux = self._apply_update(state, gas, acc=grads)
                return state, {"loss": loss, "lr": self._lr_fn(state.global_steps - 1),
                               "loss_scale": state.scaler.loss_scale, **aux}

            return jax.jit(train_batch_fn, donate_argnums=(0,))

        def train_batch_fn(state: TrainState, batch, rng):
            scale = state.scaler.loss_scale

            def scaled_loss(p):
                loss = spmd_pipeline_loss(spec["embed_fn"], spec["stage_fn"], spec["head_loss_fn"],
                                          p, batch, rng, spec["num_stages"], mesh=self.mesh,
                                          carry_keys=tuple(spec.get("carry_keys", ())),
                                          tp_stage=(spec.get("stage_fn_tp"),
                                                    spec.get("stage_tp_specs")))
                # _apply_update divides by scale*gas; loss is already the
                # micro-batch mean, so pre-multiply to cancel
                return loss * scale * gas, loss

            grads, loss = jax.grad(scaled_loss, has_aux=True)(state.params)
            if state.acc_grads == ():  # gas==1 keeps no buffers (structural)
                grads = jax.lax.with_sharding_constraint(
                    jax.tree.map(lambda g: g.astype(self.grad_acc_dtype), grads),
                    self._grad_shardings)
                state = state._replace(micro_steps=state.micro_steps + gas)
                state, aux = self._apply_update(state, gas, acc=grads)
            else:
                acc = self._accumulate(state.acc_grads, grads)
                state = state._replace(acc_grads=acc, micro_steps=state.micro_steps + gas)
                state, aux = self._apply_update(state, gas)
            return state, {"loss": loss, "lr": self._lr_fn(state.global_steps - 1),
                           "loss_scale": state.scaler.loss_scale, **aux}

        return jax.jit(train_batch_fn, donate_argnums=(0,))
