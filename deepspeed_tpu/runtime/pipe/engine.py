"""Pipeline-parallel training engine.

Reference parity: ``deepspeed/runtime/pipe/engine.py`` — ``PipelineEngine``
(:37) with ``train_batch``/``eval_batch`` (:282,:359) executing the 1F1B
instruction schedule via p2p send/recv between stage processes.

TPU-native design (NOT a port of the instruction interpreter): the entire
schedule — every micro-batch forward, inter-stage transfer, backward, and the
optimizer step — is lowered into ONE compiled XLA program:

- Stage parameters are stacked on a leading ``num_stages`` axis sharded over
  the ``pp`` mesh axis.
- A ``lax.scan`` over pipeline clock ticks runs every stage in parallel
  (``vmap`` over the stage axis; XLA partitions it so each device computes
  only its own stage) and rotates activations one stage forward with
  ``jnp.roll`` on the stage axis, which XLA lowers to a CollectivePermute
  over the ``pp`` axis — the compiled equivalent of the reference's
  ``SendActivation``/``RecvActivation`` instruction pairs
  (``pipe/engine.py:904,996``).
- ``jax.grad`` through the scan yields the reverse rotation
  (``SendGrad``/``RecvGrad``) automatically; ``jax.checkpoint`` on the stage
  body bounds live activations the way 1F1B does.
- The (pp × dp × tp) composition is expressed as shardings, so DP grad
  reduction and TP collectives are inserted by the SPMD partitioner.

The instruction-stream schedules (``schedule.py``) remain available through
the interpretive executor for heterogeneous-stage models.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu.comm as dist
from deepspeed_tpu.runtime.engine import DeepSpeedEngine, TrainState


def spmd_pipeline_loss(embed_fn: Callable,
                       stage_fn: Callable,
                       head_loss_fn: Callable,
                       params: Any,
                       microbatches: Any,
                       rng,
                       num_stages: int,
                       mesh=None,
                       carry_keys: tuple = ()) -> jnp.ndarray:
    """Run a GPipe-style pipelined forward over ``num_stages`` and return the
    mean loss over micro-batches.

    - ``params`` = {"embed": ..., "stages": <leading-dim num_stages>, "head": ...}
    - ``microbatches``: pytree with leading dim M (number of micro-batches)
    - ``embed_fn(params, mb, rng) -> x`` first-stage input (sees the full
      params so tied embeddings work — the reference's ``TiedLayerSpec``)
    - ``stage_fn(stage_params, x, aux, rng) -> x`` one stage (vmapped over stages)
    - ``head_loss_fn(params, x, mb, rng) -> scalar loss`` (last stage)
    - ``carry_keys``: micro-batch dict keys whose values must travel with the
      activations through the pipeline (e.g. attention_mask) — they are
      injected at stage 0 and rotated alongside ``x``.

    Total ticks T = M + num_stages - 1; the (S-1)/T bubble is the standard
    GPipe cost and shrinks with more micro-batches.
    """
    S = num_stages
    leaves = jax.tree.leaves(microbatches)
    M = leaves[0].shape[0]
    T = M + S - 1
    if isinstance(microbatches, dict):
        carry_keys = tuple(k for k in carry_keys if k in microbatches)

    stage_params = params["stages"]

    dp_axes = tuple(dist.data_parallel_axes(mesh)) if mesh is not None else ()
    dp = dp_axes if len(dp_axes) != 1 else dp_axes[0]

    def mb_at(t):
        """Micro-batch ``t`` (clamped) from the stacked batch."""
        idx = jnp.clip(t, 0, M - 1)
        return jax.tree.map(lambda a: jax.lax.dynamic_index_in_dim(a, idx, 0, keepdims=False),
                            microbatches)

    def constrain(x):
        if mesh is None or "pp" not in mesh.shape:
            return x
        def one(a):
            spec = [None] * a.ndim
            spec[0] = "pp"
            if a.ndim > 1 and dp_axes:
                spec[1] = dp
            return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, P(*spec)))
        return jax.tree.map(one, x)

    # initial buffers: embed of micro-batch 0 broadcast over the stage dim
    mb0 = mb_at(jnp.asarray(0, jnp.int32))
    x0 = embed_fn(params, mb0, rng)
    bufs = jnp.broadcast_to(x0[None], (S,) + x0.shape).astype(x0.dtype)
    carry0 = {k: jnp.broadcast_to(mb0[k][None], (S,) + mb0[k].shape) for k in carry_keys}
    bufs, carry0 = constrain(bufs), constrain(carry0)

    vstage = jax.vmap(stage_fn, in_axes=(0, 0, 0, None))

    def tick(state, t):
        bufs, aux, loss_sum = state
        mb = mb_at(t)
        x_in = embed_fn(params, mb, jax.random.fold_in(rng, t))
        bufs = bufs.at[0].set(x_in.astype(bufs.dtype))
        for k in carry_keys:
            aux[k] = aux[k].at[0].set(mb[k])
        bufs, aux = constrain(bufs), constrain(aux)

        outs = vstage(stage_params, bufs, aux, jax.random.fold_in(rng, t))
        # last stage completes micro-batch t - (S-1)
        mb_done = mb_at(t - (S - 1))
        loss_t = head_loss_fn(params, outs[S - 1], mb_done, jax.random.fold_in(rng, t + T))
        loss_sum = loss_sum + jnp.where(t >= S - 1, loss_t.astype(jnp.float32), 0.0)

        bufs = constrain(jnp.roll(outs, 1, axis=0))
        aux = constrain({k: jnp.roll(v, 1, axis=0) for k, v in aux.items()})
        return (bufs, aux, loss_sum), None

    init = (bufs, carry0, jnp.zeros((), jnp.float32))
    (final_bufs, _, loss_sum), _ = jax.lax.scan(tick, init, jnp.arange(T, dtype=jnp.int32))
    return loss_sum / M


class PipelineEngine(DeepSpeedEngine):
    """Engine for models exposing a homogeneous-stage pipeline:

    The model must provide ``pipeline_spec()`` returning a dict with keys
    ``embed_fn, stage_fn, head_loss_fn, num_stages`` and optional
    ``carry_keys``; its params pytree must be ``{"embed", "stages", "head"}``
    with ``stages`` leaves stacked on a leading ``num_stages`` dim.

    ``gradient_accumulation_steps`` plays the reference's ``micro_batches``
    role (pipe/engine.py: micro_batches == gas): each ``train_batch`` feeds
    gas micro-batches through the pipeline and applies one update.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        spec = self.client_model.pipeline_spec()
        self._pipe_spec = spec
        self.num_stages = spec["num_stages"]
        pp = self.mesh.shape.get("pp", 1)
        if pp > 1 and pp != self.num_stages:
            raise ValueError(f"mesh pp={pp} != model num_stages={self.num_stages}")
        self.micro_batches = self.gradient_accumulation_steps()

    def is_pipe_parallel(self) -> bool:
        return True

    def _build_train_batch_fn(self, gas: int) -> Callable:
        spec = self._pipe_spec

        def train_batch_fn(state: TrainState, batch, rng):
            scale = state.scaler.loss_scale

            def scaled_loss(p):
                loss = spmd_pipeline_loss(spec["embed_fn"], spec["stage_fn"], spec["head_loss_fn"],
                                          p, batch, rng, spec["num_stages"], mesh=self.mesh,
                                          carry_keys=tuple(spec.get("carry_keys", ())))
                # _apply_update divides by scale*gas; loss is already the
                # micro-batch mean, so pre-multiply to cancel
                return loss * scale * gas, loss

            grads, loss = jax.grad(scaled_loss, has_aux=True)(state.params)
            acc = self._accumulate(state.acc_grads, grads)
            state = state._replace(acc_grads=acc, micro_steps=state.micro_steps + gas)
            state = self._apply_update(state, gas)
            return state, {"loss": loss, "lr": self._lr_fn(state.global_steps - 1),
                           "loss_scale": state.scaler.loss_scale}

        return jax.jit(train_batch_fn, donate_argnums=(0,))
